"""Offered-load sweep through the REAL RPC admission path (ISSUE 14).

Round-5 testnets plateaued at ~850 tx/s regardless of offered load
because admission was the one verify path still serial: every
`broadcast_tx` paid one ABCI round trip (and one signature verify) at a
time. This bench drives the full front door — HTTP JSON-RPC server ->
`broadcast_tx_sync` -> mempool -> CheckTx -> app signature verify — with
the transfer app's signed workload, once with the serial per-tx path
(`mempool.batch=False`, the pre-ISSUE-14 pipeline) and once with the
ingest accumulator batching CheckTx through the scheduler, on both
curves. A committer task reaps/delivers/commits on a cadence so the
mempool, recheck, and app check-state behave like a live chain; in the
batched mode the committer delivers each reaped block as ONE
DeliverTxBatch round trip (the block executor's batch-first path), so
the e2e admitted→committed columns compare delivery-bound serial vs
batch execution too (TMTPU_DELIVER_BATCH=0 forces serial delivery even
in the batched run, matching the node kill switch).

Signatures come from the pure-python dev signers (crypto/*_math.py), so
the bench runs — and banks — in dependency-free environments; the VERIFY
side uses the app's best-available backend (registered ops backend >
native thread-parallel batch > math oracle), which is exactly what a
node would do.

The tx-lifecycle tracer (libs/txlife.py) runs at sample=1 for the whole
flood — the bench both proves the tracer's cost stays inside the
bench_compare gate (the admission numbers are measured WITH it on) and
uses its per-tx timelines to stitch admitted→committed latency: every
sampled tx carries rpc_received → parked → flushed → verdict stamps from
the real taps plus a committed stamp from the bench's committer, so the
e2e columns are measured attribution, not inference.

Emits bench_compare-compatible JSONL records:
    ingest_{curve}_serial_tx_per_sec
    ingest_{curve}_batched_tx_per_sec   (carries "vs_serial")
    ingest_{curve}_serial_p99_ms / ingest_{curve}_batched_p99_ms
        ("gate": false — single-probe tails are commit-window-bound)
    ingest_{curve}_{mode}_e2e_tx_per_sec   (first rpc_received → last
        committed window over committed-sampled txs)
    ingest_{curve}_{mode}_e2e_p99_ms       (carries p50_ms)
    ingest_{curve}_{mode}_stage_{stage}_p99_ms  (carries p50_ms; delta
        from the previous stamp, named by the later stage; "gate": false
        — attribution rows, shown by bench_compare but never gated)

Usage: python -m benchmarks.ingest_bench [--txs N] [--senders S]
           [--clients C] [--curves secp256k1[,ed25519]] [--out PATH]
"""
from __future__ import annotations

import argparse
import asyncio
import http.client
import json
import multiprocessing
import statistics
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ----------------------------------------------------------------- workload


def make_workload(curve: str, n_txs: int, n_senders: int, tag: bytes = b"\x00\x01"):
    """Pre-signed transfer txs, sharded by sender with sequential nonces
    (per-sender ordering is an app invariant, so each client thread owns
    whole senders). Returns list[list[bytes]] — one shard per sender."""
    from tendermint_tpu.abci.examples import transfer as tr

    if curve == "ed25519":
        from tendermint_tpu.crypto import ed25519_math as m
    else:
        from tendermint_tpu.crypto import secp256k1_math as m

    privs = [
        bytes([1 + (i % 250)]) * 28 + tag + i.to_bytes(2, "big")
        for i in range(n_senders)
    ]
    to = tr.address(m.pub_from_priv(privs[0]))
    per = -(-n_txs // n_senders)
    shards = []
    t0 = time.monotonic()
    for s, priv in enumerate(privs):
        shard = [
            tr.make_tx(curve, priv, to, 1, nonce)
            for nonce in range(min(per, n_txs - s * per))
        ]
        if shard:
            shards.append(shard)
    log(f"  signed {sum(map(len, shards))} {curve} txs "
        f"in {time.monotonic() - t0:.1f}s")
    return shards


# ----------------------------------------------------------------- pipeline


class Pipeline:
    """Transfer app + mempool + RPC server + committer, in-process."""

    def __init__(self, curve: str, batched: bool, commit_interval: float):
        import os

        self.curve = curve
        self.batched = batched
        # delivery rides the same mode split as admission: the serial run
        # delivers per-tx (the pre-DeliverTxBatch pipeline), the batched
        # run sends each reaped block as ONE DeliverTxBatch round trip —
        # unless the node-level kill switch forces serial delivery
        # (TMTPU_DELIVER_BATCH=0, same env the block executor honors)
        self.deliver_batched = (
            batched and os.environ.get("TMTPU_DELIVER_BATCH", "1") != "0"
        )
        self.commit_interval = commit_interval
        self.port = None
        self.committed = 0
        self.heights = 0
        self._stop = asyncio.Event()

    async def start(self):
        from tendermint_tpu.abci.examples import TransferApplication
        from tendermint_tpu.config import Config
        from tendermint_tpu.libs.txlife import TXLIFE
        from tendermint_tpu.mempool import CListMempool
        from tendermint_tpu.proxy import AppConns, LocalClientCreator
        from tendermint_tpu.rpc.core import Environment
        from tendermint_tpu.rpc.jsonrpc import JSONRPCServer

        # every tx sampled: the bench measures admission WITH the tracer
        # hot (the cost must stay inside the bench_compare gate) and
        # stitches admitted→committed latency from the timelines after
        # the run. Sized so no bench tx is ring- or index-evicted.
        TXLIFE.configure(True, sample=1, ring=1 << 20, max_txs=1 << 19)
        TXLIFE.clear()
        self.app = TransferApplication(curve=self.curve)
        self.conns = AppConns(LocalClientCreator(self.app))
        await self.conns.start()
        self.mempool = CListMempool(
            self.conns.mempool,
            max_txs=200_000,
            cache_size=300_000,
            batch=self.batched,
        )
        cfg = Config()
        cfg.mempool.size = 200_000  # bounds the async-ack backlog too
        self.env = Environment(config=cfg, mempool=self.mempool)
        self.server = JSONRPCServer(port=0)
        self.server.register_routes(self.env.routes())
        await self.server.start()
        self.port = self.server.listen_port
        self._committer = asyncio.ensure_future(self._commit_loop())

    async def _commit_block(self):
        # block-size cap (every real chain bounds blocks): also bounds
        # how much on-loop deliver work one commit inserts mid-flood
        txs = self.mempool.reap_max_txs(2048)
        if not txs:
            return
        if self.deliver_batched:
            # one ABCI round trip for the whole reaped block: the transfer
            # app sweeps CheckTx-verified txs from its hash cache and bulk
            # verifies the rest per curve (state/execution.py does exactly
            # this on a real node)
            resps = await self.conns.consensus.deliver_tx_batch(list(txs))
            ok = sum(1 for r in resps if r.is_ok)
        else:
            futs = [self.conns.consensus.deliver_tx_async(tx) for tx in txs]
            await self.conns.consensus.flush()
            ok = 0
            for f in futs:
                if (await f).is_ok:
                    ok += 1
        await self.conns.consensus.commit()
        self.heights += 1
        await self.mempool.update(self.heights, txs)
        self.committed += ok
        # the bench IS the consensus layer here, so it owns the stage the
        # real commit boundary (consensus/state.py) would stamp. AFTER
        # update(): between app Commit and the recheck the app's check
        # nonces are rolled back, so any work inserted there widens the
        # window in which flushed buckets are wholesale nonce-rejected.
        from tendermint_tpu.libs.txlife import TXLIFE

        if TXLIFE.enabled:
            from tendermint_tpu.types.tx import tx_hash

            for tx in txs:
                TXLIFE.stage("committed", tx_hash(tx), height=self.heights)

    async def _commit_loop(self):
        while not self._stop.is_set():
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.commit_interval
                )
            except asyncio.TimeoutError:
                pass
            await self._commit_block()

    async def stop_committer(self):
        self._stop.set()
        await self._committer

    async def stop(self):
        await self.server.stop()
        await self.conns.stop()


# ------------------------------------------------------------------ clients


def _post(conn, port, method, tx_hex, rid):
    """One fast-path-shaped JSON-RPC POST; returns (response dict|None,
    fresh_conn). A transport hiccup rebuilds the connection."""
    body = (
        '{"jsonrpc":"2.0","id":%d,"method":"%s",'
        '"params":{"tx":"%s"}}' % (rid, method, tx_hex)
    ).encode()
    try:
        conn.request("POST", "/", body, {"Content-Type": "application/json"})
        return json.loads(conn.getresponse().read()), conn
    except Exception:
        conn.close()
        return None, http.client.HTTPConnection("127.0.0.1", port)


def _flood_worker(port: int, shards_hex, out_q, barrier, stop, post_batch: int):
    """Greedy client PROCESS (own interpreter — a client's Python must
    not share the server's GIL, exactly like a remote tm-bench box):
    fire-and-forget broadcast_tx_async floods over one persistent
    connection, sender shards drained in nonce order — the round-5
    tm-bench shape that produced the 850 tx/s plateau. Requests ride
    JSON-RPC batch arrays (`post_batch` per POST) so client HTTP
    overhead doesn't become the measurement ceiling; both modes see the
    identical offered stream."""
    # interleave round-robin across this worker's shards so one sender's
    # nonce order is preserved while the stream mixes senders
    queue: list[str] = []
    cursors = [0] * len(shards_hex)
    while True:
        progressed = False
        for i, shard in enumerate(shards_hex):
            if cursors[i] < len(shard):
                queue.append(shard[cursors[i]])
                cursors[i] += 1
                progressed = True
        if not progressed:
            break
    conn = http.client.HTTPConnection("127.0.0.1", port)
    rid = 0
    errors = 0
    barrier.wait()
    for off in range(0, len(queue), post_batch):
        if stop.is_set():
            break
        chunk = queue[off:off + post_batch]
        rid += 1
        body = (
            '{"jsonrpc":"2.0","id":%d,"method":"broadcast_txs_async",'
            '"params":{"txs":"%s"}}' % (rid, ",".join(chunk))
        ).encode()
        for _ in range(200):
            try:
                conn.request(
                    "POST", "/", body, {"Content-Type": "application/json"}
                )
                resp = json.loads(conn.getresponse().read())
            except Exception:
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port)
                errors += 1
                time.sleep(0.01)
                continue
            # structured backpressure (rate-limited / queue full): back
            # off and resend the chunk — the bench measures sustained
            # admission, not how fast the server can say no (dedup
            # upstream makes a partial resend harmless)
            if "result" in resp:
                break
            time.sleep(0.01)
        else:
            errors += 1
    conn.close()
    out_q.put(("errors", errors))


def _probe_worker(port: int, shard_hex, out_q, barrier, stop):
    """Latency prober PROCESS: its OWN sender, sequential nonces, one
    broadcast_tx_sync at a time on a small cadence — measures per-tx
    admission latency (accepted-verdict round trip) under the flood."""
    conn = http.client.HTTPConnection("127.0.0.1", port)
    rid = 10_000_000
    latencies: list[float] = []
    barrier.wait()
    for tx_hex in shard_hex:
        if stop.is_set():
            break
        rid += 1
        for _ in range(200):
            t0 = time.perf_counter()
            resp, conn = _post(conn, port, "broadcast_tx_sync", tx_hex, rid)
            dt = time.perf_counter() - t0
            if resp is not None and "result" in resp and resp["result"].get("code") == 0:
                latencies.append(dt)
                break
            time.sleep(0.01)  # backpressure or commit-race nonce drift
        time.sleep(0.02)
    conn.close()
    out_q.put(("latencies", latencies))


# -------------------------------------------------------------------- bench


def _pct_ms(vals: list) -> tuple:
    s = sorted(vals)
    return (
        round(statistics.median(s) * 1e3, 3),
        round(s[int(0.99 * (len(s) - 1))] * 1e3, 3),
    )


def _stitch_txlife(timelines: dict) -> dict:
    """Admitted→committed stitch from the tracer's per-tx timelines.
    e2e = first stamp (rpc_received at the front door) → committed;
    per-stage deltas are from the previous stamp, named by the later
    stage (batched: parked/flushed/verdict/committed; serial has no
    park/flush stamps — CheckTx is inline — so only verdict/committed).
    e2e throughput uses the first-received → last-committed window over
    committed txs: a true end-to-end rate, not the admission clock."""
    e2e: list[float] = []
    stages: dict[str, list] = {}
    first_ns = None
    last_commit_ns = None
    for tl in timelines.values():
        prev = None
        commit_ns = None
        for t, stage, _fields in tl:
            if prev is not None:
                stages.setdefault(stage, []).append((t - prev) / 1e9)
            prev = t
            if stage == "committed" and commit_ns is None:
                commit_ns = t
        if commit_ns is None:
            continue
        e2e.append((commit_ns - tl[0][0]) / 1e9)
        t0 = tl[0][0]
        first_ns = t0 if first_ns is None else min(first_ns, t0)
        last_commit_ns = (
            commit_ns if last_commit_ns is None
            else max(last_commit_ns, commit_ns)
        )
    if not e2e:
        return {}
    window_s = (last_commit_ns - first_ns) / 1e9
    p50, p99 = _pct_ms(e2e)
    return {
        "e2e_txs": len(e2e),
        "e2e_window_s": round(window_s, 3),
        "e2e_tx_per_sec": round(len(e2e) / window_s, 1) if window_s > 0 else 0.0,
        "e2e_p50_ms": p50,
        "e2e_p99_ms": p99,
        "stages": {
            stage: dict(zip(("p50_ms", "p99_ms"), _pct_ms(vals)), n=len(vals))
            for stage, vals in sorted(stages.items())
        },
    }


async def _run_mode(curve: str, batched: bool, shards, probe_shard,
                    clients: int, commit_interval: float,
                    post_batch: int = 32) -> dict:
    pipe = Pipeline(curve, batched, commit_interval)
    await pipe.start()
    n_txs = sum(map(len, shards))
    shards_hex = [[tx.hex() for tx in s] for s in shards]
    assign = [shards_hex[i::clients] for i in range(clients)]
    ctx = multiprocessing.get_context("spawn")
    stop = ctx.Event()
    out_q = ctx.Queue()
    n_procs = len([a for a in assign if a]) + 1
    barrier = ctx.Barrier(n_procs + 1)
    procs = [
        ctx.Process(
            target=_flood_worker,
            args=(pipe.port, a, out_q, barrier, stop, post_batch),
            daemon=True,
        )
        for a in assign
        if a
    ]
    procs.append(
        ctx.Process(
            target=_probe_worker,
            args=(pipe.port, [tx.hex() for tx in probe_shard], out_q,
                  barrier, stop),
            daemon=True,
        )
    )
    for p in procs:
        p.start()
    loop = asyncio.get_running_loop()
    t0 = time.monotonic()
    await loop.run_in_executor(None, barrier.wait)  # release the herd
    # the admission clock runs until every offered tx has RESOLVED
    # through CheckTx (admitted into the pool or rejected) — interval
    # commits happen inside the window like a live chain, but the final
    # drain-everything commit is post-measurement bookkeeping
    deadline = t0 + 600.0
    while time.monotonic() < deadline:
        flooders_done = all(not p.is_alive() for p in procs[:-1])
        if (
            flooders_done
            and not pipe.env._async_txs
            and not pipe.mempool._pending
            and not pipe.mempool._bucket
        ):
            break
        await asyncio.sleep(0.02)
    elapsed = time.monotonic() - t0
    # settle the committer BEFORE reading counts: a commit in flight at
    # clock-stop has already drained the pool but not yet counted
    await pipe.stop_committer()
    admitted = pipe.committed + pipe.mempool.size()
    stop.set()
    latencies: list[float] = []
    errors = 0
    for _ in procs:
        try:
            kind, payload = await loop.run_in_executor(
                None, out_q.get, True, 30.0
            )
        except Exception:
            break
        if kind == "latencies":
            latencies = payload
        else:
            errors += payload
    join_deadline = time.monotonic() + 10.0
    while any(p.is_alive() for p in procs) and time.monotonic() < join_deadline:
        await asyncio.sleep(0.05)
    for p in procs:
        if p.is_alive():
            p.terminate()
    # drain the pool so the workload provably commits end to end
    for _ in range(100):
        await pipe._commit_block()
        if pipe.mempool.size() == 0:
            break
    await pipe.stop()
    committed = pipe.committed
    from tendermint_tpu.libs.txlife import TXLIFE

    life = _stitch_txlife(TXLIFE.timelines())
    TXLIFE.clear()
    TXLIFE.configure(False)
    lat_sorted = sorted(latencies)
    out = {
        "mode": "batched" if batched else "serial",
        "curve": curve,
        "offered": n_txs,
        "admitted": admitted,
        "committed": committed,
        "heights": pipe.heights,
        "errors": errors,
        "elapsed_s": round(elapsed, 3),
        "tx_per_sec": round(admitted / elapsed, 1) if elapsed > 0 else 0.0,
        "probe_samples": len(lat_sorted),
        "p50_ms": round(statistics.median(lat_sorted) * 1e3, 3) if lat_sorted else None,
        "p99_ms": round(lat_sorted[int(0.99 * (len(lat_sorted) - 1))] * 1e3, 3)
        if lat_sorted
        else None,
        "life": life,
    }
    return out


def _record(metric: str, value, unit: str, source: str, **extra) -> dict:
    rec = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "platform": "cpu",
        "device_kind": "cpu",
        "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "source": source,
    }
    rec.update(extra)
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--txs", type=int, default=3000)
    ap.add_argument("--senders", type=int, default=64)
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--curves", default="secp256k1,ed25519")
    # round-5 testnets committed at p50 ~1.4s; 0.5s is already a fast chain
    ap.add_argument("--commit-interval", type=float, default=0.5)
    ap.add_argument("--post-batch", type=int, default=128,
                    help="txs per JSON-RPC batch POST (client-side)")
    ap.add_argument("--out", default=None, help="append JSONL records here")
    args = ap.parse_args(argv)

    records = []
    for curve in [c for c in args.curves.split(",") if c]:
        log(f"[{curve}] generating workload ...")
        shards = make_workload(curve, args.txs, args.senders)
        probe_shard = make_workload(
            curve, max(20, min(300, args.txs // 10)), 1, tag=b"\xfe\xfd"
        )[0]
        source = (
            f"benchmarks.ingest_bench txs={args.txs} senders={args.senders} "
            f"clients={args.clients} curve={curve}"
        )
        results = {}
        for batched in (False, True):
            mode = "batched" if batched else "serial"
            log(f"[{curve}] {mode} run ...")
            res = asyncio.run(
                _run_mode(curve, batched, shards, probe_shard, args.clients,
                          args.commit_interval, args.post_batch)
            )
            results[mode] = res
            log(f"[{curve}] {mode}: {res['tx_per_sec']} tx/s "
                f"p50={res['p50_ms']}ms p99={res['p99_ms']}ms "
                f"admitted={res['admitted']} "
                f"committed={res['committed']}/{res['offered']} "
                f"heights={res['heights']} errors={res['errors']}")
            if res["life"]:
                lf = res["life"]
                per_stage = " ".join(
                    f"{s}={v['p50_ms']}/{v['p99_ms']}ms"
                    for s, v in lf["stages"].items()
                )
                log(f"[{curve}] {mode} e2e: {lf['e2e_tx_per_sec']} tx/s "
                    f"({lf['e2e_txs']} txs stitched), "
                    f"p50={lf['e2e_p50_ms']}ms p99={lf['e2e_p99_ms']}ms; "
                    f"stage p50/p99: {per_stage}")
        speedup = (
            round(results["batched"]["tx_per_sec"]
                  / results["serial"]["tx_per_sec"], 2)
            if results["serial"]["tx_per_sec"]
            else None
        )
        for mode, res in results.items():
            extra = {
                "admitted": res["admitted"],
                "committed": res["committed"],
                "heights": res["heights"],
            }
            if mode == "batched" and speedup is not None:
                extra["vs_serial"] = speedup
            records.append(_record(
                f"ingest_{curve}_{mode}_tx_per_sec", res["tx_per_sec"],
                "tx/s", source, **extra,
            ))
            if res["p99_ms"] is not None:
                # attribution, not a gate: the prober sends ONE tx at a
                # time, so its tail is set by whether a sample lands
                # inside an on-loop block commit — measured same-code
                # spread is several tens of percent on small hosts. The
                # aggregated e2e latency rows (thousands of stitched txs)
                # carry the gated latency trajectory instead.
                records.append(_record(
                    f"ingest_{curve}_{mode}_p99_ms", res["p99_ms"], "ms",
                    source, p50_ms=res["p50_ms"], gate=False,
                ))
            # admitted→committed attribution from the lifecycle tracer
            lf = res["life"]
            if lf:
                records.append(_record(
                    f"ingest_{curve}_{mode}_e2e_tx_per_sec",
                    lf["e2e_tx_per_sec"], "tx/s", source,
                    e2e_txs=lf["e2e_txs"], window_s=lf["e2e_window_s"],
                ))
                records.append(_record(
                    f"ingest_{curve}_{mode}_e2e_p99_ms", lf["e2e_p99_ms"],
                    "ms", source, p50_ms=lf["e2e_p50_ms"],
                ))
                for stage, v in lf["stages"].items():
                    # attribution, not a gate: stage dwell tails swing
                    # several multiples with workload shape (flushed p99
                    # is deadline-trigger-bound at low bucket fill), so
                    # they ride the trajectory as bench_compare "info"
                    # rows instead of red-building on shape noise.
                    records.append(_record(
                        f"ingest_{curve}_{mode}_stage_{stage}_p99_ms",
                        v["p99_ms"], "ms", source,
                        p50_ms=v["p50_ms"], n=v["n"], gate=False,
                    ))
        log(f"[{curve}] batched vs serial: {speedup}x")
    for rec in records:
        print(json.dumps(rec))
    if args.out:
        with open(args.out, "a", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
