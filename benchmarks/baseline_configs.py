"""The five BASELINE.json benchmark configs, measured end-to-end.

Each config maps to a reference hot path (BASELINE.md table):
  1. ed25519 single-sig VerifyBytes loop, 1k msgs      crypto/ed25519/ed25519.go:151
  2. Commit.VerifyCommit, 100 validators               types/validator_set.go:591-633
  3. validate_block, 1000 validators + evidence        state/validation.go:16,99,141
  4. lite DynamicVerifier chain, H headers x V vals    lite/dynamic_verifier.go:73,211
  5. mixed ed25519+secp256k1 multisig, streaming       types/vote_set.go:131,189
     VoteSet.add_votes, 10k validators

Usage: python -m benchmarks.baseline_configs [1 2 3 4 5] [--full]
Config 4 defaults to 100 headers x 500 validators; --full runs the
500 x 2000 BASELINE shape (~1M signatures to build, minutes of setup).

Serial-reference context: one CPU-core VerifyBytes loop at the measured
config-1 rate is the number every other config is compared against.
"""
from __future__ import annotations

import statistics
import sys
import time


def log(*a):
    print(*a, flush=True)


def _timeit(fn, repeat=3):
    samples = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def config1_serial_loop(n=1000):
    """Serial one-at-a-time ed25519 verify — the reference's hot-path shape."""
    from tendermint_tpu.crypto import ed25519

    priv = ed25519.gen_priv_key()
    pub = priv.pub_key()
    msgs = [b"cfg1 %d" % i for i in range(n)]
    sigs = [priv.sign(m) for m in msgs]

    t0 = time.perf_counter()
    ok = all(pub.verify(m, s) for m, s in zip(msgs, sigs))
    dt = time.perf_counter() - t0
    assert ok
    rate = n / dt
    log(f"[1] serial VerifyBytes loop: {dt * 1e3:8.1f} ms / {n} "
        f"({rate:,.0f}/s)  <- baseline anchor")
    return rate


def _commit_fixture(n_vals, chain_id="bench-chain"):
    from tendermint_tpu.types import MockPV, ValidatorSet, VoteSet, VoteType
    from tendermint_tpu.types.validator_set import Validator
    from tendermint_tpu.types.vote import BlockID, PartSetHeader, Vote, now_ns

    pvs = sorted([MockPV() for _ in range(n_vals)], key=lambda p: p.address)
    vs = ValidatorSet([Validator(pv.get_pub_key(), 10) for pv in pvs])
    h = bytes(range(32))
    bid = BlockID(h, PartSetHeader(1, h))
    voteset = VoteSet(chain_id, 3, 0, VoteType.PRECOMMIT, vs)
    votes = []
    for pv in pvs:
        idx, _ = vs.get_by_address(pv.address)
        v = Vote(VoteType.PRECOMMIT, 3, 0, bid, now_ns(), pv.address, idx)
        votes.append(pv.sign_vote(chain_id, v))
    voteset.add_votes(votes)
    return vs, voteset.make_commit(), bid, chain_id


def config2_verify_commit(n_vals=100):
    import tendermint_tpu.ops as ops

    vs, commit, bid, chain_id = _commit_fixture(n_vals)
    dt = _timeit(lambda: vs.verify_commit(chain_id, bid, 3, commit))
    log(f"[2] Commit.VerifyCommit @ {n_vals} validators: {dt * 1e3:8.1f} ms "
        f"(probed routing, threshold {ops.effective_min_batch()})")
    # forced-device routing: what a LOCAL chip's threshold (8) does with
    # this commit — over a tunnel this line just measures the RTT floor,
    # on a local chip it is the real small-commit device latency
    # (r2 VERDICT weak #4: the local-routing claim needs a recorded
    # number, not prose). Skipped when the override env var would make
    # the forced probe value a lie, and on no-accelerator hosts where
    # the device path is deliberately disabled (the XLA:CPU kernel is
    # not a device).
    import os as _os

    import jax as _jax

    if "TMTPU_MIN_DEVICE_BATCH" in _os.environ:
        log("[2] forced-device p50 skipped: TMTPU_MIN_DEVICE_BATCH is set")
    elif _jax.default_backend() == "cpu":
        log("[2] forced-device p50 skipped: no accelerator on this host")
    else:
        prev = ops._min_batch_probed
        try:
            ops._min_batch_probed = 8
            samples = []
            for _ in range(5):
                t0 = time.perf_counter()
                vs.verify_commit(chain_id, bid, 3, commit)
                samples.append(time.perf_counter() - t0)
            log(f"[2] Commit.VerifyCommit @ {n_vals} validators, "
                f"forced-device (threshold 8): p50 "
                f"{statistics.median(samples) * 1e3:8.1f} ms")
        finally:
            ops._min_batch_probed = prev
    return n_vals / dt


def config3_validate_block_shape(n_vals=1000, n_evidence=20):
    """The validate_block signature workload: LastCommit verify + per-
    evidence sig checks, batched the way state/validation.py does it."""
    from tendermint_tpu.crypto.batch import BatchVerifier
    from tendermint_tpu.types import MockPV, ValidatorSet, VoteType
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence
    from tendermint_tpu.types.validator_set import Validator
    from tendermint_tpu.types.vote import BlockID, PartSetHeader, Vote, now_ns

    vs, commit, bid, chain_id = _commit_fixture(n_vals)
    # evidence: n_evidence equivocating validators
    pv_e = [MockPV() for _ in range(n_evidence)]
    evs = []
    for pv in pv_e:
        h1, h2 = bytes(32), bytes(range(32))
        v1 = Vote(VoteType.PREVOTE, 2, 0, BlockID(h1, PartSetHeader(1, h1)),
                  now_ns(), pv.address, 0)
        v2 = Vote(VoteType.PREVOTE, 2, 0, BlockID(h2, PartSetHeader(1, h2)),
                  now_ns(), pv.address, 0)
        evs.append(
            DuplicateVoteEvidence(
                pv.get_pub_key(), pv.sign_vote(chain_id, v1),
                pv.sign_vote(chain_id, v2),
            )
        )

    def run():
        vs.verify_commit(chain_id, bid, 3, commit)
        bv = BatchVerifier()
        for ev in evs:
            ev.add_to_batch(chain_id, ev.pub_key, bv)
        ok = bv.verify_all()
        assert all(ok)

    dt = _timeit(run)
    n_sigs = n_vals + 2 * n_evidence
    log(f"[3] validate_block shape @ {n_vals} validators + {n_evidence} "
        f"evidence: {dt * 1e3:8.1f} ms ({n_sigs} sigs)")
    return n_sigs / dt


def config4_lite_chain(n_headers=100, n_vals=500):
    """Light-client header chain: every header's commit verified against a
    (rotating) valset — the DynamicVerifier bisection workload."""
    from tendermint_tpu.types import MockPV, ValidatorSet, VoteSet, VoteType
    from tendermint_tpu.types.validator_set import Validator
    from tendermint_tpu.types.vote import BlockID, PartSetHeader, Vote, now_ns

    chain_id = "lite-bench"
    pvs = sorted([MockPV() for _ in range(n_vals)], key=lambda p: p.address)
    vs = ValidatorSet([Validator(pv.get_pub_key(), 10) for pv in pvs])
    log(f"    building {n_headers} x {n_vals} signed commits "
        f"({n_headers * n_vals:,} signatures)...")
    commits = []
    for height in range(1, n_headers + 1):
        h = height.to_bytes(32, "big")
        bid = BlockID(h, PartSetHeader(1, h))
        voteset = VoteSet(chain_id, height, 0, VoteType.PRECOMMIT, vs)
        votes = []
        for pv in pvs:
            idx, _ = vs.get_by_address(pv.address)
            v = Vote(VoteType.PRECOMMIT, height, 0, bid, now_ns(), pv.address, idx)
            votes.append(pv.sign_vote(chain_id, v))
        voteset.add_votes(votes)
        commits.append((bid, voteset.make_commit()))

    n_sigs = n_headers * n_vals
    t0 = time.perf_counter()
    for height, (bid, commit) in enumerate(commits, start=1):
        vs.verify_commit(chain_id, bid, height, commit)
    dt = time.perf_counter() - t0
    log(f"[4] lite chain {n_headers} x {n_vals}, per-header: {dt:8.2f} s "
        f"({n_sigs:,} sigs, {n_sigs / dt:,.0f}/s)")

    # the fused span path (DynamicVerifier.verify_chain): every header's
    # commit in ONE cross-height batch (tendermint_tpu beats the
    # reference's per-height loop, lite/dynamic_verifier.go:73)
    from tendermint_tpu.ops import kcache
    from tendermint_tpu.ops.ed25519_batch import _pad_to_bucket
    from tendermint_tpu.types.validator_set import verify_commits

    # compile every chunk bucket outside the timed region (nodes prewarm
    # the same way) — with --full the 1M-sig span chunks at MAX_BUCKET
    # plus a remainder bucket
    buckets = set()
    for lo in range(0, n_sigs, kcache.MAX_BUCKET):
        buckets.add(_pad_to_bucket(min(kcache.MAX_BUCKET, n_sigs - lo)))
    kcache.prewarm(sorted(buckets), background=False)
    t0 = time.perf_counter()
    errs = verify_commits(
        [
            (vs, chain_id, bid, height, commit)
            for height, (bid, commit) in enumerate(commits, start=1)
        ]
    )
    dt_fused = time.perf_counter() - t0
    assert not any(errs)
    log(f"[4] lite chain {n_headers} x {n_vals}, fused span: {dt_fused:8.2f} s "
        f"({n_sigs / dt_fused:,.0f}/s)")
    return n_sigs / dt_fused


def config5_mixed_streaming(n_vals=10_000, burst=256):
    """Streaming VoteSet.add_votes with a mixed ed25519 + secp256k1 +
    2-of-3 multisig validator set, ingested in gossip-sized bursts."""
    from tendermint_tpu.crypto import ed25519 as ed
    from tendermint_tpu.crypto import secp256k1 as sk
    from tendermint_tpu.crypto.multisig import PubKeyMultisigThreshold
    from tendermint_tpu.types import ValidatorSet, VoteSet, VoteType
    from tendermint_tpu.types.priv_validator import MockPV
    from tendermint_tpu.types.validator_set import Validator
    from tendermint_tpu.types.vote import BlockID, PartSetHeader, Vote, now_ns

    chain_id = "mixed-bench"

    class SecpPV:
        def __init__(self):
            self.priv = sk.gen_priv_key()
            self.address = self.priv.pub_key().address()

        def get_pub_key(self):
            return self.priv.pub_key()

        def sign_vote(self, cid, vote):
            return vote.with_signature(self.priv.sign(vote.sign_bytes(cid)))

    class MultiPV:
        """2-of-3 threshold (ed25519 x2 + secp256k1)."""

        def __init__(self):
            self.e1, self.e2 = ed.gen_priv_key(), ed.gen_priv_key()
            self.s1 = sk.gen_priv_key()
            self.pub = PubKeyMultisigThreshold(
                2, [self.e1.pub_key(), self.e2.pub_key(), self.s1.pub_key()]
            )
            self.address = self.pub.address()

        def get_pub_key(self):
            return self.pub

        def sign_vote(self, cid, vote):
            from tendermint_tpu.crypto.multisig import Multisignature

            msg = vote.sign_bytes(cid)
            keys = [self.e1.pub_key(), self.e2.pub_key(), self.s1.pub_key()]
            ms = Multisignature(3)
            ms.add_signature_from_pubkey(self.e1.sign(msg), keys[0], keys)
            ms.add_signature_from_pubkey(self.s1.sign(msg), keys[2], keys)
            return vote.with_signature(ms.encode())

    log(f"    building {n_vals} mixed-key validators...")
    pvs = []
    for i in range(n_vals):
        if i % 3 == 0:
            pvs.append(MockPV())
        elif i % 3 == 1:
            pvs.append(SecpPV())
        else:
            pvs.append(MultiPV())
    pvs.sort(key=lambda p: p.address)
    vs = ValidatorSet([Validator(pv.get_pub_key(), 10) for pv in pvs])
    h = bytes(range(32))
    bid = BlockID(h, PartSetHeader(1, h))
    log("    signing...")
    votes = []
    for pv in pvs:
        idx, _ = vs.get_by_address(pv.address)
        v = Vote(VoteType.PRECOMMIT, 5, 0, bid, now_ns(), pv.address, idx)
        votes.append(pv.sign_vote(chain_id, v))

    # primitive sig count: 1/3 ed25519 + 1/3 secp + 1/3 * 2 multisig subs
    n_sigs = sum(1 if i % 3 == 0 else 1 if i % 3 == 1 else 2 for i in range(n_vals))

    # warm both curves' kernels on the shapes the stream will flush
    # (nodes prewarm at start — kcache.prewarm + node/__init__; first-use
    # compile/dispatch must not land inside the timed sections)
    warm_set = VoteSet(chain_id, 5, 0, VoteType.PRECOMMIT, vs)
    warm = warm_set.stream()
    warm.feed(votes[: min(warm.high_water, n_vals)])
    warm.flush()

    # (a) per-burst sync ingest — every burst verified before the next is
    # accepted (the reference's AddVote contract, batched per burst)
    voteset = VoteSet(chain_id, 5, 0, VoteType.PRECOMMIT, vs)
    t0 = time.perf_counter()
    for lo in range(0, n_vals, burst):
        voteset.add_votes(votes[lo:lo + burst])
    dt = time.perf_counter() - t0
    assert voteset.has_two_thirds_majority()
    log(f"[5] mixed VoteSet @ {n_vals} validators, per-burst sync "
        f"(burst {burst}): {dt * 1e3:8.1f} ms "
        f"({n_sigs:,} primitive sigs, {n_sigs / dt:,.0f}/s)")

    # (b) streamed ingest — the accumulate-to-hint policy: bursts collect
    # in a VoteStream and flush through device-sized launches. The live
    # consensus batcher applies the same policy with a latency deadline
    # (consensus/state.py _handle_peer_batch extends its window while
    # votes keep arriving, up to vote_batch_max_window); VoteStream is
    # the deadline-free bulk-ingest API measured here (round-2 VERDICT
    # weak #3: per-burst sync ran BELOW the serial anchor because
    # 256-vote bursts sat under the device routing threshold)
    voteset = VoteSet(chain_id, 5, 0, VoteType.PRECOMMIT, vs)
    stream = voteset.stream()
    t0 = time.perf_counter()
    for lo in range(0, n_vals, burst):
        stream.feed(votes[lo:lo + burst])
    stream.flush()
    dt_s = time.perf_counter() - t0
    assert voteset.has_two_thirds_majority()
    assert not any(stream.errors)
    log(f"[5] mixed VoteSet @ {n_vals} validators, streamed "
        f"(burst {burst}, high-water {stream.high_water}): {dt_s * 1e3:8.1f} ms "
        f"({n_sigs:,} primitive sigs, {n_sigs / dt_s:,.0f}/s)")
    return n_sigs / dt_s


def main(argv):
    full = "--full" in argv
    picks = [a for a in argv if a.isdigit()] or ["1", "2", "3", "4", "5"]
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # The env var alone is NOT authoritative: the axon TPU plugin
        # registers itself regardless, and with a wedged tunnel the first
        # backend query then hangs forever. The config update before any
        # device use is the real override (tests/conftest.py pattern) —
        # JAX_PLATFORMS=cpu must make this script tunnel-proof.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # register the batch backends exactly as a node does (node/__init__):
    # without this every config silently measures the serial fallback
    import tendermint_tpu.ops  # noqa: F401 — registers device backends
    from tendermint_tpu.crypto import native
    from tendermint_tpu.ops import kcache

    native.register()
    kcache.enable_persistent_cache()
    # measurements, not warm-up: no background warm child contending with
    # the tunnel (see bench.py)
    kcache.suppress_background_warm()
    log(f"platform: {jax.default_backend()}")
    if "1" in picks:
        config1_serial_loop()
    if "2" in picks:
        config2_verify_commit()
    if "3" in picks:
        config3_validate_block_shape()
    if "4" in picks:
        config4_lite_chain(*((500, 2000) if full else (100, 500)))
    if "5" in picks:
        config5_mixed_streaming()


if __name__ == "__main__":
    main(sys.argv[1:])
