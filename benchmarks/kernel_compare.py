"""Head-to-head device benchmark: XLA verify kernel vs Pallas verify kernel.

Usage: python benchmarks/kernel_compare.py [batch ...]
Prints per-kernel wall times (fresh device_put + launch + fetch, the honest
pipeline number bench.py uses) and agreement check.
"""
from __future__ import annotations

import sys
import time

import numpy as np


def main() -> None:
    import jax

    from tendermint_tpu.ops import ed25519_batch, kcache
    from tendermint_tpu.utils import make_sig_batch

    kcache.enable_persistent_cache()
    batches = [int(a) for a in sys.argv[1:]] or [1024, 10240]
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", flush=True)

    n_unique = 512
    pubs, msgs, sigs = make_sig_batch(n_unique, msg_prefix=b"kcmp ")
    for n in batches:
        reps = -(-n // n_unique)
        p = (pubs * reps)[:n]
        m = (msgs * reps)[:n]
        s = (sigs * reps)[:n]
        # flip one signature bad so agreement check is non-trivial
        s[1] = bytes([s[1][0] ^ 1]) + s[1][1:]
        packed, mask = ed25519_batch.prepare_batch(p, m, s)
        assert packed is not None

        kernels = {
            "xla": ed25519_batch.verify_kernel,
            # radix-8 A/B variant (85x(3 dbl + add) over a 64-entry table
            # vs 127x(2 dbl + add) over 16): ~15% fewer field multiplies,
            # 2.8x the select work — promoted to production only if this
            # on-device comparison shows a win
            "xla-r8": ed25519_batch.verify_kernel_r8,
        }
        try:
            from tendermint_tpu.ops import pallas_verify

            kernels["pallas"] = pallas_verify.pallas_verify_kernel
        except Exception as e:  # noqa: BLE001
            print(f"pallas import failed: {e!r}")

        outs = {}
        for name, fn in kernels.items():
            keys_np, sigs_np = ed25519_batch.split(packed)
            try:
                t0 = time.perf_counter()
                out = np.asarray(
                    fn(jax.device_put(keys_np, dev), jax.device_put(sigs_np, dev))
                )
                compile_s = time.perf_counter() - t0
                iters = 5
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = np.asarray(
                        fn(jax.device_put(keys_np, dev),
                           jax.device_put(sigs_np, dev))
                    )
                dt = (time.perf_counter() - t0) / iters
                outs[name] = out
                print(
                    f"B={n:6d} {name:7s} {dt * 1e3:9.2f} ms "
                    f"({n / dt:>12,.0f} sigs/s)  [first: {compile_s:.1f}s]",
                    flush=True,
                )
            except Exception as e:  # noqa: BLE001
                print(f"B={n:6d} {name:7s} FAILED: {e!r}"[:500], flush=True)
        if "xla" in outs and len(outs) > 1:
            ref = outs["xla"][:n]
            for name, out in outs.items():
                if name == "xla":
                    continue
                print(f"  xla vs {name}: agree="
                      f"{bool((ref == out[:n]).all())}  "
                      f"(valid: {int(ref.sum())}/{n})")


if __name__ == "__main__":
    main()
