"""Characterize the axon tunnel: per-op latency vs bandwidth, pipelining."""
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    # bandwidth: single device_put of increasing size
    for mb in (0.01, 2.4, 9.6, 19.2, 76.8):
        n = int(mb * 1e6 / 4)
        x = np.arange(n, dtype=np.int32)
        a = jax.device_put(x, dev); a.block_until_ready()  # warm path
        t0 = time.perf_counter()
        a = jax.device_put(x, dev)
        a.block_until_ready()
        dt = time.perf_counter() - t0
        log(f"h2d single {mb:6.2f} MB: {dt*1e3:7.1f} ms ({mb/dt:7.1f} MB/s)")

    # trivial execute latency + pipelining
    f = jax.jit(lambda x: x * 2 + 1)
    x = jax.device_put(np.arange(1024, dtype=np.int32), dev)
    np.asarray(f(x))
    t0 = time.perf_counter()
    np.asarray(f(x))
    log(f"trivial exec sync: {(time.perf_counter()-t0)*1e3:.1f} ms")
    for K in (4, 16):
        t0 = time.perf_counter()
        outs = [f(x) for _ in range(K)]
        for o in outs:
            o.block_until_ready()
        dt = time.perf_counter() - t0
        log(f"trivial exec x{K} queued: {dt*1e3:.1f} ms total, {dt/K*1e3:.2f} ms/op")

    # d2h fetch latency
    t0 = time.perf_counter()
    np.asarray(x)
    log(f"d2h fetch 4KB: {(time.perf_counter()-t0)*1e3:.1f} ms")

    # does put overlap with exec? queue put(A2), exec(A1), put(A3), exec...
    big = np.arange(int(2.4e6 / 4), dtype=np.int32)
    t0 = time.perf_counter()
    seq = []
    for _ in range(4):
        a = jax.device_put(big, dev)
        seq.append(f(a))
    for o in seq:
        o.block_until_ready()
    dt = time.perf_counter() - t0
    log(f"interleaved put(2.4MB)+exec x4: {dt*1e3:.1f} ms total, {dt/4*1e3:.1f} ms/pair")


if __name__ == "__main__":
    main()
