"""Nemesis scenario matrix — adversarial faults over real node processes.

`proc_testnet.py` proves liveness through *benign* failures (restarts,
kill-all, fuzzed links). This module is the adversarial tier the soak
round asked for (ROADMAP item 5): Byzantine validators, partitions,
asymmetric delay, mempool floods, a flapping device, and deterministic
crash-point sweeps — with every scenario asserting through the
observability planes (flight recorder events over `debug_flight_recorder`,
stitched fleet-collector timelines, `health`), so an observability
regression fails the same run that needed it.

Fault surface (all driven over public RPC, no process introspection):
- per-link faults via the `debug_fault` route (libs/fault.py): partition,
  asymmetric delay, probabilistic drop, heal;
- device-breaker control via the same route (`trip_breaker` /
  `reset_breaker` — the DeviceScheduler's wedged-device circuit breaker,
  reached through the deprecated `ops.ed25519_batch.breaker` alias);
- process schedules via signals (SIGSTOP/SIGCONT/SIGKILL — ProcTestnet
  pause/resume/kill);
- crash points via `FAIL_TEST_INDEX` (libs/fail.py), armed per node
  through ProcTestnet.start(env_extra=...);
- mempool floods via `broadcast_tx_async`.

Scenarios (catalogue with invariants: docs/nemesis.md):
  nemesis_byzantine       — an equivocating voter; DuplicateVoteEvidence
                            must gossip, verify, and land COMMITTED in a
                            block on every honest node.
  nemesis_partition       — isolate one validator; majority advances;
                            heal; zero divergence, same app hash.
  nemesis_delay_proposer  — asymmetric outbound delay on the proposer;
                            chain keeps committing, no divergence.
  nemesis_flood           — mempool flood + recheck storm under load.
  nemesis_mempool_flood   — greedy-client storm vs the flowrate-limited
                            front door: limiter engages, consensus
                            commit latency stays flat, nobody banned.
  nemesis_flapping_device — trip/reset the device breaker mid-consensus
                            on one validator; health degrades truthfully
                            and consensus never stalls.
  nemesis_sched_priority  — recheck storm across commit boundaries; the
                            device scheduler's per-class accounting must
                            show commit verify never waited behind it.
  nemesis_crash_sweep     — crash at EVERY fail.fail() index during
                            commit/replay; restart and verify (parity
                            with reference test/persist/
                            test_failure_indices.sh, networked).
  nemesis_peer_garbage_storm — a real p2p client spews malformed frames
                            on three reactor channels; the victim must
                            BAN it (trust score below threshold) within
                            a bounded window, keep it banned across
                            redials, and keep committing.
  nemesis_torn_wal        — SIGKILL a node, tear its WAL tail mid-frame;
                            restart must auto-repair (.corrupt sidecar),
                            replay, and re-converge with app-hash
                            agreement.
  nemesis_evidence_restart — evidence pending in a partitioned node's
                            pool must survive that node's restart and
                            still land COMMITTED on every node.
  nemesis_valset_churn    — the validator set changes while a node is
                            blackholed; after healing it must catch up
                            to the new set with zero divergence.
  nemesis_combined        — partition + flapping device breaker +
                            mempool flood at once; the chain keeps
                            committing and health tells the truth.
  nemesis_deliver_mixed   — one node forced onto the serial per-tx
                            DeliverTx path (TMTPU_DELIVER_BATCH=0)
                            while the rest run DeliverTxBatch; both
                            paths byte-identical: app-hash agreement,
                            correct lane shapes, zero fallbacks.

Usage:
  python -m networks.local.nemesis                 # fast scenarios
  python -m networks.local.nemesis nemesis_crash_sweep
  python -m networks.local.proc_testnet nemesis_byzantine  # same registry
"""
from __future__ import annotations

import json
import os
import sys
import time
import urllib.parse
import urllib.request

from networks.local.proc_testnet import (
    ProcTestnet,
    configure_nodes,
    enable_prometheus,
    run as _run,
)

# fail.fail() call sites per commit: 5 in consensus/state.py finalize_commit
# + 5 in state/execution.py (apply pipeline + Commit) — see tests/
# test_persist.py CRASH_INDEXES, which sweeps the same 10 on one node
N_CRASH_INDEXES = 10


# --------------------------------------------------------------- plumbing


def _enable_fault_control(i: int, cfg: dict) -> None:
    cfg["p2p"]["test_fault_control"] = True


class Nemesis:
    """Fault-injection driver over a running ProcTestnet: every action
    goes through public RPC, exactly like an external chaos controller."""

    def __init__(self, net: ProcTestnet) -> None:
        self.net = net

    def fault(self, i: int, action: str, timeout: float = 10.0, **params) -> dict:
        parts = [f"action={action}"]
        for k, v in params.items():
            if isinstance(v, (int, float)):
                parts.append(f"{k}={v}")
            else:
                # explicit quotes pin the value as a STRING through the
                # URI transport (an all-digit peer id must not coerce)
                parts.append(f"{k}={urllib.parse.quote(chr(34) + str(v) + chr(34))}")
        res = self.net.rpc(i, f"debug_fault?{'&'.join(parts)}", timeout=timeout)
        assert res is not None, f"debug_fault {action} failed on node{i}"
        return res

    # -- link faults --------------------------------------------------------

    def isolate(self, victim: int) -> None:
        """Blackhole every link between `victim` and the rest, BOTH sides
        (a one-sided partition still leaks via the unfaulted direction)."""
        vid = self.net.node_id(victim)
        assert vid, f"node{victim} has no node_id"
        self.fault(victim, "partition", peers="*")
        for i in range(self.net.n):
            if i != victim and self.net.procs.get(i) is not None:
                self.fault(i, "partition", peers=vid)

    def delay(self, i: int, ms: float, direction: str = "send") -> None:
        self.fault(i, "delay", peers="*", ms=ms, direction=direction)

    def heal_all(self) -> None:
        for i in range(self.net.n):
            if self.net.procs.get(i) is not None:
                self.fault(i, "heal")

    # -- device breaker -----------------------------------------------------

    def trip_breaker(self, i: int) -> dict:
        return self.fault(i, "trip_breaker")

    def reset_breaker(self, i: int) -> dict:
        return self.fault(i, "reset_breaker")

    # -- load ---------------------------------------------------------------

    def flood(self, n_txs: int, prefix: str) -> list[str]:
        """`broadcast_tx_async` n_txs unique txs round-robin across all
        live nodes; returns the kv keys used."""
        keys = []
        live = [i for i in range(self.net.n) if self.net.procs.get(i) is not None]
        for k in range(n_txs):
            key = f"{prefix}{k}"
            tx = "0x" + f"{key}=v{k}".encode().hex()
            i = live[k % len(live)]
            res = self.net.rpc(i, f"broadcast_tx_async?tx={tx}", timeout=10.0)
            assert res is not None, f"broadcast_tx_async failed on node{i}"
            keys.append(key)
        return keys

    # -- observability reads ------------------------------------------------

    def recorder_events(self, i: int, subsystem: str | None = None,
                        n: int = 2000) -> list[dict]:
        q = f"debug_flight_recorder?n={n}"
        if subsystem:
            q += f"&subsystem={subsystem}"
        fr = self.net.rpc(i, q, timeout=10.0)
        return fr["events"] if fr else []

    def recorder_kinds(self, i: int, subsystem: str | None = None) -> set:
        return {(e["sub"], e["kind"]) for e in self.recorder_events(i, subsystem)}

    def health(self, i: int) -> dict:
        h = self.net.rpc(i, "health", timeout=10.0)
        assert h is not None, f"health failed on node{i}"
        return h

    def debug_p2p(self, i: int) -> dict:
        """Peer-quality snapshot: trust scores, live bans, dialer state."""
        d = self.net.rpc(i, "debug_p2p", timeout=10.0)
        assert d is not None, f"debug_p2p failed on node{i}"
        return d

    def assert_no_crashes(self, nodes=None) -> None:
        """The ISSUE 7 standing invariant: tm_runtime_task_crashes_total
        stays 0 through every scenario (health serves the same counter)."""
        for i in nodes if nodes is not None else range(self.net.n):
            if self.net.procs.get(i) is None:
                continue
            h = self.health(i)
            assert h["task_crashes"] == 0, f"node{i} task crashes: {h}"

    def assert_agreement(self, height: int, nodes=None) -> None:
        """Block hash AND app hash identical on every live node that has
        `height` (the zero-divergence gate)."""
        blk, app = {}, {}
        for i in nodes if nodes is not None else range(self.net.n):
            if self.net.procs.get(i) is None:
                continue
            b = self.net.block_hash(i, height)
            a = self.net.app_hash(i, height)
            if b is not None:
                blk[i] = b
            if a is not None:
                app[i] = a
        assert len(set(blk.values())) <= 1, f"block divergence @{height}: {blk}"
        assert len(set(app.values())) <= 1, f"app-hash divergence @{height}: {app}"

    def fleet_report(self, commit_spread_s: float = 20.0) -> dict:
        """One collector pass over the whole net (recorder taps are
        always on, so stitching works without the tracing config)."""
        from tendermint_tpu.tools.collector import FleetCollector

        endpoints = [
            f"http://127.0.0.1:{self.net.rpc_port(i)}"
            for i in range(self.net.n)
            if self.net.procs.get(i) is not None
        ]
        fc = FleetCollector(endpoints, timeout=10.0)
        fc.poll()
        report = fc.report(commit_spread_s=commit_spread_s)
        path = os.path.join(self.net.root, "fleet_report.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True, default=str)
        return report


# -------------------------------------------------------------- scenarios


def scenario_byzantine(net: ProcTestnet) -> None:
    """(a) A Byzantine validator double-signs every vote (conflicting
    BlockIDs to different peer halves, consensus/byzantine.py). The
    honest 3/4 majority must keep committing, and the equivocation must
    come back as DuplicateVoteEvidence — verified by honest pools,
    gossiped through evidence/reactor.py, reaped into a proposal, and
    COMMITTED in a block that every honest node stores. Asserted through
    the flight recorder (evidence added/committed events), the block
    store over RPC, and a fleet-collector invariant pass."""
    configure_nodes(net, _enable_fault_control)
    byz = net.n - 1
    for i in range(net.n):
        if i == byz:
            net.start(i, env_extra={"TMTPU_BYZANTINE": "voter"})
        else:
            net.start(i)
    honest = [i for i in range(net.n) if i != byz]
    net.wait_all(2)

    # the byzantine node's own recorder proves the attack actually ran
    nem = Nemesis(net)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if ("byzantine", "equivocate") in nem.recorder_kinds(byz, "byzantine"):
            break
        time.sleep(0.5)
    else:
        raise AssertionError("byzantine voter never equivocated")

    # evidence must land in a committed block on some honest node...
    ev_height = None
    deadline = time.monotonic() + 120
    scanned = 0  # highest height READ successfully with no evidence
    while ev_height is None and time.monotonic() < deadline:
        top = net.height(honest[0]) or 1
        h = scanned + 1
        while h <= top:
            r = net.rpc(honest[0], f"block?height={h}", timeout=5.0)
            if r is None:
                break  # transient RPC failure: retry this height next pass
            if r["block"]["evidence"]:
                ev_height = h
                break
            scanned = h
            h += 1
        if ev_height is None:
            time.sleep(1.0)
    assert ev_height is not None, "no DuplicateVoteEvidence committed in 120s"

    # ...and the SAME evidence block on every other honest node
    for i in honest[1:]:
        net.wait_height(i, ev_height)
        r = net.rpc(i, f"block?height={ev_height}", timeout=5.0)
        assert r is not None and r["block"]["evidence"], (
            f"node{i} has no evidence at height {ev_height}"
        )
    nem.assert_agreement(ev_height, nodes=honest)

    # flight-recorder truth: honest nodes saw the evidence lifecycle
    kinds = nem.recorder_kinds(honest[0], "evidence")
    assert ("evidence", "added") in kinds, kinds
    assert ("evidence", "committed") in kinds, kinds
    nem.assert_no_crashes(honest)

    # fleet invariants (app-hash agreement, no skipped commits, no stale
    # votes, no task crashes) across honest AND byzantine observers
    report = nem.fleet_report()
    assert not report["violations"], report["violations"]
    print(
        f"nemesis_byzantine: evidence committed at height {ev_height} on all "
        f"{len(honest)} honest nodes; fleet invariants clean"
    )


scenario_byzantine.self_start = True


def scenario_partition(net: ProcTestnet) -> None:
    """(b) Partition one validator away; the 3/4 majority keeps
    committing while the victim freezes; heal; the victim re-converges
    with ZERO divergence (block + app hash). Fault windows are read back
    from the victim's flight recorder."""
    configure_nodes(net, _enable_fault_control)
    net.start_all()
    net.wait_all(3)
    nem = Nemesis(net)
    victim = net.n - 1
    rest = [i for i in range(net.n) if i != victim]

    nem.isolate(victim)
    h_cut = net.height(victim) or 3
    base = max(net.height(i) or 3 for i in rest)
    for i in rest:
        net.wait_height(i, base + 3)
    h_victim = net.height(victim)
    assert h_victim is not None and h_victim <= h_cut + 1, (
        f"victim advanced {h_cut}->{h_victim} while partitioned"
    )

    nem.heal_all()
    head = max(net.height(i) or base for i in rest)
    got = net.wait_height(victim, head, timeout=180.0)
    # zero divergence at shared heights spanning the partition window
    for probe in (max(1, h_cut - 1), h_cut, head):
        nem.assert_agreement(probe)
    kinds = nem.recorder_kinds(victim, "fault")
    assert ("fault", "partition") in kinds and ("fault", "heal") in kinds, kinds
    nem.assert_no_crashes()
    print(
        f"nemesis_partition: victim froze at {h_victim} while majority "
        f"reached {base + 3}+, healed and re-converged to {got} with zero "
        f"divergence"
    )


scenario_partition.self_start = True


def scenario_delay_proposer(net: ProcTestnet) -> None:
    """(c) Asymmetric delay on the CURRENT PROPOSER's outbound links
    only: its proposals/parts/votes arrive late everywhere while its
    inbound stays fast. Consensus must absorb the skew (extra rounds are
    fine) and keep committing with zero divergence."""
    configure_nodes(net, _enable_fault_control)
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)

    # map the live proposer to a node index via each node's validator addr
    cs = net.rpc(0, "consensus_state")
    assert cs is not None, "consensus_state failed"
    proposer_addr = cs["round_state"]["proposer"]
    target = 0
    for i in range(net.n):
        st = net.rpc(i, "status")
        if st and st["validator_info"].get("address") == proposer_addr:
            target = i
            break
    nem.delay(target, ms=400, direction="send")

    base = max(net.height(i) or 2 for i in range(net.n))
    net.wait_all(base + 3, timeout=240.0)
    nem.heal_all()
    nem.assert_agreement(base + 2)
    kinds = nem.recorder_kinds(target, "fault")
    assert ("fault", "delay") in kinds, kinds
    nem.assert_no_crashes()
    print(
        f"nemesis_delay_proposer: node{target} (proposer) delayed 400ms "
        f"outbound; chain advanced {base}->{base + 3}+ with zero divergence"
    )


scenario_delay_proposer.self_start = True


def scenario_flood(net: ProcTestnet) -> None:
    """(d) Mempool flood + recheck storm: a burst of async txs across
    every node forces multi-block commits with a non-empty mempool at
    each boundary — the recheck path — while gossip fans the burst out.
    Telemetry must tell the truth: mempool add/recheck events in the
    black box, a live tm_mempool_size series, and a drained mempool with
    every tx committed by the end."""
    mports = enable_prometheus(net)
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)
    # waves, not one burst: later waves land while earlier ones are being
    # committed, so the post-commit mempool is non-empty and the recheck
    # sweep actually runs (one mega-burst can fit a single block)
    keys: list[str] = []
    for wave in range(4):
        keys += nem.flood(60, prefix=f"nf{os.getpid()}w{wave}-")
        time.sleep(0.4)

    # every tx commits: mempools drain and a sample is queryable anywhere
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        sizes = [
            (net.rpc(i, "num_unconfirmed_txs") or {}).get("n_txs", -1)
            for i in range(net.n)
        ]
        if all(s == 0 for s in sizes):
            break
        time.sleep(1.0)
    else:
        raise AssertionError(f"mempools never drained: {sizes}")
    for key in (keys[0], keys[len(keys) // 2], keys[-1]):
        q = "0x" + key.encode().hex()
        for i in range(net.n):
            r = net.rpc(i, f"abci_query?data={q}")
            assert r and r["response"].get("value"), (key, i)

    kinds = nem.recorder_kinds(0, "mempool")
    assert ("mempool", "add") in kinds, kinds
    assert ("mempool", "recheck") in kinds, (
        f"no recheck storm observed: {kinds}"
    )
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[0]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    assert "tendermint_mempool_size" in text
    assert "tendermint_runtime_task_crashes_total 0" in text
    nem.assert_no_crashes()
    print(
        f"nemesis_flood: {len(keys)} txs committed through the storm, "
        f"mempools drained, recheck events recorded"
    )


scenario_flood.self_start = True


def scenario_mempool_flood(net: ProcTestnet) -> None:
    """(ISSUE 14) A greedy client storms one node's front door while the
    chain runs: the flowrate limiter must engage (structured JSONRPC
    refusals + recorder events + live tm_mempool_* series), consensus
    commit latency must stay flat (per-node debug_device CONSENSUS_COMMIT
    wait accounting), and NO honest peer may be banned — gossip
    over-limit drops score a non-error weight by design."""
    mports = enable_prometheus(net)

    def mutate(i: int, cfg: dict) -> None:
        cfg["rpc"]["tx_rate_limit"] = 120.0     # per-client broadcast cap
        cfg["mempool"]["gossip_tx_rate"] = 30.0  # per-peer gossip cap

    configure_nodes(net, mutate)
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)
    base = max(net.height(i) or 2 for i in range(net.n))

    # the greedy client: waved async-tx storm against node0, far over the
    # 120 tx/s ceiling; refusals are expected and counted
    accepted = 0
    limited = 0
    for wave in range(5):
        for k in range(300):
            tx = "0x" + f"mf{os.getpid()}w{wave}k{k}=v".encode().hex()
            url = (
                f"http://127.0.0.1:{net.rpc_port(0)}/"
                f"broadcast_tx_async?tx={tx}"
            )
            try:
                with urllib.request.urlopen(url, timeout=10.0) as r:
                    body = json.loads(r.read())
            except OSError:
                continue
            if "result" in body:
                accepted += 1
            else:
                err = body.get("error") or {}
                assert err.get("code") == -32001, f"unexpected error: {body}"
                limited += 1
        time.sleep(0.3)
    assert accepted > 0, "limiter refused everything — ceiling too low"
    assert limited > 0, (
        f"limiter never engaged ({accepted} accepted) — storm too slow?"
    )

    # the chain keeps committing THROUGH the storm, and commit-class
    # device admissions never waited behind the flood
    net.wait_all(base + 3, timeout=240.0)
    for i in range(net.n):
        dev = net.rpc(i, "debug_device", timeout=10.0)
        assert dev is not None, f"debug_device failed on node{i}"
        sched = dev.get("scheduler") or {}
        cc = (sched.get("classes") or {}).get("consensus_commit") or {}
        assert cc.get("wait_s_max", 0.0) < 2.0, (
            f"node{i}: commit verify delayed behind the flood: {cc}"
        )
        queues = sched.get("queues") or {}
        assert not queues.get("stalled", False), f"node{i}: {queues}"
        h = nem.health(i)
        assert "device_queue_stalled" not in h["degraded"], h

    # limiter visibility: recorder events on the stormed node, per-peer
    # gossip drops somewhere in the fleet, and the series on /metrics
    kinds = nem.recorder_kinds(0, "mempool")
    assert ("mempool", "rate_limited") in kinds, kinds
    all_kinds = set()
    for i in range(net.n):
        all_kinds |= nem.recorder_kinds(i, "mempool")
    assert ("mempool", "gossip_rate_limited") in all_kinds, all_kinds
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[0]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    assert "tendermint_mempool_rate_limited_total" in text
    assert "tendermint_mempool_batched_txs_total" in text
    assert "tendermint_mempool_size" in text

    # abuse-resistance invariant: the storm is spam pressure, not a
    # protocol violation — nobody gets banned for it
    for i in range(net.n):
        p2p = nem.debug_p2p(i)
        assert not p2p.get("bans"), f"node{i} banned a peer: {p2p['bans']}"
    nem.assert_no_crashes()
    print(
        f"nemesis_mempool_flood: {accepted} accepted / {limited} "
        f"rate-limited through 5 waves; chain advanced {base}->{base + 3} "
        f"with flat commit-class waits and zero bans"
    )


scenario_mempool_flood.self_start = True


def scenario_flapping_device(net: ProcTestnet) -> None:
    """(e) A wedged/FLAPPING device on one validator mid-consensus: the
    circuit breaker is tripped and reset repeatedly over RPC. Consensus
    must never stall (the breaker routes verification to the CPU path),
    health must report the degradation truthfully while open and recover
    after reset, and the breaker transitions must appear in the flight
    recorder — multi-node coverage for the PR 1 breaker."""
    configure_nodes(net, _enable_fault_control)
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)
    victim = 0
    for cycle in range(3):
        res = nem.trip_breaker(victim)
        assert res["breaker"].get("tripped") is True, res
        h = nem.health(victim)
        assert h["status"] == "degraded" and "device_breaker_open" in h["degraded"], h
        # consensus must advance WHILE the breaker is open
        base = net.height(victim) or 2
        net.wait_height(victim, base + 1, timeout=90.0)
        res = nem.reset_breaker(victim)
        assert res["breaker"].get("tripped") is False, res
        h = nem.health(victim)
        assert "device_breaker_open" not in h["degraded"], h
    head = max(net.height(i) or 2 for i in range(net.n))
    net.wait_all(head)
    nem.assert_agreement(max(1, head - 1))
    kinds = nem.recorder_kinds(victim, "device")
    assert ("device", "breaker") in kinds, kinds
    nem.assert_no_crashes()
    print(
        "nemesis_flapping_device: 3 trip/reset cycles, health degraded/"
        "recovered truthfully, consensus never stalled"
    )


scenario_flapping_device.self_start = True


def scenario_sched_priority(net: ProcTestnet) -> None:
    """(g) A mempool recheck flood may not delay commit verify (ISSUE 8):
    waves of async txs keep the recheck path busy across several commit
    boundaries while the chain advances. The device scheduler's per-class
    admission accounting must show consensus-commit verification flowing
    with bounded queue wait the whole time, the admission queue never
    stalls (health carries no `device_queue_stalled`), and the per-class
    series are live on /metrics."""
    mports = enable_prometheus(net)
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)
    keys: list[str] = []
    for wave in range(4):
        keys += nem.flood(60, prefix=f"sp{os.getpid()}w{wave}-")
        time.sleep(0.4)
    base = max(net.height(i) or 2 for i in range(net.n))
    net.wait_all(base + 3, timeout=240.0)  # commits DURING the storm

    for i in range(net.n):
        dev = net.rpc(i, "debug_device", timeout=10.0)
        assert dev is not None, f"debug_device failed on node{i}"
        sched = dev.get("scheduler") or {}
        classes = sched.get("classes") or {}
        cc = classes.get("consensus_commit")
        assert cc and cc["submitted"] > 0, (
            f"node{i}: no consensus_commit admissions: {classes}"
        )
        # the flood must not have delayed commit verification at the
        # scheduler: every commit-class dispatch waited under the bound
        assert cc["wait_s_max"] < 2.0, f"node{i}: commit verify delayed: {cc}"
        queues = sched.get("queues") or {}
        assert not queues.get("stalled", False), f"node{i}: queue stalled: {queues}"
        h = nem.health(i)
        assert "device_queue_stalled" not in h["degraded"], h

    kinds = nem.recorder_kinds(0, "mempool")
    assert ("mempool", "recheck") in kinds, f"no recheck storm: {kinds}"
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[0]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    assert "tendermint_device_queue_depth" in text
    assert "tendermint_device_queue_wait_seconds" in text
    nem.assert_no_crashes()
    print(
        "nemesis_sched_priority: recheck storm ran across commits; "
        "consensus_commit admissions stayed under the wait bound, "
        "queue never stalled, per-class series live"
    )


scenario_sched_priority.self_start = True


def scenario_crash_sweep(net: ProcTestnet) -> None:
    """(f) Crash-at-every-fail.fail()-index, networked (parity with the
    reference's test/persist/test_failure_indices.sh, but against live
    peers): node0 restarts with FAIL_TEST_INDEX=i, dies with rc=99 at
    the i-th durability boundary (during live commit, WAL catchup
    replay, or fast-sync apply — whichever its restart path hits first),
    restarts clean, and must re-converge with the SAME app hash as the
    fleet — for every index. TMTPU_CRASH_INDEXES=a,b,... narrows the
    sweep (CI smoke); default is all 10."""
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)
    spec = os.environ.get("TMTPU_CRASH_INDEXES")
    indexes = (
        [int(x) for x in spec.split(",") if x != ""]
        if spec else list(range(N_CRASH_INDEXES))
    )
    for idx in indexes:
        net.kill(0)
        net.start(0, env_extra={"FAIL_TEST_INDEX": idx})
        rc = net.wait_exit(0, timeout=150.0)
        assert rc == 99, f"index {idx}: expected crash rc=99, got {rc}"
        net.start(0)
        target = max(net.height(i) or 2 for i in range(1, net.n)) + 1
        got = net.wait_height(0, target, timeout=150.0)
        nem.assert_agreement(target - 1)
        print(f"  crash index {idx}: died at boundary, recovered to {got}, "
              f"app hash agrees", flush=True)
    h = nem.health(0)
    assert h["ready"] is True and h["task_crashes"] == 0, h
    kinds = nem.recorder_kinds(0)
    assert ("consensus", "commit") in kinds and ("wal", "end_height") in kinds, (
        kinds
    )
    nem.assert_no_crashes()
    print(
        f"nemesis_crash_sweep: {len(indexes)} crash indexes swept, every "
        f"restart recovered with app-hash agreement"
    )


scenario_crash_sweep.self_start = True


async def _garbage_storm_client(
    host: str, port: int, node_id: str, network: str,
    sessions: int = 6, frames_per_channel: int = 3,
) -> dict:
    """A REAL p2p client (full SecretConnection + NodeInfo handshake,
    same node key every time) that sends undecodable frames on three
    reactor channels — consensus votes (0x22), mempool (0x30), evidence
    (0x38) — then redials after every disconnect. Returns client-side
    stats; the victim-side truth is read over debug_p2p."""
    import asyncio

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.p2p.base_reactor import ChannelDescriptor
    from tendermint_tpu.p2p.conn.connection import MConnection
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.netaddress import NetAddress
    from tendermint_tpu.p2p.node_info import NodeInfo
    from tendermint_tpu.p2p.transport import Transport

    channels = [0x22, 0x30, 0x38]
    key = NodeKey(ed25519.gen_priv_key())
    ni = NodeInfo(
        node_id=key.id(), listen_addr="127.0.0.1:0", network=network,
        version="tendermint-tpu/0.1", channels=bytes(channels),
        moniker="garbage-storm",
    )
    transport = Transport(key, ni)
    stats = {"id": key.id(), "connects": 0, "dial_failures": 0, "frames": 0}
    addr = NetAddress(node_id, host, port)
    for _ in range(sessions):
        try:
            conn, _rni = await asyncio.wait_for(transport.dial(addr), 10.0)
        except Exception:
            # dial refused / conn cut during handshake — the banned case
            # closes the socket right after accept
            stats["dial_failures"] += 1
            await asyncio.sleep(0.5)
            continue
        stats["connects"] += 1
        closed = asyncio.Event()

        async def _recv(ch_id, msg):
            pass

        async def _err(e, _closed=closed):
            _closed.set()

        mconn = MConnection(
            conn, [ChannelDescriptor(c) for c in channels], _recv, _err
        )
        await mconn.start()
        try:
            for ch in channels:
                for _ in range(frames_per_channel):
                    if await mconn.send(ch, b"\xde\xad\xbe\xef" * 16):
                        stats["frames"] += 1
            # the victim cuts a misbehaving peer off; wait for it
            try:
                await asyncio.wait_for(closed.wait(), 10.0)
            except asyncio.TimeoutError:
                pass
        finally:
            await mconn.stop()
        await asyncio.sleep(0.5)
    return stats


def scenario_peer_garbage_storm(net: ProcTestnet) -> None:
    """(h) Behaviour-scored banning end to end (docs/p2p_resilience.md): a
    peer spewing malformed frames on THREE reactor channels must be banned
    within a bounded window (trust score below threshold, `peer_banned`
    recorder event, live tm_p2p_peer_bans_total series), stay banned
    across its redial attempts (`banned_reject` events), and the honest
    chain must keep committing with clean fleet invariants."""
    import asyncio

    mports = enable_prometheus(net)
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)
    victim = 0
    st = net.rpc(victim, "status")
    assert st is not None, "status failed"
    network = st["node_info"]["network"]
    vid = st["node_info"]["node_id"]
    p2p_port = net.base_port + 2 * victim  # testnet CLI layout: p2p, rpc

    stats = asyncio.run(
        _garbage_storm_client("127.0.0.1", p2p_port, vid, network)
    )
    assert stats["frames"] >= 3, f"garbage client sent too little: {stats}"

    # victim-side truth: the garbage peer is banned, its trust score is
    # below the threshold, and redials were rejected while banned
    deadline = time.monotonic() + 30
    d = {}
    while time.monotonic() < deadline:
        d = nem.debug_p2p(victim)
        if any(b["id"] == stats["id"] for b in d["bans"]):
            break
        time.sleep(0.5)
    else:
        raise AssertionError(f"garbage peer never banned: {d} / client {stats}")
    assert d["trust"].get(stats["id"], 100) < d["ban_threshold"], d["trust"]
    assert all(p["id"] != stats["id"] for p in d["peers"]), d["peers"]
    kinds = nem.recorder_kinds(victim, "p2p")
    assert ("p2p", "behaviour") in kinds, kinds
    assert ("p2p", "peer_banned") in kinds, kinds
    rejects = [
        e for e in nem.recorder_events(victim, "p2p")
        if e["kind"] == "banned_reject"
        and e.get("fields", {}).get("peer") == stats["id"]
    ]
    assert rejects, "no banned_reject: the ban did not survive redials"

    # the chain kept committing through the storm, and the ban series is live
    base = max(net.height(i) or 2 for i in range(net.n))
    net.wait_all(base + 2)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[victim]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    bans_line = [
        ln for ln in text.splitlines()
        if ln.startswith("tendermint_p2p_peer_bans_total")
    ]
    assert bans_line and float(bans_line[0].rsplit(" ", 1)[1]) >= 1, bans_line
    nem.assert_agreement(base + 1)
    nem.assert_no_crashes()
    report = nem.fleet_report()
    assert not report["violations"], report["violations"]
    print(
        f"nemesis_peer_garbage_storm: peer {stats['id'][:12]} banned after "
        f"{stats['frames']} garbage frames ({len(rejects)} redials rejected), "
        f"chain advanced to {base + 2}+, fleet invariants clean"
    )


scenario_peer_garbage_storm.self_start = True


def scenario_torn_wal(net: ProcTestnet) -> None:
    """(i) Restart durability, WAL half (ROADMAP item 5 residue): SIGKILL
    a validator, tear its WAL tail mid-frame (a frame header promising
    more payload than exists — the classic died-mid-fsync artifact), and
    restart. The node must auto-repair at open (recorder `wal repair`
    event, torn bytes preserved in a .corrupt sidecar), replay, rejoin
    consensus, and re-converge with app-hash agreement."""
    import glob
    import struct as _struct

    net.start_all()
    net.wait_all(3)
    nem = Nemesis(net)
    victim = 0
    net.kill(victim)  # SIGKILL: whatever was in flight stays as-is

    wal_path = os.path.join(net.home(victim), "data", "cs.wal", "wal")
    assert os.path.exists(wal_path), wal_path
    torn = _struct.pack(">II", 0xDEADBEEF, 512) + b"\x00" * 100
    with open(wal_path, "ab") as f:
        f.write(torn)  # header claims 512 payload bytes; 100 present
    size_before = os.path.getsize(wal_path)

    net.start(victim)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if ("wal", "repair") in nem.recorder_kinds(victim, "wal"):
            break
        time.sleep(0.5)
    else:
        raise AssertionError("no wal repair event after restart with torn tail")
    sidecars = glob.glob(wal_path + ".corrupt*")
    assert sidecars, "torn bytes were not preserved in a .corrupt sidecar"
    preserved = b"".join(open(p, "rb").read() for p in sorted(sidecars))
    assert torn in preserved, "sidecar does not contain the torn tail"
    assert os.path.getsize(wal_path) <= size_before - len(torn), (
        "WAL head was not truncated to the last clean frame"
    )

    # the repaired node replays and re-converges with the fleet
    target = max(net.height(i) or 3 for i in range(1, net.n)) + 2
    got = net.wait_height(victim, target, timeout=180.0)
    nem.assert_agreement(target - 1)
    h = nem.health(victim)
    assert h["ready"] is True and h["task_crashes"] == 0, h
    nem.assert_no_crashes()
    print(
        f"nemesis_torn_wal: WAL auto-repaired ({len(sidecars)} sidecar(s)), "
        f"node replayed and re-converged to {got} with app-hash agreement"
    )


scenario_torn_wal.self_start = True


def scenario_evidence_restart(net: ProcTestnet) -> None:
    """(j) Restart durability, evidence half (ROADMAP item 5 residue):
    DuplicateVoteEvidence is injected into a PARTITIONED node's pool (it
    cannot gossip out or commit — the evidence is pending in that pool
    and nowhere else), the node is SIGKILLed and restarted, and the
    evidence must still land COMMITTED in a block on every node — proof
    that pending evidence survives the restart through libs/db."""
    configure_nodes(net, _enable_fault_control)
    net.start_all()
    net.wait_all(3)
    nem = Nemesis(net)
    victim = 0

    # partition FIRST: the evidence must exist only in the victim's pool
    nem.isolate(victim)

    # craft real evidence: node1's validator key double-signing height 2
    # (the driver holds every testnet key, exactly like Byzantine hardware)
    from tendermint_tpu.privval import FilePVKey
    from tendermint_tpu.types import BlockID, PartSetHeader
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence
    from tendermint_tpu.types.vote import Vote, VoteType, now_ns

    key = FilePVKey.load(
        os.path.join(net.home(1), "config", "priv_validator_key.json")
    )
    gen = net.rpc(victim, "genesis", timeout=10.0)
    assert gen is not None, "genesis RPC failed"
    chain_id = gen["genesis"]["chain_id"]
    vals = net.rpc(victim, "validators?height=2", timeout=10.0)
    assert vals is not None, "validators RPC failed"
    val_index = next(
        i for i, v in enumerate(vals["validators"])
        if v["address"] == key.address.hex()
    )
    ts = now_ns()
    votes = []
    for seed in (b"equivocation-a", b"equivocation-b"):
        import hashlib

        h = hashlib.sha256(seed).digest()
        bid = BlockID(h, PartSetHeader(1, hashlib.sha256(h).digest()))
        v = Vote(VoteType.PRECOMMIT, 2, 0, bid, ts, key.address, val_index)
        votes.append(v.with_signature(key.priv_key.sign(v.sign_bytes(chain_id))))
    ev = DuplicateVoteEvidence(key.pub_key, votes[0], votes[1])

    res = net.rpc(
        victim, f"broadcast_evidence?evidence={ev.encode().hex()}", timeout=10.0
    )
    assert res is not None and res.get("hash"), f"broadcast_evidence: {res}"
    kinds = nem.recorder_kinds(victim, "evidence")
    assert ("evidence", "added") in kinds, kinds

    # restart the only holder of the pending evidence
    net.kill(victim)
    for i in range(1, net.n):
        nem.fault(i, "heal")  # unblackhole the victim's id on the others
    net.start(victim)

    # restored from the DB...
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if ("evidence", "restored") in nem.recorder_kinds(victim, "evidence"):
            break
        time.sleep(0.5)
    else:
        raise AssertionError("evidence pool did not restore pending evidence")

    # ...and still COMMITTED in a block on every node
    ev_height = None
    scanned = 0
    deadline = time.monotonic() + 150
    while ev_height is None and time.monotonic() < deadline:
        top = net.height(1) or 1
        h = scanned + 1
        while h <= top:
            r = net.rpc(1, f"block?height={h}", timeout=5.0)
            if r is None:
                break
            if r["block"]["evidence"]:
                ev_height = h
                break
            scanned = h
            h += 1
        if ev_height is None:
            time.sleep(1.0)
    assert ev_height is not None, (
        "evidence pending before the restart was never committed after it"
    )
    for i in range(net.n):
        net.wait_height(i, ev_height)
        r = net.rpc(i, f"block?height={ev_height}", timeout=5.0)
        assert r is not None and r["block"]["evidence"], (i, ev_height)
    nem.assert_agreement(ev_height)
    nem.assert_no_crashes()
    print(
        f"nemesis_evidence_restart: evidence survived node{victim}'s restart "
        f"and committed at height {ev_height} on all {net.n} nodes"
    )


scenario_evidence_restart.self_start = True


def scenario_valset_churn(net: ProcTestnet) -> None:
    """(k) Validator-set churn under partition (ROADMAP item 5 residue):
    while one validator is blackholed, the rest commit a validator-update
    tx REMOVING it from the set (persistent_kvstore `val:` txs). After
    healing, the removed node must catch up to the new, smaller set —
    following a chain it no longer votes on — with zero block/app-hash
    divergence."""

    def mutate(i: int, cfg: dict) -> None:
        cfg["base"]["proxy_app"] = (
            f"persistent_kvstore:{os.path.join(net.home(i), 'data', 'kvstore')}"
        )
        _enable_fault_control(i, cfg)

    configure_nodes(net, mutate)
    net.start_all()
    net.wait_all(3)
    nem = Nemesis(net)
    victim = net.n - 1

    from tendermint_tpu import crypto
    from tendermint_tpu.privval import FilePVKey

    key = FilePVKey.load(
        os.path.join(net.home(victim), "config", "priv_validator_key.json")
    )
    encoded_pk = crypto.encode_pubkey(key.pub_key).hex()

    nem.isolate(victim)
    h_cut = net.height(victim) or 3

    # remove the partitioned validator: total power 4 -> 3, the 3 live
    # validators still clear 2/3 both before and after the update
    tx = "0x" + f"val:{encoded_pk}!0".encode().hex()
    res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
    assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res

    deadline = time.monotonic() + 60
    n_vals = net.n
    while time.monotonic() < deadline:
        vr = net.rpc(0, "validators", timeout=5.0)
        if vr is not None:
            n_vals = len(vr["validators"])
            if n_vals == net.n - 1:
                break
        time.sleep(0.5)
    assert n_vals == net.n - 1, f"validator set never shrank: {n_vals}"

    base = max(net.height(i) or 3 for i in range(net.n - 1))
    for i in range(net.n - 1):
        net.wait_height(i, base + 3)

    nem.heal_all()
    head = max(net.height(i) or base for i in range(net.n - 1))
    got = net.wait_height(victim, head, timeout=180.0)
    # the churned node followed the new set: identical blocks + app hashes
    for probe in (max(1, h_cut - 1), int(res["height"]) + 1, head):
        nem.assert_agreement(probe)
    vr = net.rpc(victim, "validators", timeout=5.0)
    assert vr is not None and len(vr["validators"]) == net.n - 1, vr
    nem.assert_no_crashes()
    print(
        f"nemesis_valset_churn: validator removed at height {res['height']} "
        f"while partitioned; victim caught up to {got} on the new "
        f"{net.n - 1}-validator set with zero divergence"
    )


scenario_valset_churn.self_start = True


def scenario_combined(net: ProcTestnet) -> None:
    """(l) Combined faults (ROADMAP item 5 residue): a partition, a
    flapping device breaker, and a mempool flood hit SIMULTANEOUSLY. The
    majority chain must keep committing, health must name exactly the
    true degraded reasons (breaker open on the tripped node, nothing
    false elsewhere), and after healing everything drains and converges."""
    enable_prometheus(net)  # parity with production-style runs
    configure_nodes(net, _enable_fault_control)
    net.start_all()
    net.wait_all(2)
    nem = Nemesis(net)
    part_victim = net.n - 1
    breaker_victim = 0
    rest = [i for i in range(net.n) if i != part_victim]

    # all three faults at once
    nem.isolate(part_victim)
    res = nem.trip_breaker(breaker_victim)
    assert res["breaker"].get("tripped") is True, res
    keys: list[str] = []
    for wave in range(3):
        for k in range(40):
            keyname = f"cb{os.getpid()}w{wave}k{k}"
            tx = "0x" + f"{keyname}=v".encode().hex()
            i = rest[k % len(rest)]
            r = net.rpc(i, f"broadcast_tx_async?tx={tx}", timeout=10.0)
            assert r is not None, f"flood tx failed on node{i}"
            keys.append(keyname)
        time.sleep(0.3)

    # chain keeps committing THROUGH the combined faults
    base = max(net.height(i) or 2 for i in rest)
    for i in rest:
        net.wait_height(i, base + 2, timeout=180.0)

    # health tells the truth mid-fault: breaker reason on the tripped
    # node, no fabricated reasons anywhere else
    h = nem.health(breaker_victim)
    assert "device_breaker_open" in h["degraded"], h
    for i in rest:
        if i == breaker_victim:
            continue
        h = nem.health(i)
        assert h["status"] == "ok" and not h["degraded"], (i, h)

    # heal everything; the net must fully recover
    nem.reset_breaker(breaker_victim)
    nem.heal_all()
    h = nem.health(breaker_victim)
    assert "device_breaker_open" not in h["degraded"], h

    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        sizes = [
            (net.rpc(i, "num_unconfirmed_txs") or {}).get("n_txs", -1)
            for i in rest
        ]
        if all(s == 0 for s in sizes):
            break
        time.sleep(1.0)
    else:
        raise AssertionError(f"mempools never drained after heal: {sizes}")
    head = max(net.height(i) or base for i in rest)
    net.wait_height(part_victim, head, timeout=180.0)
    nem.assert_agreement(max(1, head - 1))
    kinds = nem.recorder_kinds(part_victim, "fault")
    assert ("fault", "partition") in kinds and ("fault", "heal") in kinds, kinds
    kinds = nem.recorder_kinds(breaker_victim, "device")
    assert ("device", "breaker") in kinds, kinds
    nem.assert_no_crashes()
    print(
        f"nemesis_combined: partition + open breaker + {len(keys)}-tx flood "
        f"ran simultaneously; chain advanced to {base + 2}+, health truthful, "
        f"full recovery after heal"
    )


scenario_combined.self_start = True


def scenario_statesync(net: ProcTestnet) -> None:
    """(m) State-sync bootstrap under adversarial serving (ISSUE 12
    acceptance): the last node stays down while the rest build state
    (persistent_kvstore snapshots every `interval` commits), ONE serving
    peer is armed to serve provably-corrupt chunks, then the empty node
    boots with `statesync.enable`. It must: verify the target header by
    lite bisection (LITE class visible in debug_device), reject every
    corrupt chunk BEFORE applying it (behaviour-scoring the offender and
    re-fetching elsewhere), restore app-hash-identical to the replaying
    nodes, and fast-sync only the residual heights — without ever having
    held the early history."""
    interval = 4
    replica = net.n - 1
    corrupt = net.n - 2
    mports = enable_prometheus(net)

    def mutate(i: int, cfg: dict) -> None:
        cfg["base"]["proxy_app"] = (
            f"persistent_kvstore:"
            f"{os.path.join(net.home(i), 'data', 'kvstore')}:{interval}"
        )
        _enable_fault_control(i, cfg)
        if i == replica:
            ss = cfg.setdefault("statesync", {})
            ss["enable"] = True
            ss["rpc_servers"] = f"127.0.0.1:{net.rpc_port(0)}"
            ss["discovery_time"] = 1.5
            ss["chunk_request_timeout"] = 5.0

    configure_nodes(net, mutate)
    # small chunks -> every serving peer (the corrupt one included) gets
    # chunk requests, so the proof-reject + refetch path MUST fire
    chunk_env = {"TMTPU_SNAPSHOT_CHUNK_BYTES": "512"}
    for i in range(net.n - 1):
        env = dict(chunk_env)
        if i == corrupt:
            env["TMTPU_STATESYNC_CORRUPT"] = "1"  # fault-control-gated
        net.start(i, env_extra=env)
    for i in range(net.n - 1):
        net.wait_height(i, 2)
    nem = Nemesis(net)
    nem.flood(120, prefix=f"ss{os.getpid()}-")  # state worth chunking
    # ride past several snapshot points so every server holds manifests
    for i in range(net.n - 1):
        net.wait_height(i, 3 * interval + 2, timeout=300.0)

    head_before = max(net.height(i) or 0 for i in range(net.n - 1))
    net.start(replica, env_extra=chunk_env)
    got = net.wait_height(replica, head_before, timeout=300.0)

    # the restore actually happened, end to end
    events = nem.recorder_events(replica, "statesync")
    kinds = {e["kind"] for e in events}
    for want in ("discovered", "header_verified", "offer", "chunk_applied",
                 "restore_complete", "handoff"):
        assert want in kinds, f"replica missing statesync/{want}: {kinds}"
    assert "sync_failed" not in kinds and "fallback_fastsync" not in kinds, (
        f"replica fell back to fast sync: {kinds}"
    )
    boot_h = next(
        e["fields"]["height"] for e in events if e["kind"] == "restore_complete"
    )
    # O(state) boot: residual fast sync bounded by the snapshot cadence
    # (+2 = the lite verifiability horizon: proving H needs H+1 and H+2)
    assert boot_h >= head_before - interval - 2, (
        f"stale snapshot restored: boot {boot_h}, head was {head_before}"
    )
    assert boot_h % interval == 0, f"boot height {boot_h} off the cadence"

    # the corrupt peer was caught: proof-rejected, behaviour-scored,
    # chunk re-fetched elsewhere — and the restore still completed
    corrupt_id = net.node_id(corrupt)
    bad = [e for e in events if e["kind"] == "bad_chunk"]
    assert bad, f"no bad_chunk events — corrupt serving went undetected"
    assert any(e["fields"]["peer"] == corrupt_id for e in bad), (
        f"bad_chunk blamed the wrong peer: {bad} (corrupt={corrupt_id})"
    )
    assert ("statesync", "corrupt_serve") in nem.recorder_kinds(corrupt), (
        "corrupt node never exercised its corrupt-serving hook"
    )
    behaved = [e for e in nem.recorder_events(replica, "p2p")
               if e["kind"] == "behaviour" and "bad chunk" in e["fields"].get("reason", "")]
    assert behaved, "bad_chunk never reached the behaviour plane"

    # zero divergence: the snapshot-booted node matches the replayers
    nem.assert_agreement(got)
    nem.assert_agreement(max(boot_h + 1, got - 1))
    # ...while never having held the pruned-away early history
    assert net.rpc(replica, "block?height=1", timeout=5.0) is None, (
        "snapshot-booted replica unexpectedly serves genesis history"
    )
    # a flooded key is queryable through the replica, proof included
    key_hex = f"ss{os.getpid()}-0".encode().hex()
    probe = net.rpc(replica, f"abci_query?data=0x{key_hex}&prove=true")
    assert probe is not None and probe["response"].get("value"), probe
    assert probe["response"].get("proof_ops"), probe

    # the lite bisection ran through the device scheduler at LITE class
    dev = net.rpc(replica, "debug_device", timeout=10.0)
    assert dev is not None, "debug_device failed on replica"
    lite_cls = (dev.get("scheduler") or {}).get("classes", {}).get("lite")
    assert lite_cls and lite_cls["submitted"] > 0, (
        f"no LITE-class scheduler admissions on the replica: {dev.get('scheduler')}"
    )

    # tm_statesync_* series are live and truthful
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[replica]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    for series in ("tendermint_statesync_bootstrap_height",
                   "tendermint_statesync_chunks_applied_total",
                   "tendermint_statesync_chunk_failures_total"):
        assert series in text, f"{series} missing from replica /metrics"
    bh = [line for line in text.splitlines()
          if line.startswith("tendermint_statesync_bootstrap_height")]
    assert bh and float(bh[0].split()[-1]) == boot_h, bh

    nem.assert_no_crashes()
    print(
        f"nemesis_statesync: empty node restored snapshot @{boot_h} "
        f"({len(bad)} corrupt chunk(s) rejected + re-fetched, offender "
        f"behaviour-scored), fast-synced the residual to {got}, "
        f"app-hash-identical, genesis history never held"
    )


scenario_statesync.self_start = True


def scenario_deliver_mixed(net: ProcTestnet) -> None:
    """(o) Mixed-fleet block execution: one node is forced onto the
    serial per-tx DeliverTx path via the TMTPU_DELIVER_BATCH=0 kill
    switch while the rest of the fleet executes blocks through the
    single DeliverTxBatch round trip. Both paths must be byte-identical
    — signed transfers commit on every node with app-hash agreement at
    a shared height, the serial node's flight recorder shows per-tx
    lanes (lanes == txs) with ZERO fallback pins (the kill switch is a
    choice, not a failure), the batched nodes show exactly one lane per
    block, and nothing crashes."""
    from tendermint_tpu.abci.examples import transfer as tr
    from tendermint_tpu.crypto import secp256k1_math as sm

    def mutate(i: int, cfg: dict) -> None:
        cfg["base"]["proxy_app"] = "transfer"

    configure_nodes(net, mutate)
    serial = net.n - 1
    for i in range(net.n):
        if i == serial:
            net.start(i, env_extra={"TMTPU_DELIVER_BATCH": "0"})
        else:
            net.start(i)
    net.wait_all(2)

    # workload: 2 senders x 8 sequential nonces, each sender pinned to
    # one front door so its nonce sequence admits in order
    privs = [bytes([30 + s]) * 31 + b"\x01" for s in range(2)]
    to = tr.address(sm.pub_from_priv(b"\x55" * 31 + b"\x01"))
    submitted = 0
    for nonce in range(8):
        for s, priv in enumerate(privs):
            tx = tr.make_tx("secp256k1", priv, to, 7, nonce)
            res = net.rpc(
                s % 2, f"broadcast_tx_sync?tx=0x{tx.hex()}", timeout=30.0,
            )
            assert res is not None and res.get("code") == 0, (nonce, res)
            submitted += 1

    # every transfer applies on EVERY node — including the serial one
    want = str(10**9 + 7 * submitted).encode().hex()
    deadline = time.monotonic() + 120
    missing = set(range(net.n))
    while missing and time.monotonic() < deadline:
        for i in sorted(missing):
            r = net.rpc(
                i, f'abci_query?path="/balance"&data=0x{to.hex()}'
            )
            if r and r["response"].get("value") == want:
                missing.discard(i)
        time.sleep(0.5)
    assert not missing, f"transfers not applied on nodes {sorted(missing)}"

    # recorder truth, per execution mode: batched nodes collapse each
    # tx-bearing block to one lane; the serial node fans out per tx with
    # no fallback events (env choice, not a pinned failure)
    nem = Nemesis(net)
    for i in range(net.n):
        events = nem.recorder_events(i, "state")
        batches = [e for e in events if e["kind"] == "deliver_batch"]
        assert batches, f"node{i} recorded no deliver_batch events"
        falls = [e for e in events if e["kind"] == "deliver_batch_fallback"]
        assert not falls, f"node{i} hit the per-tx fallback: {falls}"
        assert sum(e["fields"]["txs"] for e in batches) == submitted, (
            f"node{i} delivered wrong tx total"
        )
        if i == serial:
            assert all(
                e["fields"]["lanes"] == e["fields"]["txs"] for e in batches
            ), f"serial node{i} did not fan out per tx: {batches}"
            assert all(
                e["fields"]["fallback"] is False for e in batches
            ), f"kill switch mislabelled as fallback on node{i}: {batches}"
        else:
            assert all(e["fields"]["lanes"] == 1 for e in batches), (
                f"batched node{i} split a block across lanes: {batches}"
            )

    # zero divergence between the two execution paths at a height every
    # node has reached
    h = min(net.height(i) or 1 for i in range(net.n))
    nem.assert_agreement(h)
    nem.assert_agreement(max(1, h - 1))
    nem.assert_no_crashes()
    print(
        f"nemesis_deliver_mixed: {submitted} transfers committed on a "
        f"mixed fleet (node{serial} serial via kill switch, rest batched), "
        f"app-hash agreement @{h}, zero fallbacks, zero crashes"
    )


scenario_deliver_mixed.self_start = True


SCENARIOS = {
    "nemesis_byzantine": scenario_byzantine,
    "nemesis_partition": scenario_partition,
    "nemesis_delay_proposer": scenario_delay_proposer,
    "nemesis_flood": scenario_flood,
    "nemesis_mempool_flood": scenario_mempool_flood,
    "nemesis_flapping_device": scenario_flapping_device,
    "nemesis_sched_priority": scenario_sched_priority,
    "nemesis_crash_sweep": scenario_crash_sweep,
    "nemesis_peer_garbage_storm": scenario_peer_garbage_storm,
    "nemesis_torn_wal": scenario_torn_wal,
    "nemesis_evidence_restart": scenario_evidence_restart,
    "nemesis_valset_churn": scenario_valset_churn,
    "nemesis_combined": scenario_combined,
    "nemesis_statesync": scenario_statesync,
    "nemesis_deliver_mixed": scenario_deliver_mixed,
}

# the sub-10-minute set the CI nemesis job and tier-1 wrappers draw from
FAST = ["nemesis_byzantine", "nemesis_partition", "nemesis_delay_proposer",
        "nemesis_flood", "nemesis_mempool_flood", "nemesis_flapping_device",
        "nemesis_sched_priority", "nemesis_peer_garbage_storm"]

# the restart-durability + residue set: nightly CI runs these after FAST
DURABILITY = ["nemesis_torn_wal", "nemesis_evidence_restart",
              "nemesis_valset_churn", "nemesis_combined"]


def run(names=None, n: int = 4) -> None:
    """Run nemesis scenarios through proc_testnet's harness (same failure
    artifacts: node log tails + preserved logs + fleet_report.json)."""
    _run(list(names or FAST), n=n)


if __name__ == "__main__":
    run(sys.argv[1:] or None)
    print("nemesis: all scenarios passed")
