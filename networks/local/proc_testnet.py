"""Multi-node testnet scenarios over real OS processes + real TCP.

The reference's tier-3 integration harness runs N dockerized nodes on one
machine and asserts liveness through failures (test/p2p/basic/test.sh,
test/p2p/fast_sync/test.sh, test/p2p/kill_all/test.sh). This is the same
tier without the container runtime (none exists in the CI image): node
directories come from the real `testnet` CLI generator, each node is a
separate `python -m tendermint_tpu.cmd node` process on 127.0.0.1 ports,
and every assertion goes through the public RPC — so config writing,
genesis distribution, CLI flag handling, p2p dialing, WAL recovery and
fast sync are all exercised exactly as a deployment would.

Scenarios:
  basic            — N nodes, all reach height >= 3 and stay within 1 height.
  fast_sync        — stop one node; the rest advance; restart it; it catches up.
  kill_all         — SIGKILL every node; restart; chain resumes past the old head.
  atomic_broadcast — a tx sent to one node commits and is queryable on ALL.
  pex              — a node given only ONE peer discovers the rest via PEX.
  metrics          — live-path telemetry tells the truth under traffic.
  timeline         — the fleet collector stitches a cross-node per-height
                     timeline with a complete vote-arrival matrix.
  budget           — per-commit latency budgets attribute each height's
                     wall time; zero post-warmup recompiles; debug_profile
                     captures a bounded profiler window on a live node.

Usage:
  python -m networks.local.proc_testnet            # all scenarios, n=4
  python -m networks.local.proc_testnet basic      # one scenario
(The docker-compose path for hosts that have docker is networks/local/
docker-compose.yml; `make -C networks/local test` prefers docker and falls
back to this driver.)
"""
from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port_base(n_nodes: int) -> int:
    """Find a base port with 2*n consecutive free ports."""
    for base in range(21000, 60000, 64):
        try:
            socks = []
            for off in range(2 * n_nodes):
                s = socket.socket()
                s.bind(("127.0.0.1", base + off))
                socks.append(s)
            for s in socks:
                s.close()
            return base
        except OSError:
            for s in socks:
                s.close()
    raise RuntimeError("no free port range")


class ProcTestnet:
    def __init__(self, n: int = 4, root: str | None = None) -> None:
        self.n = n
        self.root = root or tempfile.mkdtemp(prefix="tmtpu-testnet-")
        self._own_root = root is None
        self.base_port = _free_port_base(n)
        self.procs: dict[int, subprocess.Popen | None] = {}
        self.logs: dict[int, object] = {}

    # -- lifecycle ----------------------------------------------------------

    def generate(self) -> None:
        """Run the real `testnet` CLI generator (reference testnet.go)."""
        subprocess.run(
            [
                sys.executable, "-m", "tendermint_tpu.cmd", "testnet",
                "--v", str(self.n), "--o", self.root,
                "--starting-port", str(self.base_port),
            ],
            check=True, cwd=REPO_ROOT, env=self._env(), capture_output=True,
        )

    def _env(self) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"       # consensus plane is host-side
        env["TMTPU_NO_PREWARM"] = "1"      # no background compiles in CI
        env["TMTPU_NO_EXPORT_CACHE"] = "1"
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        return env

    def home(self, i: int) -> str:
        return os.path.join(self.root, f"node{i}")

    def rpc_port(self, i: int) -> int:
        return self.base_port + 2 * i + 1

    def start(self, i: int, env_extra: dict | None = None) -> None:
        """Start node i; `env_extra` adds per-node environment (the
        nemesis scenarios arm FAIL_TEST_INDEX / TMTPU_BYZANTINE on one
        node only)."""
        assert self.procs.get(i) is None, f"node{i} already running"
        log = open(os.path.join(self.root, f"node{i}.log"), "ab")
        self.logs[i] = log
        env = self._env()
        if env_extra:
            env.update({k: str(v) for k, v in env_extra.items()})
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.cmd",
             "--home", self.home(i), "node"],
            cwd=REPO_ROOT, env=env, stdout=log, stderr=log,
        )

    def start_all(self) -> None:
        for i in range(self.n):
            self.start(i)

    def kill(self, i: int, sig: int = signal.SIGKILL) -> None:
        p = self.procs.get(i)
        if p is not None:
            p.send_signal(sig)
            p.wait(timeout=30)
            self.procs[i] = None

    def kill_all(self) -> None:
        for i in range(self.n):
            if self.procs.get(i) is not None:
                self.kill(i)

    def pause(self, i: int) -> None:
        """SIGSTOP: freeze the process without killing it (a wedged, not
        dead, node — the scheduler keeps its sockets open)."""
        p = self.procs.get(i)
        assert p is not None, f"node{i} not running"
        p.send_signal(signal.SIGSTOP)

    def resume(self, i: int) -> None:
        p = self.procs.get(i)
        assert p is not None, f"node{i} not running"
        p.send_signal(signal.SIGCONT)

    def wait_exit(self, i: int, timeout: float = 120.0) -> int:
        """Block until node i's process exits ON ITS OWN (crash-point
        scenarios); returns the exit code and clears the slot."""
        p = self.procs.get(i)
        assert p is not None, f"node{i} not running"
        rc = p.wait(timeout=timeout)
        self.procs[i] = None
        return rc

    def stop(self) -> None:
        for i in range(self.n):
            p = self.procs.get(i)
            if p is not None:
                p.terminate()
        for i in range(self.n):
            p = self.procs.get(i)
            if p is not None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)
                self.procs[i] = None
        for log in self.logs.values():
            log.close()
        self.logs.clear()
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)

    # -- queries --------------------------------------------------------------

    def rpc(self, i: int, path: str, timeout: float = 3.0) -> dict | None:
        """Result dict, or None (booting/killed node, or an RPC error —
        errors are printed so a failing scenario names the real cause
        instead of an undiagnosable None)."""
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{self.rpc_port(i)}/{path}", timeout=timeout
            ) as r:
                body = json.loads(r.read())
        except (OSError, ValueError):  # conn refused/timeout/bad body
            return None
        if "result" not in body:
            print(f"node{i} rpc {path.split('?')[0]} error: "
                  f"{body.get('error')}", file=sys.stderr)
            return None
        return body["result"]

    def height(self, i: int, timeout: float = 2.0) -> int | None:
        st = self.rpc(i, "status", timeout)
        if st is None:
            return None
        return int(st["sync_info"]["latest_block_height"])

    def n_peers(self, i: int) -> int:
        ni = self.rpc(i, "net_info")
        return int(ni["n_peers"]) if ni else 0

    def node_id(self, i: int) -> str | None:
        st = self.rpc(i, "status")
        return st["node_info"]["node_id"] if st else None

    def app_hash(self, i: int, height: int) -> str | None:
        """header.app_hash at `height` (state agreement probe)."""
        r = self.rpc(i, f"block?height={height}", timeout=5.0)
        return r["block"]["header"]["app_hash"] if r else None

    def block_hash(self, i: int, height: int) -> str | None:
        r = self.rpc(i, f"block?height={height}", timeout=5.0)
        return r["block_id"]["hash"] if r else None

    def wait_height(self, i: int, h: int, timeout: float = 180.0) -> int:
        """Block until node i reports height >= h; returns the height."""
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            last = self.height(i)
            if last is not None and last >= h:
                return last
            p = self.procs.get(i)
            if p is not None and p.poll() is not None:
                raise AssertionError(
                    f"node{i} exited rc={p.returncode} before height {h}; "
                    f"see {self.root}/node{i}.log"
                )
            time.sleep(0.5)
        raise AssertionError(
            f"node{i} stuck at height {last}, wanted {h} "
            f"(see {self.root}/node{i}.log)"
        )

    def wait_all(self, h: int, timeout: float = 180.0) -> list[int]:
        return [self.wait_height(i, h, timeout) for i in range(self.n)]


# -- config helpers shared by the scenarios (metrics/timeline/nemesis) -------


def configure_nodes(net: ProcTestnet, mutate) -> None:
    """Rewrite every node's config.json BEFORE any node starts;
    `mutate(i, cfg)` edits the parsed config in place."""
    assert not any(net.procs.values()), "configs must be rewritten pre-start"
    for i in range(net.n):
        cfg_path = os.path.join(net.home(i), "config", "config.json")
        with open(cfg_path, encoding="utf-8") as f:
            cfg = json.load(f)
        mutate(i, cfg)
        with open(cfg_path, "w", encoding="utf-8") as f:
            json.dump(cfg, f, indent=1, sort_keys=True)


def enable_prometheus(net: ProcTestnet) -> dict[int, int]:
    """Enable the /metrics server on a free port per node; returns
    {node index: port}."""
    mports: dict[int, int] = {}
    for i in range(net.n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        mports[i] = s.getsockname()[1]
        s.close()

    def mutate(i: int, cfg: dict) -> None:
        cfg["instrumentation"]["prometheus"] = True
        cfg["instrumentation"]["prometheus_listen_addr"] = (
            f"tcp://127.0.0.1:{mports[i]}"
        )

    configure_nodes(net, mutate)
    return mports


# -- scenarios (reference test/p2p/{basic,fast_sync,kill_all}/test.sh) -------


def scenario_basic(net: ProcTestnet) -> None:
    """All nodes alive and in consensus: everyone reaches height 3."""
    heights = net.wait_all(3)
    assert max(heights) - min(heights) <= 2, f"nodes diverged: {heights}"
    print(f"basic: all {net.n} nodes at heights {heights}")


def scenario_fast_sync(net: ProcTestnet) -> None:
    """Stop one node; the others keep committing (BFT with n-1 >= 2/3);
    restart it; it fast-syncs back to the head. The restart flips the
    victim to the v1 FSM reactor (config fast_sync.version), so one
    scenario exercises both sync implementations against live peers."""
    victim = net.n - 1
    base = net.wait_height(0, 3)
    net.kill(victim)
    target = base + 3
    for i in range(net.n - 1):
        net.wait_height(i, target)
    cfg_path = os.path.join(net.home(victim), "config", "config.json")
    with open(cfg_path, encoding="utf-8") as f:
        cfg = json.load(f)
    cfg["fast_sync"]["version"] = "v1"
    with open(cfg_path, "w", encoding="utf-8") as f:
        json.dump(cfg, f, indent=1, sort_keys=True)
    net.start(victim)
    head = net.height(0) or target
    got = net.wait_height(victim, head)
    print(f"fast_sync: node{victim} killed at ~{base}, net advanced to "
          f"{head}, node{victim} caught up to {got} via the v1 reactor")


def scenario_kill_all(net: ProcTestnet) -> None:
    """SIGKILL every node mid-consensus, restart, chain must resume —
    WAL replay + handshake recovery on every node at once."""
    net.wait_all(3)
    heights = [net.height(i) or 3 for i in range(net.n)]
    old_head = max(heights)
    net.kill_all()
    net.start_all()
    net.wait_all(old_head + 2)
    print(f"kill_all: restarted all {net.n} nodes from {old_head}, "
          f"advanced past {old_head + 2}")


def scenario_atomic_broadcast(net: ProcTestnet) -> None:
    """A tx submitted to one node is committed and queryable on every
    node (reference test/p2p/atomic_broadcast): mempool gossip + consensus
    + ABCI delivery end to end."""
    net.wait_all(2)
    key, value = f"ab{os.getpid()}", "committed"
    # 0x pins the value as hex for the URI transport (digit-only hex
    # would otherwise coerce to int)
    tx = "0x" + f"{key}={value}".encode().hex()
    res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
    assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res
    q = "0x" + key.encode().hex()
    deadline = time.monotonic() + 60
    missing = set(range(net.n))
    while missing and time.monotonic() < deadline:
        for i in sorted(missing):
            r = net.rpc(i, f"abci_query?data={q}")
            if r and r["response"].get("value"):
                missing.discard(i)
        time.sleep(0.5)
    assert not missing, f"tx not visible on nodes {sorted(missing)}"
    print(f"atomic_broadcast: tx committed at height "
          f"{res['height']}, visible on all {net.n} nodes")


def scenario_pex(net: ProcTestnet) -> None:
    """Peer discovery strictly via PEX (reference test/p2p/pex). The
    topology is rewritten BEFORE any node starts, on fresh address books:
    the loner's persistent_peers is ONLY node0, and every other node's
    list excludes the loner — so no config-driven dial can ever connect
    the loner to node1..n-2. Any peer beyond node0 exists only because
    addresses propagated through peer exchange (the loner learning others
    from node0's addrbook, or others learning the loner)."""
    loner = net.n - 1

    def mutate(i: int, cfg: dict) -> None:
        peers = cfg["p2p"]["persistent_peers"].split(",")
        if i == loner:
            cfg["p2p"]["persistent_peers"] = peers[0]  # node0 only
        else:
            cfg["p2p"]["persistent_peers"] = ",".join(peers[:loner])

    configure_nodes(net, mutate)
    net.start_all()
    deadline = time.monotonic() + 150
    peers_n = 0
    while time.monotonic() < deadline:
        peers_n = net.n_peers(loner)
        if peers_n >= net.n - 1:
            break
        time.sleep(1)
    assert peers_n >= 2, (
        f"node{loner} only reached {peers_n} peers with 1 configured and "
        f"no other config path to it — PEX discovery failed "
        f"(see {net.root}/node{loner}.log)"
    )
    net.wait_height(loner, 3)
    print(f"pex: node{loner} reached {peers_n} peers from 1 configured")


scenario_pex.self_start = True  # rewrites configs before any node starts


def scenario_metrics(net: ProcTestnet) -> None:
    """Observability acceptance (ISSUE 5): under real traffic the
    live-path telemetry tells the truth — /metrics serves nonzero
    tm_consensus_height, per-channel tm_p2p_peer_send_bytes_total and
    tm_mempool_size, and health/debug_flight_recorder answer from a
    live node."""
    mports = enable_prometheus(net)
    net.start_all()
    net.wait_all(2)
    # traffic: one committed tx (mempool admission + gossip + consensus)
    tx = "0x" + f"mx{os.getpid()}=1".encode().hex()
    res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
    assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res
    net.wait_all(int(res["height"]) + 1)

    def scrape(i: int) -> str:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{mports[i]}/metrics", timeout=5
        ) as r:
            return r.read().decode()

    def sample(text: str, prefix: str) -> float:
        vals = [
            float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith(prefix) and not line.startswith("#")
        ]
        assert vals, f"no sample for {prefix}"
        return max(vals)

    deadline = time.monotonic() + 30
    while True:  # the height gauge is sampled at 1 Hz; poll briefly
        text = scrape(0)
        if sample(text, "tendermint_consensus_height") >= 2:
            break
        assert time.monotonic() < deadline, "height gauge never moved"
        time.sleep(0.5)
    # p2p byte counters are per-channel and nonzero after gossip
    assert sample(text, 'tendermint_p2p_peer_send_bytes_total{channel="') > 0
    assert sample(text, 'tendermint_p2p_peer_receive_bytes_total{channel="') > 0
    sample(text, "tendermint_mempool_size")  # live series present
    assert sample(text, "tendermint_state_block_processing_time_count") > 0
    # health is real: ready, at height, no crashed tasks
    h = net.rpc(0, "health")
    assert h is not None and h["ready"] is True and h["height"] >= 2, h
    assert h["task_crashes"] == 0, h
    fr = net.rpc(0, "debug_flight_recorder?n=500")
    assert fr is not None, "debug_flight_recorder RPC failed"
    kinds = {(e["sub"], e["kind"]) for e in fr["events"]}
    assert ("consensus", "commit") in kinds and ("p2p", "peer_connected") in kinds
    print(
        f"metrics: height gauge moved, per-channel p2p byte counters live, "
        f"health ok on node0 ({len(fr['events'])} black-box events)"
    )


scenario_metrics.self_start = True  # rewrites configs before any node starts


def scenario_timeline(net: ProcTestnet) -> None:
    """Fleet-observability acceptance (ISSUE 6): the collector stitches a
    cross-node per-height timeline from a live 4-node net — ≥1 height
    with a COMPLETE vote-arrival matrix (every validator × every
    observing node × prevote+precommit), per-phase latency percentiles,
    nonzero device-occupancy (or explicit cpu-route) accounting — and
    the cross-node invariants hold (all validators commit each stitched
    height within the bound; no stale-round votes in flight). The report
    is written to <root>/fleet_report.json (preserved on failure for the
    CI artifact upload)."""
    mports = enable_prometheus(net)
    configure_nodes(
        net, lambda i, cfg: cfg["instrumentation"].update(tracing=True)
    )
    net.start_all()
    net.wait_all(2)
    # traffic: one committed tx, then a couple more heights of timeline
    tx = "0x" + f"tl{os.getpid()}=1".encode().hex()
    res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
    assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res
    net.wait_all(int(res["height"]) + 2)

    from tendermint_tpu.tools.collector import FleetCollector, render_text

    endpoints = [f"http://127.0.0.1:{net.rpc_port(i)}" for i in range(net.n)]
    metrics = [f"http://127.0.0.1:{mports[i]}" for i in range(net.n)]
    fc = FleetCollector(endpoints, metrics=metrics, timeout=10.0)
    fc.poll()
    # second incremental poll: exercises the since_ns cursor path end to
    # end (the second read returns only events newer than the first)
    time.sleep(1.0)
    fc.poll()
    report = fc.report(commit_spread_s=5.0)
    report_path = os.path.join(net.root, "fleet_report.json")
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)

    assert len(report["observers"]) == net.n, report["observers"]
    assert report["n_validators"] == net.n, report["n_validators"]
    stitched = report["stitched_heights"]
    assert stitched, (
        f"no height with a complete {net.n}x(prevote+precommit) "
        f"vote-arrival matrix; see {report_path}"
    )
    # per-phase latencies measured across the fleet
    for phase in ("propose_to_prevote_maj23_ms", "precommit_maj23_to_commit_ms",
                  "propose_to_commit_ms"):
        assert report["phases"].get(phase, {}).get("n", 0) > 0, (phase, report["phases"])
    # vote propagation observed by 2+ nodes
    assert report["propagation"]["vote_spread"]["precommit"]["n"] > 0
    # device-occupancy accounting: real dispatches, or the explicit
    # cpu-route tally (this testnet pins JAX_PLATFORMS=cpu, so routing
    # sends every batch to the host paths — and must SAY so)
    for node, dev in report["device"].items():
        occ = dev["occupancy"]
        assert (
            occ.get("busy_windows", 0) > 0
            or occ.get("cpu_route", {}).get("sigs", 0) > 0
        ), (node, dev)
    # occupancy series are live on /metrics too
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[0]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    assert "tendermint_device_occupancy_cpu_route_signatures_total" in text
    assert not report["violations"], report["violations"]
    print(render_text(report))
    print(f"timeline: {len(stitched)} stitched heights "
          f"{stitched[:5]}, complete {net.n}x2 vote matrices, "
          f"invariants clean")


scenario_timeline.self_start = True  # rewrites configs before any node starts


def scenario_txlife(net: ProcTestnet) -> None:
    """Transaction-lifecycle acceptance (ISSUE 16): with txlife armed at
    sample=1 on every node, one tx broadcast to node0 yields a fully
    stitched cross-node timeline in the fleet report — rpc_received on
    the origin, gossip_in on ≥2 other nodes, exactly one committed
    height fleet-wide — and the collector's tx invariants (monotone core
    stage order per node, single committed height) hold. tx_status joins
    the indexer + mempool + timeline views for the same hash. The report
    is written to <root>/fleet_report.json (preserved on failure)."""
    configure_nodes(
        net,
        lambda i, cfg: cfg["instrumentation"].update(
            txlife=True, txlife_sample=1
        ),
    )
    net.start_all()
    net.wait_all(2)
    tx = "0x" + f"txl{os.getpid()}=1".encode().hex()
    res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
    assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res
    txh = res["hash"].lower()
    net.wait_all(int(res["height"]) + 1)

    # tx_status on the origin: committed, with the sampled timeline
    # (hash convention: bare lowercase hex, no 0x — rpc/core.py:7)
    st = net.rpc(0, f"tx_status?hash={txh}")
    assert st is not None and st["status"] == "committed", st
    assert st["height"] == int(res["height"]), st
    assert st["sampled"] and st["timeline"], st
    stages = [e["stage"] for e in st["timeline"]]
    assert stages[0] == "rpc_received" and "committed" in stages, stages

    from tendermint_tpu.tools.collector import FleetCollector, render_text

    endpoints = [f"http://127.0.0.1:{net.rpc_port(i)}" for i in range(net.n)]
    fc = FleetCollector(endpoints, timeout=10.0)
    fc.poll()
    # second incremental poll: exercises the txl_seq cursor end to end
    time.sleep(1.0)
    fc.poll()
    report = fc.report(commit_spread_s=5.0)
    report_path = os.path.join(net.root, "fleet_report.json")
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)

    tl = report["txs"]["timelines"].get(txh)
    assert tl is not None, (
        f"tx {txh} not stitched; sampled txs: "
        f"{sorted(report['txs']['timelines'])[:5]}"
    )
    # origin attribution: the first rpc_received is on the node we hit
    assert tl["origin"] and "node0" in tl["origin"]["node"], tl["origin"]
    # gossip reached at least 2 other nodes (n=4, BFT needs 2f+1 anyway)
    assert len(tl["gossip_in"]) >= 2, tl["gossip_in"]
    # one committed height fleet-wide, on every node
    heights = {c["height"] for c in tl["committed"].values()}
    assert heights == {int(res["height"])}, (heights, res["height"])
    assert len(tl["committed"]) == net.n, sorted(tl["committed"])
    assert txh in report["txs"]["complete"], report["txs"]["complete"]
    assert not report["violations"], report["violations"]
    print(render_text(report))
    print(
        f"txlife: tx {txh[:12]} stitched across {len(tl['stages'])} nodes "
        f"(origin {tl['origin']['node']}, gossip_in on "
        f"{len(tl['gossip_in'])} peers, committed at "
        f"{res['height']} everywhere), invariants clean"
    )


scenario_txlife.self_start = True  # rewrites configs before any node starts


def scenario_traffic(net: ProcTestnet) -> None:
    """Wire-efficiency acceptance (ISSUE 20): with committed traffic on a
    4-node net, two collector polls (the second rides the traffic_seq
    cursor) stitch a fully-populated bandwidth matrix — every node
    reports nonzero bytes both ways against every other node — with live
    per-type vote and tx series on every node, a gossip amplification
    factor within the redundancy invariant bound, and clean fleet
    invariants. The report lands in <root>/fleet_report.json (preserved
    on failure for the CI artifact upload)."""
    net.wait_all(2)
    # committed traffic so the mempool tx series is live fleet-wide
    for i in range(3):
        tx = "0x" + f"tr{os.getpid()}k{i}=1".encode().hex()
        res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
        assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res
    net.wait_all(int(res["height"]) + 2)

    from tendermint_tpu.tools.collector import FleetCollector, render_text

    endpoints = [f"http://127.0.0.1:{net.rpc_port(i)}" for i in range(net.n)]
    fc = FleetCollector(endpoints, timeout=10.0)
    fc.poll()
    time.sleep(1.5)
    # second incremental poll: the ledger read resumes from the seq
    # cursor, and the accumulated (cumulative) rows must not shrink
    fc.poll()
    report = fc.report(commit_spread_s=5.0)
    report_path = os.path.join(net.root, "fleet_report.json")
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)

    traffic = report["traffic"]
    matrix = traffic["matrix"]
    monikers = {n["moniker"] for n in report["nodes"]}
    assert len(matrix) == net.n, sorted(matrix)
    per_node_types: dict[str, dict] = {}
    for obs, row in matrix.items():
        # fully populated: every other node present, bytes both ways
        assert set(row) == monikers - {obs}, (obs, sorted(row))
        agg: dict[str, int] = {}
        for remote, cell in row.items():
            assert cell["sent_bytes"] > 0 and cell["recv_bytes"] > 0, (
                obs, remote, cell
            )
            for mtype, bt in cell["by_type"].items():
                agg[mtype] = (agg.get(mtype, 0) + bt["sent_msgs"]
                              + bt["recv_msgs"])
        per_node_types[obs] = agg
    for obs, agg in per_node_types.items():
        assert agg.get("vote", 0) > 0, (obs, agg)
        assert agg.get("tx", 0) > 0, (obs, agg)
    # gossip redundancy within the invariant bound (the same bound
    # check_invariants enforces — assert the inputs are live too)
    amp = traffic["amplification"]["vote"]
    assert amp["delivered"] > 0, amp
    assert amp["amplification"] <= max(2.0, float(net.n)), amp
    assert not report["violations"], report["violations"]
    print(render_text(report))
    print(
        f"traffic: {net.n}x{net.n} matrix stitched, vote amplification "
        f"x{amp['amplification']} ({amp['delivered']} delivered, "
        f"{amp['redundant']} redundant), invariants clean"
    )


def scenario_budget(net: ProcTestnet) -> None:
    """Device-efficiency acceptance (ISSUE 17): on a live committing net
    the collector's --budget plane decomposes every stitched height's
    proposal→commit wall time into named additive stages — attribution
    ≥ 0.95 with a dominant term per height — the post-warmup net mints
    ZERO fresh XLA compiles between two polls (the recompile-storm
    counters stay flat), and the fault-gated debug_profile route
    captures a bounded host-profile window whose artifacts exist on
    disk. The report lands in <root>/budget_report.json (preserved on
    failure for the CI artifact upload)."""
    mports = enable_prometheus(net)
    configure_nodes(
        net, lambda i, cfg: cfg["p2p"].update(test_fault_control=True)
    )
    net.start_all()
    net.wait_all(2)
    # traffic: one committed tx, then a couple more heights to budget
    tx = "0x" + f"bg{os.getpid()}=1".encode().hex()
    res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
    assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res
    net.wait_all(int(res["height"]) + 2)

    def compile_totals() -> dict[int, float]:
        """Fleet-wide tendermint_device_compiles_total per node (0.0
        when a node never compiled — this net pins JAX_PLATFORMS=cpu,
        so ANY nonzero delta is a post-warmup recompile)."""
        totals: dict[int, float] = {}
        for i in range(net.n):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mports[i]}/metrics", timeout=5
            ) as r:
                text = r.read().decode()
            totals[i] = sum(
                float(line.rsplit(" ", 1)[1])
                for line in text.splitlines()
                if line.startswith("tendermint_device_compiles_total")
            )
        return totals

    from tendermint_tpu.tools.collector import FleetCollector, render_text

    endpoints = [f"http://127.0.0.1:{net.rpc_port(i)}" for i in range(net.n)]
    fc = FleetCollector(endpoints, timeout=10.0)
    warm = compile_totals()  # post-warmup compile baseline
    fc.poll()
    time.sleep(1.0)
    fc.poll()
    report = fc.report(commit_spread_s=5.0, budget=True)
    after = compile_totals()
    assert after == warm, ("post-warmup recompiles detected", warm, after)
    report_path = os.path.join(net.root, "budget_report.json")
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)

    budget = report.get("budget")
    assert budget and budget["n_heights"] > 0, (
        f"no budgeted heights; see {report_path}"
    )
    for row in budget["heights"]:
        assert row["attribution_frac"] >= 0.95, row
        assert row["dominant"] in row["stages"], row
    assert budget["attribution_frac_min"] >= 0.95, budget
    assert budget["dominant_counts"], budget
    assert not report["violations"], report["violations"]

    # on-demand capture: a bounded window on node0 through the
    # fault-gated route; artifacts are real files under node0's root
    out = net.rpc(0, "debug_profile?action=start&seconds=30")
    assert out is not None and out["capture"]["active"] is True, out
    time.sleep(0.3)
    out = net.rpc(0, "debug_profile?action=stop", timeout=15.0)
    assert out is not None and out["capture"]["active"] is False, out
    pstats = [a for a in out["artifacts"] if a.endswith("host_profile.pstats")]
    assert pstats and os.path.exists(pstats[0]), out
    print(render_text(report))
    print(
        f"budget: {budget['n_heights']} heights decomposed (attribution "
        f">= {budget['attribution_frac_min']:.2f}, dominant "
        + ", ".join(
            f"{k} x{v}"
            for k, v in sorted(budget["dominant_counts"].items())
        )
        + f"), zero post-warmup recompiles, "
        f"{len(out['artifacts'])} capture artifact(s)"
    )


scenario_budget.self_start = True  # rewrites configs before any node starts


def scenario_stream(net: ProcTestnet) -> None:
    """Streaming vote-pipeline acceptance (ISSUE 10): on a committing net
    with streaming forced on (vote_stream_min=1 so even this 4-validator
    net's small gossip groups dispatch async), the commit-boundary verify
    batches only the residual of never-streamed signatures — debug_device
    must show commit_verify.cached_frac > 0.9 with the last residual ≈ 0,
    stream batches must actually have dispatched and applied, and the
    sigcache/stream/residual Prometheus series must be live."""
    mports = enable_prometheus(net)

    def mutate(i: int, cfg: dict) -> None:
        cfg["consensus"]["vote_stream_min"] = 1
        cfg["instrumentation"]["tracing"] = True

    configure_nodes(net, mutate)
    net.start_all()
    net.wait_all(2)
    # traffic + heights: commits whose LastCommit checks sweep the cache
    tx = "0x" + f"st{os.getpid()}=1".encode().hex()
    res = net.rpc(0, f"broadcast_tx_commit?tx={tx}", timeout=30.0)
    assert res is not None and res.get("deliver_tx", {}).get("code", 1) == 0, res
    net.wait_all(int(res["height"]) + 3)

    deadline = time.monotonic() + 30
    while True:  # all four nodes must have dispatched stream batches
        streams = [net.rpc(i, "debug_consensus_trace?n=1") for i in range(net.n)]
        if all(
            s is not None and s.get("stream", {}).get("dispatched", 0) > 0
            and s["stream"]["applied"] > 0
            for s in streams
        ):
            break
        assert time.monotonic() < deadline, (
            f"streaming pipeline never dispatched: "
            f"{[s.get('stream') if s else None for s in streams]}"
        )
        time.sleep(0.5)
    # nothing left hanging between heights
    assert all(s["stream"]["inflight"] <= 2 for s in streams), streams

    for i in range(net.n):
        dev = net.rpc(i, "debug_device")
        assert dev is not None, f"debug_device failed on node{i}"
        cv = dev["commit_verify"]
        assert cv["verifies"] > 0, (i, cv)
        # the acceptance bar: commit verify is a cache sweep — >90% of
        # commit-boundary signatures came from the streamed path, and the
        # latest commit verify dispatched (approximately) nothing
        assert cv["cached_frac"] > 0.9, (i, cv)
        assert cv["residual_last"] <= 1, (i, cv)
        sc = dev["sigcache"]
        assert sc["enabled"] and sc["hits"] > 0 and sc["entries"] > 0, (i, sc)

    def sample(text: str, prefix: str) -> float:
        vals = [
            float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith(prefix) and not line.startswith("#")
        ]
        assert vals, f"no sample for {prefix}"
        return max(vals)

    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[0]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    assert sample(text, "tendermint_device_sigcache_hits_total") > 0
    assert sample(text, "tendermint_consensus_stream_batches_total") > 0
    assert sample(text, "tendermint_device_commit_cached_sigs_total") > 0
    sample(text, "tendermint_device_commit_residual_sigs")  # series live
    cv0 = net.rpc(0, "debug_device")["commit_verify"]
    print(
        f"stream: all {net.n} nodes dispatched+applied async vote batches; "
        f"node0 commit verifies={cv0['verifies']} "
        f"cached_frac={cv0['cached_frac']} residual_last={cv0['residual_last']}; "
        f"sigcache + stream series live"
    )


scenario_stream.self_start = True  # rewrites configs before any node starts


def scenario_transfer(net: ProcTestnet) -> None:
    """Batched tx admission end to end (ISSUE 14): the signed token-
    transfer app runs on every node, a burst of secp256k1-signed
    transfers is admitted through the batch CheckTx surface, commits on
    all nodes with balances/nonces agreeing, and the CheckTx signature
    work is VISIBLY routed through the device scheduler — debug_device
    must show MEMPOOL_CHECK-class admissions and live batch series.

    Execution is batch-first too (DeliverTxBatch): every node must show
    exactly one `deliver_batch` event per committed tx-bearing block
    (lanes=1, zero per-tx fallbacks), and the app's `deliver_verify`
    events must show the block's signature work collapsed to <=1
    scheduler dispatch per curve."""
    from tendermint_tpu.abci.examples import transfer as tr
    from tendermint_tpu.crypto import secp256k1_math as sm

    mports = enable_prometheus(net)

    def mutate(i: int, cfg: dict) -> None:
        cfg["base"]["proxy_app"] = "transfer"

    configure_nodes(net, mutate)
    net.start_all()
    net.wait_all(2)

    # workload: 3 senders x 10 sequential nonces, signed with the dev
    # signers (verifies on every backend the nodes might route to)
    privs = [bytes([10 + s]) * 31 + b"\x01" for s in range(3)]
    to = tr.address(sm.pub_from_priv(b"\x77" * 31 + b"\x01"))
    submitted = 0
    for nonce in range(10):
        for s, priv in enumerate(privs):
            tx = tr.make_tx("secp256k1", priv, to, 5, nonce)
            # each SENDER sticks to one front door: its nonce sequence
            # must reach one node's CheckTx shadow state in order (the
            # gossip echo of nonce n racing a submit of n+1 to a
            # different node would reject honestly)
            res = net.rpc(
                s % 2, f"broadcast_tx_sync?tx=0x{tx.hex()}", timeout=30.0,
            )
            assert res is not None and res.get("code") == 0, (nonce, res)
            submitted += 1

    # every tx commits: recipient balance reflects all 30 transfers on
    # EVERY node, and sender nonces advanced
    want = str(10**9 + 5 * submitted).encode().hex()
    deadline = time.monotonic() + 120
    missing = set(range(net.n))
    while missing and time.monotonic() < deadline:
        for i in sorted(missing):
            r = net.rpc(
                i, f'abci_query?path="/balance"&data=0x{to.hex()}'
            )
            if r and r["response"].get("value") == want:
                missing.discard(i)
        time.sleep(0.5)
    assert not missing, f"transfers not applied on nodes {sorted(missing)}"
    r = net.rpc(
        0,
        f'abci_query?path="/nonce"&data=0x'
        f"{tr.address(sm.pub_from_priv(privs[0])).hex()}",
    )
    assert r and bytes.fromhex(r["response"]["value"]) == b"10", r

    # the proof the tentpole asks for: admission signature work flowed
    # through the scheduler under the MEMPOOL_CHECK class
    ok_nodes = 0
    for i in range(net.n):
        dev = net.rpc(i, "debug_device", timeout=10.0)
        assert dev is not None, f"debug_device failed on node{i}"
        mc = ((dev.get("scheduler") or {}).get("classes") or {}).get(
            "mempool_check"
        ) or {}
        if mc.get("submitted", 0) > 0:
            ok_nodes += 1
    assert ok_nodes >= 2, (
        "MEMPOOL_CHECK class never live in debug_device on the nodes "
        "that took admissions"
    )
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mports[0]}/metrics", timeout=5
    ) as r:
        text = r.read().decode()
    assert "tendermint_mempool_batched_txs_total" in text
    assert "tendermint_mempool_batch_lanes" in text

    # batch-first execution (DeliverTxBatch tentpole): every node ran
    # each tx-bearing block as ONE batch round trip — no per-tx fallback
    # anywhere, no block split across batches — and the transfer app's
    # per-block verification sweep took <=1 scheduler dispatch per curve
    # (this workload is single-curve, so <=1 total per block)
    total_batches = 0
    for i in range(net.n):
        fr = net.rpc(
            i, "debug_flight_recorder?subsystem=state&n=2000", timeout=10.0
        )
        assert fr is not None, f"debug_flight_recorder failed on node{i}"
        events = fr["events"]
        falls = [e for e in events if e["kind"] == "deliver_batch_fallback"]
        assert not falls, f"per-tx delivery fallback on node{i}: {falls}"
        batches = [e for e in events if e["kind"] == "deliver_batch"]
        assert batches, f"no deliver_batch events on node{i}"
        heights = [e["fields"]["height"] for e in batches]
        assert len(heights) == len(set(heights)), (
            f"node{i}: a block was delivered in more than one batch: "
            f"{sorted(heights)}"
        )
        for e in batches:
            assert e["fields"]["lanes"] == 1, (i, e)
            assert e["fields"]["fallback"] is False, (i, e)
        assert sum(e["fields"]["txs"] for e in batches) == submitted, (
            i, batches,
        )
        fra = net.rpc(
            i, "debug_flight_recorder?subsystem=app&n=2000", timeout=10.0
        )
        assert fra is not None, f"debug_flight_recorder(app) failed on node{i}"
        sweeps = [
            e for e in fra["events"] if e["kind"] == "deliver_verify"
        ]
        assert sweeps, f"no deliver_verify events on node{i}"
        for e in sweeps:
            f = e["fields"]
            assert f["dispatches"] <= 1, (i, e)  # <=1 per curve, 1 curve
            assert f["cached"] + f["verified"] == f["txs"], (i, e)
        total_batches += len(batches)
    print(
        f"transfer: {submitted} secp-signed transfers committed on all "
        f"{net.n} nodes; MEMPOOL_CHECK admissions live on {ok_nodes} nodes; "
        f"{total_batches} single-lane delivery batches, zero fallbacks"
    )


scenario_transfer.self_start = True  # rewrites configs before any node starts


def _rss_kb(pid: int) -> int | None:
    try:
        with open(f"/proc/{pid}/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return None


def scenario_soak(net: ProcTestnet, duration: float = 600.0) -> None:
    """Long-horizon stability (reference test/p2p/kill_all + the multi-day
    testnet class, p2p/fuzz.go:14): every peer link runs through
    FuzzedConnection (config p2p.test_fuzz — 5% drops, 10% delays after a
    10s grace), one random node is SIGKILLed and restarted every ~45s,
    and for `duration` seconds the net must (a) keep committing, (b)
    never diverge — block hashes at shared heights are compared across
    every node pair each cycle — and (c) hold RSS bounded (< 3x the
    minute-one footprint per node). TMTPU_SOAK_DURATION overrides the
    duration (the committed run log uses the full 600s)."""
    import random as _random

    duration = float(os.environ.get("TMTPU_SOAK_DURATION", duration))
    rng = _random.Random(1234)

    def mutate(i: int, cfg: dict) -> None:
        cfg["p2p"]["test_fuzz"] = True
        # the loop watchdog dumps task stacks if a node's loop stalls —
        # without it a soak-found wedge is an undiagnosable silent node
        cfg["instrumentation"]["watchdog_interval"] = 2.0
        cfg["instrumentation"]["watchdog_grace"] = 30.0

    configure_nodes(net, mutate)
    net.start_all()
    net.wait_all(2)
    t0 = time.monotonic()
    base_rss: dict[int, int] = {}
    last_height = 2
    kills = 0
    checks = 0
    while time.monotonic() - t0 < duration:
        cycle_end = time.monotonic() + 45.0
        # progress: the live majority must advance while one node may lag
        target = last_height + 2
        live = [i for i in range(net.n) if net.procs.get(i) is not None]
        last_height = max(
            net.wait_height(i, target, timeout=120.0) for i in live
        )
        # divergence: block hash at a shared committed height must be
        # identical on every node that has it
        probe_h = max(1, last_height - 2)
        hashes = {}
        for i in live:
            r = net.rpc(i, f"block?height={probe_h}", timeout=5.0)
            if r is not None:
                hashes[i] = r["block_id"]["hash"]
        assert len(set(hashes.values())) <= 1, (
            f"DIVERGENCE at height {probe_h}: {hashes}"
        )
        checks += 1
        # memory: bounded growth per node
        for i in live:
            p = net.procs.get(i)
            if p is None:
                continue
            rss = _rss_kb(p.pid)
            if rss is None:
                continue
            if time.monotonic() - t0 > 60 and i not in base_rss:
                base_rss[i] = rss
            if i in base_rss:
                assert rss < 3 * base_rss[i], (
                    f"node{i} RSS {rss}kB >= 3x minute-one {base_rss[i]}kB"
                )
        # churn: SIGKILL one random node, let the rest commit, restart it
        if time.monotonic() - t0 + 30 < duration:
            victim = rng.randrange(net.n)
            if net.procs.get(victim) is not None:
                net.kill(victim)
                kills += 1
                time.sleep(5)
                net.start(victim)
        while time.monotonic() < cycle_end and (
            time.monotonic() - t0 < duration
        ):
            time.sleep(1)
    # closing: every node (restarted ones included) converges to the head
    head = last_height
    finals = net.wait_all(head, timeout=240.0)
    print(
        f"soak: {duration:.0f}s, {kills} kill/restart cycles, "
        f"{checks} divergence checks (all identical), heights {finals}, "
        f"fuzzed links, RSS bounded (<3x) on all nodes"
    )


scenario_soak.self_start = True  # rewrites configs before any node starts

SCENARIOS = {
    "basic": scenario_basic,
    "fast_sync": scenario_fast_sync,
    "kill_all": scenario_kill_all,
    "atomic_broadcast": scenario_atomic_broadcast,
    "pex": scenario_pex,
    "metrics": scenario_metrics,
    "timeline": scenario_timeline,
    "txlife": scenario_txlife,
    "traffic": scenario_traffic,
    "budget": scenario_budget,
    "stream": scenario_stream,
    "transfer": scenario_transfer,
    "soak": scenario_soak,
}


def all_scenarios() -> dict:
    """Core scenarios + the adversarial nemesis matrix (lazy import —
    nemesis.py imports this module)."""
    from networks.local import nemesis

    return {**SCENARIOS, **nemesis.SCENARIOS}


def run(names=None, n: int = 4) -> None:
    # the default sweep excludes the 10-minute soak and the nemesis
    # matrix; ask for those by name (or via networks.local.nemesis)
    registry = SCENARIOS if names is None else all_scenarios()
    names = list(names or [s for s in SCENARIOS if s != "soak"])
    for name in names:
        scenario = registry[name]
        net = ProcTestnet(n=n)
        try:
            net.generate()
            if not getattr(scenario, "self_start", False):
                net.start_all()
            scenario(net)
        except BaseException as exc:
            # the temp root is deleted in stop(): surface each node's log
            # tail NOW and preserve the full logs for post-mortem
            err = getattr(exc, "stderr", None)  # generator CalledProcessError
            if err:
                print(f"--- generator stderr ---\n{err.decode(errors='replace')[-1500:]}",
                      file=sys.stderr)
            keep = tempfile.mkdtemp(prefix=f"tmtpu-{name}-failed-")
            # the collector's fleet/budget reports (timeline/budget
            # scenarios) ride with the logs so CI can upload them as
            # failure artifacts
            for rpt in ("fleet_report.json", "budget_report.json"):
                try:
                    shutil.copy(os.path.join(net.root, rpt), keep)
                except OSError:
                    pass
            # WAL .corrupt sidecars (auto-repair evidence) ride with the
            # failure artifacts too — a repaired-then-still-failed run is
            # undiagnosable without the torn bytes
            import glob as _glob

            for src in _glob.glob(
                os.path.join(net.root, "node*", "data", "cs.wal", "*.corrupt*")
            ) + _glob.glob(
                # debug_profile capture artifacts (budget scenario)
                os.path.join(net.root, "node*", "profiles", "*", "*")
            ):
                rel = os.path.relpath(src, net.root).replace(os.sep, "_")
                try:
                    shutil.copy(src, os.path.join(keep, rel))
                except OSError:
                    pass
            for i in range(net.n):
                src = os.path.join(net.root, f"node{i}.log")
                try:
                    shutil.copy(src, keep)
                except OSError:
                    pass  # a failed copy must not suppress the tail print
                try:
                    with open(src, "rb") as f:
                        f.seek(max(0, os.fstat(f.fileno()).st_size - 1500))
                        tail = f.read().decode(errors="replace")
                    print(f"--- node{i}.log tail ---\n{tail}", file=sys.stderr)
                except OSError:
                    pass
            print(f"--- full node logs preserved in {keep} ---", file=sys.stderr)
            raise
        finally:
            net.stop()


if __name__ == "__main__":
    run(sys.argv[1:] or None)
    print("proc testnet: all scenarios passed")
