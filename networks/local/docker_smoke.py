"""Docker-compose testnet smoke: all 4 containerized nodes reach height 3
(reference test/p2p/basic/test.sh). Run via `make -C networks/local
test-docker` on a host with a docker daemon; RPC ports per
docker-compose.yml."""
from __future__ import annotations

import json
import sys
import time
import urllib.request

RPC_PORTS = [26657, 26660, 26662, 26664]  # per docker-compose.yml: each
# node maps host (p2p, rpc) pairs 26656-7, 26659-60, 26661-2, 26663-4


def height(port: int) -> int | None:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=2
        ) as r:
            st = json.loads(r.read())
        return int(st["result"]["sync_info"]["latest_block_height"])
    except Exception:  # noqa: BLE001 — container still booting
        return None


def main() -> int:
    deadline = time.monotonic() + 300
    heights = {p: None for p in RPC_PORTS}
    while time.monotonic() < deadline:
        heights = {p: height(p) for p in RPC_PORTS}
        if all(h is not None and h >= 3 for h in heights.values()):
            print(f"docker testnet live: {heights}")
            return 0
        time.sleep(2)
    print(f"docker testnet failed to reach height 3: {heights}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
