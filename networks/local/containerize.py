"""Rewrite testnet-CLI configs for the docker-compose topology.

The `testnet` generator emits a single-host layout (127.0.0.1, staggered
ports); inside the compose network every node has its own IP
(192.167.10.2..N per docker-compose.yml) and the standard ports. This
mirrors the reference's sed step in test/p2p/local_testnet_start.sh.

Usage: python networks/local/containerize.py networks/local/build
"""
from __future__ import annotations

import json
import os
import sys

P2P_PORT = 26656
RPC_PORT = 26657
BASE_IP = "192.167.10.{}"  # node i -> .2+i, per docker-compose.yml


def containerize(build_dir: str) -> None:
    nodes = sorted(
        d for d in os.listdir(build_dir)
        if d.startswith("node")
        and os.path.isdir(os.path.join(build_dir, d))
    )
    ids = {}
    for d in nodes:
        cfg_path = os.path.join(build_dir, d, "config", "config.json")
        with open(cfg_path, encoding="utf-8") as f:
            cfg = json.load(f)
        # recover the node id from the old persistent_peers line (written
        # by the generator as <id>@127.0.0.1:<port> in node order)
        for j, entry in enumerate(cfg["p2p"]["persistent_peers"].split(",")):
            ids[j] = entry.split("@", 1)[0]
        break
    peers = ",".join(
        f"{ids[i]}@{BASE_IP.format(2 + i)}:{P2P_PORT}" for i in range(len(nodes))
    )
    for d in nodes:
        cfg_path = os.path.join(build_dir, d, "config", "config.json")
        with open(cfg_path, encoding="utf-8") as f:
            cfg = json.load(f)
        cfg["p2p"]["laddr"] = f"tcp://0.0.0.0:{P2P_PORT}"
        cfg["rpc"]["laddr"] = f"tcp://0.0.0.0:{RPC_PORT}"
        cfg["p2p"]["persistent_peers"] = peers
        with open(cfg_path, "w", encoding="utf-8") as f:
            json.dump(cfg, f, indent=1, sort_keys=True)
    print(f"containerized {len(nodes)} node configs (peers: {peers[:60]}...)")


if __name__ == "__main__":
    containerize(sys.argv[1] if len(sys.argv) > 1 else "networks/local/build")
