"""Remote-cluster deployment harness (reference networks/remote/: terraform
droplet provisioning + ansible install/start/stop/status playbooks).

Re-designed rather than translated: one dependency-free Python tool over
plain ssh/rsync — the reference's ansible playbooks assume a Go binary and
systemd units; this framework ships as a Python package whose nodes run
`python -m tendermint_tpu.cmd node`, so the harness (a) generates the
N-node testnet locally with the real `testnet` CLI, (b) rewrites each
node's p2p/rpc addresses to the target hosts, (c) pushes code + config,
(d) start/stop/status over ssh. Provisioning (the terraform half) is
cloud-specific and out of scope — point the inventory at any hosts you can
ssh into (TPU VMs included; nodes use the accelerator automatically when
one is visible).

Inventory: a text file, one `user@host` per line (comments with #).

Usage:
  python -m networks.remote.deploy -i hosts.txt init      # configs + push
  python -m networks.remote.deploy -i hosts.txt start
  python -m networks.remote.deploy -i hosts.txt status
  python -m networks.remote.deploy -i hosts.txt stop
  python -m networks.remote.deploy -i hosts.txt reset     # wipe data, keep keys
"""
from __future__ import annotations

import argparse
import json
import os

import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
REMOTE_DIR = "~/tendermint-tpu"
P2P_PORT = 26656
RPC_PORT = 26657


def read_inventory(path: str) -> list[str]:
    hosts = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line)
    if not hosts:
        raise SystemExit(f"no hosts in {path}")
    return hosts


def ssh(host: str, cmd: str, check: bool = True) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["ssh", "-o", "BatchMode=yes", host, cmd],
        check=check, capture_output=True, text=True,
    )


def _bare_host(host: str) -> str:
    return host.split("@", 1)[-1]


def cmd_init(hosts: list[str], build_dir: str) -> None:
    """Generate configs with the real testnet CLI, then rewrite addresses
    for the remote topology and push code + per-node config."""
    n = len(hosts)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cmd", "testnet",
         "--v", str(n), "--o", build_dir],
        check=True, cwd=REPO_ROOT,
    )
    # collect node ids from the generated node keys, then rewrite
    # listen/peer addresses from 127.0.0.1:<seq> to <host>:26656
    ids = []
    for i in range(n):
        with open(os.path.join(build_dir, f"node{i}", "config", "node_key.json"),
                  encoding="utf-8") as f:
            json.load(f)  # validate
        out = subprocess.run(
            [sys.executable, "-m", "tendermint_tpu.cmd",
             "--home", os.path.join(build_dir, f"node{i}"), "show_node_id"],
            check=True, cwd=REPO_ROOT, capture_output=True, text=True,
        )
        ids.append(out.stdout.strip())
    peers = ",".join(
        f"{ids[i]}@{_bare_host(hosts[i])}:{P2P_PORT}" for i in range(n)
    )
    for i in range(n):
        cfg_path = os.path.join(build_dir, f"node{i}", "config", "config.json")
        with open(cfg_path, encoding="utf-8") as f:
            cfg = json.load(f)
        cfg["p2p"]["laddr"] = f"tcp://0.0.0.0:{P2P_PORT}"
        cfg["rpc"]["laddr"] = f"tcp://0.0.0.0:{RPC_PORT}"
        cfg["p2p"]["persistent_peers"] = peers
        with open(cfg_path, "w", encoding="utf-8") as f:
            json.dump(cfg, f, indent=1, sort_keys=True)
    for i, host in enumerate(hosts):
        print(f"pushing code + node{i} config to {host}")
        ssh(host, f"mkdir -p {REMOTE_DIR}")
        subprocess.run(
            ["rsync", "-a", "--delete",
             "--exclude", ".git", "--exclude", "__pycache__",
             "--exclude", "networks/remote/build",
             f"{REPO_ROOT}/", f"{host}:{REMOTE_DIR}/code/"],
            check=True,
        )
        subprocess.run(
            ["rsync", "-a", os.path.join(build_dir, f"node{i}") + "/",
             f"{host}:{REMOTE_DIR}/home/"],
            check=True,
        )
    print(f"initialized {n} nodes")


def cmd_start(hosts: list[str]) -> None:
    for host in hosts:
        ssh(
            host,
            # `;` separators: `&` must background ONLY the node command so
            # $! is the python PID, not a wrapper subshell's
            f"cd {REMOTE_DIR}/code; "
            f"nohup python -m tendermint_tpu.cmd --home {REMOTE_DIR}/home node "
            f"> {REMOTE_DIR}/node.log 2>&1 & "
            f"echo $! > {REMOTE_DIR}/node.pid; echo started",
        )
        print(f"{host}: started")


def cmd_stop(hosts: list[str]) -> None:
    # kill exactly the PID recorded at start — a pkill pattern would match
    # ANY process whose command line mentions the node module (editors,
    # tails, unrelated checkouts)  (ADVICE r3)
    for host in hosts:
        ssh(
            host,
            f"[ -f {REMOTE_DIR}/node.pid ] && "
            f"kill $(cat {REMOTE_DIR}/node.pid) 2>/dev/null; "
            f"rm -f {REMOTE_DIR}/node.pid; true",
            check=False,
        )
        print(f"{host}: stopped")


def cmd_status(hosts: list[str]) -> None:
    for host in hosts:
        r = ssh(
            host,
            f"curl -s --max-time 3 http://127.0.0.1:{RPC_PORT}/status || true",
            check=False,
        )
        try:
            st = json.loads(r.stdout)["result"]["sync_info"]
            print(f"{host}: height {st['latest_block_height']}")
        except Exception:  # noqa: BLE001 — node down/unreachable
            print(f"{host}: DOWN")


def cmd_reset(hosts: list[str]) -> None:
    for host in hosts:
        ssh(
            host,
            f"cd {REMOTE_DIR}/code && "
            f"python -m tendermint_tpu.cmd --home {REMOTE_DIR}/home unsafe_reset_all",
            check=False,
        )
        print(f"{host}: reset")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-i", "--inventory", required=True)
    ap.add_argument(
        "action", choices=["init", "start", "stop", "status", "reset"]
    )
    ap.add_argument(
        "--build-dir",
        default=os.path.join(REPO_ROOT, "networks", "remote", "build"),
    )
    args = ap.parse_args()
    hosts = read_inventory(args.inventory)
    if args.action == "init":
        cmd_init(hosts, args.build_dir)
    elif args.action == "start":
        cmd_start(hosts)
    elif args.action == "stop":
        cmd_stop(hosts)
    elif args.action == "status":
        cmd_status(hosts)
    elif args.action == "reset":
        cmd_reset(hosts)


if __name__ == "__main__":
    main()
