"""Canonical deterministic binary encoding (CBE).

The reference encodes all wire/disk/sign-bytes with go-amino (registered
concrete types, proto3-compatible wire format); determinism of sign-bytes is
consensus-critical (reference: types/canonical.go, types/codec.go). Rather
than imitate amino's quirks, this framework defines a small, documented,
deterministic encoding:

- fixed-width big-endian integers (u8/u16/u32/u64, i64 two's complement)
- length-prefixed byte strings (u32 length + raw bytes)
- structs are the concatenation of their fields in a fixed, documented order
- unions (message types) are a 1-byte tag followed by the payload

Big-endian fixed-width was chosen over varints because it is branch-free to
produce in bulk on the host when forming device batches of sign-bytes, and
trivially canonical (one byte representation per value).

Encoding is intentionally *not* self-describing: every message type owns its
encode/decode pair. `Writer`/`Reader` are the only primitives.
"""
from __future__ import annotations

import struct

# Prebound Struct.pack methods: encoding is a node-profile hot spot
# (~1.1M field appends under tm-bench load), and `struct.pack(">I", v)`
# pays a format-cache lookup per call that `Struct.pack` does not.
_PACK_B = struct.Struct(">B").pack
_PACK_H = struct.Struct(">H").pack
_PACK_I = struct.Struct(">I").pack
_PACK_Q = struct.Struct(">Q").pack
_PACK_q = struct.Struct(">q").pack


class Writer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "Writer":
        self._parts.append(_PACK_B(v))
        return self

    def u16(self, v: int) -> "Writer":
        self._parts.append(_PACK_H(v))
        return self

    def u32(self, v: int) -> "Writer":
        self._parts.append(_PACK_I(v))
        return self

    def u64(self, v: int) -> "Writer":
        self._parts.append(_PACK_Q(v))
        return self

    def i64(self, v: int) -> "Writer":
        self._parts.append(_PACK_q(v))
        return self

    def bool(self, v: bool) -> "Writer":
        self._parts.append(b"\x01" if v else b"\x00")
        return self

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b if type(b) is bytes else bytes(b))
        return self

    def bytes(self, b: bytes) -> "Writer":
        # flattened u32(len)+raw: this pair is the single hottest encode
        # call (one per tx field, per header field, per commit sig)
        p = self._parts
        p.append(_PACK_I(len(b)))
        p.append(b if type(b) is bytes else bytes(b))
        return self

    def str(self, s: str) -> "Writer":
        if not s:  # empty strings dominate ABCI response fields
            self._parts.append(b"\x00\x00\x00\x00")
            return self
        return self.bytes(s.encode("utf-8"))

    def build(self) -> bytes:
        return b"".join(self._parts)


class DecodeError(Exception):
    pass


def as_decode_error(fn, data, what: str):
    """Run decoder `fn(data)` normalizing every conversion fault to
    DecodeError. Malformed (or adversarial) bytes must surface as
    DecodeError, never a raw fault: str fields can hold invalid UTF-8
    (UnicodeDecodeError ⊂ ValueError), dict->dataclass converters index
    into nested messages, and re-packing through Writer raises
    struct.error on out-of-range ints. Transport loops key their
    drop-the-connection handling on DecodeError alone and treat anything
    else as a bug."""
    try:
        return fn(data)
    except DecodeError:
        raise
    except (ValueError, KeyError, IndexError, TypeError, OverflowError,
            struct.error) as e:
        raise DecodeError(f"malformed {what}: {e!r}") from e


class Reader:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._buf):
            raise DecodeError(
                f"short read: need {n} bytes at {self._pos}, have {len(self._buf)}"
            )
        b = self._buf[self._pos : self._pos + n]
        self._pos += n
        return b

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def bool(self) -> bool:
        v = self.u8()
        if v not in (0, 1):
            raise DecodeError(f"bad bool byte {v}")
        return v == 1

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def bytes(self) -> bytes:
        n = self.u32()
        return self._take(n)

    def str(self) -> str:
        return self.bytes().decode("utf-8")

    def remaining(self) -> int:
        return len(self._buf) - self._pos

    def done(self) -> bool:
        return self._pos == len(self._buf)

    def expect_done(self) -> None:
        if not self.done():
            raise DecodeError(f"{self.remaining()} trailing bytes")
