"""Evidence gossip reactor.

Reference parity: evidence/reactor.go — EvidenceChannel 0x38, one
broadcastEvidenceRoutine per peer following the pool's clist; peers behind
the evidence height wait until they catch up (here: evidence is sent
unconditionally and the receiving pool rejects what it cannot verify yet).
"""
from __future__ import annotations

import asyncio

from tendermint_tpu.behaviour import PeerBehaviour
from tendermint_tpu.encoding import Reader, Writer
from tendermint_tpu.evidence import EvidenceError, EvidencePool
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.libs.recorder import RECORDER
from tendermint_tpu.p2p.base_reactor import BaseReactor, ChannelDescriptor
from tendermint_tpu.types.evidence import decode_evidence

EVIDENCE_CHANNEL = 0x38


def encode_evidence_message(evs: list) -> bytes:
    w = Writer().u8(1).u32(len(evs))
    for ev in evs:
        w.bytes(ev.encode())
    return w.build()


def decode_evidence_message(data: bytes) -> list:
    r = Reader(data)
    tag = r.u8()
    if tag != 1:
        raise ValueError(f"unknown evidence message tag {tag}")
    n = r.u32()
    out = [decode_evidence(r.bytes()) for _ in range(n)]
    r.expect_done()
    return out


class EvidenceReactor(BaseReactor):
    traffic_family = "evidence"

    def __init__(self, pool: EvidencePool, logger: Logger = NOP) -> None:
        super().__init__("EvidenceReactor")
        self.pool = pool
        self.log = logger
        self._peer_tasks: dict[str, asyncio.Task] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(EVIDENCE_CHANNEL, priority=5, recv_message_capacity=1 << 20)]

    def classify(self, ch_id: int, msg: bytes) -> str:
        return "evidence" if msg and msg[0] == 1 else "other"

    async def add_peer(self, peer) -> None:
        self._peer_tasks[peer.id] = self.spawn(
            self._broadcast_routine(peer), f"evidence-gossip-{peer.id}"
        )

    async def remove_peer(self, peer, reason) -> None:
        t = self._peer_tasks.pop(peer.id, None)
        if t is not None:
            t.cancel()

    async def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        try:
            evs = decode_evidence_message(msg_bytes)
        except Exception as e:
            self.log.error("bad evidence message", peer=peer.id, err=repr(e))
            await self.report(
                peer, PeerBehaviour.bad_message(peer.id, f"evidence: {e!r}")
            )
            return
        for ev in evs:
            if self.pool.is_pending(ev) or self.pool.is_committed(ev):
                # already held or already punished: the delivery carried
                # nothing new (normal gossip echo, but wire waste)
                self.note_redundant(peer, "evidence")
                continue
            try:
                self.pool.add_evidence(ev)
            except EvidenceError as e:
                # Not necessarily Byzantine: height skew between peers makes
                # valid evidence unverifiable here (too old for us, or from a
                # height we haven't stored validators for). Reject the
                # evidence, keep the peer — but remember the smell: a peer
                # that ONLY ever sends unverifiable evidence decays.
                RECORDER.record(
                    "evidence", "rejected", peer=peer.id,
                    height=ev.height(), err=str(e)[:200],
                )
                self.log.info("rejected evidence from peer", peer=peer.id, err=str(e))
                await self.report(
                    peer, PeerBehaviour.unverifiable_evidence(peer.id, str(e)[:80])
                )

    async def _broadcast_routine(self, peer) -> None:
        el = None
        while True:
            if el is None:
                el = await self.pool.evidence_list.front_wait()
            ev = el.value
            ok = await peer.send(EVIDENCE_CHANNEL, encode_evidence_message([ev]))
            if not ok:
                await asyncio.sleep(0.1)
                continue
            # it has now been sent to at least one peer: off the priority
            # outqueue (reference reactor.go broadcastEvidenceRoutine ->
            # store MarkEvidenceAsBroadcasted); still pending until committed
            self.pool.mark_broadcasted(ev)
            RECORDER.record(
                "evidence", "gossip_sent", peer=peer.id, height=ev.height(),
            )
            el = await el.next_wait()
