"""Evidence pool — pending/committed Byzantine evidence.

Reference parity: evidence/pool.go:17 (validate via state.VerifyEvidence,
clist for gossip, prune on block commit), evidence/store.go (pending/
committed prefixes with priority keys).
"""
from __future__ import annotations

import struct

from tendermint_tpu.libs.clist import CList
from tendermint_tpu.libs.db import DB
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.state import State, StateStore
from tendermint_tpu.state.validation import ValidationError, verify_evidence
from tendermint_tpu.types.evidence import Evidence, decode_evidence


class EvidenceError(Exception):
    pass


class EvidencePool:
    def __init__(
        self, db: DB, state_store: StateStore, state: State, logger: Logger = NOP
    ) -> None:
        self._db = db
        self.state_store = state_store
        self.state = state
        self.log = logger
        self.evidence_list = CList()  # gossip data structure
        self._in_list: dict[bytes, object] = {}
        # load pending from disk
        for _, raw in self._db.iterate_prefix(b"EV:pending:"):
            ev = decode_evidence(raw)
            self._in_list[ev.hash()] = self.evidence_list.push_back(ev)

    def _pending_key(self, ev: Evidence) -> bytes:
        return b"EV:pending:" + struct.pack(">Q", ev.height()) + ev.hash()

    def _committed_key(self, ev: Evidence) -> bytes:
        return b"EV:committed:" + ev.hash()

    def is_committed(self, ev: Evidence) -> bool:
        return self._db.has(self._committed_key(ev))

    def is_pending(self, ev: Evidence) -> bool:
        return self._db.has(self._pending_key(ev))

    def add_evidence(self, ev: Evidence) -> None:
        """Verify and admit new evidence (reference pool.go AddEvidence)."""
        if self.is_committed(ev) or self.is_pending(ev):
            return
        try:
            verify_evidence(self.state, self.state_store, ev)
        except ValidationError as e:
            raise EvidenceError(str(e)) from e
        self._db.set(self._pending_key(ev), ev.encode())
        self._in_list[ev.hash()] = self.evidence_list.push_back(ev)
        self.log.info("added evidence", evidence=str(ev))

    def pending_evidence(self, max_bytes: int = -1) -> list[Evidence]:
        out = []
        total = 0
        for _, raw in self._db.iterate_prefix(b"EV:pending:"):
            ev = decode_evidence(raw)
            if max_bytes >= 0 and total + len(raw) > max_bytes:
                break
            total += len(raw)
            out.append(ev)
        return out

    def mark_committed(self, evidence: list[Evidence]) -> None:
        for ev in evidence:
            self._db.set(self._committed_key(ev), b"1")
            self._db.delete(self._pending_key(ev))
            el = self._in_list.pop(ev.hash(), None)
            if el is not None:
                self.evidence_list.remove(el)

    def update(self, block, state: State) -> None:
        """Reference pool.go Update: mark block evidence committed, prune
        expired pending evidence."""
        self.state = state
        self.mark_committed(block.evidence)
        max_age = state.consensus_params.evidence.max_age
        for _, raw in list(self._db.iterate_prefix(b"EV:pending:")):
            ev = decode_evidence(raw)
            if ev.height() < state.last_block_height - max_age:
                self._db.delete(self._pending_key(ev))
                el = self._in_list.pop(ev.hash(), None)
                if el is not None:
                    self.evidence_list.remove(el)
