"""Evidence pool — pending/committed Byzantine evidence.

Reference parity: evidence/pool.go:17 (validate via state.VerifyEvidence,
clist for gossip, prune on block commit) and evidence/store.go's keyed
store: three key families,

  EV:pending:<height><hash>              all uncommitted evidence
  EV:outqueue:<inv-priority><height><hash>  broadcast queue, PRIORITY order
  EV:committed:<hash>                    seen-on-chain marker

where priority = the offending validator's voting power at the evidence
height (store.go:13-24 "Schema for indexing evidence (note you need both
height and hash to find a piece of evidence)" + priorityKey). Iterating the
outqueue ascending yields highest-priority evidence first (the inverted
big-endian priority), which is the order the gossip clist is seeded in on
restart — the strongest equivocations travel first.
"""
from __future__ import annotations

import struct

from tendermint_tpu.libs.clist import CList
from tendermint_tpu.libs.db import DB
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.libs.recorder import RECORDER
from tendermint_tpu.state import State, StateStore
from tendermint_tpu.state.validation import ValidationError, verify_evidence
from tendermint_tpu.types.evidence import Evidence, decode_evidence

_MAX_U64 = (1 << 64) - 1


class EvidenceError(Exception):
    pass


class EvidencePool:
    def __init__(
        self, db: DB, state_store: StateStore, state: State, logger: Logger = NOP
    ) -> None:
        self._db = db
        self.state_store = state_store
        self.state = state
        self.log = logger
        # libs/metrics.EvidenceMetrics | None, set by the node when
        # Prometheus is on (tm_evidence_* series)
        self.metrics = None
        self.evidence_list = CList()  # gossip data structure
        self._in_list: dict[bytes, object] = {}
        # Seed the gossip list from the outqueue: priority order (reference
        # reactor broadcasts PriorityEvidence first on start), then any
        # pending evidence already marked broadcasted, in height order.
        for _, raw in self._db.iterate_prefix(b"EV:outqueue:"):
            ev = decode_evidence(raw)
            if ev.hash() not in self._in_list:
                self._in_list[ev.hash()] = self.evidence_list.push_back(ev)
        for _, raw in self._db.iterate_prefix(b"EV:pending:"):
            ev = decode_evidence(raw)
            if ev.hash() not in self._in_list:
                self._in_list[ev.hash()] = self.evidence_list.push_back(ev)
        if self._in_list:
            # restart durability: pending evidence from a previous run is
            # back on the gossip list — make the black box say so
            RECORDER.record(
                "evidence", "restored", count=len(self._in_list),
            )

    def _pending_count(self) -> int:
        return sum(1 for _ in self._db.iterate_prefix(b"EV:pending:"))

    def _set_pending_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.pending.set(self._pending_count())

    # -- keys (reference evidence/store.go:37-57) --------------------------

    def _pending_key(self, ev: Evidence) -> bytes:
        return b"EV:pending:" + struct.pack(">Q", ev.height()) + ev.hash()

    def _outqueue_key(self, ev: Evidence, priority: int) -> bytes:
        return (
            b"EV:outqueue:"
            + struct.pack(">Q", _MAX_U64 - max(0, priority))
            + struct.pack(">Q", ev.height())
            + ev.hash()
        )

    def _committed_key(self, ev: Evidence) -> bytes:
        return b"EV:committed:" + ev.hash()

    def _priority_of(self, ev: Evidence) -> int:
        """Offending validator's voting power at the evidence height
        (reference pool.go AddEvidence computes evidenceParams priority)."""
        try:
            vals = self.state_store.load_validators(ev.height())
            _, val = vals.get_by_address(ev.address())
            return val.voting_power if val is not None else 0
        except Exception:  # noqa: BLE001 — missing historical valset
            return 0

    # -- queries ------------------------------------------------------------

    def is_committed(self, ev: Evidence) -> bool:
        return self._db.has(self._committed_key(ev))

    def is_pending(self, ev: Evidence) -> bool:
        return self._db.has(self._pending_key(ev))

    def pending_evidence(self, max_bytes: int = -1) -> list[Evidence]:
        """Height-ordered pending evidence (block proposal reaping)."""
        out = []
        total = 0
        for _, raw in self._db.iterate_prefix(b"EV:pending:"):
            ev = decode_evidence(raw)
            if max_bytes >= 0 and total + len(raw) > max_bytes:
                break
            total += len(raw)
            out.append(ev)
        return out

    def priority_evidence(self) -> list[Evidence]:
        """Outqueue evidence, highest priority first (reference
        store.go PriorityEvidence)."""
        return [
            decode_evidence(raw)
            for _, raw in self._db.iterate_prefix(b"EV:outqueue:")
        ]

    # -- mutation -----------------------------------------------------------

    def add_evidence(self, ev: Evidence) -> None:
        """Verify and admit new evidence (reference pool.go AddEvidence)."""
        if self.is_committed(ev) or self.is_pending(ev):
            return
        try:
            verify_evidence(self.state, self.state_store, ev)
        except ValidationError as e:
            raise EvidenceError(str(e)) from e
        priority = self._priority_of(ev)
        self._db.set(self._pending_key(ev), ev.encode())
        self._db.set(self._outqueue_key(ev, priority), ev.encode())
        # remember the insertion-time priority so outqueue keys can be
        # deleted exactly even after historical valsets are pruned
        self._db.set(b"EV:prio:" + ev.hash(), struct.pack(">Q", priority))
        self._in_list[ev.hash()] = self.evidence_list.push_back(ev)
        RECORDER.record(
            "evidence", "added", height=ev.height(),
            addr=ev.address().hex(), priority=priority,
        )
        self._set_pending_gauge()
        self.log.info("added evidence", evidence=str(ev), priority=priority)

    def _stored_priority(self, ev: Evidence) -> int:
        raw = self._db.get(b"EV:prio:" + ev.hash())
        return struct.unpack(">Q", raw)[0] if raw else self._priority_of(ev)

    def mark_broadcasted(self, ev: Evidence) -> None:
        """Reference store.go MarkEvidenceAsBroadcasted: drop from the
        outqueue (it stays pending until committed)."""
        self._db.delete(self._outqueue_key(ev, self._stored_priority(ev)))

    def mark_committed(self, evidence: list[Evidence]) -> None:
        for ev in evidence:
            self._db.set(self._committed_key(ev), b"1")
            self._remove_pending(ev)
            RECORDER.record(
                "evidence", "committed", height=ev.height(),
                addr=ev.address().hex(),
            )
            if self.metrics is not None:
                self.metrics.committed_total.inc()
        if evidence:
            self._set_pending_gauge()

    def _remove_pending(self, ev: Evidence) -> None:
        self._db.delete(self._pending_key(ev))
        self._db.delete(self._outqueue_key(ev, self._stored_priority(ev)))
        self._db.delete(b"EV:prio:" + ev.hash())
        el = self._in_list.pop(ev.hash(), None)
        if el is not None:
            self.evidence_list.remove(el)

    def update(self, block, state: State) -> None:
        """Reference pool.go Update: mark block evidence committed, prune
        expired pending evidence."""
        self.state = state
        self.mark_committed(block.evidence)
        max_age = state.consensus_params.evidence.max_age
        pruned = 0
        for _, raw in list(self._db.iterate_prefix(b"EV:pending:")):
            ev = decode_evidence(raw)
            if ev.height() < state.last_block_height - max_age:
                self._remove_pending(ev)
                pruned += 1
        if pruned:
            RECORDER.record(
                "evidence", "pruned", count=pruned,
                height=state.last_block_height, max_age=max_age,
            )
            if self.metrics is not None:
                self.metrics.pruned_total.inc(pruned)
            self._set_pending_gauge()
