"""Protobuf wire compatibility for the ABCI socket protocol.

The reference's ABCI is a cross-language protocol: a protobuf
Request/Response oneof over a socket, each message framed by a SIGNED
(zigzag) varint length prefix — Go's `binary.PutVarint` at
/root/reference/abci/types/messages.go:54 and the read side at
abci/client/socket_client.go:122 via `binary.ReadVarint`. This module
hand-rolls that wire format (schema: /root/reference/abci/types/types.proto)
so existing Go/Rust/Java ABCI apps can talk to this node and existing
tendermint nodes can drive this framework's apps, with no protobuf
runtime dependency. The internal CBE codec (abci/types.py) remains the
default; select this one with `--abci proto` (abci-cli) or
`codec="proto"` on ABCIServer / SocketClient.

Scope: the 11-method Request/Response oneof plus every embedded type it
references (ConsensusParams, Header, ValidatorUpdate, Event, Proof,
Timestamp...). proto3 implicit-presence rules: scalar zero values are
omitted on encode, unknown fields are skipped on decode (forward compat).

Field mapping notes (internal dataclass <-> proto):
- `events: dict[str, list[str]]` <-> `repeated Event`: the dict key is
  the compound tag `<event_type>.<attr_key>` tendermint indexes by, so
  Event{type=t, attributes=[{key=k, value=v}]} decodes to
  events["t.k"] += [v] and a dict entry "t.k" encodes to one Event per
  (t, k) group. Keys with no dot map to Event{type=key} with attribute
  key "" (lossless for the app-visible query strings, which always use
  the compound form).
- timestamps: int nanoseconds <-> google.protobuf.Timestamp.
- consensus_params / header CBE bytes <-> structured proto messages via
  the domain dataclasses (types/params.py, types/block.py).
- ValidatorUpdate.pub_key: crypto.encode_pubkey bytes <-> abci PubKey
  {type: "ed25519"|"secp256k1", data} (reference abci/types/pubkey.go:4).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.types import MAX_MSG_SIZE
from tendermint_tpu.encoding import DecodeError, as_decode_error


# ---------------------------------------------------------------- varints


def encode_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_svarint(n: int) -> bytes:
    """Signed (zigzag) varint — the FRAME length prefix uses this."""
    return encode_uvarint((n << 1) ^ (n >> 63) if n < 0 else n << 1)


def decode_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if pos >= len(data):
            raise DecodeError("truncated varint")
        b = data[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            # Go's binary.ReadUvarint overflow rule: a varint must fit
            # uint64. Without this, 2^64+k decodes as a silent wrong value
            # for int fields and an out-of-range int for u64 fields that
            # only explodes later, outside the wire seam's normalization.
            if val >= 1 << 64:
                raise DecodeError("varint overflows 64 bits")
            return val, pos
        shift += 7
        if shift >= 70:  # > 10 bytes is malformed even if the value fits
            raise DecodeError("varint too long")


def _varint64(n: int) -> bytes:
    """proto3 int64/int32: negative values are 10-byte two's complement."""
    return encode_uvarint(n & 0xFFFFFFFFFFFFFFFF)


def _to_signed64(n: int) -> int:
    return n - (1 << 64) if n >= 1 << 63 else n


# ------------------------------------------------------------ descriptors
#
# A message descriptor is a list of fields; each field is
# (number, attr, kind, sub) with kind one of:
#   "i64"/"i32"  varint, two's complement negative    (int64/int32/uint*)
#   "u64"        varint, non-negative
#   "bool"       varint 0/1
#   "str"        length-delimited utf-8
#   "bytes"      length-delimited
#   "msg"        embedded message, sub = Desc
#   "rep_msg"    repeated embedded message, sub = Desc
#   "rep_str"    repeated string
#   "rep_bytes"  repeated bytes
#   "rep_u64"    repeated non-negative varint, PACKED (proto3 default)
# Values are plain dicts at this layer; the mapping layer below converts
# dict <-> the abci/types.py dataclasses.


@dataclass
class Desc:
    name: str
    fields: list[tuple[int, str, str, Any]] = field(default_factory=list)

    def __post_init__(self) -> None:
        # descriptors are module-level constants; build the decode lookup
        # once, not per message
        self._by_num = {
            num: (attr, kind, sub) for num, attr, kind, sub in self.fields
        }

    def encode(self, v: dict) -> bytes:
        out = bytearray()
        for num, attr, kind, sub in self.fields:
            val = v.get(attr)
            if val is None:
                continue
            if kind in ("i64", "i32", "u64"):
                if val == 0:
                    continue
                out += encode_uvarint(num << 3 | 0)
                out += _varint64(int(val))
            elif kind == "bool":
                if not val:
                    continue
                out += encode_uvarint(num << 3 | 0) + b"\x01"
            elif kind == "str":
                if val == "":
                    continue
                enc = val.encode()
                out += encode_uvarint(num << 3 | 2) + encode_uvarint(len(enc)) + enc
            elif kind == "bytes":
                if val == b"":
                    continue
                out += encode_uvarint(num << 3 | 2) + encode_uvarint(len(val)) + val
            elif kind == "msg":
                enc = sub.encode(val)
                out += encode_uvarint(num << 3 | 2) + encode_uvarint(len(enc)) + enc
            elif kind == "rep_msg":
                for item in val:
                    enc = sub.encode(item)
                    out += encode_uvarint(num << 3 | 2) + encode_uvarint(len(enc)) + enc
            elif kind == "rep_str":
                for item in val:
                    enc = item.encode()
                    out += encode_uvarint(num << 3 | 2) + encode_uvarint(len(enc)) + enc
            elif kind == "rep_bytes":
                # every item is emitted, including empty ones: repeated
                # presence is meaningful (a zero-byte tx is still a tx)
                for item in val:
                    out += encode_uvarint(num << 3 | 2) + encode_uvarint(len(item)) + item
            elif kind == "rep_u64":
                if not val:
                    continue
                packed = b"".join(encode_uvarint(int(item)) for item in val)
                out += (
                    encode_uvarint(num << 3 | 2)
                    + encode_uvarint(len(packed))
                    + packed
                )
            else:  # pragma: no cover - descriptor bug
                raise AssertionError(f"bad kind {kind}")
        return bytes(out)

    def decode(self, data: bytes) -> dict:
        v: dict[str, Any] = {}
        by_num = self._by_num
        pos = 0
        while pos < len(data):
            tag, pos = decode_uvarint(data, pos)
            num, wt = tag >> 3, tag & 7
            if wt == 0:
                raw, pos = decode_uvarint(data, pos)
                payload: Any = raw
            elif wt == 2:
                ln, pos = decode_uvarint(data, pos)
                if pos + ln > len(data):
                    raise DecodeError(f"{self.name}: truncated field {num}")
                payload = data[pos : pos + ln]
                pos += ln
            elif wt in (5, 1):  # fixed32 / fixed64: no field in this
                # schema uses them — skippable only when UNKNOWN, and the
                # payload must actually be present (a frame cut mid-field
                # is malformed, not a default value)
                n = 4 if wt == 5 else 8
                if pos + n > len(data):
                    raise DecodeError(f"{self.name}: truncated field {num}")
                pos += n
                payload = None
            else:
                raise DecodeError(f"{self.name}: bad wire type {wt}")
            if num not in by_num:
                continue  # unknown field: forward compat
            attr, kind, sub = by_num[num]
            # wire type must agree with the declared kind: a varint (or
            # fixed) payload for a length-delimited field — or vice versa —
            # is malformed bytes, not a value to coerce or silently drop
            # (fuzz-found: .decode() on int; review-found: known i64 sent
            # as fixed64 decoded to its default)
            if kind == "rep_u64":
                # proto3 accepts BOTH packed (wt 2) and unpacked (wt 0)
                # encodings for repeated varints (spec: parsers must)
                if wt == 0:
                    v.setdefault(attr, []).append(payload)
                    continue
                if wt != 2:
                    raise DecodeError(
                        f"{self.name}: field {num} kind {kind} got wire type {wt}"
                    )
                vals = v.setdefault(attr, [])
                p = 0
                while p < len(payload):
                    item, p = decode_uvarint(payload, p)
                    vals.append(item)
                continue
            if wt != (2 if kind in ("str", "bytes", "msg", "rep_msg", "rep_str", "rep_bytes") else 0):
                raise DecodeError(
                    f"{self.name}: field {num} kind {kind} got wire type {wt}"
                )
            if kind in ("i64", "i32"):
                v[attr] = _to_signed64(payload)
            elif kind == "u64":
                v[attr] = payload
            elif kind == "bool":
                v[attr] = bool(payload)
            elif kind == "str":
                v[attr] = payload.decode()
            elif kind == "bytes":
                v[attr] = bytes(payload)
            elif kind == "msg":
                v[attr] = sub.decode(payload)
            elif kind == "rep_msg":
                v.setdefault(attr, []).append(sub.decode(payload))
            elif kind == "rep_str":
                v.setdefault(attr, []).append(payload.decode())
            elif kind == "rep_bytes":
                v.setdefault(attr, []).append(bytes(payload))
        return v


# schema: /root/reference/abci/types/types.proto (field numbers verbatim)
TIMESTAMP = Desc("Timestamp", [(1, "seconds", "i64", None), (2, "nanos", "i32", None)])
PUBKEY = Desc("PubKey", [(1, "type", "str", None), (2, "data", "bytes", None)])
VALIDATOR_UPDATE = Desc(
    "ValidatorUpdate", [(1, "pub_key", "msg", PUBKEY), (2, "power", "i64", None)]
)
VALIDATOR = Desc("Validator", [(1, "address", "bytes", None), (3, "power", "i64", None)])
VOTE_INFO = Desc(
    "VoteInfo",
    [(1, "validator", "msg", VALIDATOR), (2, "signed_last_block", "bool", None)],
)
LAST_COMMIT_INFO = Desc(
    "LastCommitInfo", [(1, "round", "i32", None), (2, "votes", "rep_msg", VOTE_INFO)]
)
EVIDENCE = Desc(
    "Evidence",
    [
        (1, "type", "str", None),
        (2, "validator", "msg", VALIDATOR),
        (3, "height", "i64", None),
        (4, "time", "msg", TIMESTAMP),
        (5, "total_voting_power", "i64", None),
    ],
)
KVPAIR = Desc("KVPair", [(1, "key", "bytes", None), (2, "value", "bytes", None)])
EVENT = Desc(
    "Event", [(1, "type", "str", None), (2, "attributes", "rep_msg", KVPAIR)]
)
BLOCK_PARAMS = Desc(
    "BlockParams", [(1, "max_bytes", "i64", None), (2, "max_gas", "i64", None)]
)
EVIDENCE_PARAMS = Desc("EvidenceParams", [(1, "max_age", "i64", None)])
VALIDATOR_PARAMS = Desc("ValidatorParams", [(1, "pub_key_types", "rep_str", None)])
CONSENSUS_PARAMS = Desc(
    "ConsensusParams",
    [
        (1, "block", "msg", BLOCK_PARAMS),
        (2, "evidence", "msg", EVIDENCE_PARAMS),
        (3, "validator", "msg", VALIDATOR_PARAMS),
    ],
)
VERSION = Desc("Version", [(1, "Block", "u64", None), (2, "App", "u64", None)])
PART_SET_HEADER = Desc(
    "PartSetHeader", [(1, "total", "i32", None), (2, "hash", "bytes", None)]
)
BLOCK_ID = Desc(
    "BlockID",
    [(1, "hash", "bytes", None), (2, "parts_header", "msg", PART_SET_HEADER)],
)
HEADER = Desc(
    "Header",
    [
        (1, "version", "msg", VERSION),
        (2, "chain_id", "str", None),
        (3, "height", "i64", None),
        (4, "time", "msg", TIMESTAMP),
        (5, "num_txs", "i64", None),
        (6, "total_txs", "i64", None),
        (7, "last_block_id", "msg", BLOCK_ID),
        (8, "last_commit_hash", "bytes", None),
        (9, "data_hash", "bytes", None),
        (10, "validators_hash", "bytes", None),
        (11, "next_validators_hash", "bytes", None),
        (12, "consensus_hash", "bytes", None),
        (13, "app_hash", "bytes", None),
        (14, "last_results_hash", "bytes", None),
        (15, "evidence_hash", "bytes", None),
        (16, "proposer_address", "bytes", None),
    ],
)
PROOF_OP = Desc(
    "ProofOp",
    [(1, "type", "str", None), (2, "key", "bytes", None), (3, "data", "bytes", None)],
)
PROOF = Desc("Proof", [(1, "ops", "rep_msg", PROOF_OP)])
SNAPSHOT = Desc(
    "Snapshot",
    [
        (1, "height", "u64", None),
        (2, "format", "u64", None),
        (3, "chunks", "u64", None),
        (4, "hash", "bytes", None),
        (5, "metadata", "bytes", None),
    ],
)

REQ_ECHO = Desc("RequestEcho", [(1, "message", "str", None)])
REQ_FLUSH = Desc("RequestFlush", [])
REQ_INFO = Desc(
    "RequestInfo",
    [
        (1, "version", "str", None),
        (2, "block_version", "u64", None),
        (3, "p2p_version", "u64", None),
    ],
)
REQ_SET_OPTION = Desc(
    "RequestSetOption", [(1, "key", "str", None), (2, "value", "str", None)]
)
REQ_INIT_CHAIN = Desc(
    "RequestInitChain",
    [
        (1, "time", "msg", TIMESTAMP),
        (2, "chain_id", "str", None),
        (3, "consensus_params", "msg", CONSENSUS_PARAMS),
        (4, "validators", "rep_msg", VALIDATOR_UPDATE),
        (5, "app_state_bytes", "bytes", None),
    ],
)
REQ_QUERY = Desc(
    "RequestQuery",
    [
        (1, "data", "bytes", None),
        (2, "path", "str", None),
        (3, "height", "i64", None),
        (4, "prove", "bool", None),
    ],
)
REQ_BEGIN_BLOCK = Desc(
    "RequestBeginBlock",
    [
        (1, "hash", "bytes", None),
        (2, "header", "msg", HEADER),
        (3, "last_commit_info", "msg", LAST_COMMIT_INFO),
        (4, "byzantine_validators", "rep_msg", EVIDENCE),
    ],
)
REQ_CHECK_TX = Desc(
    "RequestCheckTx", [(1, "tx", "bytes", None), (2, "type", "i32", None)]
)
# batch admission extension (docs/tx_ingestion.md) — NOT in the reference
# types.proto; `type` follows RequestCheckTx's CheckTxType enum (0 = new,
# 1 = recheck)
REQ_CHECK_TX_BATCH = Desc(
    "RequestCheckTxBatch",
    [(1, "txs", "rep_bytes", None), (2, "type", "i32", None)],
)
REQ_DELIVER_TX = Desc("RequestDeliverTx", [(1, "tx", "bytes", None)])
# batch execution extension (docs/tx_ingestion.md) — NOT in the reference
# types.proto; the execution-side twin of RequestCheckTxBatch
REQ_DELIVER_TX_BATCH = Desc(
    "RequestDeliverTxBatch", [(1, "txs", "rep_bytes", None)]
)
REQ_END_BLOCK = Desc("RequestEndBlock", [(1, "height", "i64", None)])
REQ_COMMIT = Desc("RequestCommit", [])
REQ_LIST_SNAPSHOTS = Desc("RequestListSnapshots", [])
REQ_OFFER_SNAPSHOT = Desc(
    "RequestOfferSnapshot",
    [(1, "snapshot", "msg", SNAPSHOT), (2, "app_hash", "bytes", None)],
)
REQ_LOAD_SNAPSHOT_CHUNK = Desc(
    "RequestLoadSnapshotChunk",
    [(1, "height", "u64", None), (2, "format", "u64", None), (3, "chunk", "u64", None)],
)
REQ_APPLY_SNAPSHOT_CHUNK = Desc(
    "RequestApplySnapshotChunk",
    [(1, "index", "u64", None), (2, "chunk", "bytes", None), (3, "sender", "str", None)],
)

RESP_EXCEPTION = Desc("ResponseException", [(1, "error", "str", None)])
RESP_ECHO = Desc("ResponseEcho", [(1, "message", "str", None)])
RESP_FLUSH = Desc("ResponseFlush", [])
RESP_INFO = Desc(
    "ResponseInfo",
    [
        (1, "data", "str", None),
        (2, "version", "str", None),
        (3, "app_version", "u64", None),
        (4, "last_block_height", "i64", None),
        (5, "last_block_app_hash", "bytes", None),
    ],
)
RESP_SET_OPTION = Desc(
    "ResponseSetOption",
    [(1, "code", "u64", None), (3, "log", "str", None), (4, "info", "str", None)],
)
RESP_INIT_CHAIN = Desc(
    "ResponseInitChain",
    [
        (1, "consensus_params", "msg", CONSENSUS_PARAMS),
        (2, "validators", "rep_msg", VALIDATOR_UPDATE),
    ],
)
RESP_QUERY = Desc(
    "ResponseQuery",
    [
        (1, "code", "u64", None),
        (3, "log", "str", None),
        (4, "info", "str", None),
        (5, "index", "i64", None),
        (6, "key", "bytes", None),
        (7, "value", "bytes", None),
        (8, "proof", "msg", PROOF),
        (9, "height", "i64", None),
        (10, "codespace", "str", None),
    ],
)
RESP_BEGIN_BLOCK = Desc("ResponseBeginBlock", [(1, "events", "rep_msg", EVENT)])
_TX_RESULT_FIELDS = [
    (1, "code", "u64", None),
    (2, "data", "bytes", None),
    (3, "log", "str", None),
    (4, "info", "str", None),
    (5, "gas_wanted", "i64", None),
    (6, "gas_used", "i64", None),
    (7, "events", "rep_msg", EVENT),
    (8, "codespace", "str", None),
]
RESP_CHECK_TX = Desc("ResponseCheckTx", list(_TX_RESULT_FIELDS))
RESP_CHECK_TX_BATCH = Desc(
    "ResponseCheckTxBatch", [(1, "responses", "rep_msg", RESP_CHECK_TX)]
)
RESP_DELIVER_TX = Desc("ResponseDeliverTx", list(_TX_RESULT_FIELDS))
RESP_DELIVER_TX_BATCH = Desc(
    "ResponseDeliverTxBatch", [(1, "responses", "rep_msg", RESP_DELIVER_TX)]
)
RESP_END_BLOCK = Desc(
    "ResponseEndBlock",
    [
        (1, "validator_updates", "rep_msg", VALIDATOR_UPDATE),
        (2, "consensus_param_updates", "msg", CONSENSUS_PARAMS),
        (3, "events", "rep_msg", EVENT),
    ],
)
RESP_COMMIT = Desc(
    "ResponseCommit",
    [(2, "data", "bytes", None), (3, "retain_height", "i64", None)],
)
RESP_LIST_SNAPSHOTS = Desc(
    "ResponseListSnapshots", [(1, "snapshots", "rep_msg", SNAPSHOT)]
)
RESP_OFFER_SNAPSHOT = Desc("ResponseOfferSnapshot", [(1, "result", "u64", None)])
RESP_LOAD_SNAPSHOT_CHUNK = Desc(
    "ResponseLoadSnapshotChunk", [(1, "chunk", "bytes", None)]
)
RESP_APPLY_SNAPSHOT_CHUNK = Desc(
    "ResponseApplySnapshotChunk",
    [
        (1, "result", "u64", None),
        (2, "refetch_chunks", "rep_u64", None),
        (3, "reject_senders", "rep_str", None),
    ],
)


# ------------------------------------------------------- value converters


def _ns_to_ts(ns: int) -> dict:
    return {"seconds": ns // 1_000_000_000, "nanos": ns % 1_000_000_000}


def _ts_to_ns(ts: dict | None) -> int:
    if not ts:
        return 0
    return ts.get("seconds", 0) * 1_000_000_000 + ts.get("nanos", 0)


def _pubkey_to_proto(enc: bytes) -> dict:
    from tendermint_tpu.crypto import decode_pubkey
    from tendermint_tpu.crypto.ed25519 import PubKeyEd25519

    pk = decode_pubkey(enc)
    type_ = "ed25519" if isinstance(pk, PubKeyEd25519) else "secp256k1"
    return {"type": type_, "data": pk.bytes()}


def _pubkey_from_proto(v: dict | None) -> bytes:
    from tendermint_tpu.crypto import encode_pubkey
    from tendermint_tpu.crypto.ed25519 import PubKeyEd25519
    from tendermint_tpu.crypto.secp256k1 import PubKeySecp256k1

    if not v:
        return b""
    data = v.get("data", b"")
    if v.get("type", "ed25519") == "ed25519":
        return encode_pubkey(PubKeyEd25519(data))
    return encode_pubkey(PubKeySecp256k1(data))


def _vu_to_proto(u: abci.ValidatorUpdate) -> dict:
    return {"pub_key": _pubkey_to_proto(u.pub_key), "power": u.power}


def _vu_from_proto(v: dict) -> abci.ValidatorUpdate:
    return abci.ValidatorUpdate(_pubkey_from_proto(v.get("pub_key")), v.get("power", 0))


def _params_to_proto(enc: bytes) -> dict | None:
    from tendermint_tpu.types.params import ConsensusParams

    if not enc:
        return None
    p = ConsensusParams.decode(enc)
    return {
        "block": {"max_bytes": p.block.max_bytes, "max_gas": p.block.max_gas},
        "evidence": {"max_age": p.evidence.max_age},
        "validator": {"pub_key_types": list(p.validator.pub_key_types)},
    }


def _params_from_proto(v: dict | None) -> bytes:
    from tendermint_tpu.types.params import (
        BlockParams,
        ConsensusParams,
        EvidenceParams,
        ValidatorParams,
    )

    if not v:
        return b""
    b = v.get("block") or {}
    e = v.get("evidence") or {}
    val = v.get("validator") or {}
    defaults = ConsensusParams()
    return ConsensusParams(
        block=BlockParams(
            max_bytes=b.get("max_bytes", defaults.block.max_bytes),
            max_gas=b.get("max_gas", defaults.block.max_gas),
        ),
        evidence=EvidenceParams(max_age=e.get("max_age", defaults.evidence.max_age)),
        validator=ValidatorParams(
            pub_key_types=tuple(val.get("pub_key_types", ("ed25519",)))
        ),
    ).encode()


def _header_to_proto(enc: bytes) -> dict | None:
    from tendermint_tpu.types.block import Header

    if not enc:
        return None
    h = Header.decode(enc)
    return {
        "version": {"Block": h.version.block, "App": h.version.app},
        "chain_id": h.chain_id,
        "height": h.height,
        "time": _ns_to_ts(h.time),
        "num_txs": h.num_txs,
        "total_txs": h.total_txs,
        "last_block_id": {
            "hash": h.last_block_id.hash,
            "parts_header": {
                "total": h.last_block_id.parts.total,
                "hash": h.last_block_id.parts.hash,
            },
        },
        "last_commit_hash": h.last_commit_hash,
        "data_hash": h.data_hash,
        "validators_hash": h.validators_hash,
        "next_validators_hash": h.next_validators_hash,
        "consensus_hash": h.consensus_hash,
        "app_hash": h.app_hash,
        "last_results_hash": h.last_results_hash,
        "evidence_hash": h.evidence_hash,
        "proposer_address": h.proposer_address,
    }


def _header_from_proto(v: dict | None) -> bytes:
    from tendermint_tpu.types.block import Header, Version
    from tendermint_tpu.types.part_set import PartSetHeader
    from tendermint_tpu.types.vote import BlockID

    if not v:
        return b""
    ver = v.get("version") or {}
    bid = v.get("last_block_id") or {}
    psh = bid.get("parts_header") or {}
    return Header(
        version=Version(ver.get("Block", 0), ver.get("App", 0)),
        chain_id=v.get("chain_id", ""),
        height=v.get("height", 0),
        time=_ts_to_ns(v.get("time")),
        num_txs=v.get("num_txs", 0),
        total_txs=v.get("total_txs", 0),
        last_block_id=BlockID(
            bid.get("hash", b""),
            PartSetHeader(psh.get("total", 0), psh.get("hash", b"")),
        ),
        last_commit_hash=v.get("last_commit_hash", b""),
        data_hash=v.get("data_hash", b""),
        validators_hash=v.get("validators_hash", b""),
        next_validators_hash=v.get("next_validators_hash", b""),
        consensus_hash=v.get("consensus_hash", b""),
        app_hash=v.get("app_hash", b""),
        last_results_hash=v.get("last_results_hash", b""),
        evidence_hash=v.get("evidence_hash", b""),
        proposer_address=v.get("proposer_address", b""),
    ).encode()


def _events_to_proto(events: dict[str, list[str]]) -> list[dict]:
    """dict["type.key"] -> Event{type, attributes=[{key, value}]} groups."""
    by_type: dict[str, list[dict]] = {}
    for compound in sorted(events):
        type_, _, key = compound.partition(".")
        for val in events[compound]:
            by_type.setdefault(type_, []).append(
                {"key": key.encode(), "value": val.encode()}
            )
    return [{"type": t, "attributes": attrs} for t, attrs in by_type.items()]


def _events_from_proto(evs: list[dict] | None) -> dict[str, list[str]]:
    out: dict[str, list[str]] = {}
    for ev in evs or []:
        type_ = ev.get("type", "")
        for attr in ev.get("attributes", []):
            key = attr.get("key", b"").decode("utf-8", "replace")
            compound = f"{type_}.{key}" if key else type_
            out.setdefault(compound, []).append(
                attr.get("value", b"").decode("utf-8", "replace")
            )
    return out


def _snapshot_to_proto(s: "abci.Snapshot") -> dict:
    return {
        "height": s.height,
        "format": s.format,
        "chunks": s.chunks,
        "hash": s.hash,
        "metadata": s.metadata,
    }


def _snapshot_from_proto(v: dict | None) -> "abci.Snapshot":
    v = v or {}
    return abci.Snapshot(
        height=v.get("height", 0),
        format=v.get("format", 0),
        chunks=v.get("chunks", 0),
        hash=v.get("hash", b""),
        metadata=v.get("metadata", b""),
    )


def _proof_to_proto(ops: list) -> dict | None:
    if not ops:
        return None
    return {"ops": [{"type": op.type, "key": op.key, "data": op.data} for op in ops]}


def _proof_from_proto(v: dict | None) -> list:
    from tendermint_tpu.crypto.merkle import ProofOp

    if not v:
        return []
    return [
        ProofOp(o.get("type", ""), o.get("key", b""), o.get("data", b""))
        for o in v.get("ops", [])
    ]


# -------------------------------------------------- dataclass <-> dict
#
# Each entry: dataclass -> (oneof field number, Desc, to_dict, from_dict).


def _checktx_to_proto(o: "abci.ResponseCheckTx") -> dict:
    """Shared by the ResponseCheckTx arm and each batch-response item."""
    return {
        "code": o.code,
        "data": o.data,
        "log": o.log,
        "info": o.info,
        "gas_wanted": o.gas_wanted,
        "gas_used": o.gas_used,
        "events": _events_to_proto(o.events),
        "codespace": o.codespace,
    }


def _checktx_from_proto(v: dict) -> "abci.ResponseCheckTx":
    return abci.ResponseCheckTx(
        code=v.get("code", 0),
        data=v.get("data", b""),
        log=v.get("log", ""),
        info=v.get("info", ""),
        gas_wanted=v.get("gas_wanted", 0),
        gas_used=v.get("gas_used", 0),
        events=_events_from_proto(v.get("events")),
        codespace=v.get("codespace", ""),
    )


def _delivertx_to_proto(o: "abci.ResponseDeliverTx") -> dict:
    """Shared by the ResponseDeliverTx arm and each batch-response item."""
    return {
        "code": o.code,
        "data": o.data,
        "log": o.log,
        "info": o.info,
        "gas_wanted": o.gas_wanted,
        "gas_used": o.gas_used,
        "events": _events_to_proto(o.events),
        "codespace": o.codespace,
    }


def _delivertx_from_proto(v: dict) -> "abci.ResponseDeliverTx":
    return abci.ResponseDeliverTx(
        code=v.get("code", 0),
        data=v.get("data", b""),
        log=v.get("log", ""),
        info=v.get("info", ""),
        gas_wanted=v.get("gas_wanted", 0),
        gas_used=v.get("gas_used", 0),
        events=_events_from_proto(v.get("events")),
        codespace=v.get("codespace", ""),
    )


def _mk(cls, attrs_defaults: list[tuple[str, Any]]):
    def from_dict(v: dict):
        return cls(**{a: v.get(a, d) for a, d in attrs_defaults})

    return from_dict


_REQ_MAP: list[tuple[int, type, Desc, Callable, Callable]] = [
    (
        2,
        abci.RequestEcho,
        REQ_ECHO,
        lambda o: {"message": o.message},
        _mk(abci.RequestEcho, [("message", "")]),
    ),
    (3, abci.RequestFlush, REQ_FLUSH, lambda o: {}, lambda v: abci.RequestFlush()),
    (
        4,
        abci.RequestInfo,
        REQ_INFO,
        lambda o: {
            "version": o.version,
            "block_version": o.block_version,
            "p2p_version": o.p2p_version,
        },
        _mk(
            abci.RequestInfo,
            [("version", ""), ("block_version", 0), ("p2p_version", 0)],
        ),
    ),
    (
        5,
        abci.RequestSetOption,
        REQ_SET_OPTION,
        lambda o: {"key": o.key, "value": o.value},
        _mk(abci.RequestSetOption, [("key", ""), ("value", "")]),
    ),
    (
        6,
        abci.RequestInitChain,
        REQ_INIT_CHAIN,
        lambda o: {
            "time": _ns_to_ts(o.time) if o.time else None,
            "chain_id": o.chain_id,
            "consensus_params": _params_to_proto(o.consensus_params),
            "validators": [_vu_to_proto(u) for u in o.validators],
            "app_state_bytes": o.app_state_bytes,
        },
        lambda v: abci.RequestInitChain(
            time=_ts_to_ns(v.get("time")),
            chain_id=v.get("chain_id", ""),
            consensus_params=_params_from_proto(v.get("consensus_params")),
            validators=[_vu_from_proto(u) for u in v.get("validators", [])],
            app_state_bytes=v.get("app_state_bytes", b""),
        ),
    ),
    (
        7,
        abci.RequestQuery,
        REQ_QUERY,
        lambda o: {
            "data": o.data,
            "path": o.path,
            "height": o.height,
            "prove": o.prove,
        },
        _mk(
            abci.RequestQuery,
            [("data", b""), ("path", ""), ("height", 0), ("prove", False)],
        ),
    ),
    (
        8,
        abci.RequestBeginBlock,
        REQ_BEGIN_BLOCK,
        lambda o: {
            "hash": o.hash,
            "header": _header_to_proto(o.header),
            "last_commit_info": {
                "round": 0,
                "votes": [
                    {
                        "validator": {"address": vi.address, "power": vi.power},
                        "signed_last_block": vi.signed_last_block,
                    }
                    for vi in o.last_commit_votes
                ]
                or None,
            },
            "byzantine_validators": [
                {
                    "type": ev.type,
                    "validator": {"address": ev.address},
                    "height": ev.height,
                    "total_voting_power": ev.total_voting_power,
                }
                for ev in o.byzantine_validators
            ],
        },
        lambda v: abci.RequestBeginBlock(
            hash=v.get("hash", b""),
            header=_header_from_proto(v.get("header")),
            last_commit_votes=[
                abci.VoteInfo(
                    address=(vi.get("validator") or {}).get("address", b""),
                    power=(vi.get("validator") or {}).get("power", 0),
                    signed_last_block=vi.get("signed_last_block", False),
                )
                for vi in (v.get("last_commit_info") or {}).get("votes", [])
            ],
            byzantine_validators=[
                abci.EvidenceInfo(
                    type=ev.get("type", ""),
                    address=(ev.get("validator") or {}).get("address", b""),
                    height=ev.get("height", 0),
                    total_voting_power=ev.get("total_voting_power", 0),
                )
                for ev in v.get("byzantine_validators", [])
            ],
        ),
    ),
    (
        9,
        abci.RequestCheckTx,
        REQ_CHECK_TX,
        lambda o: {"tx": o.tx, "type": 0 if o.new_check else 1},
        lambda v: abci.RequestCheckTx(
            tx=v.get("tx", b""), new_check=v.get("type", 0) == 0
        ),
    ),
    # batch admission extension — oneof number 20 is past every arm the
    # v0.34 reference schema uses, so a reference peer treats it as an
    # unknown field (empty oneof -> exception response, clean fallback)
    (
        20,
        abci.RequestCheckTxBatch,
        REQ_CHECK_TX_BATCH,
        lambda o: {"txs": list(o.txs), "type": 0 if o.new_check else 1},
        lambda v: abci.RequestCheckTxBatch(
            txs=list(v.get("txs", [])), new_check=v.get("type", 0) == 0
        ),
    ),
    (
        19,
        abci.RequestDeliverTx,
        REQ_DELIVER_TX,
        lambda o: {"tx": o.tx},
        _mk(abci.RequestDeliverTx, [("tx", b"")]),
    ),
    # batch execution extension — oneof number 21 is past every arm the
    # v0.34 reference schema uses (20 = CheckTxBatch), so a reference peer
    # treats it as an unknown field (empty oneof -> exception response,
    # clean fallback)
    (
        21,
        abci.RequestDeliverTxBatch,
        REQ_DELIVER_TX_BATCH,
        lambda o: {"txs": list(o.txs)},
        lambda v: abci.RequestDeliverTxBatch(txs=list(v.get("txs", []))),
    ),
    (
        11,
        abci.RequestEndBlock,
        REQ_END_BLOCK,
        lambda o: {"height": o.height},
        _mk(abci.RequestEndBlock, [("height", 0)]),
    ),
    (12, abci.RequestCommit, REQ_COMMIT, lambda o: {}, lambda v: abci.RequestCommit()),
    # state-sync methods (v0.34 oneof numbering — new relative to the
    # /root/reference schema, which predates ABCI snapshots)
    (
        13,
        abci.RequestListSnapshots,
        REQ_LIST_SNAPSHOTS,
        lambda o: {},
        lambda v: abci.RequestListSnapshots(),
    ),
    (
        14,
        abci.RequestOfferSnapshot,
        REQ_OFFER_SNAPSHOT,
        lambda o: {
            "snapshot": _snapshot_to_proto(o.snapshot),
            "app_hash": o.app_hash,
        },
        lambda v: abci.RequestOfferSnapshot(
            snapshot=_snapshot_from_proto(v.get("snapshot")),
            app_hash=v.get("app_hash", b""),
        ),
    ),
    (
        15,
        abci.RequestLoadSnapshotChunk,
        REQ_LOAD_SNAPSHOT_CHUNK,
        lambda o: {"height": o.height, "format": o.format, "chunk": o.chunk},
        _mk(
            abci.RequestLoadSnapshotChunk,
            [("height", 0), ("format", 0), ("chunk", 0)],
        ),
    ),
    (
        16,
        abci.RequestApplySnapshotChunk,
        REQ_APPLY_SNAPSHOT_CHUNK,
        lambda o: {"index": o.index, "chunk": o.chunk, "sender": o.sender},
        _mk(
            abci.RequestApplySnapshotChunk,
            [("index", 0), ("chunk", b""), ("sender", "")],
        ),
    ),
]

_RESP_MAP: list[tuple[int, type, Desc, Callable, Callable]] = [
    (
        1,
        abci.ResponseException,
        RESP_EXCEPTION,
        lambda o: {"error": o.error},
        _mk(abci.ResponseException, [("error", "")]),
    ),
    (
        2,
        abci.ResponseEcho,
        RESP_ECHO,
        lambda o: {"message": o.message},
        _mk(abci.ResponseEcho, [("message", "")]),
    ),
    (3, abci.ResponseFlush, RESP_FLUSH, lambda o: {}, lambda v: abci.ResponseFlush()),
    (
        4,
        abci.ResponseInfo,
        RESP_INFO,
        lambda o: {
            "data": o.data,
            "version": o.version,
            "app_version": o.app_version,
            "last_block_height": o.last_block_height,
            "last_block_app_hash": o.last_block_app_hash,
        },
        _mk(
            abci.ResponseInfo,
            [
                ("data", ""),
                ("version", ""),
                ("app_version", 0),
                ("last_block_height", 0),
                ("last_block_app_hash", b""),
            ],
        ),
    ),
    (
        5,
        abci.ResponseSetOption,
        RESP_SET_OPTION,
        lambda o: {"code": o.code, "log": o.log, "info": o.info},
        _mk(abci.ResponseSetOption, [("code", 0), ("log", ""), ("info", "")]),
    ),
    (
        6,
        abci.ResponseInitChain,
        RESP_INIT_CHAIN,
        lambda o: {
            "consensus_params": _params_to_proto(o.consensus_params),
            "validators": [_vu_to_proto(u) for u in o.validators],
        },
        lambda v: abci.ResponseInitChain(
            consensus_params=_params_from_proto(v.get("consensus_params")),
            validators=[_vu_from_proto(u) for u in v.get("validators", [])],
        ),
    ),
    (
        7,
        abci.ResponseQuery,
        RESP_QUERY,
        lambda o: {
            "code": o.code,
            "log": o.log,
            "info": o.info,
            "index": o.index,
            "key": o.key,
            "value": o.value,
            "proof": _proof_to_proto(o.proof_ops),
            "height": o.height,
            "codespace": o.codespace,
        },
        lambda v: abci.ResponseQuery(
            code=v.get("code", 0),
            log=v.get("log", ""),
            info=v.get("info", ""),
            index=v.get("index", 0),
            key=v.get("key", b""),
            value=v.get("value", b""),
            proof_ops=_proof_from_proto(v.get("proof")),
            height=v.get("height", 0),
            codespace=v.get("codespace", ""),
        ),
    ),
    (
        8,
        abci.ResponseBeginBlock,
        RESP_BEGIN_BLOCK,
        lambda o: {"events": _events_to_proto(o.events)},
        lambda v: abci.ResponseBeginBlock(events=_events_from_proto(v.get("events"))),
    ),
    (
        9,
        abci.ResponseCheckTx,
        RESP_CHECK_TX,
        _checktx_to_proto,
        _checktx_from_proto,
    ),
    # batch admission extension (pairs with RequestCheckTxBatch arm 20)
    (
        18,
        abci.ResponseCheckTxBatch,
        RESP_CHECK_TX_BATCH,
        lambda o: {"responses": [_checktx_to_proto(r) for r in o.responses]},
        lambda v: abci.ResponseCheckTxBatch(
            responses=[_checktx_from_proto(r) for r in v.get("responses", [])]
        ),
    ),
    (
        10,
        abci.ResponseDeliverTx,
        RESP_DELIVER_TX,
        _delivertx_to_proto,
        _delivertx_from_proto,
    ),
    # batch execution extension (pairs with RequestDeliverTxBatch arm 21)
    (
        19,
        abci.ResponseDeliverTxBatch,
        RESP_DELIVER_TX_BATCH,
        lambda o: {"responses": [_delivertx_to_proto(r) for r in o.responses]},
        lambda v: abci.ResponseDeliverTxBatch(
            responses=[_delivertx_from_proto(r) for r in v.get("responses", [])]
        ),
    ),
    (
        11,
        abci.ResponseEndBlock,
        RESP_END_BLOCK,
        lambda o: {
            "validator_updates": [_vu_to_proto(u) for u in o.validator_updates],
            "consensus_param_updates": _params_to_proto(o.consensus_param_updates),
            "events": _events_to_proto(o.events),
        },
        lambda v: abci.ResponseEndBlock(
            validator_updates=[
                _vu_from_proto(u) for u in v.get("validator_updates", [])
            ],
            consensus_param_updates=_params_from_proto(
                v.get("consensus_param_updates")
            ),
            events=_events_from_proto(v.get("events")),
        ),
    ),
    (
        12,
        abci.ResponseCommit,
        RESP_COMMIT,
        lambda o: {"data": o.data, "retain_height": o.retain_height},
        _mk(abci.ResponseCommit, [("data", b""), ("retain_height", 0)]),
    ),
    (
        14,
        abci.ResponseListSnapshots,
        RESP_LIST_SNAPSHOTS,
        lambda o: {"snapshots": [_snapshot_to_proto(s) for s in o.snapshots]},
        lambda v: abci.ResponseListSnapshots(
            snapshots=[_snapshot_from_proto(s) for s in v.get("snapshots", [])]
        ),
    ),
    (
        15,
        abci.ResponseOfferSnapshot,
        RESP_OFFER_SNAPSHOT,
        lambda o: {"result": o.result},
        _mk(abci.ResponseOfferSnapshot, [("result", 0)]),
    ),
    (
        16,
        abci.ResponseLoadSnapshotChunk,
        RESP_LOAD_SNAPSHOT_CHUNK,
        lambda o: {"chunk": o.chunk},
        _mk(abci.ResponseLoadSnapshotChunk, [("chunk", b"")]),
    ),
    (
        17,
        abci.ResponseApplySnapshotChunk,
        RESP_APPLY_SNAPSHOT_CHUNK,
        lambda o: {
            "result": o.result,
            "refetch_chunks": list(o.refetch_chunks),
            "reject_senders": list(o.reject_senders),
        },
        lambda v: abci.ResponseApplySnapshotChunk(
            result=v.get("result", 0),
            refetch_chunks=[int(x) for x in v.get("refetch_chunks", [])],
            reject_senders=list(v.get("reject_senders", [])),
        ),
    ),
]


def _encode_oneof(obj, mapping) -> bytes:
    for num, cls, desc, to_dict, _ in mapping:
        if isinstance(obj, cls):
            inner = desc.encode({k: v for k, v in to_dict(obj).items() if v is not None})
            return encode_uvarint(num << 3 | 2) + encode_uvarint(len(inner)) + inner
    raise DecodeError(f"no proto mapping for {type(obj).__name__}")


def _decode_oneof(data: bytes, mapping):
    pos = 0
    result = None
    while pos < len(data):
        tag, pos = decode_uvarint(data, pos)
        num, wt = tag >> 3, tag & 7
        if wt != 2:
            raise DecodeError(f"oneof: unexpected wire type {wt}")
        ln, pos = decode_uvarint(data, pos)
        if pos + ln > len(data):
            raise DecodeError(f"oneof: truncated arm {num} ({ln} bytes claimed)")
        payload = data[pos : pos + ln]
        pos += ln
        for mnum, _, desc, _, from_dict in mapping:
            if mnum == num:
                result = from_dict(desc.decode(payload))
                break
    if result is None:
        raise DecodeError("empty/unknown oneof message")
    return result


# --------------------------------------------------------- bare messages
#
# The reference's gRPC services carry the per-method messages DIRECTLY
# (service ABCIApplication in abci/types/types.proto:332 — `rpc
# Echo(RequestEcho) returns (ResponseEcho)`), not the Request/Response
# oneof envelope the socket protocol frames. These helpers (de)serialize
# that bare form so the gRPC transport can reuse this codec.

_BARE: dict[str, tuple[type, "Desc", Callable, Callable]] = {}
for _mapping in (_REQ_MAP, _RESP_MAP):
    for _num, _cls, _desc, _to, _from in _mapping:
        _BARE[_desc.name] = (_cls, _desc, _to, _from)
del _mapping, _num, _cls, _desc, _to, _from


def encode_bare(obj) -> bytes:
    """Serialize one Request*/Response* object as a bare protobuf message
    (gRPC body format — no oneof envelope, no length framing)."""
    name = type(obj).__name__
    entry = _BARE.get(name)
    if entry is None or not isinstance(obj, entry[0]):
        raise DecodeError(f"no bare proto mapping for {name}")
    _, desc, to_dict, _ = entry
    return desc.encode(
        {k: v for k, v in to_dict(obj).items() if v is not None}
    )


def decode_bare(name: str, data: bytes):
    """Decode a bare protobuf message by its schema name (e.g.
    "RequestEcho") into the corresponding abci types object."""
    entry = _BARE.get(name)
    if entry is None:
        raise DecodeError(f"unknown bare message {name}")
    _, desc, _, from_dict = entry
    return as_decode_error(lambda d: from_dict(desc.decode(d)), data, name)


def encode_request(req) -> bytes:
    return _encode_oneof(req, _REQ_MAP)


def decode_request(data: bytes):
    return as_decode_error(lambda d: _decode_oneof(d, _REQ_MAP), data, "request")


def encode_response(resp) -> bytes:
    return _encode_oneof(resp, _RESP_MAP)


def decode_response(data: bytes):
    return as_decode_error(lambda d: _decode_oneof(d, _RESP_MAP), data, "response")


# ---------------------------------------------------------------- framing


def frame(payload: bytes) -> bytes:
    """Reference framing: SIGNED (zigzag) varint length + protobuf bytes
    (abci/types/messages.go:54 uses binary.PutVarint, not PutUvarint)."""
    return encode_svarint(len(payload)) + payload


async def read_frame(reader) -> bytes:
    """Read one zigzag-varint-length-prefixed message from an asyncio
    stream. Raises asyncio.IncompleteReadError at clean EOF."""
    raw = 0
    shift = 0
    while True:
        b = (await reader.readexactly(1))[0]
        raw |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise DecodeError("frame varint too long")
    ln = (raw >> 1) ^ -(raw & 1)  # zigzag decode
    if ln < 0 or ln > MAX_MSG_SIZE:
        raise DecodeError(f"bad frame length {ln}")
    return await reader.readexactly(ln)
