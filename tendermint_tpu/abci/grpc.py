"""ABCI over gRPC — client, server, and the GRPCApplication adapter.

Reference parity: abci/client/grpc_client.go, abci/server/grpc_server.go,
abci/types/application.go:78 (GRPCApplication). Selectable exactly like the
reference: `--abci grpc` on the node / `abci-cli --abci grpc`, or a
`grpc://host:port` proxy_app address.

Wire format — the server registers BOTH services (generic raw-bytes
method handlers; grpcio-tools/protoc codegen is not in the image):

- /types.ABCIApplication/<Method> — the reference's actual service path
  (types.proto `package types`, service at abci/types/types.proto:332)
  with bare per-method PROTOBUF bodies (`rpc Echo(RequestEcho) returns
  (ResponseEcho)` — no oneof envelope), via abci/proto.py's codec. An
  unmodified reference-built gRPC app/client connects here.
- /tendermint.abci.types.ABCIApplication/<Method> — this repo's earlier
  CBE-bodied surface, kept for in-repo compatibility.

The client picks by `codec`: "proto" (default — talks to either this
server or a reference one) or "cbe" (legacy path).
"""
from __future__ import annotations

import asyncio

import grpc
import grpc.aio

from tendermint_tpu.abci import proto as pb
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import ABCIClientError, Client
from tendermint_tpu.abci.types import (
    decode_request,
    decode_response,
    encode_request,
    encode_response,
)
from tendermint_tpu.libs.service import BaseService

SERVICE = "tendermint.abci.types.ABCIApplication"  # legacy CBE bodies
SERVICE_PROTO = "types.ABCIApplication"  # reference path, protobuf bodies

# method name -> request class (reference types.proto service methods)
_METHODS = {
    "Echo": abci.RequestEcho,
    "Flush": abci.RequestFlush,
    "Info": abci.RequestInfo,
    "SetOption": abci.RequestSetOption,
    "DeliverTx": abci.RequestDeliverTx,
    "DeliverTxBatch": abci.RequestDeliverTxBatch,
    "CheckTx": abci.RequestCheckTx,
    "CheckTxBatch": abci.RequestCheckTxBatch,
    "Query": abci.RequestQuery,
    "Commit": abci.RequestCommit,
    "InitChain": abci.RequestInitChain,
    "BeginBlock": abci.RequestBeginBlock,
    "EndBlock": abci.RequestEndBlock,
    "ListSnapshots": abci.RequestListSnapshots,
    "OfferSnapshot": abci.RequestOfferSnapshot,
    "LoadSnapshotChunk": abci.RequestLoadSnapshotChunk,
    "ApplySnapshotChunk": abci.RequestApplySnapshotChunk,
}


class GRPCApplication:
    """Reference abci/types/application.go:78 — wraps an Application so
    each ABCI call is a unary gRPC method. Echo/Flush are handled here (the
    Application interface does not carry them)."""

    def __init__(self, app: abci.Application) -> None:
        self.app = app

    def handle(self, req):
        a = self.app
        if isinstance(req, abci.RequestEcho):
            return abci.ResponseEcho(req.message)
        if isinstance(req, abci.RequestFlush):
            return abci.ResponseFlush()
        if isinstance(req, abci.RequestInfo):
            return a.info(req)
        if isinstance(req, abci.RequestSetOption):
            return a.set_option(req)
        if isinstance(req, abci.RequestInitChain):
            return a.init_chain(req)
        if isinstance(req, abci.RequestQuery):
            return a.query(req)
        if isinstance(req, abci.RequestBeginBlock):
            return a.begin_block(req)
        if isinstance(req, abci.RequestCheckTx):
            return a.check_tx(req)
        if isinstance(req, abci.RequestCheckTxBatch):
            return a.check_tx_batch(req)
        if isinstance(req, abci.RequestDeliverTx):
            return a.deliver_tx(req)
        if isinstance(req, abci.RequestDeliverTxBatch):
            return a.deliver_tx_batch(req)
        if isinstance(req, abci.RequestEndBlock):
            return a.end_block(req)
        if isinstance(req, abci.RequestCommit):
            return a.commit()
        if isinstance(req, abci.RequestListSnapshots):
            return a.list_snapshots(req)
        if isinstance(req, abci.RequestOfferSnapshot):
            return a.offer_snapshot(req)
        if isinstance(req, abci.RequestLoadSnapshotChunk):
            return a.load_snapshot_chunk(req)
        if isinstance(req, abci.RequestApplySnapshotChunk):
            return a.apply_snapshot_chunk(req)
        raise ValueError(f"unknown request {req!r}")


class GRPCABCIServer(BaseService):
    """Reference abci/server/grpc_server.go — serves a GRPCApplication."""

    def __init__(self, app: abci.Application, address: str) -> None:
        super().__init__("GRPCABCIServer")
        self.wrapped = GRPCApplication(app)
        self.address = address.replace("grpc://", "").replace("tcp://", "")
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None

    async def on_start(self) -> None:
        self._server = grpc.aio.server()
        cbe_handlers = {}
        proto_handlers = {}
        for name in _METHODS:
            cbe_handlers[name] = grpc.unary_unary_rpc_method_handler(
                self._make_handler(),
                request_deserializer=None,
                response_serializer=None,
            )
            proto_handlers[name] = grpc.unary_unary_rpc_method_handler(
                self._make_proto_handler(name),
                request_deserializer=None,
                response_serializer=None,
            )
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(SERVICE, cbe_handlers),
                grpc.method_handlers_generic_handler(
                    SERVICE_PROTO, proto_handlers
                ),
            )
        )
        self.port = self._server.add_insecure_port(self.address)
        await self._server.start()

    def _make_handler(self):
        wrapped = self.wrapped

        async def handler(request: bytes, context) -> bytes:
            try:
                req = decode_request(request)
                resp = wrapped.handle(req)
            except Exception as e:  # noqa: BLE001 — app panic -> exception resp
                resp = abci.ResponseException(str(e))
            return encode_response(resp)

        return handler

    def _make_proto_handler(self, name: str):
        """Reference-wire handler: bare protobuf bodies. The method name
        fixes the request type (RequestEcho for Echo, ...); app faults
        become gRPC status errors — the proto service has no
        ResponseException arm per method (types.proto:332)."""
        wrapped = self.wrapped
        req_name = f"Request{name}"

        async def handler(request: bytes, context) -> bytes:
            try:
                req = pb.decode_bare(req_name, request)
            except Exception as e:  # noqa: BLE001 — malformed bytes
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, f"bad {req_name}: {e}"
                )
            try:
                resp = wrapped.handle(req)
            except Exception as e:  # noqa: BLE001 — app panic
                await context.abort(grpc.StatusCode.UNKNOWN, str(e))
            return pb.encode_bare(resp)

        return handler

    async def on_stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.5)


class GRPCClient(Client):
    """Reference abci/client/grpc_client.go — the ABCI client over gRPC.

    ABCI requires DeliverTx calls to reach the app in block order, and
    grpc.aio gives no cross-RPC execution-order guarantee, so every request
    goes through ONE ordered worker (the reference funnels through a single
    request queue for the same reason, grpc_client.go). *_async returns a
    future like the socket client's pipelined sends."""

    def __init__(self, address: str, codec: str = "proto") -> None:
        super().__init__("GRPCABCIClient")
        self.address = address.replace("grpc://", "").replace("tcp://", "")
        if codec not in ("proto", "cbe"):
            raise ValueError(f"unknown grpc codec {codec!r}")
        self.codec = codec
        self._channel: grpc.aio.Channel | None = None
        self._fns: dict = {}
        self._queue: asyncio.Queue = asyncio.Queue()

    async def on_start(self) -> None:
        self._channel = grpc.aio.insecure_channel(self.address)
        service = SERVICE_PROTO if self.codec == "proto" else SERVICE
        for name in _METHODS:
            self._fns[name] = self._channel.unary_unary(
                f"/{service}/{name}",
                request_serializer=None,
                response_deserializer=None,
            )
        self.spawn(self._send_routine(), "grpc-abci-send")

    async def on_stop(self) -> None:
        if self._channel is not None:
            await self._channel.close()

    async def _send_routine(self) -> None:
        """Ordered execution of queued requests."""
        while True:
            method, req, fut = await self._queue.get()
            if fut.done():  # caller gave up
                continue
            try:
                if self.codec == "proto":
                    payload = await self._fns[method](pb.encode_bare(req))
                    resp = pb.decode_bare(f"Response{method}", payload)
                else:
                    payload = await self._fns[method](encode_request(req))
                    resp = decode_response(payload)
            except grpc.aio.AioRpcError as e:
                fut.set_exception(
                    ABCIClientError(f"grpc: {e.code().name}: {e.details()}")
                )
                continue
            except Exception as e:  # noqa: BLE001
                fut.set_exception(ABCIClientError(str(e)))
                continue
            if isinstance(resp, abci.ResponseException):
                fut.set_exception(ABCIClientError(resp.error))
            else:
                fut.set_result(resp)

    def _enqueue(self, method: str, req) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._queue.put_nowait((method, req, fut))
        return fut

    async def _call(self, method: str, req) -> object:
        return await self._enqueue(method, req)

    async def echo(self, message: str):
        return await self._call("Echo", abci.RequestEcho(message))

    async def info(self, req):
        return await self._call("Info", req)

    async def set_option(self, req):
        return await self._call("SetOption", req)

    async def query(self, req):
        return await self._call("Query", req)

    async def check_tx(self, req):
        return await self._call("CheckTx", req)

    async def check_tx_batch(self, req):
        return await self._call("CheckTxBatch", req)

    async def init_chain(self, req):
        return await self._call("InitChain", req)

    async def begin_block(self, req):
        return await self._call("BeginBlock", req)

    async def deliver_tx(self, req):
        return await self._call("DeliverTx", req)

    async def deliver_tx_batch(self, req):
        return await self._call("DeliverTxBatch", req)

    async def end_block(self, req):
        return await self._call("EndBlock", req)

    async def commit(self):
        return await self._call("Commit", abci.RequestCommit())

    async def list_snapshots(self, req):
        return await self._call("ListSnapshots", req)

    async def offer_snapshot(self, req):
        return await self._call("OfferSnapshot", req)

    async def load_snapshot_chunk(self, req):
        return await self._call("LoadSnapshotChunk", req)

    async def apply_snapshot_chunk(self, req):
        return await self._call("ApplySnapshotChunk", req)

    async def flush(self) -> None:
        """Wait for everything queued so far to have been executed."""
        await self._call("Flush", abci.RequestFlush())

    def deliver_tx_async(self, req) -> asyncio.Future:
        return self._enqueue("DeliverTx", req)

    def check_tx_async(self, req) -> asyncio.Future:
        return self._enqueue("CheckTx", req)
