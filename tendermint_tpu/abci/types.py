"""ABCI message types + Application interface.

Reference parity: abci/types/application.go:11-30 and the Request/Response
oneof in abci/types/types.proto. Messages are plain dataclasses with CBE
encode/decode (tagged union for the socket protocol). `events` on
CheckTx/DeliverTx are the reference's kv tag pairs feeding the tx indexer
and pubsub filters.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.encoding import DecodeError, Reader, Writer, as_decode_error

# One bound for every ABCI transport (CBE and proto framing): the
# reference abci/types/messages.go maxMsgSize. A length prefix above this
# is malformed framing — reject BEFORE waiting on the payload, or one
# garbage header pins a connection handler forever.
MAX_MSG_SIZE = 104857600


async def read_cbe_frame(reader) -> bytes:
    """Read one 4-byte-length-prefixed CBE message from an asyncio stream
    — both ends of the socket protocol (server.py / client.py) use this.
    Raises asyncio.IncompleteReadError at clean EOF."""
    import struct

    hdr = await reader.readexactly(4)
    (ln,) = struct.unpack(">I", hdr)
    if ln > MAX_MSG_SIZE:
        raise DecodeError(f"frame length {ln} > max {MAX_MSG_SIZE}")
    return await reader.readexactly(ln)

CODE_TYPE_OK = 0


# ---------------------------------------------------------------------------
# auxiliary payload types


@dataclass
class ValidatorUpdate:
    """abci.ValidatorUpdate: pubkey (CBE-encoded crypto pubkey) + power."""

    pub_key: bytes  # crypto.encode_pubkey output
    power: int

    def encode_into(self, w: Writer) -> None:
        w.bytes(self.pub_key).i64(self.power)

    @classmethod
    def read(cls, r: Reader) -> "ValidatorUpdate":
        return cls(r.bytes(), r.i64())


@dataclass
class VoteInfo:
    """Per-validator commit participation, passed to BeginBlock."""

    address: bytes
    power: int
    signed_last_block: bool

    def encode_into(self, w: Writer) -> None:
        w.bytes(self.address).i64(self.power).bool(self.signed_last_block)

    @classmethod
    def read(cls, r: Reader) -> "VoteInfo":
        return cls(r.bytes(), r.i64(), r.bool())


@dataclass
class EvidenceInfo:
    type: str
    address: bytes
    height: int
    total_voting_power: int

    def encode_into(self, w: Writer) -> None:
        w.str(self.type).bytes(self.address).u64(self.height).i64(self.total_voting_power)

    @classmethod
    def read(cls, r: Reader) -> "EvidenceInfo":
        return cls(r.str(), r.bytes(), r.u64(), r.i64())


@dataclass
class Snapshot:
    """abci.Snapshot (reference abci/types/types.proto Snapshot): an
    app-state snapshot offered between nodes over the state-sync channel.
    `hash` addresses the whole snapshot (sha256 over the chunk hashes);
    `metadata` is app-specific — the kvstore packs the per-chunk sha256
    list there so the reactor can reject a corrupt chunk before the app
    sees it (docs/state_sync.md)."""

    height: int = 0
    format: int = 1
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""

    def encode_into(self, w: Writer) -> None:
        w.u64(self.height).u32(self.format).u32(self.chunks)
        w.bytes(self.hash).bytes(self.metadata)

    @classmethod
    def read(cls, r: Reader) -> "Snapshot":
        return cls(r.u64(), r.u32(), r.u32(), r.bytes(), r.bytes())

    def key(self) -> tuple:
        """Identity for dedup across peers (reference statesync/snapshots.go)."""
        return (self.height, self.format, self.chunks, self.hash, self.metadata)


# ResponseOfferSnapshot.result (reference abci/types/types.proto)
OFFER_SNAPSHOT_UNKNOWN = 0
OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5

# ResponseApplySnapshotChunk.result
APPLY_CHUNK_UNKNOWN = 0
APPLY_CHUNK_ACCEPT = 1
APPLY_CHUNK_ABORT = 2
APPLY_CHUNK_RETRY = 3
APPLY_CHUNK_RETRY_SNAPSHOT = 4
APPLY_CHUNK_REJECT_SNAPSHOT = 5


def _encode_events(w: Writer, events: dict[str, list[str]]) -> None:
    w.u32(len(events))
    for k in sorted(events):
        w.str(k)
        w.u32(len(events[k]))
        for v in events[k]:
            w.str(v)


def _read_events(r: Reader) -> dict[str, list[str]]:
    out: dict[str, list[str]] = {}
    for _ in range(r.u32()):
        k = r.str()
        out[k] = [r.str() for _ in range(r.u32())]
    return out


# ---------------------------------------------------------------------------
# requests


@dataclass
class RequestEcho:
    message: str = ""


@dataclass
class RequestFlush:
    pass


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0


@dataclass
class RequestSetOption:
    key: str = ""
    value: str = ""


@dataclass
class RequestInitChain:
    time: int = 0
    chain_id: str = ""
    consensus_params: bytes = b""  # encoded ConsensusParams
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: bytes = b""  # encoded types.Header
    last_commit_votes: list[VoteInfo] = field(default_factory=list)
    byzantine_validators: list[EvidenceInfo] = field(default_factory=list)


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    new_check: bool = True  # False = recheck after a block commit


@dataclass
class RequestCheckTxBatch:
    """Batch admission (docs/tx_ingestion.md): one round trip carries a
    whole ingest bucket so the app can fuse per-tx signature work into a
    single device-scheduler submission. NOT in the reference protocol —
    an extension this repo's node and apps speak on every transport; the
    mempool falls back to per-tx CheckTx (loudly) when the app side
    errors on it (reference Go apps answer the unknown oneof arm with an
    exception response, so the probe degrades cleanly)."""

    txs: list[bytes] = field(default_factory=list)
    new_check: bool = True  # False = post-commit recheck of survivors


@dataclass
class RequestDeliverTx:
    tx: bytes = b""


@dataclass
class RequestDeliverTxBatch:
    """Batch execution (docs/tx_ingestion.md): one round trip carries the
    whole decided block so the app can fuse per-tx signature work into a
    single device-scheduler submission per curve. NOT in the reference
    protocol — the execution-side twin of RequestCheckTxBatch; the block
    executor falls back to per-tx DeliverTx (loudly) when the app side
    errors on it (reference Go apps answer the unknown oneof arm with an
    exception response, so the probe degrades cleanly)."""

    txs: list[bytes] = field(default_factory=list)


@dataclass
class RequestEndBlock:
    height: int = 0


@dataclass
class RequestCommit:
    pass


# -- state sync (reference abci/types/application.go StateSyncer methods) ---


@dataclass
class RequestListSnapshots:
    pass


@dataclass
class RequestOfferSnapshot:
    snapshot: Snapshot = field(default_factory=Snapshot)
    app_hash: bytes = b""  # from the light-client-verified header


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""  # peer id, so the app can ask to reject it


# ---------------------------------------------------------------------------
# responses


@dataclass
class ResponseEcho:
    message: str = ""


@dataclass
class ResponseFlush:
    pass


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseSetOption:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""  # reference carries it (types.proto); was dropped on both transports


@dataclass
class ResponseInitChain:
    consensus_params: bytes = b""
    validators: list[ValidatorUpdate] = field(default_factory=list)


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: list = field(default_factory=list)  # list[merkle.ProofOp]
    height: int = 0
    codespace: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseBeginBlock:
    events: dict[str, list[str]] = field(default_factory=dict)


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: dict[str, list[str]] = field(default_factory=dict)
    codespace: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        w = Writer().u32(self.code).bytes(self.data).str(self.log)
        w.i64(self.gas_wanted).i64(self.gas_used)
        _encode_events(w, self.events)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "ResponseCheckTx":
        r = Reader(data)
        out = cls(
            code=r.u32(), data=r.bytes(), log=r.str(), gas_wanted=r.i64(), gas_used=r.i64()
        )
        out.events = _read_events(r)
        return out


@dataclass
class ResponseCheckTxBatch:
    """One ResponseCheckTx per RequestCheckTxBatch.txs entry, in order."""

    responses: list[ResponseCheckTx] = field(default_factory=list)


@dataclass
class ResponseDeliverTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: dict[str, list[str]] = field(default_factory=dict)
    codespace: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        w = Writer().u32(self.code).bytes(self.data).str(self.log)
        w.i64(self.gas_wanted).i64(self.gas_used)
        _encode_events(w, self.events)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "ResponseDeliverTx":
        r = Reader(data)
        out = cls(
            code=r.u32(), data=r.bytes(), log=r.str(), gas_wanted=r.i64(), gas_used=r.i64()
        )
        out.events = _read_events(r)
        return out


@dataclass
class ResponseDeliverTxBatch:
    """One ResponseDeliverTx per RequestDeliverTxBatch.txs entry, in order."""

    responses: list[ResponseDeliverTx] = field(default_factory=list)


@dataclass
class ResponseEndBlock:
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: bytes = b""
    events: dict[str, list[str]] = field(default_factory=dict)


@dataclass
class ResponseCommit:
    data: bytes = b""  # the app hash
    # Reference v0.34 ResponseCommit.retain_height: blocks BELOW this
    # height are no longer needed by the app and may be pruned from the
    # block store — height retain_height itself is kept, matching
    # BlockStore.prune (state/execution honours it; snapshot-booted
    # replicas already advertise their base over fast sync, so peers
    # never assume genesis history is present).
    retain_height: int = 0


@dataclass
class ResponseListSnapshots:
    snapshots: list[Snapshot] = field(default_factory=list)


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_UNKNOWN


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_CHUNK_UNKNOWN
    refetch_chunks: list[int] = field(default_factory=list)
    reject_senders: list[str] = field(default_factory=list)


@dataclass
class ResponseException:
    error: str = ""


# ---------------------------------------------------------------------------
# Application interface


class Application:
    """Reference abci/types/application.go:11-30."""

    def info(self, req: RequestInfo) -> ResponseInfo: ...

    def set_option(self, req: RequestSetOption) -> ResponseSetOption: ...

    def query(self, req: RequestQuery) -> ResponseQuery: ...

    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx: ...

    def check_tx_batch(self, req: RequestCheckTxBatch) -> ResponseCheckTxBatch: ...

    def init_chain(self, req: RequestInitChain) -> ResponseInitChain: ...

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock: ...

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx: ...

    def deliver_tx_batch(self, req: RequestDeliverTxBatch) -> ResponseDeliverTxBatch: ...

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock: ...

    def commit(self) -> ResponseCommit: ...

    # -- state sync (reference application.go StateSyncer; no-snapshot apps
    # inherit the empty defaults from BaseApplication) -----------------

    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots: ...

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot: ...

    def load_snapshot_chunk(
        self, req: RequestLoadSnapshotChunk
    ) -> ResponseLoadSnapshotChunk: ...

    def apply_snapshot_chunk(
        self, req: RequestApplySnapshotChunk
    ) -> ResponseApplySnapshotChunk: ...


class BaseApplication(Application):
    """No-op base (reference abci/types/application.go:33)."""

    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def set_option(self, req: RequestSetOption) -> ResponseSetOption:
        return ResponseSetOption()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery(code=CODE_TYPE_OK)

    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx(code=CODE_TYPE_OK)

    def check_tx_batch(self, req: RequestCheckTxBatch) -> ResponseCheckTxBatch:
        """Default: per-tx loop through check_tx — apps without batchable
        work inherit correct (if unfused) batch semantics for free. Apps
        with bulk signature verification override this (examples/
        transfer.py) to verify the whole bucket in one backend call."""
        return ResponseCheckTxBatch(
            responses=[
                self.check_tx(RequestCheckTx(tx, req.new_check)) for tx in req.txs
            ]
        )

    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock:
        return ResponseBeginBlock()

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx:
        return ResponseDeliverTx(code=CODE_TYPE_OK)

    def deliver_tx_batch(self, req: RequestDeliverTxBatch) -> ResponseDeliverTxBatch:
        """Default: per-tx loop through deliver_tx — apps without batchable
        work inherit correct (if unfused) block execution for free. Apps
        with bulk signature verification override this (examples/
        transfer.py) to verify the whole block in one backend call per
        curve."""
        return ResponseDeliverTxBatch(
            responses=[self.deliver_tx(RequestDeliverTx(tx)) for tx in req.txs]
        )

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots:
        return ResponseListSnapshots()

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot(result=OFFER_SNAPSHOT_REJECT)

    def load_snapshot_chunk(
        self, req: RequestLoadSnapshotChunk
    ) -> ResponseLoadSnapshotChunk:
        return ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(
        self, req: RequestApplySnapshotChunk
    ) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk(result=APPLY_CHUNK_ABORT)


# ---------------------------------------------------------------------------
# socket wire codec: tagged union

_REQ_TAGS: list[tuple[int, type]] = [
    (1, RequestEcho),
    (2, RequestFlush),
    (3, RequestInfo),
    (4, RequestSetOption),
    (5, RequestInitChain),
    (6, RequestQuery),
    (7, RequestBeginBlock),
    (8, RequestCheckTx),
    (9, RequestDeliverTx),
    (10, RequestEndBlock),
    (11, RequestCommit),
    (12, RequestListSnapshots),
    (13, RequestOfferSnapshot),
    (14, RequestLoadSnapshotChunk),
    (15, RequestApplySnapshotChunk),
    (16, RequestCheckTxBatch),
    (17, RequestDeliverTxBatch),
]
_RESP_TAGS: list[tuple[int, type]] = [
    (1, ResponseEcho),
    (2, ResponseFlush),
    (3, ResponseInfo),
    (4, ResponseSetOption),
    (5, ResponseInitChain),
    (6, ResponseQuery),
    (7, ResponseBeginBlock),
    (8, ResponseCheckTx),
    (9, ResponseDeliverTx),
    (10, ResponseEndBlock),
    (11, ResponseCommit),
    (12, ResponseException),
    (13, ResponseListSnapshots),
    (14, ResponseOfferSnapshot),
    (15, ResponseLoadSnapshotChunk),
    (16, ResponseApplySnapshotChunk),
    (17, ResponseCheckTxBatch),
    (18, ResponseDeliverTxBatch),
]


def _encode_msg(msg) -> bytes:
    """Generic dataclass field encoder (schema fixed by field order)."""
    w = Writer()
    for name, val in vars(msg).items():
        if isinstance(val, bool):
            w.bool(val)
        elif isinstance(val, int):
            w.i64(val)
        elif isinstance(val, bytes):
            w.bytes(val)
        elif isinstance(val, str):
            w.str(val)
        elif isinstance(val, dict):
            _encode_events(w, val)
        elif isinstance(val, Snapshot):
            val.encode_into(w)
        elif isinstance(val, list):
            w.u32(len(val))
            for item in val:
                if hasattr(item, "encode_into"):
                    item.encode_into(w)
                elif isinstance(item, bool):
                    w.bool(item)
                elif isinstance(item, bytes):  # e.g. RequestCheckTxBatch.txs
                    w.bytes(item)
                elif isinstance(item, int):  # e.g. refetch_chunks
                    w.u64(item)
                elif isinstance(item, str):  # e.g. reject_senders
                    w.str(item)
                elif isinstance(item, (ResponseCheckTx, ResponseDeliverTx)):
                    # nested message: length-prefixed recursive encoding
                    # (covers every field incl. info/codespace, unlike the
                    # legacy ResponseCheckTx/ResponseDeliverTx.encode wire
                    # shape)
                    w.bytes(_encode_msg(item))
                else:  # merkle.ProofOp
                    from tendermint_tpu.crypto.merkle import ProofOp

                    assert isinstance(item, ProofOp)
                    w.str(item.type).bytes(item.key).bytes(item.data)
        else:
            raise TypeError(f"cannot encode field {name}={val!r}")
    return w.build()


def _decode_msg(cls, data: bytes):
    import dataclasses as dc

    r = Reader(data)
    kwargs = {}
    for f in dc.fields(cls):
        if f.type in ("bool", bool):
            kwargs[f.name] = r.bool()
        elif f.type in ("int", int):
            kwargs[f.name] = r.i64()
        elif f.type in ("bytes", bytes):
            kwargs[f.name] = r.bytes()
        elif f.type in ("str", str):
            kwargs[f.name] = r.str()
        elif "dict" in str(f.type):
            kwargs[f.name] = _read_events(r)
        elif "list[bytes]" in str(f.type):
            kwargs[f.name] = [r.bytes() for _ in range(r.u32())]
        elif "list[ResponseCheckTx]" in str(f.type):
            kwargs[f.name] = [
                _decode_msg(ResponseCheckTx, r.bytes()) for _ in range(r.u32())
            ]
        elif "list[ResponseDeliverTx]" in str(f.type):
            kwargs[f.name] = [
                _decode_msg(ResponseDeliverTx, r.bytes()) for _ in range(r.u32())
            ]
        elif "list[Snapshot]" in str(f.type):
            kwargs[f.name] = [Snapshot.read(r) for _ in range(r.u32())]
        elif "Snapshot" in str(f.type):
            kwargs[f.name] = Snapshot.read(r)
        elif "list[int]" in str(f.type):
            kwargs[f.name] = [r.u64() for _ in range(r.u32())]
        elif "list[str]" in str(f.type):
            kwargs[f.name] = [r.str() for _ in range(r.u32())]
        elif "ValidatorUpdate" in str(f.type):
            kwargs[f.name] = [ValidatorUpdate.read(r) for _ in range(r.u32())]
        elif "VoteInfo" in str(f.type):
            kwargs[f.name] = [VoteInfo.read(r) for _ in range(r.u32())]
        elif "EvidenceInfo" in str(f.type):
            kwargs[f.name] = [EvidenceInfo.read(r) for _ in range(r.u32())]
        elif f.name == "proof_ops":
            from tendermint_tpu.crypto.merkle import ProofOp

            kwargs[f.name] = [
                ProofOp(r.str(), r.bytes(), r.bytes()) for _ in range(r.u32())
            ]
        else:
            raise TypeError(f"cannot decode field {f.name}: {f.type}")
    r.expect_done()
    return cls(**kwargs)


def encode_request(req) -> bytes:
    for tag, cls in _REQ_TAGS:
        if type(req) is cls:
            return bytes([tag]) + _encode_msg(req)
    raise TypeError(f"unknown request {req!r}")


def decode_request(data: bytes):
    if not data:
        raise DecodeError("empty request")
    tag = data[0]
    for t, cls in _REQ_TAGS:
        if t == tag:
            return as_decode_error(
                lambda d: _decode_msg(cls, d), data[1:], "request"
            )
    raise DecodeError(f"unknown request tag {tag}")


def encode_response(resp) -> bytes:
    for tag, cls in _RESP_TAGS:
        if type(resp) is cls:
            return bytes([tag]) + _encode_msg(resp)
    raise TypeError(f"unknown response {resp!r}")


def decode_response(data: bytes):
    if not data:
        raise DecodeError("empty response")
    tag = data[0]
    for t, cls in _RESP_TAGS:
        if t == tag:
            return as_decode_error(
                lambda d: _decode_msg(cls, d), data[1:], "response"
            )
    raise DecodeError(f"unknown response tag {tag}")
