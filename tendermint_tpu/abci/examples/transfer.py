"""Token-transfer example app — the second real workload (ISSUE 14).

Where the kvstore exercises raw commit throughput, this app exercises the
BASELINE config-5 mixed-curve shape at the APP layer: every transaction
carries a real signature (secp256k1 or ed25519), and admission verifies
them in BULK through the batch CheckTx surface (`check_tx_batch`) — one
backend call per ingest bucket, routed through the DeviceScheduler at
MEMPOOL_CHECK priority by the mempool's priority scope — while
nonce/balance bookkeeping stays per-tx. On a validator that is already
streaming ed25519 votes through the scheduler, transfer traffic proves
mixed ed25519 (votes) + secp256k1 (txs) work packs onto one mesh.

Transaction wire format (CBE, docs/tx_ingestion.md):

    tx         = u8(curve_tag) bytes(pub) bytes(to) u64(amount) u64(nonce) bytes(sig)
    sign bytes = str(DOMAIN) u8(curve_tag) bytes(pub) bytes(to) u64(amount) u64(nonce)
    curve_tag  : 1 = ed25519 (32-byte pub), 2 = secp256k1 (33-byte compressed)
    address    = sha256(pub)[:20]

State machine: every account starts at `initial_balance` (faucet model —
deterministic across nodes, no genesis ceremony needed for benches);
a transfer requires the SENDER's exact next nonce (replay protection)
and sufficient balance. CheckTx runs against a shadow "check state"
that is replaced by the committed state at every Commit (the standard
ABCI convention), so a burst of sequential nonces from one account all
admit while a replayed or gapped nonce rejects.

Signature verification backend, best-available:
  1. the registered crypto.batch backend (tendermint_tpu.ops — device or
     native route THROUGH the DeviceScheduler, so admission work shows up
     under the MEMPOOL_CHECK class in debug_device);
  2. the native batch library (crypto/native.py, thread-parallel C++);
  3. the pure-python math oracles (crypto/*_math.py) — correct anywhere,
     fast nowhere; keeps the app usable in dependency-free environments.
"""
from __future__ import annotations

import hashlib
import json
from collections import OrderedDict
from dataclasses import dataclass

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding import DecodeError, Reader, Writer

DOMAIN = "tmtpu/transfer/v1"

CURVE_ED25519 = 1
CURVE_SECP256K1 = 2
_CURVE_NAMES = {CURVE_ED25519: "ed25519", CURVE_SECP256K1: "secp256k1"}
_CURVE_TAGS = {v: k for k, v in _CURVE_NAMES.items()}
_PUB_SIZES = {CURVE_ED25519: 32, CURVE_SECP256K1: 33}

ADDRESS_SIZE = 20

# response codes (codespace "transfer")
CODE_OK = abci.CODE_TYPE_OK
CODE_ENCODING = 1
CODE_BAD_SIGNATURE = 2
CODE_BAD_NONCE = 3
CODE_INSUFFICIENT_FUNDS = 4
CODE_BAD_CURVE = 5

# bound on the admission-verified tx-hash cache DeliverTx consults to
# skip re-verifying signatures it already checked (txs arriving in a
# block from another node's mempool still verify fully)
_CHECKED_CACHE = 65536


def address(pub: bytes) -> bytes:
    return hashlib.sha256(pub).digest()[:ADDRESS_SIZE]


@dataclass
class TransferTx:
    curve: int
    pub: bytes
    to: bytes
    amount: int
    nonce: int
    sig: bytes

    @property
    def sender(self) -> bytes:
        return address(self.pub)

    def sign_bytes(self) -> bytes:
        return sign_bytes(self.curve, self.pub, self.to, self.amount, self.nonce)


def sign_bytes(curve: int, pub: bytes, to: bytes, amount: int, nonce: int) -> bytes:
    return (
        Writer().str(DOMAIN).u8(curve).bytes(pub).bytes(to)
        .u64(amount).u64(nonce).build()
    )


# the signed payload is the DOMAIN prefix + the tx minus its trailing
# signature field (u32 length prefix + 64 bytes) — slicing beats
# re-encoding every field on the admission hot path
_DOMAIN_PREFIX = Writer().str(DOMAIN).build()
_SIG_FIELD_LEN = 4 + 64


def sign_bytes_of(tx: bytes) -> bytes:
    """sign_bytes derived from the encoded tx (== the field-wise
    construction above; pinned by a test)."""
    return _DOMAIN_PREFIX + tx[:-_SIG_FIELD_LEN]


def encode_tx(curve: int, pub: bytes, to: bytes, amount: int, nonce: int, sig: bytes) -> bytes:
    return (
        Writer().u8(curve).bytes(pub).bytes(to).u64(amount).u64(nonce)
        .bytes(sig).build()
    )


def decode_tx(tx: bytes) -> TransferTx:
    r = Reader(tx)
    curve = r.u8()
    if curve not in _CURVE_NAMES:
        raise DecodeError(f"unknown curve tag {curve}")
    pub = r.bytes()
    if len(pub) != _PUB_SIZES[curve]:
        raise DecodeError(f"bad pubkey size {len(pub)} for curve {curve}")
    to = r.bytes()
    if len(to) != ADDRESS_SIZE:
        raise DecodeError(f"bad recipient size {len(to)}")
    amount = r.u64()
    nonce = r.u64()
    sig = r.bytes()
    if len(sig) != 64:
        raise DecodeError(f"bad signature size {len(sig)}")
    r.expect_done()
    return TransferTx(curve, pub, to, amount, nonce, sig)


def make_tx(curve_name: str, priv: bytes, to: bytes, amount: int, nonce: int) -> bytes:
    """Sign + encode a transfer with the pure-python dev signers
    (crypto/*_math.py) — works without the `cryptography` package; the
    signatures verify on every backend. Workload-generation helper for
    ingest_bench, tests, and the proc scenario."""
    curve = _CURVE_TAGS[curve_name]
    if curve == CURVE_ED25519:
        from tendermint_tpu.crypto import ed25519_math as m
    else:
        from tendermint_tpu.crypto import secp256k1_math as m
    pub = m.pub_from_priv(priv)
    sig = m.sign(priv, sign_bytes(curve, pub, to, amount, nonce))
    return encode_tx(curve, pub, to, amount, nonce, sig)


def verify_sigs(curve_name: str, pubs, msgs, sigs) -> list[bool]:
    """Bulk-verify one curve's triples on the best available backend (see
    module docstring). Raw-bytes API on purpose: the PubKey key stack
    needs the `cryptography` package, the backends don't."""
    if not pubs:
        return []
    from tendermint_tpu.crypto import batch as cbatch

    backend = cbatch.get_backend(curve_name)
    if backend is not None:
        return list(backend(list(pubs), list(msgs), list(sigs)))
    from tendermint_tpu.crypto import native

    if native.load() is not None:
        if curve_name == "ed25519":
            return native.ed25519_verify_batch(pubs, msgs, sigs)
        return native.secp256k1_verify_batch(pubs, msgs, sigs)
    if curve_name == "ed25519":
        from tendermint_tpu.crypto import ed25519_math as m
    else:
        from tendermint_tpu.crypto import secp256k1_math as m
    return [m.verify(p, s_msg, s) for p, s_msg, s in zip(pubs, msgs, sigs)]


class TransferApplication(abci.BaseApplication):
    def __init__(self, curve: str = "secp256k1", initial_balance: int = 10**9) -> None:
        if curve not in _CURVE_TAGS:
            raise ValueError(f"unknown curve {curve!r}")
        # advisory default for workload tooling; the wire accepts both
        self.curve = curve
        self.initial_balance = int(initial_balance)
        # committed state
        self.balances: dict[bytes, int] = {}
        self.nonces: dict[bytes, int] = {}
        self.height = 0
        self.app_hash = b""
        self.tx_count = 0
        # CheckTx shadow state (replaced by committed state at Commit)
        self._check_balances: dict[bytes, int] = {}
        self._check_nonces: dict[bytes, int] = {}
        # admission-verified tx hashes: DeliverTx skips re-verifying these
        self._checked: OrderedDict[bytes, None] = OrderedDict()
        # current block's delivered-tx digest accumulator
        self._block_hasher = hashlib.sha256()
        self._block_txs = 0

    # -- balances ------------------------------------------------------------

    def balance(self, addr: bytes) -> int:
        return self.balances.get(addr, self.initial_balance)

    def nonce(self, addr: bytes) -> int:
        return self.nonces.get(addr, 0)

    def _check_balance(self, addr: bytes) -> int:
        return self._check_balances.get(addr, self.balance(addr))

    def _check_nonce(self, addr: bytes) -> int:
        return self._check_nonces.get(addr, self.nonce(addr))

    # -- admission -----------------------------------------------------------

    def _mark_checked(self, tx: bytes) -> None:
        key = hashlib.sha256(tx).digest()
        self._checked[key] = None
        self._checked.move_to_end(key)
        while len(self._checked) > _CHECKED_CACHE:
            self._checked.popitem(last=False)

    def _stateful_check(self, t: TransferTx) -> abci.ResponseCheckTx:
        """Nonce/balance admission against the CheckTx shadow state;
        applies the tx to the shadow on success."""
        sender = t.sender
        expected = self._check_nonce(sender)
        if t.nonce != expected:
            return abci.ResponseCheckTx(
                code=CODE_BAD_NONCE, codespace="transfer",
                log=f"bad nonce {t.nonce}, expected {expected}",
            )
        bal = self._check_balance(sender)
        if bal < t.amount:
            return abci.ResponseCheckTx(
                code=CODE_INSUFFICIENT_FUNDS, codespace="transfer",
                log=f"balance {bal} < amount {t.amount}",
            )
        self._check_nonces[sender] = expected + 1
        self._check_balances[sender] = bal - t.amount
        self._check_balances[t.to] = self._check_balance(t.to) + t.amount
        return abci.ResponseCheckTx(code=CODE_OK, gas_wanted=1)

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return self.check_tx_batch(
            abci.RequestCheckTxBatch([req.tx], req.new_check)
        ).responses[0]

    def check_tx_batch(self, req: abci.RequestCheckTxBatch) -> abci.ResponseCheckTxBatch:
        """Signatures in bulk, nonce/balance per tx (module docstring).

        On recheck (new_check=False) signatures were already verified at
        admission — only the stateful pass reruns against the fresh
        shadow state, so a post-commit recheck storm costs zero
        signature work."""
        out: list[abci.ResponseCheckTx | None] = [None] * len(req.txs)
        parsed: list[tuple[int, TransferTx]] = []
        for i, tx in enumerate(req.txs):
            try:
                parsed.append((i, decode_tx(tx)))
            except DecodeError as e:
                out[i] = abci.ResponseCheckTx(
                    code=CODE_ENCODING, codespace="transfer", log=str(e)
                )
        if req.new_check:
            by_curve: dict[str, list[tuple[int, TransferTx]]] = {}
            for i, t in parsed:
                by_curve.setdefault(_CURVE_NAMES[t.curve], []).append((i, t))
            sig_ok: dict[int, bool] = {}
            for curve_name, items in by_curve.items():
                verdicts = verify_sigs(
                    curve_name,
                    [t.pub for _, t in items],
                    [sign_bytes_of(req.txs[i]) for i, _ in items],
                    [t.sig for _, t in items],
                )
                for (i, _), ok in zip(items, verdicts):
                    sig_ok[i] = bool(ok)
            for i, t in parsed:
                if not sig_ok.get(i, False):
                    out[i] = abci.ResponseCheckTx(
                        code=CODE_BAD_SIGNATURE, codespace="transfer",
                        log="signature verification failed",
                    )
        for i, t in parsed:
            if out[i] is not None:
                continue
            res = self._stateful_check(t)
            if res.is_ok and req.new_check:
                self._mark_checked(req.txs[i])
            out[i] = res
        return abci.ResponseCheckTxBatch(responses=out)  # type: ignore[arg-type]

    # -- delivery ------------------------------------------------------------

    def _apply_transfer(self, t: TransferTx, key: bytes) -> abci.ResponseDeliverTx:
        """The stateful tail of delivery — nonce/balance checks + apply —
        shared verbatim by deliver_tx and deliver_tx_batch so the two
        paths cannot drift (the batch surface fuses ONLY signature
        verification; the per-tx apply order is identical)."""
        sender = t.sender
        expected = self.nonce(sender)
        if t.nonce != expected:
            return abci.ResponseDeliverTx(
                code=CODE_BAD_NONCE, codespace="transfer",
                log=f"bad nonce {t.nonce}, expected {expected}",
            )
        bal = self.balance(sender)
        if bal < t.amount:
            return abci.ResponseDeliverTx(
                code=CODE_INSUFFICIENT_FUNDS, codespace="transfer",
                log=f"balance {bal} < amount {t.amount}",
            )
        self.nonces[sender] = expected + 1
        self.balances[sender] = bal - t.amount
        self.balances[t.to] = self.balance(t.to) + t.amount
        self.tx_count += 1
        self._block_hasher.update(key)
        self._block_txs += 1
        return abci.ResponseDeliverTx(
            code=CODE_OK, gas_used=1,
            events={
                "transfer.from": [sender.hex()],
                "transfer.to": [t.to.hex()],
                "transfer.amount": [str(t.amount)],
            },
        )

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        try:
            t = decode_tx(req.tx)
        except DecodeError as e:
            return abci.ResponseDeliverTx(
                code=CODE_ENCODING, codespace="transfer", log=str(e)
            )
        key = hashlib.sha256(req.tx).digest()
        if key in self._checked:
            del self._checked[key]
        else:
            # not admission-verified HERE (block built elsewhere): verify
            ok = verify_sigs(
                _CURVE_NAMES[t.curve], [t.pub], [sign_bytes_of(req.tx)], [t.sig]
            )[0]
            if not ok:
                return abci.ResponseDeliverTx(
                    code=CODE_BAD_SIGNATURE, codespace="transfer",
                    log="signature verification failed",
                )
        return self._apply_transfer(t, key)

    def deliver_tx_batch(self, req: abci.RequestDeliverTxBatch) -> abci.ResponseDeliverTxBatch:
        """Whole-block delivery: signature work fused to ONE bulk-verify
        call per curve, everything else per tx in block order.

        CheckTx-verified txs collapse to verified-hash cache sweeps (the
        sweep consumes the entry in block order, so a duplicate tx later
        in the same block misses the cache and fully verifies — exactly
        the serial path's behaviour); foreign txs (block built on another
        node from gossip we never admitted) batch-verify in bulk through
        the same backend ladder admission uses, here under the executor's
        CONSENSUS_COMMIT priority scope. Responses are byte-identical to
        per-tx deliver_tx over the same sequence (pinned by tests)."""
        from tendermint_tpu.libs.recorder import RECORDER

        out: list[abci.ResponseDeliverTx | None] = [None] * len(req.txs)
        parsed: list[tuple[int, TransferTx]] = []
        for i, tx in enumerate(req.txs):
            try:
                parsed.append((i, decode_tx(tx)))
            except DecodeError as e:
                out[i] = abci.ResponseDeliverTx(
                    code=CODE_ENCODING, codespace="transfer", log=str(e)
                )
        keys = {i: hashlib.sha256(req.txs[i]).digest() for i, _ in parsed}
        cached = 0
        foreign: list[tuple[int, TransferTx]] = []
        for i, t in parsed:
            if keys[i] in self._checked:
                del self._checked[keys[i]]
                cached += 1
            else:
                foreign.append((i, t))
        by_curve: dict[str, list[tuple[int, TransferTx]]] = {}
        for i, t in foreign:
            by_curve.setdefault(_CURVE_NAMES[t.curve], []).append((i, t))
        for curve_name, items in by_curve.items():
            verdicts = verify_sigs(
                curve_name,
                [t.pub for _, t in items],
                [sign_bytes_of(req.txs[i]) for i, _ in items],
                [t.sig for _, t in items],
            )
            for (i, _), ok in zip(items, verdicts):
                if not ok:
                    out[i] = abci.ResponseDeliverTx(
                        code=CODE_BAD_SIGNATURE, codespace="transfer",
                        log="signature verification failed",
                    )
        for i, t in parsed:
            if out[i] is None:
                out[i] = self._apply_transfer(t, keys[i])
        # curve split + cache efficiency for the observability plane
        # (docs/observability.md): `dispatches` pins the ≤1-scheduler-
        # dispatch-per-curve invariant, `cached` the CheckTx-cache sweep
        RECORDER.record(
            "app", "deliver_verify", height=self.height + 1,
            txs=len(req.txs), cached=cached, verified=len(foreign),
            dispatches=len(by_curve),
            curves={c: len(items) for c, items in by_curve.items()},
        )
        return abci.ResponseDeliverTxBatch(responses=out)  # type: ignore[arg-type]

    def commit(self) -> abci.ResponseCommit:
        self.height += 1
        # app hash: a chain over delivered-tx digests — deterministic in
        # the applied tx sequence, O(block) not O(state)
        h = hashlib.sha256()
        h.update(self.app_hash)
        h.update(self._block_hasher.digest())
        h.update(self.tx_count.to_bytes(8, "big"))
        self.app_hash = h.digest()
        self._block_hasher = hashlib.sha256()
        self._block_txs = 0
        # CheckTx shadow state restarts from the committed state; the
        # mempool's recheck replays surviving txs into it in clist order
        self._check_balances = {}
        self._check_nonces = {}
        return abci.ResponseCommit(data=self.app_hash)

    # -- info/query ----------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps(
                {"accounts": len(self.balances), "curve": self.curve}
            ),
            version="transfer/0.1.0",
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        """Paths: /balance and /nonce, data = 20-byte address (raw or
        hex). Unproven reads of the committed state."""
        data = req.data
        if len(data) == 2 * ADDRESS_SIZE:
            try:
                data = bytes.fromhex(data.decode())
            except ValueError:
                pass
        if len(data) != ADDRESS_SIZE:
            return abci.ResponseQuery(
                code=CODE_ENCODING, codespace="transfer",
                log=f"query data must be a {ADDRESS_SIZE}-byte address",
            )
        if req.path == "/nonce":
            val = self.nonce(data)
        else:
            val = self.balance(data)
        return abci.ResponseQuery(
            code=CODE_OK, key=req.data, value=str(val).encode(),
            height=self.height,
        )

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        if req.app_state_bytes:
            try:
                opts = json.loads(req.app_state_bytes)
                self.initial_balance = int(
                    opts.get("initial_balance", self.initial_balance)
                )
            except (ValueError, TypeError, AttributeError):
                pass
        return abci.ResponseInitChain()
