"""KVStore example app.

Reference parity: abci/example/kvstore/kvstore.go:59 (merkle KV app; txs are
"key=value" or "val" meaning key==value; Query supports /store with
optional merkle proofs) and persistent_kvstore.go:26,172 (adds disk
persistence, InitChain validator bookkeeping, and "val:PUBKEY!POWER"
transactions that produce EndBlock validator updates).
"""
from __future__ import annotations

import json
import os

from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto import merkle, sum_sha256
from tendermint_tpu.encoding import Writer

VALIDATOR_TX_PREFIX = b"val:"


class KVStoreApplication(abci.BaseApplication):
    """provable=True (default) roots the app hash in a merkle map of the
    state so Query(prove=True) proofs chain to the verified header — a
    feature the reference's kvstore lacks (its Query TODOs the proof out).
    The map root costs O(state) tree folding per Commit; provable=False is
    the reference-parity app (kvstore.go:111 — app hash is just the
    encoded tx count, O(1)), the right mode for throughput benchmarking."""

    def __init__(self, provable: bool = True) -> None:
        self.provable = provable
        self.state: dict[str, bytes] = {}
        self.height = 0
        self.app_hash = b""
        self.tx_count = 0
        # encoded leaf per key, maintained on writes: Commit re-folds the
        # tree over cached leaves instead of re-encoding + re-sha-ing every
        # value (the naive recompute was O(state) of redundant hashing per
        # block and the single biggest cost of a loaded node's commit round)
        self._leaves: dict[str, bytes] = {}

    # -- helpers ------------------------------------------------------------

    def _leaf(self, key: str) -> bytes:
        return Writer().str(key).bytes(sum_sha256(self.state[key])).build()

    def _compute_app_hash(self) -> bytes:
        if not self.provable:
            return self.tx_count.to_bytes(8, "big")
        return merkle.hash_from_byte_slices(
            [self._leaves[k] for k in sorted(self._leaves)]
        )

    @staticmethod
    def _parse_tx(tx: bytes) -> tuple[str, bytes]:
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k, v = tx, tx
        return k.decode("utf-8", "replace"), v

    # -- ABCI ---------------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": len(self.state)}),
            version="kvstore/0.1.0",
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        key, value = self._parse_tx(req.tx)
        self.state[key] = value
        if self.provable:  # non-provable mode must not pay per-tx hashing
            self._leaves[key] = self._leaf(key)
        self.tx_count += 1
        return abci.ResponseDeliverTx(
            code=abci.CODE_TYPE_OK,
            events={"app.creator": ["kvstore"], "app.key": [key]},
        )

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        self.height = req.height
        return abci.ResponseEndBlock()

    def commit(self) -> abci.ResponseCommit:
        self.app_hash = self._compute_app_hash()
        return abci.ResponseCommit(data=self.app_hash)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        key = req.data.decode("utf-8", "replace")
        value = self.state.get(key)
        resp = abci.ResponseQuery(
            code=abci.CODE_TYPE_OK,
            key=req.data,
            value=value if value is not None else b"",
            height=self.height,
            log="exists" if value is not None else "does not exist",
        )
        if req.prove and value is not None and self.provable:
            # merkle proof of (key, sha256(value)) in the sorted state map
            keys = sorted(self._leaves)
            items = [self._leaves[k] for k in keys]
            root, proofs = merkle.proofs_from_byte_slices(items)
            idx = keys.index(key)
            op = merkle.SimpleValueOp(req.data, proofs[idx])
            resp.proof_ops = [op.proof_op()]
        return resp


class PersistentKVStoreApplication(KVStoreApplication):
    """Adds disk persistence + validator-update transactions
    (reference persistent_kvstore.go)."""

    def __init__(self, db_dir: str) -> None:
        super().__init__()
        self.db_dir = db_dir
        os.makedirs(db_dir, exist_ok=True)
        self._db_path = os.path.join(db_dir, "kvstore_state.json")
        self.validators: dict[str, int] = {}  # pubkey hex -> power
        self._pending_updates: list[abci.ValidatorUpdate] = []
        self._load()

    def _load(self) -> None:
        if os.path.exists(self._db_path):
            with open(self._db_path) as f:
                d = json.load(f)
            self.state = {k: bytes.fromhex(v) for k, v in d["state"].items()}
            self._leaves = {k: self._leaf(k) for k in self.state}
            self.height = d["height"]
            self.app_hash = bytes.fromhex(d["app_hash"])
            self.validators = d.get("validators", {})

    def _save(self) -> None:
        with open(self._db_path, "w") as f:
            json.dump(
                {
                    "state": {k: v.hex() for k, v in self.state.items()},
                    "height": self.height,
                    "app_hash": self.app_hash.hex(),
                    "validators": self.validators,
                },
                f,
            )

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for vu in req.validators:
            self.validators[vu.pub_key.hex()] = vu.power
        return abci.ResponseInitChain()

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            if self._parse_validator_tx(req.tx) is None:
                return abci.ResponseCheckTx(code=1, log="bad validator tx")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    @staticmethod
    def _parse_validator_tx(tx: bytes) -> tuple[bytes, int] | None:
        # format: val:<pubkey hex>!<power>
        body = tx[len(VALIDATOR_TX_PREFIX) :]
        if b"!" not in body:
            return None
        pk_hex, power_s = body.split(b"!", 1)
        try:
            return bytes.fromhex(pk_hex.decode()), int(power_s)
        except ValueError:
            return None

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            parsed = self._parse_validator_tx(req.tx)
            if parsed is None:
                return abci.ResponseDeliverTx(code=1, log="bad validator tx")
            pub_key, power = parsed
            self._pending_updates.append(abci.ValidatorUpdate(pub_key, power))
            if power == 0:
                self.validators.pop(pub_key.hex(), None)
            else:
                self.validators[pub_key.hex()] = power
            return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)
        return super().deliver_tx(req)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        self.height = req.height
        updates, self._pending_updates = self._pending_updates, []
        return abci.ResponseEndBlock(validator_updates=updates)

    def commit(self) -> abci.ResponseCommit:
        resp = super().commit()
        self._save()
        return resp
