"""KVStore example app.

Reference parity: abci/example/kvstore/kvstore.go:59 (merkle KV app; txs are
"key=value" or "val" meaning key==value; Query supports /store with
optional merkle proofs) and persistent_kvstore.go:26,172 (adds disk
persistence, InitChain validator bookkeeping, and "val:PUBKEY!POWER"
transactions that produce EndBlock validator updates).
"""
from __future__ import annotations

import json
import os
import shutil

from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto import merkle, sum_sha256
from tendermint_tpu.encoding import DecodeError, Reader, Writer

VALIDATOR_TX_PREFIX = b"val:"

# State-sync snapshot chunk format (format=1, docs/state_sync.md): each
# chunk is a contiguous run of the SORTED state map — u32 start index,
# u32 count, (key, value) pairs — followed by a merkle.RangeProof binding
# those pairs to the snapshot's app hash. Chunks are sha256-addressed:
# Snapshot.metadata carries the per-chunk digest list and Snapshot.hash
# commits to all of them.
SNAPSHOT_FORMAT = 1
_CHUNK_TARGET_ENV = "TMTPU_SNAPSHOT_CHUNK_BYTES"
CHUNK_TARGET_BYTES = 65536


def encode_chunk(start: int, pairs: list[tuple[str, bytes]], proof: merkle.RangeProof) -> bytes:
    w = Writer().u32(start).u32(len(pairs))
    for k, v in pairs:
        w.str(k).bytes(v)
    w.bytes(proof.encode())
    return w.build()


def decode_chunk(data: bytes) -> tuple[int, list[tuple[str, bytes]], merkle.RangeProof]:
    r = Reader(data)
    start = r.u32()
    pairs = [(r.str(), r.bytes()) for _ in range(r.u32())]
    proof = merkle.RangeProof.decode(r.bytes())
    r.expect_done()
    return start, pairs, proof


def encode_chunk_hashes(hashes: list[bytes]) -> bytes:
    w = Writer().u32(len(hashes))
    for h in hashes:
        w.bytes(h)
    return w.build()


def decode_chunk_hashes(metadata: bytes) -> list[bytes]:
    r = Reader(metadata)
    hashes = [r.bytes() for _ in range(r.u32())]
    r.expect_done()
    return hashes


def snapshot_hash(chunk_hashes: list[bytes]) -> bytes:
    return sum_sha256(b"".join(chunk_hashes))


class KVStoreApplication(abci.BaseApplication):
    """provable=True (default) roots the app hash in a merkle map of the
    state so Query(prove=True) proofs chain to the verified header — a
    feature the reference's kvstore lacks (its Query TODOs the proof out).
    The map root costs O(state) tree folding per Commit; provable=False is
    the reference-parity app (kvstore.go:111 — app hash is just the
    encoded tx count, O(1)), the right mode for throughput benchmarking."""

    def __init__(self, provable: bool = True) -> None:
        self.provable = provable
        self.state: dict[str, bytes] = {}
        self.height = 0
        self.app_hash = b""
        self.tx_count = 0
        # encoded leaf per key, maintained on writes: Commit re-folds the
        # tree over cached leaves instead of re-encoding + re-sha-ing every
        # value (the naive recompute was O(state) of redundant hashing per
        # block and the single biggest cost of a loaded node's commit round)
        self._leaves: dict[str, bytes] = {}

    # -- helpers ------------------------------------------------------------

    def _leaf(self, key: str) -> bytes:
        return Writer().str(key).bytes(sum_sha256(self.state[key])).build()

    def _compute_app_hash(self) -> bytes:
        if not self.provable:
            return self.tx_count.to_bytes(8, "big")
        return merkle.hash_from_byte_slices(
            [self._leaves[k] for k in sorted(self._leaves)]
        )

    @staticmethod
    def _parse_tx(tx: bytes) -> tuple[str, bytes]:
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k, v = tx, tx
        return k.decode("utf-8", "replace"), v

    # -- ABCI ---------------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": len(self.state)}),
            version="kvstore/0.1.0",
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        key, value = self._parse_tx(req.tx)
        self.state[key] = value
        if self.provable:  # non-provable mode must not pay per-tx hashing
            self._leaves[key] = self._leaf(key)
        self.tx_count += 1
        return abci.ResponseDeliverTx(
            code=abci.CODE_TYPE_OK,
            events={"app.creator": ["kvstore"], "app.key": [key]},
        )

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        self.height = req.height
        return abci.ResponseEndBlock()

    def commit(self) -> abci.ResponseCommit:
        self.app_hash = self._compute_app_hash()
        return abci.ResponseCommit(data=self.app_hash)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        key = req.data.decode("utf-8", "replace")
        value = self.state.get(key)
        resp = abci.ResponseQuery(
            code=abci.CODE_TYPE_OK,
            key=req.data,
            value=value if value is not None else b"",
            height=self.height,
            log="exists" if value is not None else "does not exist",
        )
        if req.prove and value is not None and self.provable:
            # merkle proof of (key, sha256(value)) in the sorted state map
            keys = sorted(self._leaves)
            items = [self._leaves[k] for k in keys]
            root, proofs = merkle.proofs_from_byte_slices(items)
            idx = keys.index(key)
            op = merkle.SimpleValueOp(req.data, proofs[idx])
            resp.proof_ops = [op.proof_op()]
        return resp


class PersistentKVStoreApplication(KVStoreApplication):
    """Adds disk persistence + validator-update transactions
    (reference persistent_kvstore.go), and — when `snapshot_interval` is
    set — chunked, proof-carrying state snapshots every that-many commits
    plus the matching restore path (the four ABCI state-sync methods).
    Old blocks below the oldest kept snapshot are released via
    ResponseCommit.retain_height, so a long-lived replica's block store
    stays O(snapshot window), not O(history)."""

    def __init__(
        self,
        db_dir: str,
        snapshot_interval: int = 0,
        snapshot_keep: int = 2,
    ) -> None:
        super().__init__()
        self.db_dir = db_dir
        os.makedirs(db_dir, exist_ok=True)
        self._db_path = os.path.join(db_dir, "kvstore_state.json")
        self.validators: dict[str, int] = {}  # pubkey hex -> power
        self._pending_updates: list[abci.ValidatorUpdate] = []
        self.snapshot_interval = max(0, int(snapshot_interval))
        self.snapshot_keep = max(1, int(snapshot_keep))
        self._snapshot_dir = os.path.join(db_dir, "snapshots")
        self._snapshots: dict[int, abci.Snapshot] = {}  # height -> manifest
        self._restore: dict | None = None  # in-flight restore state
        self._load()
        self._load_snapshots()

    # -- snapshot serving side ---------------------------------------------

    def _load_snapshots(self) -> None:
        if not os.path.isdir(self._snapshot_dir):
            return
        for name in sorted(os.listdir(self._snapshot_dir)):
            manifest = os.path.join(self._snapshot_dir, name, "manifest.json")
            try:
                with open(manifest, encoding="utf-8") as f:
                    d = json.load(f)
                snap = abci.Snapshot(
                    height=d["height"],
                    format=d["format"],
                    chunks=d["chunks"],
                    hash=bytes.fromhex(d["hash"]),
                    metadata=bytes.fromhex(d["metadata"]),
                )
            except (OSError, ValueError, KeyError):
                continue  # torn write of a dying snapshot attempt: skip it
            self._snapshots[snap.height] = snap

    def _chunk_path(self, height: int, index: int) -> str:
        return os.path.join(self._snapshot_dir, f"{height:020d}", f"chunk_{index}")

    def _take_snapshot(self) -> None:
        """Chunk the sorted state map; every chunk carries a RangeProof to
        the app hash just committed."""
        keys = sorted(self._leaves)
        if not keys:
            return  # nothing to snapshot (and nothing to prove)
        leaves = [self._leaves[k] for k in keys]
        target = int(os.environ.get(_CHUNK_TARGET_ENV, CHUNK_TARGET_BYTES))
        target = max(1, target)
        chunks: list[bytes] = []
        start = 0
        # one subtree cache for the whole snapshot: adjacent chunk proofs
        # share out-of-range subtree roots, so this runs on the commit
        # path at O(n) total hashing instead of O(n × chunks)
        subtrees: dict = {}
        while start < len(keys):
            size = 0
            end = start
            while end < len(keys) and (size == 0 or size < target):
                size += len(keys[end]) + len(self.state[keys[end]]) + 16
                end += 1
            proof = merkle.range_proof(
                leaves, start, end - start, subtree_cache=subtrees
            )
            pairs = [(k, self.state[k]) for k in keys[start:end]]
            chunks.append(encode_chunk(start, pairs, proof))
            start = end
        chunk_hashes = [sum_sha256(c) for c in chunks]
        snap = abci.Snapshot(
            height=self.height,
            format=SNAPSHOT_FORMAT,
            chunks=len(chunks),
            hash=snapshot_hash(chunk_hashes),
            metadata=encode_chunk_hashes(chunk_hashes),
        )
        snap_dir = os.path.join(self._snapshot_dir, f"{snap.height:020d}")
        os.makedirs(snap_dir, exist_ok=True)
        for i, chunk in enumerate(chunks):
            with open(self._chunk_path(snap.height, i), "wb") as f:
                f.write(chunk)
        # manifest LAST: its presence marks the snapshot complete
        with open(os.path.join(snap_dir, "manifest.json"), "w", encoding="utf-8") as f:
            json.dump(
                {
                    "height": snap.height,
                    "format": snap.format,
                    "chunks": snap.chunks,
                    "hash": snap.hash.hex(),
                    "metadata": snap.metadata.hex(),
                    "app_hash": self.app_hash.hex(),
                },
                f,
            )
        self._snapshots[snap.height] = snap
        for old in sorted(self._snapshots)[: -self.snapshot_keep]:
            del self._snapshots[old]
            shutil.rmtree(
                os.path.join(self._snapshot_dir, f"{old:020d}"), ignore_errors=True
            )

    def retain_height(self) -> int:
        """Blocks below the oldest kept snapshot are prunable — a peer
        bootstrapping from our snapshots only ever fast-syncs forward from
        one of them (advertised bases keep honest peers away from the
        pruned range)."""
        if not self._snapshots:
            return 0
        return min(self._snapshots)

    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        snaps = [self._snapshots[h] for h in sorted(self._snapshots, reverse=True)]
        return abci.ResponseListSnapshots(snapshots=snaps)

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        snap = self._snapshots.get(req.height)
        if snap is None or snap.format != req.format or not (0 <= req.chunk < snap.chunks):
            return abci.ResponseLoadSnapshotChunk()
        try:
            with open(self._chunk_path(req.height, req.chunk), "rb") as f:
                return abci.ResponseLoadSnapshotChunk(chunk=f.read())
        except OSError:
            return abci.ResponseLoadSnapshotChunk()

    # -- snapshot restore side ---------------------------------------------

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        snap = req.snapshot
        if snap.format != SNAPSHOT_FORMAT:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT_FORMAT)
        if snap.height <= 0 or snap.chunks <= 0 or not req.app_hash:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT)
        try:
            chunk_hashes = decode_chunk_hashes(snap.metadata)
        except DecodeError:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT)
        if len(chunk_hashes) != snap.chunks or snapshot_hash(chunk_hashes) != snap.hash:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT)
        self._restore = {
            "snapshot": snap,
            "app_hash": req.app_hash,  # light-client-verified: the proof root
            "chunk_hashes": chunk_hashes,
            "applied": 0,
            "pairs": [],
        }
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        rs = self._restore
        if rs is None:
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ABORT)

        def retry() -> abci.ResponseApplySnapshotChunk:
            # corrupt/forged chunk: never applied; ask the reactor to
            # refetch this index from someone else and drop the sender
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY,
                refetch_chunks=[req.index],
                reject_senders=[req.sender] if req.sender else [],
            )

        if req.index != rs["applied"]:  # chunks apply strictly in order
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY, refetch_chunks=[rs["applied"]]
            )
        if sum_sha256(req.chunk) != rs["chunk_hashes"][req.index]:
            return retry()
        try:
            start, pairs, proof = decode_chunk(req.chunk)
        except DecodeError:
            return retry()
        leaves = [
            Writer().str(k).bytes(sum_sha256(v)).build() for k, v in pairs
        ]
        if (
            start != len(rs["pairs"])
            or proof.start != start
            or proof.count != len(pairs)
            or not proof.verify(rs["app_hash"], leaves)
        ):
            return retry()
        if req.index == rs["snapshot"].chunks - 1 and proof.total != start + len(pairs):
            return retry()  # final chunk must complete the tree
        rs["pairs"].extend(pairs)
        rs["applied"] += 1
        if rs["applied"] == rs["snapshot"].chunks:
            self.state = {k: v for k, v in rs["pairs"]}
            self._leaves = {k: self._leaf(k) for k in self.state}
            # validator bookkeeping rides the snapshotted state as val:
            # records (_set_validator_record) — rebuild the dict from them
            self.validators = {
                k[len("val:"):]: int(v)
                for k, v in self.state.items()
                if k.startswith("val:")
            }
            self.height = rs["snapshot"].height
            self.tx_count = 0  # unknowable from state alone; provable mode unused
            self.app_hash = self._compute_app_hash()
            if self.app_hash != rs["app_hash"]:
                # unreachable given the per-chunk proofs; belt + suspenders
                self._restore = None
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_REJECT_SNAPSHOT
                )
            self._save()
            self._restore = None
        return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)

    def _load(self) -> None:
        if os.path.exists(self._db_path):
            with open(self._db_path) as f:
                d = json.load(f)
            self.state = {k: bytes.fromhex(v) for k, v in d["state"].items()}
            self._leaves = {k: self._leaf(k) for k in self.state}
            self.height = d["height"]
            self.app_hash = bytes.fromhex(d["app_hash"])
            self.validators = d.get("validators", {})

    def _save(self) -> None:
        with open(self._db_path, "w") as f:
            json.dump(
                {
                    "state": {k: v.hex() for k, v in self.state.items()},
                    "height": self.height,
                    "app_hash": self.app_hash.hex(),
                    "validators": self.validators,
                },
                f,
            )

    def _set_validator_record(self, pk_hex: str, power: int) -> None:
        """Mirror the validator bookkeeping into the snapshotted state map
        (the reference persistent_kvstore keeps validator records IN app
        state for exactly this reason): a snapshot-restored replica
        rebuilds `self.validators` from these keys, so restore loses
        nothing. `val:` keys cannot collide with user txs — deliver_tx
        routes anything with that prefix to the validator parser."""
        key = f"val:{pk_hex}"
        if power == 0:
            self.state.pop(key, None)
            self._leaves.pop(key, None)
        else:
            self.state[key] = str(power).encode()
            if self.provable:
                self._leaves[key] = self._leaf(key)

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for vu in req.validators:
            self.validators[vu.pub_key.hex()] = vu.power
            self._set_validator_record(vu.pub_key.hex(), vu.power)
        return abci.ResponseInitChain()

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            if self._parse_validator_tx(req.tx) is None:
                return abci.ResponseCheckTx(code=1, log="bad validator tx")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    @staticmethod
    def _parse_validator_tx(tx: bytes) -> tuple[bytes, int] | None:
        # format: val:<pubkey hex>!<power>
        body = tx[len(VALIDATOR_TX_PREFIX) :]
        if b"!" not in body:
            return None
        pk_hex, power_s = body.split(b"!", 1)
        try:
            return bytes.fromhex(pk_hex.decode()), int(power_s)
        except ValueError:
            return None

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            parsed = self._parse_validator_tx(req.tx)
            if parsed is None:
                return abci.ResponseDeliverTx(code=1, log="bad validator tx")
            pub_key, power = parsed
            self._pending_updates.append(abci.ValidatorUpdate(pub_key, power))
            if power == 0:
                self.validators.pop(pub_key.hex(), None)
            else:
                self.validators[pub_key.hex()] = power
            self._set_validator_record(pub_key.hex(), power)
            return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)
        return super().deliver_tx(req)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        self.height = req.height
        updates, self._pending_updates = self._pending_updates, []
        return abci.ResponseEndBlock(validator_updates=updates)

    def commit(self) -> abci.ResponseCommit:
        resp = super().commit()
        self._save()
        if (
            self.provable
            and self.snapshot_interval
            and self.height > 0
            and self.height % self.snapshot_interval == 0
        ):
            self._take_snapshot()
        resp.retain_height = self.retain_height() if self.snapshot_interval else 0
        return resp
