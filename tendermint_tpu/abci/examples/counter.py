"""Counter example app (reference abci/example/counter/counter.go:11):
optionally-serial nonce application used by mempool and consensus tests."""
from __future__ import annotations

from tendermint_tpu.abci import types as abci


class CounterApplication(abci.BaseApplication):
    def __init__(self, serial: bool = False) -> None:
        self.serial = serial
        self.tx_count = 0
        self.height = 0

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=f"txs:{self.tx_count}",
            last_block_height=self.height,
            last_block_app_hash=self._hash() if self.height else b"",
        )

    def set_option(self, req: abci.RequestSetOption) -> abci.ResponseSetOption:
        if req.key == "serial":
            self.serial = req.value == "on"
        return abci.ResponseSetOption()

    def _nonce(self, tx: bytes) -> int:
        return int.from_bytes(tx, "big") if tx else 0

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if self.serial:
            if len(req.tx) > 8:
                return abci.ResponseCheckTx(code=1, log="tx too big")
            if self._nonce(req.tx) < self.tx_count:
                return abci.ResponseCheckTx(code=2, log="nonce too low")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if self.serial:
            if self._nonce(req.tx) != self.tx_count:
                return abci.ResponseDeliverTx(
                    code=2, log=f"expected nonce {self.tx_count}"
                )
        self.tx_count += 1
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        self.height = req.height
        return abci.ResponseEndBlock()

    def _hash(self) -> bytes:
        return self.tx_count.to_bytes(8, "big")

    def commit(self) -> abci.ResponseCommit:
        if self.tx_count == 0 and self.height <= 1:
            return abci.ResponseCommit(data=b"")
        return abci.ResponseCommit(data=self._hash())
