"""Example ABCI applications — the standard test fixtures
(reference abci/example/: kvstore, persistent_kvstore, counter) plus the
signed token-transfer workload (transfer, docs/tx_ingestion.md)."""
from tendermint_tpu.abci.examples.counter import CounterApplication  # noqa: F401
from tendermint_tpu.abci.examples.kvstore import (  # noqa: F401
    KVStoreApplication,
    PersistentKVStoreApplication,
)
from tendermint_tpu.abci.examples.transfer import TransferApplication  # noqa: F401
