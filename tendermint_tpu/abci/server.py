"""ABCI socket server (reference abci/server/socket_server.go).

Serves an Application over the length-prefixed framed protocol; requests
from one connection are processed in order (the protocol is ordered), but
multiple connections (consensus/mempool/query) are independent, matching
proxy.AppConns' three connections.
"""
from __future__ import annotations

import asyncio
import struct

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.types import decode_request, encode_response
from tendermint_tpu.encoding import DecodeError
from tendermint_tpu.libs.service import BaseService


class ABCIServer(BaseService):
    """codec="cbe" (native framing, 4-byte length) or codec="proto"
    (reference-compatible: zigzag-varint-framed protobuf — lets existing
    Go/Rust ABCI clients, i.e. a stock tendermint node, drive this app;
    see abci/proto.py)."""

    def __init__(
        self, app: abci.Application, address: str, codec: str = "cbe"
    ) -> None:
        super().__init__("ABCIServer")
        self.app = app
        self.address = address
        self.codec = codec
        self._server: asyncio.AbstractServer | None = None

    async def on_start(self) -> None:
        if self.address.startswith("unix://"):
            self._server = await asyncio.start_unix_server(
                self._handle, self.address[len("unix://") :]
            )
        else:
            host, port = self.address.replace("tcp://", "").rsplit(":", 1)
            self._server = await asyncio.start_server(self._handle, host, int(port))

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        if self.codec == "proto":
            from tendermint_tpu.abci import proto as pb

            read = pb.read_frame

            def decode(data):
                return pb.decode_request(data)

            def encode(resp):
                return pb.frame(pb.encode_response(resp))
        else:

            read = abci.read_cbe_frame
            decode = decode_request

            def encode(resp):
                payload = encode_response(resp)
                return struct.pack(">I", len(payload)) + payload

        try:
            while True:
                req = decode(await read(reader))
                try:
                    resp = self._dispatch(req)
                except Exception as e:  # app panic -> exception response
                    resp = abci.ResponseException(str(e))
                writer.write(encode(resp))
                if isinstance(req, abci.RequestFlush):
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except DecodeError:
            # malformed client bytes (wrong codec, fuzzer, attacker): drop
            # this connection; the server keeps serving others — the
            # reference socket server likewise kills only the offending
            # conn (abci/server/socket_server.go waitForError path)
            pass
        finally:
            writer.close()

    def _dispatch(self, req):
        a = self.app
        if isinstance(req, abci.RequestEcho):
            return abci.ResponseEcho(req.message)
        if isinstance(req, abci.RequestFlush):
            return abci.ResponseFlush()
        if isinstance(req, abci.RequestInfo):
            return a.info(req)
        if isinstance(req, abci.RequestSetOption):
            return a.set_option(req)
        if isinstance(req, abci.RequestInitChain):
            return a.init_chain(req)
        if isinstance(req, abci.RequestQuery):
            return a.query(req)
        if isinstance(req, abci.RequestBeginBlock):
            return a.begin_block(req)
        if isinstance(req, abci.RequestCheckTx):
            return a.check_tx(req)
        if isinstance(req, abci.RequestCheckTxBatch):
            return a.check_tx_batch(req)
        if isinstance(req, abci.RequestDeliverTx):
            return a.deliver_tx(req)
        if isinstance(req, abci.RequestDeliverTxBatch):
            return a.deliver_tx_batch(req)
        if isinstance(req, abci.RequestEndBlock):
            return a.end_block(req)
        if isinstance(req, abci.RequestCommit):
            return a.commit()
        if isinstance(req, abci.RequestListSnapshots):
            return a.list_snapshots(req)
        if isinstance(req, abci.RequestOfferSnapshot):
            return a.offer_snapshot(req)
        if isinstance(req, abci.RequestLoadSnapshotChunk):
            return a.load_snapshot_chunk(req)
        if isinstance(req, abci.RequestApplySnapshotChunk):
            return a.apply_snapshot_chunk(req)
        return abci.ResponseException(f"unknown request {req!r}")
