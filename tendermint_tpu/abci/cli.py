"""abci-cli — exercise an ABCI application from the command line.

Reference parity: abci/cmd/abci-cli — subcommands echo/info/deliver_tx/
check_tx/commit/query against a running ABCI server (plus this repo's
deliver_tx_batch extension: every positional arg is one tx, answered with
per-tx codes), a batch/console mode
reading commands from stdin (the reference's .abci script files under
abci/tests/test_cli/), and `kvstore`/`counter` to serve the example apps.

    python -m tendermint_tpu.abci.cli kvstore --address tcp://127.0.0.1:26658
    python -m tendermint_tpu.abci.cli --address tcp://127.0.0.1:26658 console
"""
from __future__ import annotations

import argparse
import asyncio
import shlex
import sys

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import SocketClient
from tendermint_tpu.abci.server import ABCIServer


def _parse_bytes(s: str) -> bytes:
    """The reference accepts 0x-hex or quoted strings."""
    if s.startswith("0x") or s.startswith("0X"):
        return bytes.fromhex(s[2:])
    if len(s) >= 2 and s[0] == '"' and s[-1] == '"':
        return s[1:-1].encode()
    return s.encode()


async def run_command(client: SocketClient, cmd: str, args: list[str]) -> str:
    if cmd == "echo":
        res = await client.echo(" ".join(args))
        return f"-> data: {res.message}"
    if cmd == "info":
        res = await client.info(abci.RequestInfo())
        return (
            f"-> data: {res.data}\n-> last_block_height: {res.last_block_height}\n"
            f"-> last_block_app_hash: 0x{res.last_block_app_hash.hex().upper()}"
        )
    if cmd == "deliver_tx":
        res = await client.deliver_tx(abci.RequestDeliverTx(tx=_parse_bytes(args[0]) if args else b""))
        return f"-> code: {res.code}" + (f"\n-> log: {res.log}" if res.log else "")
    if cmd == "deliver_tx_batch":
        res = await client.deliver_tx_batch(
            abci.RequestDeliverTxBatch(txs=[_parse_bytes(a) for a in args])
        )
        out = []
        for i, r in enumerate(res.responses):
            out.append(
                f"-> [{i}] code: {r.code}" + (f" log: {r.log}" if r.log else "")
            )
        return "\n".join(out) if out else "-> (empty batch)"
    if cmd == "check_tx":
        res = await client.check_tx(abci.RequestCheckTx(tx=_parse_bytes(args[0]) if args else b""))
        return f"-> code: {res.code}" + (f"\n-> log: {res.log}" if res.log else "")
    if cmd == "commit":
        res = await client.commit()
        return f"-> data.hex: 0x{res.data.hex().upper()}"
    if cmd == "query":
        res = await client.query(
            abci.RequestQuery(data=_parse_bytes(args[0]) if args else b"")
        )
        out = [f"-> code: {res.code}"]
        if res.log:
            out.append(f"-> log: {res.log}")
        if res.key:
            out.append(f"-> key: {res.key.decode('utf-8', 'replace')}")
        if res.value:
            out.append(f"-> value: {res.value.decode('utf-8', 'replace')}")
        return "\n".join(out)
    if cmd == "set_option":
        await client.set_option(abci.RequestSetOption(key=args[0] if args else "", value=args[1] if len(args) > 1 else ""))
        return "-> code: 0"
    raise ValueError(f"unknown command {cmd!r}")


async def console(client: SocketClient, stream=sys.stdin) -> int:
    """Reference abci-cli console / batch mode: one command per line."""
    loop = asyncio.get_event_loop()
    while True:
        line = await loop.run_in_executor(None, stream.readline)
        if not line:
            return 0
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = shlex.split(line, posix=False)
        print(f"> {line}")
        try:
            print(await run_command(client, parts[0], parts[1:]))
        except Exception as e:
            print(f"-> error: {e}")


async def _amain(args) -> int:
    if args.command in ("kvstore", "counter"):
        if args.command == "kvstore":
            from tendermint_tpu.abci.examples import KVStoreApplication

            app = KVStoreApplication()
        else:
            from tendermint_tpu.abci.examples import CounterApplication

            app = CounterApplication(serial=args.serial)
        if args.abci == "grpc":
            from tendermint_tpu.abci.grpc import GRPCABCIServer

            server = GRPCABCIServer(app, args.address)
        elif args.abci == "proto":
            server = ABCIServer(app, args.address, codec="proto")
        else:
            server = ABCIServer(app, args.address)
        await server.start()
        print(
            f"{args.command} ABCI app listening on {args.address} ({args.abci})",
            file=sys.stderr,
        )
        try:
            await asyncio.Event().wait()
        finally:
            # Ctrl-C cancels the wait: close the listener and its
            # per-connection handlers before the loop shuts down
            await server.stop()
        return 0

    if args.abci == "grpc":
        from tendermint_tpu.abci.grpc import GRPCClient

        client = GRPCClient(args.address)
    elif args.abci == "proto":
        client = SocketClient(args.address, codec="proto")
    else:
        client = SocketClient(args.address)
    await client.start()
    try:
        if args.command in ("console", "batch"):
            return await console(client)
        print(await run_command(client, args.command, args.args))
        return 0
    finally:
        await client.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="abci-cli")
    p.add_argument("--address", default="tcp://127.0.0.1:26658")
    p.add_argument(
        "--abci", default="socket", choices=["socket", "grpc", "proto"],
        help="transport (reference abci-cli --abci); proto = the "
        "reference's protobuf socket wire, for cross-implementation apps",
    )
    p.add_argument("--serial", action="store_true", help="counter: enforce tx ordering")
    p.add_argument(
        "command",
        choices=[
            "echo", "info", "deliver_tx", "deliver_tx_batch", "check_tx",
            "commit", "query", "set_option", "console", "batch", "kvstore",
            "counter",
        ],
    )
    p.add_argument("args", nargs="*")
    args = p.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    raise SystemExit(main())
