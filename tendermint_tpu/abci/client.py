"""ABCI clients.

Reference parity: abci/client/client.go:21 (Client = async+sync API),
abci/client/local_client.go:16 (in-process, global lock),
abci/client/socket_client.go:26,122,154 (pipelined request queue + FIFO
response matching over a length-prefixed socket).

Async methods return awaitables; the "Sync" variants of the reference are
just `await` here. Pipelining: `deliver_tx_async` enqueues without waiting;
`flush` drains the pipeline — exactly the reference's usage pattern in
state/execution.go:284-293.
"""
from __future__ import annotations

import asyncio
import struct

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding import DecodeError
from tendermint_tpu.abci.types import (
    decode_response,
    encode_request,
)
from tendermint_tpu.libs.service import BaseService


class ABCIClientError(Exception):
    pass


class Client(BaseService):
    """Interface: one async method per ABCI request + flush."""

    async def echo(self, message: str) -> abci.ResponseEcho: ...
    async def info(self, req: abci.RequestInfo) -> abci.ResponseInfo: ...
    async def set_option(self, req: abci.RequestSetOption) -> abci.ResponseSetOption: ...
    async def query(self, req: abci.RequestQuery) -> abci.ResponseQuery: ...
    async def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx: ...
    async def check_tx_batch(
        self, req: abci.RequestCheckTxBatch
    ) -> abci.ResponseCheckTxBatch: ...
    async def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain: ...
    async def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock: ...
    async def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx: ...
    async def deliver_tx_batch(
        self, req: abci.RequestDeliverTxBatch
    ) -> abci.ResponseDeliverTxBatch: ...
    async def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock: ...
    async def commit(self) -> abci.ResponseCommit: ...
    async def list_snapshots(
        self, req: abci.RequestListSnapshots
    ) -> abci.ResponseListSnapshots: ...
    async def offer_snapshot(
        self, req: abci.RequestOfferSnapshot
    ) -> abci.ResponseOfferSnapshot: ...
    async def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk: ...
    async def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk: ...
    async def flush(self) -> None: ...

    def deliver_tx_async(self, req: abci.RequestDeliverTx) -> "asyncio.Future":
        """Pipelined delivery; result available after flush()."""
        raise NotImplementedError

    def check_tx_async(self, req: abci.RequestCheckTx) -> "asyncio.Future":
        raise NotImplementedError


class LocalClient(Client):
    """In-process app behind one lock (reference local_client.go:16)."""

    def __init__(self, app: abci.Application, lock: asyncio.Lock | None = None) -> None:
        super().__init__("LocalABCIClient")
        self.app = app
        # one shared lock per app across the 3 proxy connections, like the
        # reference's global mutex
        self._lock = lock or asyncio.Lock()

    async def _call(self, fn, *args):
        async with self._lock:
            return fn(*args)

    async def echo(self, message: str) -> abci.ResponseEcho:
        return abci.ResponseEcho(message)

    async def info(self, req):
        return await self._call(self.app.info, req)

    async def set_option(self, req):
        return await self._call(self.app.set_option, req)

    async def query(self, req):
        return await self._call(self.app.query, req)

    async def check_tx(self, req):
        return await self._call(self.app.check_tx, req)

    async def check_tx_batch(self, req):
        """Bulk admission runs OFF the event loop: the whole point of the
        batch surface is that the app fuses per-tx signature work into
        one device-scheduler submission, and that submission BLOCKS for
        its verdicts — inline it would stall every other coroutine for
        the duration of a device round trip. The app lock is held across
        the thread hop, so app calls stay strictly serialized; to_thread
        copies the contextvars, so the mempool's MEMPOOL_CHECK priority
        scope reaches the backend."""
        async with self._lock:
            return await asyncio.to_thread(self.app.check_tx_batch, req)

    async def init_chain(self, req):
        return await self._call(self.app.init_chain, req)

    async def begin_block(self, req):
        return await self._call(self.app.begin_block, req)

    async def deliver_tx(self, req):
        return await self._call(self.app.deliver_tx, req)

    async def deliver_tx_batch(self, req):
        """Block execution runs OFF the event loop, same shape as
        check_tx_batch: the app fuses the whole block's signature work
        into one device-scheduler submission per curve, and that
        submission BLOCKS for its verdicts. The app lock is held across
        the thread hop, so app calls stay strictly serialized; to_thread
        copies the contextvars, so the executor's CONSENSUS_COMMIT
        priority scope reaches the backend."""
        async with self._lock:
            return await asyncio.to_thread(self.app.deliver_tx_batch, req)

    async def end_block(self, req):
        return await self._call(self.app.end_block, req)

    async def commit(self):
        return await self._call(self.app.commit)

    async def list_snapshots(self, req):
        return await self._call(self.app.list_snapshots, req)

    async def offer_snapshot(self, req):
        return await self._call(self.app.offer_snapshot, req)

    async def load_snapshot_chunk(self, req):
        return await self._call(self.app.load_snapshot_chunk, req)

    async def apply_snapshot_chunk(self, req):
        return await self._call(self.app.apply_snapshot_chunk, req)

    async def flush(self) -> None:
        return None

    def _call_fast(self, fn, req):
        """Application methods are synchronous and `_call` never awaits
        while holding the lock, so when the lock is free the call can run
        inline and return an already-resolved future — no Task object per
        transaction (deliver+check task churn was a top node-profile
        cost). Falls back to a real task when another connection holds
        the lock mid-acquire."""
        if self._lock.locked():
            return asyncio.ensure_future(self._call(fn, req))
        fut = asyncio.get_running_loop().create_future()
        try:
            fut.set_result(fn(req))
        except BaseException as e:  # noqa: BLE001 — future carries it
            fut.set_exception(e)
        return fut

    def deliver_tx_async(self, req):
        return self._call_fast(self.app.deliver_tx, req)

    def check_tx_async(self, req):
        return self._call_fast(self.app.check_tx, req)


class SocketClient(Client):
    """Length-prefixed framed protocol over TCP or unix socket, pipelined:
    requests are written immediately, responses matched FIFO
    (reference socket_client.go:122,154)."""

    def __init__(self, address: str, codec: str = "cbe") -> None:
        super().__init__("SocketABCIClient")
        self.address = address
        # codec="proto": reference-compatible zigzag-varint-framed protobuf
        # — this node can drive any existing Go/Rust ABCI app (abci/proto.py).
        # Resolved ONCE here into (encode_frame, read_one) so the wire
        # format is a single-point decision, not a per-call branch.
        self.codec = codec
        if codec == "proto":
            from tendermint_tpu.abci import proto as pb

            self._encode_frame = lambda req: pb.frame(pb.encode_request(req))

            async def read_one():
                return pb.decode_response(await pb.read_frame(self._reader))
        else:

            def _encode_cbe(req):
                payload = encode_request(req)
                return struct.pack(">I", len(payload)) + payload

            self._encode_frame = _encode_cbe

            async def read_one():
                return decode_response(
                    await abci.read_cbe_frame(self._reader)
                )

        self._read_one = read_one
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._pending: asyncio.Queue[asyncio.Future] = asyncio.Queue()
        self._conn_err: Exception | None = None

    async def on_start(self) -> None:
        if self.address.startswith("unix://"):
            self._reader, self._writer = await asyncio.open_unix_connection(
                self.address[len("unix://") :]
            )
        else:
            host, port = self.address.replace("tcp://", "").rsplit(":", 1)
            self._reader, self._writer = await asyncio.open_connection(host, int(port))
        self.spawn(self._recv_routine(), "abci-recv")

    async def on_stop(self) -> None:
        if self._writer is not None:
            self._writer.close()

    async def _recv_routine(self) -> None:
        try:
            while True:
                resp = await self._read_one()
                fut = self._pending.get_nowait()
                if isinstance(resp, abci.ResponseException):
                    fut.set_exception(ABCIClientError(resp.error))
                else:
                    fut.set_result(resp)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            asyncio.QueueEmpty,
            DecodeError,  # malformed wire data (e.g. wrong-codec peer)
        ) as e:
            self._conn_err = e
            while not self._pending.empty():
                fut = self._pending.get_nowait()
                if not fut.done():
                    fut.set_exception(ABCIClientError(f"connection lost: {e}"))
        except asyncio.CancelledError:
            pass

    def _send(self, req) -> asyncio.Future:
        if self._conn_err is not None:
            raise ABCIClientError(f"connection lost: {self._conn_err}")
        self._writer.write(self._encode_frame(req))
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending.put_nowait(fut)
        return fut

    async def _send_wait(self, req):
        fut = self._send(req)
        await self._drain()
        return await fut

    async def _drain(self) -> None:
        await self._writer.drain()

    async def echo(self, message: str):
        return await self._send_wait(abci.RequestEcho(message))

    async def info(self, req):
        return await self._send_wait(req)

    async def set_option(self, req):
        return await self._send_wait(req)

    async def query(self, req):
        return await self._send_wait(req)

    async def check_tx(self, req):
        return await self._send_wait(req)

    async def check_tx_batch(self, req):
        return await self._send_wait(req)

    async def init_chain(self, req):
        return await self._send_wait(req)

    async def begin_block(self, req):
        return await self._send_wait(req)

    async def deliver_tx(self, req):
        return await self._send_wait(req)

    async def deliver_tx_batch(self, req):
        return await self._send_wait(req)

    async def end_block(self, req):
        return await self._send_wait(req)

    async def commit(self):
        return await self._send_wait(abci.RequestCommit())

    async def list_snapshots(self, req):
        return await self._send_wait(req)

    async def offer_snapshot(self, req):
        return await self._send_wait(req)

    async def load_snapshot_chunk(self, req):
        return await self._send_wait(req)

    async def apply_snapshot_chunk(self, req):
        return await self._send_wait(req)

    async def flush(self) -> None:
        fut = self._send(abci.RequestFlush())
        await self._drain()
        await fut

    def deliver_tx_async(self, req):
        return self._send(req)

    def check_tx_async(self, req):
        return self._send(req)
