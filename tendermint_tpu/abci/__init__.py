"""ABCI — the application blockchain interface.

Reference parity: abci/types/application.go:11-30 (the 11-method
Application interface), abci/client (socket/local clients with async
pipelining), abci/server, abci/example (kvstore/counter test fixtures).
Wire format here is CBE-framed (u32 length + 1-byte tag + payload) instead
of length-prefixed protobuf; semantics are unchanged.
"""
from tendermint_tpu.abci.types import (  # noqa: F401
    CODE_TYPE_OK,
    Application,
    BaseApplication,
    RequestBeginBlock,
    RequestCheckTx,
    RequestCommit,
    RequestDeliverTx,
    RequestEcho,
    RequestEndBlock,
    RequestFlush,
    RequestInfo,
    RequestInitChain,
    RequestQuery,
    RequestSetOption,
    ResponseBeginBlock,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEcho,
    ResponseEndBlock,
    ResponseFlush,
    ResponseInfo,
    ResponseInitChain,
    ResponseQuery,
    ResponseSetOption,
    ValidatorUpdate,
)
