"""proxy — the node's three typed ABCI connections.

Reference parity: proxy/multi_app_conn.go:12,30,64 (AppConns starts
consensus/mempool/query clients), proxy/app_conn.go:11-43 (typed facades),
proxy/client.go:15,27,66 (ClientCreator mapping --proxy_app to an
in-process example app, a local client, or a socket client).
"""
from __future__ import annotations

import asyncio

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import client as abci_client
from tendermint_tpu.abci.client import Client, LocalClient, SocketClient
from tendermint_tpu.libs.service import BaseService


class ClientCreator:
    """Creates one ABCI client per proxy connection."""

    def new_client(self) -> Client:
        raise NotImplementedError


class LocalClientCreator(ClientCreator):
    """In-process app shared behind one lock (reference NewLocalClientCreator)."""

    def __init__(self, app: abci.Application) -> None:
        self.app = app
        self._lock = asyncio.Lock()

    def new_client(self) -> Client:
        return LocalClient(self.app, self._lock)


class RemoteClientCreator(ClientCreator):
    """Connection to an external app process: socket framing by default,
    gRPC for `grpc://` addresses or transport="grpc" (reference
    NewRemoteClientCreator's socket/grpc transport switch). The "proto"
    transport speaks the reference's zigzag-varint-framed protobuf socket
    protocol (abci/proto.py), so this node can drive an existing Go/Rust
    ABCI app unchanged."""

    def __init__(self, address: str, transport: str = "socket") -> None:
        self.address = address
        self.transport = "grpc" if address.startswith("grpc://") else transport

    def new_client(self) -> Client:
        if self.transport == "grpc":
            from tendermint_tpu.abci.grpc import GRPCClient

            return GRPCClient(self.address)
        if self.transport == "proto":
            return SocketClient(self.address, codec="proto")
        return SocketClient(self.address)


def default_client_creator(
    proxy_app: str,
    app: abci.Application | None = None,
    transport: str = "socket",
) -> ClientCreator:
    """Reference proxy/client.go:66 DefaultClientCreator."""
    if app is not None:
        return LocalClientCreator(app)
    if proxy_app == "kvstore":
        from tendermint_tpu.abci.examples import KVStoreApplication

        return LocalClientCreator(KVStoreApplication())
    if proxy_app == "persistent_kvstore" or proxy_app.startswith(
        "persistent_kvstore:"
    ):
        # "persistent_kvstore:<dir>[:<snapshot_interval>]" — disk
        # persistence + validator-update txs (reference abci-cli "kvstore
        # <dir>"); the dir rides in the proxy_app string so each testnet
        # node gets its own state file. A trailing integer segment enables
        # state-sync snapshots every that-many commits (docs/state_sync.md).
        from tendermint_tpu.abci.examples import PersistentKVStoreApplication

        _, _, app_dir = proxy_app.partition(":")
        interval = 0
        head, _, tail = app_dir.rpartition(":")
        if head and tail.isdigit():
            app_dir, interval = head, int(tail)
        return LocalClientCreator(
            PersistentKVStoreApplication(
                app_dir or "kvstore-data", snapshot_interval=interval
            )
        )
    if proxy_app == "transfer" or proxy_app.startswith("transfer:"):
        # "transfer[:<curve>[:<initial_balance>]]" — the signed token-
        # transfer workload (docs/tx_ingestion.md): per-tx secp256k1 (or
        # ed25519) signatures verified in bulk through the batch CheckTx
        # surface and the device scheduler.
        from tendermint_tpu.abci.examples import TransferApplication

        parts = proxy_app.split(":")
        curve = parts[1] if len(parts) > 1 and parts[1] else "secp256k1"
        initial = int(parts[2]) if len(parts) > 2 and parts[2] else 10**9
        return LocalClientCreator(
            TransferApplication(curve=curve, initial_balance=initial)
        )
    if proxy_app == "counter":
        from tendermint_tpu.abci.examples import CounterApplication

        return LocalClientCreator(CounterApplication())
    if proxy_app == "counter_serial":
        from tendermint_tpu.abci.examples import CounterApplication

        return LocalClientCreator(CounterApplication(serial=True))
    if proxy_app == "noop":
        return LocalClientCreator(abci.BaseApplication())
    return RemoteClientCreator(proxy_app, transport)


class AppConnConsensus:
    """Reference proxy/app_conn.go:11 — the consensus connection facade."""

    def __init__(self, client: Client) -> None:
        self._client = client

    async def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return await self._client.init_chain(req)

    async def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        return await self._client.begin_block(req)

    def deliver_tx_async(self, tx: bytes) -> asyncio.Future:
        return self._client.deliver_tx_async(abci.RequestDeliverTx(tx))

    async def deliver_tx_batch(self, txs: list[bytes]) -> list[abci.ResponseDeliverTx]:
        """One round trip for a whole decided block (docs/tx_ingestion.md).
        Raises whatever the transport raises — the block executor owns the
        loud per-tx fallback for apps that don't implement the batch arm."""
        res = await self._client.deliver_tx_batch(abci.RequestDeliverTxBatch(txs))
        if len(res.responses) != len(txs):
            raise abci_client.ABCIClientError(
                f"DeliverTxBatch returned {len(res.responses)} responses "
                f"for {len(txs)} txs"
            )
        return res.responses

    async def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return await self._client.end_block(req)

    async def commit(self) -> abci.ResponseCommit:
        return await self._client.commit()

    async def flush(self) -> None:
        await self._client.flush()


class AppConnMempool:
    def __init__(self, client: Client) -> None:
        self._client = client

    def check_tx_async(self, tx: bytes, new_check: bool = True) -> asyncio.Future:
        return self._client.check_tx_async(abci.RequestCheckTx(tx, new_check))

    async def check_tx(self, tx: bytes, new_check: bool = True) -> abci.ResponseCheckTx:
        return await self._client.check_tx(abci.RequestCheckTx(tx, new_check))

    async def check_tx_batch(
        self, txs: list[bytes], new_check: bool = True
    ) -> list[abci.ResponseCheckTx]:
        """One round trip for a whole ingest bucket (docs/tx_ingestion.md).
        Raises whatever the transport raises — the mempool owns the loud
        per-tx fallback for apps that don't implement the batch arm."""
        res = await self._client.check_tx_batch(
            abci.RequestCheckTxBatch(txs, new_check)
        )
        if len(res.responses) != len(txs):
            raise abci_client.ABCIClientError(
                f"CheckTxBatch returned {len(res.responses)} responses "
                f"for {len(txs)} txs"
            )
        return res.responses

    async def flush(self) -> None:
        await self._client.flush()


class AppConnQuery:
    def __init__(self, client: Client) -> None:
        self._client = client

    async def echo(self, msg: str) -> abci.ResponseEcho:
        return await self._client.echo(msg)

    async def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return await self._client.info(req)

    async def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        return await self._client.query(req)

    async def set_option(self, req: abci.RequestSetOption) -> abci.ResponseSetOption:
        return await self._client.set_option(req)


class AppConnSnapshot:
    """The state-sync connection facade (reference proxy/app_conn.go
    AppConnSnapshot, v0.34): snapshot serving + restore, kept off the
    consensus/mempool/query connections so a replica answering chunk
    requests never contends with block execution."""

    def __init__(self, client: Client) -> None:
        self._client = client

    async def list_snapshots(
        self, req: abci.RequestListSnapshots
    ) -> abci.ResponseListSnapshots:
        return await self._client.list_snapshots(req)

    async def offer_snapshot(
        self, req: abci.RequestOfferSnapshot
    ) -> abci.ResponseOfferSnapshot:
        return await self._client.offer_snapshot(req)

    async def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        return await self._client.load_snapshot_chunk(req)

    async def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        return await self._client.apply_snapshot_chunk(req)


class AppConns(BaseService):
    """Reference proxy/multi_app_conn.go:30 — starts the four clients."""

    def __init__(self, creator: ClientCreator) -> None:
        super().__init__("AppConns")
        self._creator = creator
        self.consensus: AppConnConsensus | None = None
        self.mempool: AppConnMempool | None = None
        self.query: AppConnQuery | None = None
        self.snapshot: AppConnSnapshot | None = None
        self._clients: list[Client] = []

    async def on_start(self) -> None:
        for attr, facade in (
            ("consensus", AppConnConsensus),
            ("mempool", AppConnMempool),
            ("query", AppConnQuery),
            ("snapshot", AppConnSnapshot),
        ):
            client = self._creator.new_client()
            await client.start()
            self._clients.append(client)
            setattr(self, attr, facade(client))

    async def on_stop(self) -> None:
        for c in self._clients:
            await c.stop()
