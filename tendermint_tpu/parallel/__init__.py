"""Multi-chip parallelism for the verification data plane.

The reference verifies commit signatures serially on a single core
(types/validator_set.go:591-633); its only "distributed backend" is the p2p
TCP stack (SURVEY §2.3).  In the TPU-native framework the scaling axis is
signatures-per-commit: a commit's (pubkey, msg, sig) batch is sharded across
the chips of a `jax.sharding.Mesh` on the batch dimension — the framework's
data-parallel axis — and the quorum decision (sum of voting power of valid
signatures vs 2/3 threshold) is computed on-device with a `psum` collective
riding ICI.
"""
from tendermint_tpu.parallel.sharded import (
    build_commit_verifier,
    build_secp_stream_verifier,
    build_sharded_verifier,
    build_stream_verifier,
    make_batch_mesh,
    shard_inputs,
)

__all__ = [
    "build_commit_verifier",
    "build_secp_stream_verifier",
    "build_sharded_verifier",
    "build_stream_verifier",
    "make_batch_mesh",
    "shard_inputs",
]
