"""Batch-dimension sharding of the Ed25519 verify kernel over a device mesh.

Replaces the reference's serial `VerifyCommit` loop
(types/validator_set.go:591-633) at scale: the signature batch is split
across chips (`PartitionSpec(None, "batch")` on the (22, B) limb arrays),
each chip runs the Straus/Shamir double-scalar-multiplication loop on its
shard, and the 2/3-quorum voting-power sum is reduced with `psum` over ICI.

Two entry points:
- `build_sharded_verifier(mesh)` — pjit'd verify: bitmap out, sharded in/out.
- `build_commit_verifier(mesh)` — shard_map'd full commit decision: verify +
  on-device voting-power reduction; returns (bitmap, total_valid_power).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.device import profiler as _profiler
from tendermint_tpu.ops import ed25519_batch

AXIS = "batch"

# The packed (49, B) wire array carries the batch on axis 1 (wire rows on
# axis 0): shard the batch, replicate nothing — every row is per-signature.
_PACKED_SPEC = P(None, AXIS)


def _shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map across jax versions: `jax.shard_map(..., check_vma=)` on
    current jax, `jax.experimental.shard_map.shard_map(..., check_rep=)`
    on 0.4.x (the container's pinned jax). The relaxed check is the same
    either way: the Straus fori_loop carry starts from broadcast module
    constants (identity point), which trips the varying-axes/replication
    check even though every lane's compute is genuinely per-shard."""
    try:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _legacy

        return _legacy(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def make_batch_mesh(devices=None) -> Mesh:
    """A 1-D mesh over the batch axis (all chips verify-data-parallel)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (AXIS,))


def check_divisible(batch: int, mesh: Mesh) -> None:
    """Raise a clear ValueError — not an XLA shape crash deep inside
    shard_map — when a batch does not split evenly over the mesh.
    `_pad_to_bucket` buckets (powers of two ≥ 128 and multiples of 4096)
    are always divisible by the power-of-two meshes `device/mesh.py`
    resolves; a ragged batch here means a caller bypassed the padding."""
    n = int(mesh.size)
    if n and batch % n:
        raise ValueError(
            f"batch of {batch} lanes does not divide over a {n}-device "
            f"mesh — pad to a mesh-divisible bucket first "
            f"(ops/ed25519_batch._pad_to_bucket guarantees this for the "
            f"power-of-two meshes device/mesh.py builds)"
        )


def shard_inputs(mesh: Mesh, packed):
    """Place a `prepare_batch` packed array onto the mesh, batch-sharded.

    The batch dim must be divisible by the mesh size; `prepare_batch` pads to
    power-of-two buckets, so any power-of-two mesh divides it.
    """
    check_divisible(int(packed.shape[1]), mesh)
    return jax.device_put(packed, NamedSharding(mesh, _PACKED_SPEC))


def _donate_default(mesh: Mesh) -> bool:
    """Whether the per-batch (signature) wire block should be donated to
    the compiled program: on TPU donation lets XLA reuse the input HBM for
    scratch so streamed buckets stay device-resident with no extra copy;
    XLA:CPU does not implement buffer donation and would warn per program,
    so the virtual test mesh leaves it off."""
    return mesh.devices.flat[0].platform == "tpu"


def build_sharded_verifier(mesh: Mesh):
    """jit the verify kernel with explicit batch shardings over `mesh`."""
    return _profiler.wrap(
        f"ed25519_packed_mesh{mesh.size}",
        jax.jit(
            lambda packed: ed25519_batch.verify_core(*ed25519_batch.unpack(packed)),
            in_shardings=(NamedSharding(mesh, _PACKED_SPEC),),
            out_shardings=NamedSharding(mesh, P(AXIS)),
        ),
    )


def build_stream_verifier(mesh: Mesh, donate: bool | None = None):
    """jit'd (keys, sigs) -> ok bitmap, batch-sharded over the mesh, using
    the platform-preferred kernel per shard (the Pallas/Mosaic kernel on
    TPU, the XLA kernel elsewhere). This is the production multi-chip
    entry: the DeviceScheduler's packed dispatches route through it (via
    ops/ed25519_batch and device/mesh.py) whenever the resolved mesh has
    more than one device, so a v4-8 slice splits every chunk across its
    chips with zero cross-chip traffic (verdicts are per-signature; the
    quorum sum happens on host where 63-bit voting power lives).

    The jit carries matched in/out shardings (callers place the wire
    blocks with exactly these, so no resharding happens at the call
    boundary) and — on TPU — donates the per-batch sig block so streamed
    buckets stay device-resident (`donate` overrides; the cached pubkey
    block is NEVER donated, it is reused across commits)."""
    import jax as _jax

    from tendermint_tpu.ops import kcache

    kcache.enable_persistent_cache()
    _, kernel = kcache._kernel_for(mesh.devices.flat[0].platform)

    def local(keys, sigs):
        return kernel(keys, sigs)

    mapped = _shard_map(
        local, mesh, (P(None, AXIS), P(None, AXIS)), P(AXIS)
    )
    sh = NamedSharding(mesh, _PACKED_SPEC)
    jitted = _jax.jit(
        mapped,
        in_shardings=(sh, sh),
        out_shardings=NamedSharding(mesh, P(AXIS)),
        donate_argnums=(1,)
        if (donate if donate is not None else _donate_default(mesh))
        else (),
    )

    timed = _profiler.wrap(f"ed25519_stream_mesh{mesh.size}", jitted)

    def run(keys, sigs):
        check_divisible(int(sigs.shape[1]), mesh)
        return timed(keys, sigs)

    # the raw jitted program, for AOT lowering (ops/aot.py bakes exactly
    # the program the live path runs: a Mosaic kernel cannot be GSPMD-
    # partitioned by pjit alone, it must stay wrapped in this shard_map)
    run.jitted = jitted
    return run


def build_secp_stream_verifier(mesh: Mesh, donate: bool | None = None):
    """jit'd (sigs (32, B), keys (16, B)) -> ok bitmap for secp256k1-ECDSA,
    batch-sharded over the mesh (SURVEY §7: BOTH curves' batches shard
    across chips — a mixed-curve 10k-validator commit, BASELINE config 5's
    shape, splits its secp share over the same mesh as its ed25519 share).
    Per shard: the Mosaic kernel on TPU, the XLA variant elsewhere (the
    virtual CPU test mesh has no Mosaic). Reference serial analog:
    /root/reference/crypto/secp256k1/secp256k1_nocgo.go:21-50."""
    from tendermint_tpu.ops import kcache, secp_batch

    # sharded programs have no export-blob layer; the persistent XLA
    # cache is what saves the next process (and the next test run) the
    # cold compile — enable it here so direct builder users get it too
    kcache.enable_persistent_cache()
    if mesh.devices.flat[0].platform == "tpu":
        from tendermint_tpu.ops import pallas_secp

        def local(sigs, keys):
            return pallas_secp.secp_verify_kernel(sigs, keys)

    else:
        # Non-TPU mesh (the virtual 8-CPU test mesh): the limb kernels
        # are Mosaic-shaped and pathological to compile on XLA:CPU
        # (>18 min measured — see pallas_secp.secp_verify_xla notes), so
        # the per-shard body calls back into the host verifier. The
        # sharding semantics under test — PartitionSpec, shard splits,
        # boundary lanes — are identical; Mosaic codegen itself is
        # covered by the device-gated tier (tools/tpu_artifact.sh).
        def local(sigs, keys):
            return jax.pure_callback(
                secp_batch.host_verify_blocks,
                jax.ShapeDtypeStruct((sigs.shape[1],), bool),
                sigs,
                keys,
            )

    mapped = _shard_map(
        local, mesh, (P(None, AXIS), P(None, AXIS)), P(AXIS)
    )
    sh = NamedSharding(mesh, _PACKED_SPEC)
    jitted = jax.jit(
        mapped,
        in_shardings=(sh, sh),
        out_shardings=NamedSharding(mesh, P(AXIS)),
        # arg 0 is the per-batch sig block ((u1,u2,t1,t2) planes); the
        # cached Q block (arg 1) is reused across batches — never donated
        donate_argnums=(0,)
        if (donate if donate is not None else _donate_default(mesh))
        else (),
    )

    timed = _profiler.wrap(f"secp_stream_mesh{mesh.size}", jitted)

    def run(sigs, keys):
        check_divisible(int(sigs.shape[1]), mesh)
        return timed(sigs, keys)

    return run


def build_commit_verifier(mesh: Mesh):
    """shard_map'd commit decision: per-chip verify + psum'd valid count.

    Returns fn(packed) -> (ok_bitmap (B,), n_valid ()).
    The exact 2/3 voting-power quorum is computed on host from the bitmap
    (voting power is 63-bit in the reference — MaxTotalVotingPower = 2^60/8,
    types/validator_set.go:807-845 — which does not fit device int32 math);
    the psum here gives the fast all-chips-agree valid count over ICI.
    """

    def local(packed):
        ok = ed25519_batch.verify_core(*ed25519_batch.unpack(packed))
        n_valid = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), AXIS)
        return ok, n_valid

    mapped = _shard_map(local, mesh, (_PACKED_SPEC,), (P(AXIS), P()))
    return _profiler.wrap(f"ed25519_commit_mesh{mesh.size}", jax.jit(mapped))
