"""Batch-dimension sharding of the Ed25519 verify kernel over a device mesh.

Replaces the reference's serial `VerifyCommit` loop
(types/validator_set.go:591-633) at scale: the signature batch is split
across chips (`PartitionSpec(None, "batch")` on the (22, B) limb arrays),
each chip runs the Straus/Shamir double-scalar-multiplication loop on its
shard, and the 2/3-quorum voting-power sum is reduced with `psum` over ICI.

Two entry points:
- `build_sharded_verifier(mesh)` — pjit'd verify: bitmap out, sharded in/out.
- `build_commit_verifier(mesh)` — shard_map'd full commit decision: verify +
  on-device voting-power reduction; returns (bitmap, total_valid_power).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.ops import ed25519_batch

AXIS = "batch"

# Positional layout of the kernel inputs; packed word arrays carry the
# batch on axis 1 (words on axis 0), parity is per-signature.
_INPUT_SPECS = {
    "a_x_w": P(None, AXIS),
    "a_y_w": P(None, AXIS),
    "a_t_w": P(None, AXIS),
    "s_w": P(None, AXIS),
    "h_w": P(None, AXIS),
    "yr_w": P(None, AXIS),
    "x_parity": P(AXIS),
}


def make_batch_mesh(devices=None) -> Mesh:
    """A 1-D mesh over the batch axis (all chips verify-data-parallel)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (AXIS,))


def shard_inputs(mesh: Mesh, inputs: dict) -> dict:
    """Place a `prepare_batch` input dict onto the mesh, batch-sharded.

    The batch dim must be divisible by the mesh size; `prepare_batch` pads to
    power-of-two buckets, so any power-of-two mesh divides it.
    """
    out = {}
    for k, v in inputs.items():
        out[k] = jax.device_put(v, NamedSharding(mesh, _INPUT_SPECS[k]))
    return out


def build_sharded_verifier(mesh: Mesh):
    """jit the verify kernel with explicit batch shardings over `mesh`."""
    in_shardings = tuple(
        NamedSharding(mesh, _INPUT_SPECS[k])
        for k in (
            "a_x_w", "a_y_w", "a_t_w", "s_w", "h_w", "yr_w",
            "x_parity",
        )
    )
    return jax.jit(
        ed25519_batch.verify_kernel.__wrapped__,
        in_shardings=in_shardings,
        out_shardings=NamedSharding(mesh, P(AXIS)),
    )


def build_commit_verifier(mesh: Mesh):
    """shard_map'd commit decision: per-chip verify + psum'd valid count.

    Returns fn(a_x_w, ..., x_parity) -> (ok_bitmap (B,), n_valid ()).
    The exact 2/3 voting-power quorum is computed on host from the bitmap
    (voting power is 63-bit in the reference — MaxTotalVotingPower = 2^60/8,
    types/validator_set.go:807-845 — which does not fit device int32 math);
    the psum here gives the fast all-chips-agree valid count over ICI.
    """

    def local(a_x_w, a_y_w, a_t_w, s_w, h_w, yr_w, x_parity):
        ok = ed25519_batch.verify_kernel.__wrapped__(
            a_x_w, a_y_w, a_t_w, s_w, h_w, yr_w, x_parity
        )
        n_valid = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), AXIS)
        return ok, n_valid

    spec_in = tuple(
        _INPUT_SPECS[k]
        for k in (
            "a_x_w", "a_y_w", "a_t_w", "s_w", "h_w", "yr_w",
            "x_parity",
        )
    )
    # check_vma=False: the Shamir fori_loop carry starts from broadcast
    # module constants (identity point), which trips the varying-axes check
    # even though every lane's compute is genuinely per-shard.
    mapped = jax.shard_map(
        local, mesh=mesh, in_specs=spec_in, out_specs=(P(AXIS), P()),
        check_vma=False,
    )
    return jax.jit(mapped)
