"""TimeoutTicker — the single consensus timer.

Reference parity: consensus/ticker.go:17,94 — one timer; scheduling a
timeout overwrites the pending one only for a later (height, round, step);
fired timeouts are delivered on a channel (here: asyncio.Queue).
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass

from tendermint_tpu.consensus.round_state import RoundStep
from tendermint_tpu.libs.service import BaseService


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float
    height: int
    round: int
    step: RoundStep

    def hrs(self) -> tuple[int, int, int]:
        return (self.height, self.round, int(self.step))


class TimeoutTicker(BaseService):
    def __init__(self) -> None:
        super().__init__("TimeoutTicker")
        self.tock: asyncio.Queue[TimeoutInfo] = asyncio.Queue()
        self._current: TimeoutInfo | None = None
        self._timer: asyncio.TimerHandle | None = None

    async def on_stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        """Only later (H,R,S) may replace a pending timeout
        (reference ticker.go:94 timeoutRoutine)."""
        if self._current is not None and self._timer is not None:
            if ti.hrs() <= self._current.hrs():
                return
            self._timer.cancel()
        self._current = ti
        loop = asyncio.get_event_loop()
        self._timer = loop.call_later(ti.duration, self._fire, ti)

    def _fire(self, ti: TimeoutInfo) -> None:
        self._current = None
        self._timer = None
        self.tock.put_nowait(ti)
