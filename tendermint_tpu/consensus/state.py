"""ConsensusState — the Tendermint BFT state machine.

Reference parity: consensus/state.go — single receive routine serializing
all input (:587), step functions enterNewRound/enterPropose/enterPrevote/
enterPrevoteWait/enterPrecommit/enterPrecommitWait/enterCommit/
finalizeCommit (:774-1354), POL lock/unlock rules (:1060-1156,1596-1630),
WAL write-ahead of every message (:630,635), monotonic vote time
(:1681-1739), panic-on-invariant = halt (:600-613), fail.fail() crash
points across the commit pipeline (:1287-1344).

asyncio mapping: goroutine -> task, channel -> Queue; the single
receive_routine task preserves the reference's total ordering of state
transitions.
"""
from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field

from tendermint_tpu.config import ConsensusConfig
from tendermint_tpu.consensus import messages as m
from tendermint_tpu.consensus.round_state import HeightVoteSet, RoundState, RoundStep
from tendermint_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker
from tendermint_tpu.consensus.wal import (
    WAL,
    EndHeightMessage,
    EventDataRoundState,
    MsgInfo,
    NilWAL,
    WALTimeoutInfo,
)
from tendermint_tpu.device.priorities import Priority, priority_scope
from tendermint_tpu.libs import fail
from tendermint_tpu.libs import trace as tmtrace
from tendermint_tpu.libs.recorder import RECORDER
from tendermint_tpu.libs.txlife import TXLIFE
from tendermint_tpu.types.tx import tx_hash
from tendermint_tpu.libs.sigcache import SIG_CACHE
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.libs.service import BaseService, spawn_logged
from tendermint_tpu.state import State
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.types import (
    Block,
    BlockID,
    PartSet,
    Proposal,
    Vote,
    VoteSet,
    VoteType,
)
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.priv_validator import PrivValidator
from tendermint_tpu.types.vote import now_ns
from tendermint_tpu.types.vote_set import ConflictingVoteError


class ConsensusHalt(Exception):
    """Invariant broken — halt rather than diverge (reference :600-613)."""


@dataclass
class _Internal:
    """Sentinel wrapper distinguishing our own messages in the WAL."""

    mi: MsgInfo


@dataclass
class _StreamBatch:
    """One vote group in flight on the streaming verify pipeline: its
    signatures are verifying off-loop (DeviceScheduler, CONSENSUS class)
    while the consensus loop keeps ingesting the next gossip window.
    Verdicts apply through `ConsensusState._stream_apply` in dispatch
    order — the completion stage that preserves the serial-equivalent
    accept/reject semantics `VoteSet.add_votes(errors=[])` documents."""

    vote_set: object
    votes: list
    pending: object  # types.vote_set.PendingVotes
    height: int
    task: asyncio.Task | None = None
    span: object | None = None
    t0: float = field(default=0.0)


class ConsensusState(BaseService):
    def __init__(
        self,
        config: ConsensusConfig,
        state: State,
        block_exec: BlockExecutor,
        block_store,
        mempool=None,
        evidence_pool=None,
        priv_validator: PrivValidator | None = None,
        wal: WAL | None = None,
        event_bus=None,
        logger: Logger = NOP,
        tracer: tmtrace.Tracer | None = None,
    ) -> None:
        super().__init__("ConsensusState")
        self.config = config
        # consensus timeline tracing (libs/trace): one trace per height,
        # child spans per round step; default-off NOP tracer
        self.tracer = tracer or tmtrace.NOP
        self._height_span: tmtrace.Span | None = None
        self._step_span: tmtrace.Span | None = None
        # live-path Prometheus (libs/metrics.ConsensusMetrics), set by the
        # node when instrumentation.prometheus is on; taps guard on None
        self.metrics = None
        self._last_commit_mono = 0.0
        self.block_exec = block_exec
        self.block_store = block_store
        self.mempool = mempool
        self.evidence_pool = evidence_pool
        self.priv_validator = priv_validator
        self.wal = wal or NilWAL()
        self.event_bus = event_bus
        self.log = logger

        self.rs = RoundState()
        self.state: State | None = None

        self.peer_msg_queue: asyncio.Queue[MsgInfo] = asyncio.Queue(maxsize=1000)
        self.internal_msg_queue: asyncio.Queue[MsgInfo] = asyncio.Queue(maxsize=1000)
        self.ticker = TimeoutTicker()
        # synchronous switch for reactor wakeups (reference libs/events usage)
        self.event_switch = EventSwitch()
        self._last_vote_time = 0

        # streaming vote-verification pipeline (docs/vote_pipeline.md):
        # bounded queue of vote batches whose signatures are verifying
        # off-loop; verdicts apply in dispatch order
        self._stream_inflight: deque[_StreamBatch] = deque()
        self._stream_dispatched = 0
        self._stream_applied = 0

        self.done_first_block = asyncio.Event()
        self.update_to_state(state)

    # ------------------------------------------------------------------
    # lifecycle

    async def on_start(self) -> None:
        await self.ticker.start()
        self._catchup_replay()
        self.spawn(self.receive_routine(), "cs-receive")
        self.schedule_round_0()

    async def on_stop(self) -> None:
        await self.ticker.stop()
        # in-flight stream verifies: nothing will apply their verdicts —
        # cancel the wrappers (the worker thread finishes on its own and
        # the result is dropped; exceptions are consumed by the cancel)
        while self._stream_inflight:
            sb = self._stream_inflight.popleft()
            if sb.task is not None:
                sb.task.cancel()
            if sb.span is not None:
                sb.span.set(cancelled=True)
                self.tracer.finish(sb.span)
        self.wal.flush()

    def _catchup_replay(self) -> None:
        """Reference consensus/replay.go:100 catchupReplay: re-feed WAL
        messages recorded after the last height barrier."""
        from tendermint_tpu.consensus import replay

        replay.catchup_replay(self, self.rs.height)

    # ------------------------------------------------------------------
    # state/round bookkeeping

    def update_to_state(self, state: State) -> None:
        """Reference :1342 updateToState — prepare RoundState for the next
        height after a commit (or at boot)."""
        if self.rs.commit_round > -1 and 0 < self.rs.height != state.last_block_height:
            raise ConsensusHalt(
                f"updateToState expected state height {self.rs.height}, got "
                f"{state.last_block_height}"
            )
        last_commit = None
        if state.last_block_height > 0:
            if self.rs.commit_round > -1 and self.rs.votes is not None:
                precommits = self.rs.votes.precommits(self.rs.commit_round)
                if precommits is None or not precommits.has_two_thirds_majority():
                    raise ConsensusHalt("updateToState without +2/3 precommits")
                last_commit = precommits
            elif self.rs.last_commit is not None and self.rs.height == state.last_block_height + 1:
                last_commit = self.rs.last_commit
            else:
                # boot: rebuild from the seen commit in the store
                seen = self.block_store.load_seen_commit(state.last_block_height)
                if seen is not None:
                    vs = VoteSet(
                        state.chain_id,
                        state.last_block_height,
                        seen.round(),
                        VoteType.PRECOMMIT,
                        state.last_validators,
                    )
                    vs.add_votes([p for p in seen.precommits if p is not None])
                    last_commit = vs

        height = state.last_block_height + 1
        self.rs = RoundState(
            height=height,
            round=0,
            step=RoundStep.NEW_HEIGHT,
            start_time=self._commit_start_time(),
            validators=state.validators,
            votes=HeightVoteSet(state.chain_id, height, state.validators),
            last_commit=last_commit,
            last_validators=state.last_validators,
            commit_round=-1,
        )
        self.state = state
        RECORDER.record("consensus", "new_height", height=height)
        # verified-signature cache: entries older than the retain window
        # can no longer appear in any commit this node will verify
        SIG_CACHE.advance(height)
        m = self.metrics
        if m is not None and state.validators is not None:
            m.validators.set(state.validators.size())
            m.validators_power.set(state.validators.total_voting_power())
        self._trace_new_height()

    def _commit_start_time(self) -> float:
        return time.monotonic() + self.config.commit_time()

    def schedule_round_0(self) -> None:
        sleep = max(0.0, self.rs.start_time - time.monotonic())
        self.ticker.schedule_timeout(
            TimeoutInfo(sleep, self.rs.height, 0, RoundStep.NEW_HEIGHT)
        )

    def is_proposer(self) -> bool:
        if self.priv_validator is None:
            return False
        return self.rs.validators.get_proposer().address == self.priv_validator.address

    def round_state_event(self) -> EventDataRoundState:
        return EventDataRoundState(self.rs.height, self.rs.round, self.rs.step.name)

    # ------------------------------------------------------------------
    # input

    async def send_internal(self, msg, peer_id: str = "") -> None:
        await self.internal_msg_queue.put(MsgInfo(msg, peer_id))

    async def send_peer_msg(self, msg, peer_id: str) -> None:
        await self.peer_msg_queue.put(MsgInfo(msg, peer_id))

    async def receive_routine(self) -> None:
        """Reference :587 — the single-threaded heart. Extended for the
        streaming vote pipeline: when verify batches are in flight, the
        select also wakes on the oldest batch's verdicts, which apply
        before any newer input (those votes arrived first)."""
        while True:
            peer_get = asyncio.ensure_future(self.peer_msg_queue.get())
            internal_get = asyncio.ensure_future(self.internal_msg_queue.get())
            tock_get = asyncio.ensure_future(self.ticker.tock.get())
            waiters = {peer_get, internal_get, tock_get}
            stream_head = (
                self._stream_inflight[0].task if self._stream_inflight else None
            )
            if stream_head is not None:
                waiters.add(stream_head)
            done, pending = await asyncio.wait(
                waiters,
                return_when=asyncio.FIRST_COMPLETED,
            )
            for p in pending:
                if p is not stream_head:
                    # the stream verify keeps running across loop turns;
                    # only this turn's queue getters are abandoned
                    p.cancel()
            try:
                # .result() below is non-blocking: asyncio.wait just
                # reported these futures done
                if stream_head is not None and stream_head.done():
                    await self._stream_apply_completed()
                if internal_get in done:
                    mi = internal_get.result()  # tmlint: disable=TM101
                    # serial order: in-flight vote batches precede our own
                    # message — apply their verdicts before acting on it
                    await self._stream_drain()
                    self.wal.write_sync(mi)  # our own msgs: fsync (:635)
                    await self.handle_msg(mi)
                if peer_get in done:
                    await self._handle_peer_batch(peer_get.result())  # tmlint: disable=TM101
                if tock_get in done:
                    ti = tock_get.result()  # tmlint: disable=TM101
                    # timeout decisions must observe every tally already
                    # dispatched for verification
                    await self._stream_drain()
                    self.wal.write(
                        WALTimeoutInfo(ti.duration, ti.height, ti.round, int(ti.step))
                    )
                    await self.handle_timeout(ti)
            except ConsensusHalt:
                self.log.error("CONSENSUS FAILURE: halting node")
                raise
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.log.error("consensus error", err=repr(e))
                import traceback

                self.log.debug("traceback", tb=traceback.format_exc())

    async def handle_msg(self, mi: MsgInfo) -> None:
        msg, peer_id = mi.msg, mi.peer_id
        if isinstance(msg, m.ProposalMessage):
            await self.set_proposal(msg.proposal)
        elif isinstance(msg, m.BlockPartMessage):
            added = await self.add_proposal_block_part(msg, peer_id)
            if added:
                self.event_switch.fire_event("block_part", (msg, peer_id))
        elif isinstance(msg, m.VoteMessage):
            await self.try_add_vote(msg.vote, peer_id)
        else:
            self.log.error("unknown consensus message", msg=type(msg).__name__)

    def _drain_peer_queue(self, batch: list[MsgInfo]) -> None:
        cap = self.config.vote_batch_cap
        while len(batch) < cap:
            try:
                batch.append(self.peer_msg_queue.get_nowait())
            except asyncio.QueueEmpty:
                return

    async def _handle_peer_batch(self, first: MsgInfo) -> None:
        """Micro-batch peer messages (SURVEY §7 hard part b): drain the burst
        already queued; if it contains 2+ votes, wait one short deadline
        (config.vote_batch_window) for the rest of the burst to land, then
        process — consecutive votes for the same (H, R, type) go through ONE
        `VoteSet.add_votes` signature batch. While votes KEEP ARRIVING and
        the batch is still under the signature backend's accumulation hint,
        the wait extends window-by-window up to vote_batch_max_window, so a
        large-validator-set vote storm accumulates past the device routing
        threshold instead of serializing as sub-threshold windows (the same
        accumulate-to-hint policy as types.VoteStream). A singleton vote
        takes the serial path immediately and an idle queue stops the
        accumulation after one empty window, so small-validator-count
        latency does not regress. Replaces the reference's strictly
        per-vote serial verify (types/vote_set.go:189)."""
        batch = [first]
        self._drain_peer_queue(batch)
        window = self.config.vote_batch_window
        if (
            window > 0
            and len(batch) > 1
            and sum(isinstance(mi.msg, m.VoteMessage) for mi in batch) > 1
        ):
            from tendermint_tpu.crypto import batch as _cb

            # streamed flushes dispatch through the scheduler's packer,
            # so one routing threshold already fills device lanes; the
            # synchronous path keeps the amortizing multi-threshold hint
            hint = (
                _cb.stream_flush_hint()
                if self.config.vote_stream_async
                else _cb.accumulation_hint()
            )
            cap = self.config.vote_batch_cap
            deadline = (
                asyncio.get_event_loop().time()
                + max(self.config.vote_batch_max_window, window)
            )
            # the accumulation target can never exceed what the net can
            # produce: a (height, round) has at most validator-set-size
            # votes per type (x2 for prevote+precommit interleave), so a
            # small net's batch completes at set size instead of chasing
            # the device hint it can never reach
            target = min(hint, cap, max(2 * self.rs.validators.size(), 8))
            while True:
                before = len(batch)
                await asyncio.sleep(window)
                self._drain_peer_queue(batch)
                now = asyncio.get_event_loop().time()
                if (
                    len(batch) == before  # queue went idle
                    or len(batch) >= target
                    or now >= deadline
                ):
                    break
                # a steady sub-target trickle must not pin every batch to
                # the full max window (ADVICE r3): stop early when the
                # observed arrival rate cannot plausibly reach the target
                # by the deadline — the trickle is the workload, not a
                # burst edge
                arrived = len(batch) - before
                projected = arrived * max((deadline - now) / window, 0.0)
                if len(batch) + projected < target:
                    break
        # WAL order = arrival order, written before any processing (:630)
        for mi in batch:
            self.wal.write(mi)
        votes: list[MsgInfo] = []
        for mi in batch:
            if isinstance(mi.msg, m.VoteMessage):
                votes.append(mi)
                continue
            await self._flush_vote_run(votes)
            # non-vote messages (proposal, block part) act on the tally:
            # verdicts of every dispatched vote batch land first, so the
            # outcome matches the serial arrival order
            await self._stream_drain()
            # per-message error isolation, as if each were its own loop turn
            try:
                await self.handle_msg(mi)
            except (ConsensusHalt, asyncio.CancelledError):
                raise
            except Exception as e:
                self.log.error("consensus error", err=repr(e))
        await self._flush_vote_run(votes)

    async def _flush_vote_run(self, votes: list[MsgInfo]) -> None:
        """Group a run of consecutive VoteMessages by (height, round, type)
        and bulk-add each group; preserves arrival order within and across
        groups as far as the (commutative) VoteSet tally is concerned.
        Each group is error-isolated like a serial loop turn would be."""
        if not votes:
            return
        groups: dict[tuple, list[MsgInfo]] = {}
        for mi in votes:
            v = mi.msg.vote
            groups.setdefault((v.height, v.round, int(v.type)), []).append(mi)
        votes.clear()
        for group in groups.values():
            try:
                if len(group) == 1:
                    await self.try_add_vote(group[0].msg.vote, group[0].peer_id)
                else:
                    await self._try_add_vote_group(group)
            except (ConsensusHalt, asyncio.CancelledError):
                raise
            except Exception as e:
                self.log.error("consensus error", err=repr(e))

    async def handle_timeout(self, ti: TimeoutInfo) -> None:
        """Reference :692 handleTimeout."""
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and int(ti.step) < int(rs.step)
        ):
            return
        if ti.step == RoundStep.NEW_HEIGHT:
            await self.enter_new_round(ti.height, 0)
        elif ti.step == RoundStep.NEW_ROUND:
            await self.enter_propose(ti.height, 0)
        elif ti.step == RoundStep.PROPOSE:
            if self.event_bus:
                await self.event_bus.publish_timeout_propose(self.round_state_event())
            await self.enter_prevote(ti.height, ti.round)
        elif ti.step == RoundStep.PREVOTE_WAIT:
            if self.event_bus:
                await self.event_bus.publish_timeout_wait(self.round_state_event())
            await self.enter_precommit(ti.height, ti.round)
        elif ti.step == RoundStep.PRECOMMIT_WAIT:
            if self.event_bus:
                await self.event_bus.publish_timeout_wait(self.round_state_event())
            await self.enter_precommit(ti.height, ti.round)
            await self.enter_new_round(ti.height, ti.round + 1)

    # ------------------------------------------------------------------
    # step functions

    async def enter_new_round(self, height: int, round_: int) -> None:
        """Reference :774."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT
        ):
            return
        self.log.debug("enterNewRound", height=height, round=round_)
        if round_ > rs.round:
            validators = rs.validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)
            rs.validators = validators
        rs.round = round_
        rs.step = RoundStep.NEW_ROUND
        if round_ > 0:
            # round 0 keeps the proposal from NewHeight; later rounds reset
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_)
        rs.triggered_timeout_precommit = False
        RECORDER.record("consensus", "step", height=height, round=round_,
                        step=rs.step.name)
        if self.metrics is not None:
            self.metrics.rounds.set(round_)
        self._trace_step()
        if self.event_bus:
            await self.event_bus.publish_new_round(self.round_state_event())
        self.event_switch.fire_event("new_round_step", self.rs)

        wait_for_txs = (
            not self.config.create_empty_blocks
            and round_ == 0
            and self.mempool is not None
            and self.mempool.size() == 0
        )
        if wait_for_txs:
            self.spawn(self._wait_for_txs(height, round_), "cs-wait-txs")
        else:
            await self.enter_propose(height, round_)

    async def _wait_for_txs(self, height: int, round_: int) -> None:
        await self.mempool.tx_available.wait()
        if self.rs.height == height and self.rs.round == round_:
            await self.enter_propose(height, round_)

    async def enter_propose(self, height: int, round_: int) -> None:
        """Reference :836."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PROPOSE)
        ):
            return
        self.log.debug("enterPropose", height=height, round=round_)
        rs.step = RoundStep.PROPOSE
        self._new_step()
        self.ticker.schedule_timeout(
            TimeoutInfo(
                self.config.propose_timeout(round_), height, round_, RoundStep.PROPOSE
            )
        )
        if self.priv_validator is not None and self.is_proposer():
            await self.decide_proposal(height, round_)
        if self.is_proposal_complete():
            await self.enter_prevote(height, round_)

    async def decide_proposal(self, height: int, round_: int) -> None:
        """Reference :895 defaultDecideProposal (overridable — the byzantine
        test plugs a double-proposer here)."""
        rs = self.rs
        if rs.valid_block is not None:
            block, parts = rs.valid_block, rs.valid_block_parts
        else:
            commit = None
            if height == 1:
                commit = None
            elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
                commit = rs.last_commit.make_commit()
            else:
                self.log.error("propose without LastCommit majority")
                return
            block = self.block_exec.create_proposal_block(
                height, self.state, commit, self.priv_validator.address
            )
            parts = block.make_part_set()
        block_id = BlockID(block.hash(), parts.header())
        proposal = Proposal(height, round_, rs.valid_round, block_id, now_ns())
        try:
            if hasattr(self.priv_validator, "sign_proposal_async"):
                proposal = await self.priv_validator.sign_proposal_async(
                    self.state.chain_id, proposal
                )
            else:
                proposal = self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:
            self.log.error("failed to sign proposal", err=repr(e))
            return
        await self.send_internal(m.ProposalMessage(proposal))
        for i in range(parts.total):
            await self.send_internal(m.BlockPartMessage(height, round_, parts.get_part(i)))
        self.log.info("proposed block", height=height, round=round_, hash=block.hash())

    def is_proposal_complete(self) -> bool:
        """Reference :891."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    async def enter_prevote(self, height: int, round_: int) -> None:
        """Reference :1008."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PREVOTE)
        ):
            return
        self.log.debug("enterPrevote", height=height, round=round_)
        rs.step = RoundStep.PREVOTE
        self._new_step()
        # sign and broadcast prevote (reference :1029 doPrevote)
        if rs.locked_block is not None:
            await self.sign_add_vote(VoteType.PREVOTE, rs.locked_block.hash(),
                                     rs.locked_block_parts.header())
        elif rs.proposal_block is None:
            await self.sign_add_vote(VoteType.PREVOTE, b"", None)
        else:
            try:
                self.block_exec.validate_block(self.state, rs.proposal_block)
                await self.sign_add_vote(
                    VoteType.PREVOTE,
                    rs.proposal_block.hash(),
                    rs.proposal_block_parts.header(),
                )
            except Exception as e:
                self.log.error("invalid proposal block; prevoting nil", err=repr(e))
                await self.sign_add_vote(VoteType.PREVOTE, b"", None)

    async def enter_prevote_wait(self, height: int, round_: int) -> None:
        """Reference :1044."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PREVOTE_WAIT)
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise ConsensusHalt("enterPrevoteWait without +2/3 prevotes")
        rs.step = RoundStep.PREVOTE_WAIT
        self._new_step()
        self.ticker.schedule_timeout(
            TimeoutInfo(
                self.config.prevote_timeout(round_), height, round_, RoundStep.PREVOTE_WAIT
            )
        )

    async def enter_precommit(self, height: int, round_: int) -> None:
        """Reference :1060 — the POL lock/unlock rules."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PRECOMMIT)
        ):
            return
        self.log.debug("enterPrecommit", height=height, round=round_)
        rs.step = RoundStep.PRECOMMIT
        self._new_step()

        prevotes = rs.votes.prevotes(round_)
        block_id, has_maj = (
            prevotes.two_thirds_majority() if prevotes else (BlockID(), False)
        )
        if not has_maj:
            # no polka: precommit nil (keep locks)
            await self.sign_add_vote(VoteType.PRECOMMIT, b"", None)
            return
        if self.event_bus:
            await self.event_bus.publish_polka(self.round_state_event())
        pol_round, _ = rs.votes.pol_info()
        if pol_round < round_:
            raise ConsensusHalt(f"POLRound {pol_round} < {round_} with polka")

        if block_id.is_zero():
            # +2/3 prevoted nil: unlock (reference :1102)
            if rs.locked_block is not None:
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                if self.event_bus:
                    await self.event_bus.publish_unlock(self.round_state_event())
            await self.sign_add_vote(VoteType.PRECOMMIT, b"", None)
            return
        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            # relock (reference :1120)
            rs.locked_round = round_
            if self.event_bus:
                await self.event_bus.publish_relock(self.round_state_event())
            await self.sign_add_vote(VoteType.PRECOMMIT, block_id.hash, block_id.parts)
            return
        if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
            # lock the proposal block (reference :1132)
            try:
                self.block_exec.validate_block(self.state, rs.proposal_block)
            except Exception as e:
                raise ConsensusHalt(f"+2/3 prevoted an invalid block: {e}")
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            if self.event_bus:
                await self.event_bus.publish_lock(self.round_state_event())
            await self.sign_add_vote(VoteType.PRECOMMIT, block_id.hash, block_id.parts)
            return
        # polka for a block we don't have: unlock, fetch, precommit nil (:1147)
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
            block_id.parts
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet(block_id.parts)
        if self.event_bus:
            await self.event_bus.publish_unlock(self.round_state_event())
        await self.sign_add_vote(VoteType.PRECOMMIT, b"", None)

    async def enter_precommit_wait(self, height: int, round_: int) -> None:
        """Reference :1163."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise ConsensusHalt("enterPrecommitWait without +2/3 precommits")
        rs.triggered_timeout_precommit = True
        self._new_step()
        self.ticker.schedule_timeout(
            TimeoutInfo(
                self.config.precommit_timeout(round_),
                height,
                round_,
                RoundStep.PRECOMMIT_WAIT,
            )
        )

    async def enter_commit(self, height: int, commit_round: int) -> None:
        """Reference :1184."""
        rs = self.rs
        if rs.height != height or int(rs.step) >= int(RoundStep.COMMIT):
            return
        self.log.debug("enterCommit", height=height, commit_round=commit_round)
        rs.step = RoundStep.COMMIT
        rs.commit_round = commit_round
        rs.commit_time = time.monotonic()
        self._new_step()

        precommits = rs.votes.precommits(commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok:
            raise ConsensusHalt("enterCommit without +2/3 precommit majority")
        # if we have the locked block, it's the committed one
        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.parts
            ):
                # we don't have the committed block yet: wait for parts.
                # Reference :1224-1227 — the evsw fire makes the reactor
                # broadcast NewValidBlock so peers learn our (empty) part
                # bit array and re-send parts they wrongly think we have.
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet(block_id.parts)
                if self.event_bus:
                    await self.event_bus.publish_valid_block(self.round_state_event())
                self.event_switch.fire_event("valid_block", rs)
                return
        await self.try_finalize_commit(height)

    async def try_finalize_commit(self, height: int) -> None:
        """Reference :1237."""
        rs = self.rs
        if rs.height != height:
            raise ConsensusHalt("tryFinalizeCommit on wrong height")
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok or block_id.is_zero():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return  # don't have the block yet
        await self.finalize_commit(height)

    async def finalize_commit(self, height: int) -> None:
        """Reference :1261 — the commit pipeline with crash points."""
        rs = self.rs
        if rs.height != height or rs.step != RoundStep.COMMIT:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, _ = precommits.two_thirds_majority()
        block, parts = rs.proposal_block, rs.proposal_block_parts
        if not block.hashes_to(block_id):
            raise ConsensusHalt("cannot finalize: proposal block does not hash to maj23")
        self.block_exec.validate_block(self.state, block)
        fail.fail()  # crash point (reference :1287)
        if self.block_store.height() < block.header.height:
            seen_commit = precommits.make_commit()
            self.block_store.save_block(block, parts, seen_commit)
        fail.fail()  # crash point (reference :1301)
        self.wal.write_sync(EndHeightMessage(height))  # (:1316)
        fail.fail()  # crash point (reference :1318)

        state_copy = self.state.copy()
        with tmtrace.span("apply_block", height=height, txs=len(block.data.txs)):
            new_state = await self.block_exec.apply_block(
                state_copy, BlockID(block.hash(), parts.header()), block
            )
        fail.fail()  # crash point (reference :1336)
        self._observe_commit(height, block, parts)
        self.update_to_state(new_state)
        fail.fail()  # crash point (reference :1344)
        self._last_vote_time = 0
        self.done_first_block.set()
        self.schedule_round_0()
        self.event_switch.fire_event("new_round_step", self.rs)

    def _observe_commit(self, height: int, block, parts) -> None:
        """Black-box + Prometheus tap at the commit boundary: the block
        stats the reference feeds from consensus/metrics.go call sites."""
        now = time.monotonic()
        interval = now - self._last_commit_mono if self._last_commit_mono else 0.0
        self._last_commit_mono = now
        RECORDER.record(
            "consensus", "commit", height=height, round=self.rs.commit_round,
            txs=len(block.data.txs), interval_ms=round(interval * 1e3, 1),
        )
        if TXLIFE.enabled:
            for tx in block.data.txs:
                TXLIFE.stage("committed", tx_hash(tx), height=height)
        m = self.metrics
        if m is None:
            return
        m.height.set(height)
        m.num_txs.set(len(block.data.txs))
        m.total_txs.add(len(block.data.txs))
        m.block_size_bytes.set(parts.byte_size())
        if interval:
            m.block_interval_seconds.observe(interval)
        if block.last_commit is not None:
            m.missing_validators.set(
                sum(1 for p in block.last_commit.precommits if p is None)
            )
        m.byzantine_validators.set(len(block.evidence))

    def _new_step(self) -> None:
        rsd = self.round_state_event()
        self.wal.write(rsd)
        RECORDER.record("consensus", "step", height=rsd.height, round=rsd.round,
                        step=rsd.step)
        self._trace_step()
        self.event_switch.fire_event("new_round_step", self.rs)
        if self.event_bus:
            spawn_logged(
                self.event_bus.publish_new_round_step(rsd),
                logger=self.log,
                name="event-bus-new-round-step",
            )

    # ------------------------------------------------------------------
    # timeline tracing (libs/trace): one root span per height, one child
    # span per round step. Steps are open-ended — a step span ends when
    # the NEXT step begins — so this uses the tracer's manual API; spans
    # recorded deeper in the call stack (batch_verify, ed25519_batch,
    # apply_block) attach to the active step via the trace contextvar.

    def _trace_new_height(self) -> None:
        t = self.tracer
        if not t.enabled:
            return
        if self._step_span is not None:
            t.finish(self._step_span)
            self._step_span = None
        if self._height_span is not None:
            t.finish(self._height_span)
        self._height_span = t.begin("height", height=self.rs.height)

    def _trace_step(self) -> None:
        t, hs = self.tracer, self._height_span
        if hs is None or not t.enabled:
            return
        rs = self.rs
        name = rs.step.name.lower()
        prev = self._step_span
        if prev is not None:
            if prev.name == name and prev.attrs.get("round") == rs.round:
                return  # same step re-announced (e.g. precommit_wait)
            t.finish(prev)
        self._step_span = t.child(hs, name, height=rs.height, round=rs.round)

    # ------------------------------------------------------------------
    # proposal handling

    async def set_proposal(self, proposal: Proposal) -> None:
        """Reference defaultSetProposal (:1399)."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round
        ):
            raise ValueError("invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposal.verify(self.state.chain_id, proposer.pub_key):
            raise ValueError("invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(proposal.block_id.parts)
        RECORDER.record("consensus", "proposal", height=proposal.height,
                        round=proposal.round)
        self.log.info("received proposal", height=proposal.height, round=proposal.round)

    async def add_proposal_block_part(self, msg: m.BlockPartMessage, peer_id: str) -> bool:
        """Reference :1426 addProposalBlockPart."""
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if not added:
            return False
        if rs.proposal_block_parts.is_complete() and rs.proposal_block is None:
            try:
                rs.proposal_block = Block.decode(rs.proposal_block_parts.get_data())
            except Exception as e:
                raise ConsensusHalt(f"undecodable proposal block: {e}")
            self.log.info("received complete proposal block",
                          height=rs.proposal_block.header.height,
                          hash=rs.proposal_block.hash())
            if TXLIFE.enabled:
                # fires on the proposer too — its own parts arrive
                # through the internal queue, so this one tap covers
                # every node that assembled the block
                for tx in rs.proposal_block.data.txs:
                    TXLIFE.stage("proposed", tx_hash(tx),
                                 height=rs.height, round=rs.round)
            if self.event_bus:
                await self.event_bus.publish_complete_proposal(self.round_state_event())
            prevotes = rs.votes.prevotes(rs.round)
            block_id, has_maj = (
                prevotes.two_thirds_majority() if prevotes else (BlockID(), False)
            )
            if has_maj and not block_id.is_zero() and rs.valid_round < rs.round:
                if rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = rs.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
            if int(rs.step) <= int(RoundStep.PROPOSE) and self.is_proposal_complete():
                await self.enter_prevote(rs.height, rs.round)
            elif rs.step == RoundStep.COMMIT:
                await self.try_finalize_commit(rs.height)
        return added

    # ------------------------------------------------------------------
    # votes

    async def try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """Reference :1504 — equivocation becomes evidence."""
        try:
            return await self.add_vote(vote, peer_id)
        except ConflictingVoteError as e:
            await self._handle_conflicting_vote(vote, e)
            return False

    async def _handle_conflicting_vote(self, vote: Vote, e: ConflictingVoteError) -> None:
        if self.priv_validator is not None and vote.validator_address == self.priv_validator.address:
            self.log.error("found conflicting vote from ourselves; did you restart with a stale WAL?")
            return
        _, val = self.rs.validators.get_by_address(vote.validator_address)
        if val is not None and self.evidence_pool is not None:
            ev = DuplicateVoteEvidence(val.pub_key, e.existing, e.conflicting)
            try:
                self.evidence_pool.add_evidence(ev)
                self.log.info("added evidence for conflicting vote")
            except Exception as err:
                self.log.error("failed to add evidence", err=repr(err))
        # the equivocating vote may still have been tallied under a
        # peer-claimed maj23 block (vote_set peer_maj23 tracking) and
        # pushed that block over 2/3 — re-run the step transitions,
        # which are guard-idempotent, so the new majority is acted on
        if vote.height == self.rs.height and self.rs.votes is not None:
            if vote.type == VoteType.PRECOMMIT:
                await self._on_precommit_added(vote)
            else:
                await self._on_prevote_added(vote)

    async def _try_add_vote_group(self, group: list[MsgInfo]) -> None:
        """Bulk ingest of a gossip burst sharing one (height, round, type):
        one `add_votes` call = one batched signature verification, then the
        exact per-vote side effects (events, evidence, step transitions) a
        serial add_vote sequence would have produced."""
        rs = self.rs
        votes = [mi.msg.vote for mi in group]
        v0 = votes[0]
        # precommits for the previous height (LastCommit catch-up, :1545)
        if v0.height + 1 == rs.height and v0.type == VoteType.PRECOMMIT:
            if rs.step != RoundStep.NEW_HEIGHT or rs.last_commit is None:
                return
            errors: list = []
            added = rs.last_commit.add_votes(votes, errors=errors)
            for vote, ok, err in zip(votes, added, errors):
                if isinstance(err, ConflictingVoteError):
                    # last-height equivocation still becomes evidence
                    await self._handle_conflicting_vote(vote, err)
                    continue
                if err is not None:
                    self.log.error("consensus error", err=repr(err))
                if not ok:
                    continue
                self.log.debug("added vote to LastCommit")
                if self.event_bus:
                    await self.event_bus.publish_vote(vote)
                self.event_switch.fire_event("vote", vote)
            if any(added) and self.config.skip_timeout_commit and rs.last_commit.has_all():
                await self.enter_new_round(rs.height, 0)
            return
        if v0.height != rs.height:
            return
        # route the whole group to one VoteSet. A round we have not created
        # yet is the rare catchup case — take the serial path so the
        # per-peer catchup-round accounting charges each vote's own peer
        # (height_vote_set.go:111), not the group leader.
        vs = (
            rs.votes.prevotes(v0.round)
            if v0.type == VoteType.PREVOTE
            else rs.votes.precommits(v0.round)
        )
        if vs is None:
            for mi in group:
                await self.try_add_vote(mi.msg.vote, mi.peer_id)
            return
        if (
            self.config.vote_stream_async
            and len(votes) >= max(1, self.config.vote_stream_min)
        ):
            await self._stream_dispatch(vs, votes, v0.height)
            return
        errors = []
        added = vs.add_votes(votes, errors=errors)
        await self._apply_vote_outcomes(votes, added, errors, v0.height)

    async def _apply_vote_outcomes(
        self, votes: list[Vote], added: list[bool], errors: list, height: int
    ) -> None:
        """Per-vote side effects after a bulk add — the exact events,
        evidence, and step transitions a serial add_vote sequence would
        have produced. Shared by the synchronous group path and the
        streaming pipeline's verdict-apply stage."""
        for vote, ok, err in zip(votes, added, errors):
            if self.rs.height != height:
                # a vote earlier in this group completed a commit and moved
                # us to the next height: the remaining votes are stale, and
                # a serial add_vote would have dropped them here too
                break
            if ok:
                await self._post_add_vote(vote)
            elif isinstance(err, ConflictingVoteError):
                await self._handle_conflicting_vote(vote, err)
            elif err is not None:
                # same visibility a serial add_vote raise would have had
                self.log.error("consensus error", err=repr(err))

    # ------------------------------------------------------------------
    # streaming vote-verification pipeline (docs/vote_pipeline.md).
    #
    # The synchronous group path above blocks the consensus loop on
    # `bv.verify_all()` — the full device round trip. Here the verify
    # stage runs off-loop: `VoteSet.begin_add_votes` prepares the batch
    # (prechecks, dedup, verified-signature-cache sweep) on the loop,
    # the cache-missed signatures dispatch through the crypto backends
    # on a worker thread (device-bound groups queue on the
    # DeviceScheduler at CONSENSUS class), and the verdicts apply back
    # on the loop in dispatch order — batch N verifies on-device while
    # gossip window N+1 ingests. Serial-equivalence is preserved by the
    # apply-stage re-evaluation in `VoteSet.finish_add_votes` plus the
    # drain barriers in receive_routine/_handle_peer_batch (non-vote
    # messages, internal messages, and timeouts never act on a tally
    # with unapplied verdicts).

    async def _stream_dispatch(self, vs: VoteSet, votes: list[Vote], height: int) -> None:
        errors: list = []
        pending = vs.begin_add_votes(votes, errors=errors)
        if pending.n_verify == 0:
            # every signature was cached, duplicate, or precheck-rejected:
            # nothing to dispatch — apply inline
            added = vs.finish_add_votes(pending, [])
            await self._apply_vote_outcomes(votes, added, errors, height)
            return
        if len(self._stream_inflight) >= max(1, self.config.vote_stream_inflight):
            # pipeline full (double-buffer bound): absorb the oldest
            # batch's verdicts before dispatching another
            await self._stream_apply(self._stream_inflight.popleft())
        sb = _StreamBatch(vs, votes, pending, height, t0=time.monotonic())
        t, hs = self.tracer, self._height_span
        if t.enabled and hs is not None:
            sb.span = t.child(
                hs, "vote_stream", height=height, n=len(votes),
                verify=pending.n_verify,
            )
        sb.task = asyncio.ensure_future(self._stream_verify(pending))
        self._stream_inflight.append(sb)
        self._stream_dispatched += 1
        RECORDER.record(
            "consensus", "stream_dispatch", height=height, n=len(votes),
            verify=pending.n_verify, inflight=len(self._stream_inflight),
        )
        mm = self.metrics
        if mm is not None:
            mm.stream_batches_total.inc()
            mm.stream_inflight_batches.set(len(self._stream_inflight))

    async def _stream_verify(self, pending) -> list[bool]:
        """The off-loop verify stage: the prepared batch's cache-missed
        signatures run through the crypto backends on a worker thread —
        device-bound groups enter the DeviceScheduler's admission queue
        at CONSENSUS class, sub-threshold groups take the host paths —
        while the consensus loop keeps ingesting."""
        with priority_scope(Priority.CONSENSUS_COMMIT):
            return await asyncio.to_thread(pending.bv.verify_all)

    async def _stream_apply(self, sb: _StreamBatch) -> None:
        """Completion stage: apply one batch's verdicts with the exact
        serial-equivalent semantics of the synchronous path."""
        wait_s = 0.0
        try:
            results = await sb.task
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — same isolation as the
            # sync path: _flush_vote_run logs a backend error and drops
            # the group; no verdict ever applies unverified
            self.log.error("consensus error", err=repr(e))
            results = None
        else:
            wait_s = time.monotonic() - sb.t0
            added = sb.vote_set.finish_add_votes(sb.pending, results)
            self._stream_applied += 1
            await self._apply_vote_outcomes(
                sb.votes, added, sb.pending.errors, sb.height
            )
        if sb.span is not None:
            sb.span.set(wait_ms=round(wait_s * 1e3, 3),
                        failed=results is None)
            self.tracer.finish(sb.span)
        RECORDER.record(
            "consensus", "stream_apply", height=sb.height, n=len(sb.votes),
            wait_ms=round(wait_s * 1e3, 3),
            inflight=len(self._stream_inflight),
        )
        mm = self.metrics
        if mm is not None:
            mm.stream_inflight_batches.set(len(self._stream_inflight))
            if results is not None:
                mm.stream_wait_seconds.observe(wait_s)

    async def _stream_apply_completed(self) -> None:
        """Apply every leading in-flight batch whose verify finished —
        always oldest-first, so verdicts land in dispatch order."""
        while self._stream_inflight and self._stream_inflight[0].task.done():
            await self._stream_apply(self._stream_inflight.popleft())

    async def _stream_drain(self) -> None:
        """Barrier: wait for and apply ALL in-flight verdicts. Called
        before any input that acts on the tally outside the vote path."""
        while self._stream_inflight:
            await self._stream_apply(self._stream_inflight.popleft())

    async def _post_add_vote(self, vote: Vote) -> None:
        """Events + step transitions after a vote lands (reference :1582)."""
        if self.event_bus:
            await self.event_bus.publish_vote(vote)
        self.event_switch.fire_event("vote", vote)
        if vote.type == VoteType.PREVOTE:
            await self._on_prevote_added(vote)
        else:
            await self._on_precommit_added(vote)

    async def add_vote(self, vote: Vote, peer_id: str) -> bool:
        """Reference :1534 addVote."""
        rs = self.rs
        # precommit for the previous height (LastCommit catch-up)
        if vote.height + 1 == rs.height and vote.type == VoteType.PRECOMMIT:
            if rs.step != RoundStep.NEW_HEIGHT or rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if added:
                self.log.debug("added vote to LastCommit")
                if self.event_bus:
                    await self.event_bus.publish_vote(vote)
                self.event_switch.fire_event("vote", vote)
                if self.config.skip_timeout_commit and rs.last_commit.has_all():
                    await self.enter_new_round(rs.height, 0)
            return added
        if vote.height != rs.height:
            return False

        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        await self._post_add_vote(vote)
        return True

    async def _on_prevote_added(self, vote: Vote) -> None:
        """Reference :1596-1656 — unlock on higher POL, valid-block update,
        step transitions."""
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round)
        block_id, has_maj = prevotes.two_thirds_majority()
        if has_maj:
            # unlock if there's a polka for something else in a later round
            if (
                rs.locked_block is not None
                and rs.locked_round < vote.round <= rs.round
                and rs.locked_block.hash() != block_id.hash
            ):
                self.log.info("unlocking because of POL", locked_round=rs.locked_round)
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                if self.event_bus:
                    await self.event_bus.publish_unlock(self.round_state_event())
            # update valid block (reference :1627)
            if (
                not block_id.is_zero()
                and rs.valid_round < vote.round
                and vote.round == rs.round
            ):
                if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    # we don't have the block: start collecting it
                    rs.proposal_block = None
                    if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(block_id.parts):
                        rs.proposal_block_parts = PartSet(block_id.parts)
                    rs.valid_round = vote.round
                    rs.valid_block = None
                    rs.valid_block_parts = None
                self.event_switch.fire_event("valid_block", rs)
                if self.event_bus:
                    await self.event_bus.publish_valid_block(self.round_state_event())

        # transitions (reference :1639)
        if rs.round < vote.round and prevotes.has_two_thirds_any():
            await self.enter_new_round(rs.height, vote.round)
        elif rs.round == vote.round and int(RoundStep.PREVOTE) <= int(rs.step):
            if has_maj and (self.is_proposal_complete() or block_id.is_zero()):
                await self.enter_precommit(rs.height, vote.round)
            elif prevotes.has_two_thirds_any():
                await self.enter_prevote_wait(rs.height, vote.round)
        elif rs.proposal is not None and 0 <= rs.proposal.pol_round == vote.round:
            if self.is_proposal_complete():
                await self.enter_prevote(rs.height, rs.round)

    async def _on_precommit_added(self, vote: Vote) -> None:
        """Reference :1659-1679."""
        rs = self.rs
        precommits = rs.votes.precommits(vote.round)
        block_id, has_maj = precommits.two_thirds_majority()
        if has_maj:
            await self.enter_new_round(rs.height, vote.round)
            await self.enter_precommit(rs.height, vote.round)
            if not block_id.is_zero():
                await self.enter_commit(rs.height, vote.round)
                if self.config.skip_timeout_commit and precommits.has_all():
                    await self.enter_new_round(rs.height, 0)
            else:
                await self.enter_precommit_wait(rs.height, vote.round)
        elif rs.round <= vote.round and precommits.has_two_thirds_any():
            await self.enter_new_round(rs.height, vote.round)
            await self.enter_precommit_wait(rs.height, vote.round)

    async def sign_add_vote(
        self, type_: VoteType, hash_: bytes, parts_header
    ) -> Vote | None:
        """Reference :1728 signAddVote + :1681 voteTime monotonicity."""
        if self.priv_validator is None:
            return None
        rs = self.rs
        idx, val = rs.validators.get_by_address(self.priv_validator.address)
        if val is None:
            return None  # not a validator this height
        from tendermint_tpu.types import PartSetHeader

        block_id = BlockID(hash_, parts_header or PartSetHeader())
        ts = max(now_ns(), self._last_vote_time + 1, self.state.last_block_time + 1)
        self._last_vote_time = ts
        vote = Vote(
            type_, rs.height, rs.round, block_id, ts, self.priv_validator.address, idx
        )
        try:
            # remote signers (privval.remote.SignerClient) expose an async
            # variant; file/mock signers are synchronous
            if hasattr(self.priv_validator, "sign_vote_async"):
                vote = await self.priv_validator.sign_vote_async(self.state.chain_id, vote)
            else:
                vote = self.priv_validator.sign_vote(self.state.chain_id, vote)
        except Exception as e:
            self.log.error("failed to sign vote", err=repr(e))
            return None
        await self.send_internal(m.VoteMessage(vote))
        return vote
