"""Consensus write-ahead log.

Reference parity: consensus/wal.go — TimedWALMessage framing with CRC32 +
length (:270), Write vs fsynced WriteSync (:177,191), EndHeightMessage
height barrier (:39), rotating autofile group storage, backward
SearchForEndHeight (:213). Every message the state machine consumes is
logged BEFORE processing so a crash replays deterministically.

Auto-repair (reference wal.go:76 + the repair logic the reference leaves
to an operator running `tendermint debug`): a process that dies mid-write
leaves a torn frame at the tail — a truncated header, a short payload, or
a CRC mismatch. `repair_wal` runs at every open: each WAL file is scanned
with the same stop-at-first-corrupt frame machinery `decode_frames` uses,
the torn tail is moved into a `<file>.corrupt` sidecar (never deleted —
it is postmortem evidence), and the file is truncated to the last clean
frame boundary. Replay then proceeds from an intact log instead of the
node refusing to start or silently appending after garbage.
"""
from __future__ import annotations

import io
import os
import struct
import time
import zlib
from dataclasses import dataclass

from tendermint_tpu.consensus.messages import (
    decode_consensus_message,
    encode_consensus_message,
)
from tendermint_tpu.encoding import DecodeError, Reader, Writer
from tendermint_tpu.libs.autofile import Group
from tendermint_tpu.libs.recorder import RECORDER

MAX_WAL_MSG_SIZE = 1024 * 1024  # 1MB per message hard cap (reference wal.go)


@dataclass(frozen=True)
class EndHeightMessage:
    """Reference wal.go:39 — written after a height commits."""

    height: int


@dataclass(frozen=True)
class WALTimeoutInfo:
    duration: float
    height: int
    round: int
    step: int


@dataclass
class MsgInfo:
    """A consensus message + its source peer ('' = internal)."""

    msg: object
    peer_id: str = ""


@dataclass
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass
class TimedWALMessage:
    time_ns: int
    msg: object


def _encode_wal_msg(msg) -> bytes:
    w = Writer()
    if isinstance(msg, EndHeightMessage):
        w.u8(1).u64(msg.height)
    elif isinstance(msg, WALTimeoutInfo):
        w.u8(2).u64(int(msg.duration * 1e9)).u64(msg.height).u32(msg.round).u8(msg.step)
    elif isinstance(msg, MsgInfo):
        w.u8(3).str(msg.peer_id).bytes(encode_consensus_message(msg.msg))
    elif isinstance(msg, EventDataRoundState):
        w.u8(4).u64(msg.height).u32(msg.round).str(msg.step)
    else:
        raise TypeError(f"cannot WAL-encode {msg!r}")
    return w.build()


def _decode_wal_msg(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == 1:
        return EndHeightMessage(r.u64())
    if tag == 2:
        return WALTimeoutInfo(r.u64() / 1e9, r.u64(), r.u32(), r.u8())
    if tag == 3:
        peer = r.str()
        return MsgInfo(decode_consensus_message(r.bytes()), peer)
    if tag == 4:
        return EventDataRoundState(r.u64(), r.u32(), r.str())
    raise DecodeError(f"unknown WAL tag {tag}")


def encode_frame(tm: TimedWALMessage) -> bytes:
    """crc32(payload) u32 | length u32 | payload (reference wal.go:270)."""
    payload = Writer().u64(tm.time_ns).raw(_encode_wal_msg(tm.msg)).build()
    if len(payload) > MAX_WAL_MSG_SIZE:
        raise ValueError(f"WAL message too big: {len(payload)}")
    return struct.pack(">II", zlib.crc32(payload), len(payload)) + payload


class WALCorruptionError(Exception):
    pass


def decode_frames(stream: io.BufferedIOBase):
    """Yield TimedWALMessages; raises WALCorruptionError on a bad frame
    (callers may treat a corrupt tail as a crash artifact)."""
    while True:
        hdr = stream.read(8)
        if len(hdr) == 0:
            return
        if len(hdr) < 8:
            raise WALCorruptionError("truncated frame header")
        crc, length = struct.unpack(">II", hdr)
        if length > MAX_WAL_MSG_SIZE:
            raise WALCorruptionError(f"frame too big: {length}")
        payload = stream.read(length)
        if len(payload) < length:
            raise WALCorruptionError("truncated frame payload")
        if zlib.crc32(payload) != crc:
            raise WALCorruptionError("crc mismatch")
        r = Reader(payload)
        time_ns = r.u64()
        try:
            msg = _decode_wal_msg(payload[8:])
        except DecodeError as e:
            raise WALCorruptionError(f"bad WAL message: {e}") from e
        yield TimedWALMessage(time_ns, msg)


def scan_clean_frames(stream: io.BufferedIOBase) -> tuple[int, int, str | None]:
    """Walk frames, stopping at the first corrupt one. Returns
    (n_clean_frames, clean_byte_length, error-or-None) — the byte length
    is the truncation point auto-repair cuts at."""
    frames = 0
    clean = 0
    try:
        for _ in decode_frames(stream):
            frames += 1
            clean = stream.tell()
    except WALCorruptionError as e:
        return frames, clean, str(e)
    return frames, clean, None


def _sidecar_path(path: str) -> str:
    """First free `<path>.corrupt[.N]` name — repeated crashes must not
    overwrite earlier evidence."""
    cand = path + ".corrupt"
    n = 0
    while os.path.exists(cand):
        n += 1
        cand = f"{path}.corrupt.{n}"
    return cand


def _wal_files(head_path: str) -> list[str]:
    """The group's files in stream order: numbered chunks ascending, then
    the head (mirrors autofile.Group.read_all without opening the head
    for append)."""
    d = os.path.dirname(head_path) or "."
    base = os.path.basename(head_path)
    chunks = []
    if os.path.isdir(d):
        for name in os.listdir(d):
            if name.startswith(base + "."):
                suffix = name[len(base) + 1:]
                if suffix.isdigit():
                    chunks.append(int(suffix))
    out = [f"{head_path}.{i:03d}" for i in sorted(chunks)]
    if os.path.exists(head_path):
        out.append(head_path)
    return out


def repair_wal(head_path: str) -> list[dict]:
    """Auto-repair every file of the WAL group at `head_path`.

    For the FIRST file containing a corrupt frame: bytes from the last
    clean frame boundary onward move to a `.corrupt` sidecar and the file
    is truncated there. Every LATER file is untrusted (the stream after a
    corrupt point has no anchored framing) and is moved aside wholesale —
    in practice a crash tears only the final file, so this is the rare
    multi-chunk corruption case, not the common path.

    Returns one record per repaired file:
    {path, sidecar, kept_bytes, removed_bytes, kept_frames, reason}.
    Frames never span files (Group.write appends whole frames; rotation
    renames complete files), so per-file scanning is exact.
    """
    repairs: list[dict] = []
    corrupted = False
    for path in _wal_files(head_path):
        size = os.path.getsize(path)
        if corrupted:
            # everything after a torn file is untrusted: preserve wholesale
            sidecar = _sidecar_path(path)
            os.rename(path, sidecar)
            repairs.append({
                "path": path, "sidecar": sidecar, "kept_bytes": 0,
                "removed_bytes": size, "kept_frames": 0,
                "reason": "follows corrupt file",
            })
            continue
        with open(path, "rb") as f:
            frames, clean, err = scan_clean_frames(f)
        if err is None:
            continue
        corrupted = True
        sidecar = _sidecar_path(path)
        with open(path, "rb") as f:
            f.seek(clean)
            torn = f.read()
        with open(sidecar, "wb") as f:
            f.write(torn)
            f.flush()
            os.fsync(f.fileno())
        with open(path, "r+b") as f:
            f.truncate(clean)
            f.flush()
            os.fsync(f.fileno())
        repairs.append({
            "path": path, "sidecar": sidecar, "kept_bytes": clean,
            "removed_bytes": size - clean, "kept_frames": frames,
            "reason": err,
        })
    for r in repairs:
        RECORDER.record(
            "wal", "repair", file=os.path.basename(r["path"]),
            kept_bytes=r["kept_bytes"], removed_bytes=r["removed_bytes"],
            kept_frames=r["kept_frames"], reason=r["reason"][:200],
        )
    return repairs


class WAL:
    """Reference wal.go:57 baseWAL."""

    def __init__(
        self, path: str, head_size_limit: int = 10 * 1024 * 1024,
        repair: bool = True,
    ) -> None:
        # auto-repair BEFORE the group opens the head for append: a torn
        # tail would otherwise poison every later read (and a new frame
        # appended after garbage is unreachable by the scanner)
        self.repairs = repair_wal(path) if repair else []
        self.group = Group(path, head_size_limit=head_size_limit)

    def write(self, msg) -> None:
        # WAL timestamps are operator-facing replay metadata, never hashed
        # or compared across replicas — wall time is the point here
        self.group.write(encode_frame(TimedWALMessage(time.time_ns(), msg)))  # tmlint: disable=TM201
        if isinstance(msg, EndHeightMessage):
            # the height barrier is the WAL event a postmortem reads for
            RECORDER.record("wal", "end_height", height=msg.height)

    def write_sync(self, msg) -> None:
        self.write(msg)
        t0 = time.monotonic()
        self.group.flush_sync()
        # fsync barriers are the commit round's dominant disk cost: a slow
        # disk shows up in the black box as stretched wal/fsync events
        RECORDER.record("wal", "fsync", ms=round((time.monotonic() - t0) * 1e3, 3))

    def flush(self) -> None:
        self.group.flush()

    def close(self) -> None:
        self.group.close()

    def iter_all(self):
        """Decode every readable message (stops at the first corrupt frame);
        the `replay` CLI command and WAL repair tooling use this."""
        try:
            for tm in decode_frames(self.group.reader()):
                yield tm
        except WALCorruptionError:
            return

    def search_for_end_height(self, height: int):
        """Return an iterator of messages AFTER #ENDHEIGHT for height, or
        None if not found (reference wal.go:213). height=0 with an empty WAL
        counts as found (fresh chain)."""
        msgs = []
        found = height == 0
        try:
            for tm in decode_frames(self.group.reader()):
                if found:
                    msgs.append(tm)
                if isinstance(tm.msg, EndHeightMessage) and tm.msg.height == height:
                    found = True
                    msgs = []
        except WALCorruptionError:
            # corrupt tail: everything before it is still usable
            pass
        return msgs if found else None


class NilWAL:
    """Reference wal.go:382 — used when WAL is disabled."""

    def write(self, msg) -> None:
        pass

    def write_sync(self, msg) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def search_for_end_height(self, height: int):
        return None if height > 0 else []
