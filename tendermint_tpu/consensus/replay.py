"""Crash recovery: WAL catchup replay + ABCI handshake replay.

Reference parity: consensus/replay.go —
(1) catchupReplay (:100): after boot, messages logged since the last height
    barrier are re-fed through the state machine (called from
    ConsensusState.on_start); signing is disabled during replay because every
    own vote/proposal was WriteSync'd to the WAL before use.
(2) Handshaker (:241): ABCI Info -> compare app height vs block-store height
    vs state height -> ReplayBlocks (:285) brings the application back in
    sync with the chain, including InitChain for fresh apps and full
    ApplyBlock for the final block when state lags the store by one (the
    crash-between-SaveBlock-and-SaveState case).
"""
from __future__ import annotations

from tendermint_tpu.abci import types as abci
from tendermint_tpu import crypto
from tendermint_tpu.consensus.wal import (
    EndHeightMessage,
    EventDataRoundState,
    MsgInfo,
    WALTimeoutInfo,
)
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.state import State, StateStore
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.types import BlockID, GenesisDoc, ValidatorSet
from tendermint_tpu.types.validator import Validator


def catchup_replay(cs, cs_height: int) -> None:
    """Reference :100. Feeds WAL messages synchronously into the state
    machine's queues for the receive routine to process on start — with
    replay-time signing disabled via the logged votes themselves."""
    # if the WAL already contains the end of cs_height, our state is stale —
    # replaying would double-sign (reference :61 panics here too)
    if cs_height >= 1 and cs.wal.search_for_end_height(cs_height) is not None:
        raise RuntimeError(
            f"WAL contains end of height {cs_height}; state appears stale"
        )
    msgs = cs.wal.search_for_end_height(cs_height - 1)
    if msgs is None:
        if cs_height > 1:
            cs.log.info("no WAL data for height", height=cs_height)
        return
    count = 0
    for tm in msgs:
        msg = tm.msg
        if isinstance(msg, EndHeightMessage):
            continue
        if isinstance(msg, EventDataRoundState):
            continue
        if isinstance(msg, WALTimeoutInfo):
            continue  # timeouts re-fire naturally
        if isinstance(msg, MsgInfo):
            cs.peer_msg_queue.put_nowait(MsgInfo(msg.msg, "replay"))
            count += 1
    if count:
        cs.log.info("replaying WAL messages", count=count, height=cs_height)


class HandshakeError(Exception):
    pass


class Handshaker:
    """Reference :200-453."""

    def __init__(
        self,
        state_store: StateStore,
        state: State,
        block_store,
        genesis: GenesisDoc,
        event_bus=None,
        logger: Logger = NOP,
    ) -> None:
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis = genesis
        self.event_bus = event_bus
        self.log = logger
        self.n_blocks = 0

    async def handshake(self, app_conns) -> State:
        """Sync the app with the chain; returns the (possibly new) state."""
        info = await app_conns.query.info(abci.RequestInfo(version="tendermint-tpu"))
        app_height = max(0, info.last_block_height)
        app_hash = info.last_block_app_hash
        self.log.info(
            "ABCI handshake", app_height=app_height, app_hash=app_hash.hex()[:12]
        )
        state = await self.replay_blocks(self.initial_state, app_conns, app_height, app_hash)
        self.log.info("handshake complete", height=state.last_block_height)
        return state

    async def replay_blocks(
        self, state: State, app_conns, app_height: int, app_hash: bytes
    ) -> State:
        """Reference :285 ReplayBlocks."""
        store_height = self.block_store.height()
        state_height = state.last_block_height

        # InitChain for a fresh app
        if app_height == 0:
            validators = [
                abci.ValidatorUpdate(crypto.encode_pubkey(v.pub_key), v.power)
                for v in self.genesis.validators
            ]
            req = abci.RequestInitChain(
                time=self.genesis.genesis_time,
                chain_id=self.genesis.chain_id,
                consensus_params=self.genesis.consensus_params.encode(),
                validators=validators,
                app_state_bytes=self.genesis.app_state,
            )
            res = await app_conns.consensus.init_chain(req)
            if state_height == 0:
                # adopt app-provided genesis validators/params
                if res.validators:
                    vals = [
                        Validator(crypto.decode_pubkey(vu.pub_key), vu.power)
                        for vu in res.validators
                    ]
                    state.validators = ValidatorSet(vals)
                    state.next_validators = state.validators.copy_increment_proposer_priority(1)
                self.state_store.save(state)

        if store_height == 0:
            return state

        if app_height > store_height:
            raise HandshakeError(
                f"app block height {app_height} ahead of store {store_height}"
            )
        if state_height > store_height:
            raise HandshakeError(
                f"state height {state_height} ahead of store {store_height}"
            )

        # replay blocks the app is missing
        if store_height > state_height + 1:
            raise HandshakeError(
                f"store height {store_height} > state height {state_height} + 1"
            )

        exec_ = BlockExecutor(self.state_store, app_conns.consensus, event_bus=self.event_bus)

        # blocks <= state_height: exec against the app only (state has them)
        for h in range(app_height + 1, min(store_height, state_height) + 1):
            self.log.info("replaying block to app", height=h)
            block = self.block_store.load_block(h)
            await exec_._exec_block_on_proxy_app(state, block)
            await app_conns.consensus.commit()
            self.n_blocks += 1

        if store_height == state_height + 1:
            # crash between SaveBlock and SaveState: full ApplyBlock
            block = self.block_store.load_block(store_height)
            self.log.info("applying final block", height=store_height)
            if app_height == store_height:
                # app already has it: replay state update only, using the
                # stored ABCI responses (reference mock app path :499-534)
                responses = self.state_store.load_abci_responses(store_height)
                if responses is None:
                    raise HandshakeError(
                        f"no ABCI responses stored for height {store_height}"
                    )
                validator_updates = exec_._validate_validator_updates(
                    responses.end_block.validator_updates if responses.end_block else [],
                    state.consensus_params,
                )
                block_id = BlockID(block.hash(), block.make_part_set().header())
                state = exec_._update_state(
                    state, block_id, block, responses, validator_updates
                )
                state.app_hash = app_hash
                self.state_store.save(state)
            else:
                block_id = BlockID(block.hash(), block.make_part_set().header())
                state = await exec_.apply_block(state, block_id, block)
            self.n_blocks += 1

        # verify app hash consistency
        if state.app_hash and app_hash and state.last_block_height == app_height:
            info2 = await app_conns.query.info(abci.RequestInfo())
            if info2.last_block_app_hash != state.app_hash:
                raise HandshakeError(
                    f"app hash mismatch after replay: app "
                    f"{info2.last_block_app_hash.hex()} != state {state.app_hash.hex()}"
                )
        return state
