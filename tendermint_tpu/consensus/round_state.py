"""Round state + height vote bookkeeping.

Reference parity: consensus/types/round_state.go:16,67 (8-step enum +
RoundState snapshot), consensus/types/height_vote_set.go:36,111
(prevotes+precommits per round with peer-triggered round bounding),
consensus/types/peer_round_state.go.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass

from tendermint_tpu.libs.bit_array import BitArray
from tendermint_tpu.types import (
    Block,
    BlockID,
    PartSet,
    PartSetHeader,
    Proposal,
    ValidatorSet,
    Vote,
    VoteSet,
    VoteType,
)


class RoundStep(enum.IntEnum):
    """Reference round_state.go:16."""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


class HeightVoteSet:
    """Reference height_vote_set.go:36 — one prevote + one precommit VoteSet
    per round; rounds created on demand; peer-suggested rounds bounded so a
    Byzantine peer can't make us allocate unboundedly."""

    MAX_PEER_CATCHUP_ROUNDS = 2

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet) -> None:
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.round = 0
        self._sets: dict[int, dict[VoteType, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ not in self._sets:
            self._sets[round_] = {
                VoteType.PREVOTE: VoteSet(
                    self.chain_id, self.height, round_, VoteType.PREVOTE, self.val_set
                ),
                VoteType.PRECOMMIT: VoteSet(
                    self.chain_id, self.height, round_, VoteType.PRECOMMIT, self.val_set
                ),
            }

    def set_round(self, round_: int) -> None:
        """Create vote sets up to round+1 (reference SetRound)."""
        for r in range(self.round, round_ + 2):
            self._add_round(r)
        self.round = round_

    def add_vote(self, vote: Vote, peer_id: str = "") -> bool:
        """Reference height_vote_set.go:111 AddVote. (The gossip
        micro-batcher does NOT route through here: it targets existing
        rounds via prevotes()/precommits() and falls back to this serial
        path when a vote names a round we have not created, so the per-peer
        catchup bounding below charges each vote's own peer.)"""
        if vote.round not in self._sets:
            rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
            if len(rounds) >= self.MAX_PEER_CATCHUP_ROUNDS:
                raise ValueError("peer has sent votes for too many catchup rounds")
            self._add_round(vote.round)
            rounds.append(vote.round)
        return self._sets[vote.round][vote.type].add_vote(vote)

    def prevotes(self, round_: int) -> VoteSet | None:
        return self._sets.get(round_, {}).get(VoteType.PREVOTE)

    def precommits(self, round_: int) -> VoteSet | None:
        return self._sets.get(round_, {}).get(VoteType.PRECOMMIT)

    def pol_info(self) -> tuple[int, BlockID]:
        """Highest round with a prevote 2/3 majority (reference POLInfo)."""
        for r in sorted(self._sets, reverse=True):
            vs = self.prevotes(r)
            if vs is not None:
                bid, ok = vs.two_thirds_majority()
                if ok:
                    return r, bid
        return -1, BlockID()

    def set_peer_maj23(self, round_: int, type_: VoteType, peer_id: str, block_id: BlockID) -> None:
        self._add_round(round_)
        self._sets[round_][type_].set_peer_maj23(peer_id, block_id)


@dataclass
class RoundState:
    """Reference round_state.go:67 — the consensus state snapshot."""

    height: int = 0
    round: int = 0
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time: float = 0.0
    commit_time: float = 0.0
    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None
    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None
    votes: HeightVoteSet | None = None
    commit_round: int = -1
    last_commit: VoteSet | None = None
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def event_data(self) -> dict:
        return {
            "height": self.height,
            "round": self.round,
            "step": self.step.name,
        }


@dataclass
class PeerRoundState:
    """Reference peer_round_state.go — our view of one peer's progress."""

    height: int = 0
    round: int = -1
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time: float = 0.0
    proposal: bool = False
    proposal_block_parts_header: PartSetHeader = PartSetHeader()
    proposal_block_parts: BitArray | None = None
    proposal_pol_round: int = -1
    proposal_pol: BitArray | None = None
    prevotes: BitArray | None = None
    precommits: BitArray | None = None
    last_commit_round: int = -1
    last_commit: BitArray | None = None
    catchup_commit_round: int = -1
    catchup_commit: BitArray | None = None
