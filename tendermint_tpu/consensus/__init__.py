"""Consensus — the Tendermint BFT state machine and its support systems
(reference consensus/): ConsensusState, WAL, replay/handshake, timeout
ticker, reactor."""
