"""Byzantine behaviour harness — a validator that equivocates on purpose.

The in-process byzantine tests (tests/test_byzantine.py, reference
consensus/byzantine_test.go) patch a ConsensusState inside one pytest
process. The nemesis scenario matrix needs the same attacker as a REAL
node process in a real testnet, so the equivocation travels over real
TCP gossip and the resulting `DuplicateVoteEvidence` exercises
`evidence/reactor.py` end to end — verified, gossiped, reaped into a
proposal, and committed in a block on every honest node.

`install_byzantine_voter(node)` replaces the node's `sign_add_vote`
with one that signs TWO conflicting votes per step (the honest target
and a fabricated BlockID) and sends each directly to a different half
of the connected peers, bypassing the node's own state machine — the
byzantine VOTER shape. The honest 3/4 majority keeps committing; gossip
relay brings both conflicting votes together on honest nodes, whose
`ConflictingVoteError` handler mints the evidence.

Double-sign protection: `FilePV.sign_vote` would (correctly) refuse the
second signature, so the harness signs the raw sign-bytes with the
underlying key — exactly what real Byzantine hardware would do.

Armed ONLY when both hold (networks/local/nemesis.py sets both):
- env `TMTPU_BYZANTINE=voter`
- config `p2p.test_fault_control` is true (the nemesis master switch)
"""
from __future__ import annotations

from tendermint_tpu.libs.recorder import RECORDER
from tendermint_tpu.types import BlockID
from tendermint_tpu.types.vote import Vote, now_ns


def _raw_sign(pv, sign_bytes: bytes) -> bytes:
    """Sign bypassing any double-sign guard: FilePV keeps the key at
    .key.priv_key, MockPV at ._priv."""
    key = getattr(getattr(pv, "key", None), "priv_key", None)
    if key is None:
        key = getattr(pv, "_priv", None)
    if key is None:
        raise TypeError(f"cannot extract signing key from {type(pv).__name__}")
    return key.sign(sign_bytes)


def install_byzantine_voter(node) -> None:
    """Patch `node.consensus_state.sign_add_vote` into the equivocating
    voter. Must be called after the node's switch + consensus state are
    built (node/__init__.py build step 10)."""
    import hashlib

    from tendermint_tpu.consensus import messages as m
    from tendermint_tpu.consensus.reactor import VOTE_CHANNEL
    from tendermint_tpu.types import PartSetHeader

    cs = node.consensus_state

    async def sign_add_vote(type_, hash_, parts_header):
        rs = cs.rs
        pv = cs.priv_validator
        if pv is None:
            return None
        addr = pv.address
        idx, val = rs.validators.get_by_address(addr)
        if val is None:
            return None
        real_bid = BlockID(hash_, parts_header or PartSetHeader())
        seed = b"equivocate-%d-%d" % (rs.height, rs.round)
        fake_h = hashlib.sha256(seed).digest()
        fake_bid = BlockID(fake_h, PartSetHeader(1, hashlib.sha256(fake_h).digest()))
        ts = now_ns()
        votes = []
        for bid in (real_bid, fake_bid):
            v = Vote(type_, rs.height, rs.round, bid, ts, addr, idx)
            votes.append(
                v.with_signature(_raw_sign(pv, v.sign_bytes(cs.state.chain_id)))
            )
        peers = sorted(node.switch.peers.list(), key=lambda p: p.id)
        half = (len(peers) + 1) // 2
        for i, peer in enumerate(peers):
            v = votes[0] if i < half else votes[1]
            await peer.send(
                VOTE_CHANNEL, m.encode_consensus_message(m.VoteMessage(v))
            )
        RECORDER.record(
            "byzantine", "equivocate", height=rs.height, round=rs.round,
            type=int(type_), peers=len(peers),
        )
        return None

    cs.sign_add_vote = sign_add_vote
    RECORDER.record("byzantine", "armed", mode="voter")
