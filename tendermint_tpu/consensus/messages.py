"""Consensus wire messages (shared by the reactor, the WAL, and replay).

Reference parity: consensus/reactor.go message types (NewRoundStep,
NewValidBlock, Proposal, ProposalPOL, BlockPart, Vote, HasVote,
VoteSetMaj23, VoteSetBits) and consensus/wal.go msgInfo/timeoutInfo
framing. Tagged-union CBE encoding.
"""
from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.consensus.round_state import RoundStep
from tendermint_tpu.encoding import DecodeError, Reader, Writer
from tendermint_tpu.libs.bit_array import BitArray
from tendermint_tpu.types import BlockID, Part, PartSetHeader, Proposal, Vote, VoteType


@dataclass
class NewRoundStepMessage:
    height: int
    round: int
    step: RoundStep
    seconds_since_start_time: int
    last_commit_round: int


@dataclass
class NewValidBlockMessage:
    height: int
    round: int
    block_parts_header: PartSetHeader
    block_parts: BitArray
    is_commit: bool


@dataclass
class ProposalMessage:
    proposal: Proposal


@dataclass
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: BitArray


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part


@dataclass
class VoteMessage:
    vote: Vote


@dataclass
class HasVoteMessage:
    height: int
    round: int
    type: VoteType
    index: int


@dataclass
class VoteSetMaj23Message:
    height: int
    round: int
    type: VoteType
    block_id: BlockID


@dataclass
class VoteSetBitsMessage:
    height: int
    round: int
    type: VoteType
    block_id: BlockID
    votes: BitArray


_TAGS: list[tuple[int, type]] = [
    (1, NewRoundStepMessage),
    (2, NewValidBlockMessage),
    (3, ProposalMessage),
    (4, ProposalPOLMessage),
    (5, BlockPartMessage),
    (6, VoteMessage),
    (7, HasVoteMessage),
    (8, VoteSetMaj23Message),
    (9, VoteSetBitsMessage),
]

# tag byte -> traffic-accounting label (wire-efficiency observatory);
# tags are unique across all four consensus channels, so one map serves
# STATE/DATA/VOTE/VOTE_SET_BITS alike
TYPE_LABELS: dict[int, str] = {
    1: "new_round_step",
    2: "new_valid_block",
    3: "proposal",
    4: "proposal_pol",
    5: "block_part",
    6: "vote",
    7: "has_vote",
    8: "vote_set_maj23",
    9: "vote_set_bits",
}


def encode_consensus_message(msg) -> bytes:
    w = Writer()
    if isinstance(msg, NewRoundStepMessage):
        w.u8(1).u64(msg.height).u32(msg.round).u8(int(msg.step))
        w.u64(msg.seconds_since_start_time).i64(msg.last_commit_round)
    elif isinstance(msg, NewValidBlockMessage):
        w.u8(2).u64(msg.height).u32(msg.round)
        msg.block_parts_header.encode_into(w)
        w.raw(msg.block_parts.encode())
        w.bool(msg.is_commit)
    elif isinstance(msg, ProposalMessage):
        w.u8(3).bytes(msg.proposal.encode())
    elif isinstance(msg, ProposalPOLMessage):
        w.u8(4).u64(msg.height).i64(msg.proposal_pol_round)
        w.raw(msg.proposal_pol.encode())
    elif isinstance(msg, BlockPartMessage):
        w.u8(5).u64(msg.height).u32(msg.round).bytes(msg.part.encode())
    elif isinstance(msg, VoteMessage):
        w.u8(6).bytes(msg.vote.encode())
    elif isinstance(msg, HasVoteMessage):
        w.u8(7).u64(msg.height).u32(msg.round).u8(int(msg.type)).u32(msg.index)
    elif isinstance(msg, VoteSetMaj23Message):
        w.u8(8).u64(msg.height).u32(msg.round).u8(int(msg.type))
        msg.block_id.encode_into(w)
    elif isinstance(msg, VoteSetBitsMessage):
        w.u8(9).u64(msg.height).u32(msg.round).u8(int(msg.type))
        msg.block_id.encode_into(w)
        w.raw(msg.votes.encode())
    else:
        raise TypeError(f"unknown consensus message {msg!r}")
    return w.build()


# Decode-time bit-array caps (the post-v0.32 reference added the same as
# a DoS fix; v0.32.3 itself lacked them). A part-set of a max-size block
# is < 1,601 parts; validator sets are bounded well under 10,000 in
# practice (BASELINE config 5's 10k shape is the inclusive ceiling).
MAX_BLOCK_PARTS_COUNT = 1601
MAX_VOTES_COUNT = 10_000


def decode_consensus_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == 1:
        return NewRoundStepMessage(r.u64(), r.u32(), RoundStep(r.u8()), r.u64(), r.i64())
    if tag == 2:
        return NewValidBlockMessage(
            r.u64(), r.u32(), PartSetHeader.read(r),
            BitArray.read(r, max_size=MAX_BLOCK_PARTS_COUNT), r.bool()
        )
    if tag == 3:
        return ProposalMessage(Proposal.decode(r.bytes()))
    if tag == 4:
        return ProposalPOLMessage(
            r.u64(), r.i64(), BitArray.read(r, max_size=MAX_VOTES_COUNT)
        )
    if tag == 5:
        return BlockPartMessage(r.u64(), r.u32(), Part.decode(r.bytes()))
    if tag == 6:
        return VoteMessage(Vote.decode(r.bytes()))
    if tag == 7:
        return HasVoteMessage(r.u64(), r.u32(), VoteType(r.u8()), r.u32())
    if tag == 8:
        return VoteSetMaj23Message(r.u64(), r.u32(), VoteType(r.u8()), BlockID.read(r))
    if tag == 9:
        return VoteSetBitsMessage(
            r.u64(), r.u32(), VoteType(r.u8()), BlockID.read(r),
            BitArray.read(r, max_size=MAX_VOTES_COUNT)
        )
    raise DecodeError(f"unknown consensus message tag {tag}")


def validate_consensus_message(msg) -> None:
    """ValidateBasic for wire-received consensus messages (reference
    reactor.go:1406-1640): structural bounds the DECODER cannot know —
    above all, that an advertised bit array's size agrees with the part
    count it claims to describe. Soak-found: a corrupted-but-decodable
    NewValidBlock whose bit array disagrees with its header poisons
    PeerState so set_has_proposal_block_part can never mark progress and
    the data-gossip routine re-sends the same part forever (the reference
    rejects exactly this at ValidateBasic, reactor.go:1456-1460). Raises
    DecodeError; the reactor's receive treats it like malformed bytes
    (peer stopped).

    Unsigned wire fields (height/round/index decode as u64/u32) cannot be
    negative, so the reference's negative-value checks reduce here to the
    two genuinely signed fields. Zero-size VoteSetBits is legal — a node
    answering VoteSetMaj23 without a matching vote set replies with an
    empty array (reactor.py:431), exactly as the reference permits."""
    if isinstance(msg, NewValidBlockMessage):
        if msg.block_parts.size != msg.block_parts_header.total:
            raise DecodeError(
                f"NewValidBlock: bit array size {msg.block_parts.size} != "
                f"header total {msg.block_parts_header.total}"
            )
    elif isinstance(msg, ProposalPOLMessage):
        if msg.proposal_pol_round < 0:
            raise DecodeError("ProposalPOL: negative proposal_pol_round")
        if msg.proposal_pol.size == 0:
            raise DecodeError("ProposalPOL: empty bit array")
    elif isinstance(msg, NewRoundStepMessage):
        if (msg.height == 1 and msg.last_commit_round != -1) or (
            msg.height > 1 and msg.last_commit_round < -1
        ):
            raise DecodeError(
                f"NewRoundStep: invalid last_commit_round "
                f"{msg.last_commit_round} at height {msg.height}"
            )
