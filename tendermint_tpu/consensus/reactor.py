"""Consensus gossip reactor — 4 p2p channels + 3 gossip routines per peer.

Reference parity: consensus/reactor.go:37 — channels State(0x20)/Data(0x21)/
Vote(0x22)/VoteSetBits(0x23) (:22-26,130); per-peer gossipDataRoutine
(block parts + catchup, :465,559), gossipVotesRoutine (picks a random needed
vote via peer bit arrays, :602,673), queryMaj23Routine (:729); PeerState
mirror with bit arrays (:904,1025); broadcasts NewRoundStep/HasVote on
internal events (:379-446); SwitchToConsensus from fast sync (:101).

asyncio tasks replace goroutines; the EventSwitch wakeups from
ConsensusState are bridged onto an ordered broadcast queue so gossip never
runs inside the consensus state machine's critical path.
"""
from __future__ import annotations

import asyncio
import time

from tendermint_tpu.behaviour import PeerBehaviour
from tendermint_tpu.consensus import messages as m
from tendermint_tpu.consensus.round_state import PeerRoundState, RoundState, RoundStep
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.libs.bit_array import BitArray
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.libs.recorder import RECORDER
from tendermint_tpu.p2p.base_reactor import BaseReactor, ChannelDescriptor
from tendermint_tpu.types import PartSetHeader, Vote, VoteType
from tendermint_tpu.types.vote_set import VoteSet

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

PEER_GOSSIP_SLEEP = 0.1  # reference config/config.go PeerGossipSleepDuration
PEER_QUERY_MAJ23_SLEEP = 2.0


class PeerState:
    """Our running mirror of one peer's consensus progress.

    Reference consensus/reactor.go:904 — updated from incoming messages and
    consulted by the gossip routines to decide what the peer still needs.
    """

    KEY = "consensus_peer_state"

    def __init__(self, peer) -> None:
        self.peer = peer
        self.prs = PeerRoundState()

    # -- queries ------------------------------------------------------

    def get_round_state(self) -> PeerRoundState:
        return self.prs

    # -- updates from our own state machine ---------------------------

    def set_has_proposal(self, proposal) -> None:
        prs = self.prs
        if prs.height != proposal.height or prs.round != proposal.round:
            return
        if prs.proposal:
            return
        prs.proposal = True
        prs.proposal_block_parts_header = proposal.block_id.parts
        if prs.proposal_block_parts is None:
            prs.proposal_block_parts = BitArray(proposal.block_id.parts.total)
        prs.proposal_pol_round = proposal.pol_round
        prs.proposal_pol = None  # until ProposalPOLMessage arrives

    def init_proposal_block_parts(self, header: PartSetHeader) -> None:
        if self.prs.proposal_block_parts is not None:
            return
        self.prs.proposal_block_parts_header = header
        self.prs.proposal_block_parts = BitArray(header.total)

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        prs = self.prs
        if prs.height != height or prs.round != round_:
            return
        if prs.proposal_block_parts is not None:
            prs.proposal_block_parts.set_index(index, True)

    def set_has_vote(self, height: int, round_: int, type_: VoteType, index: int) -> None:
        ba = self._get_vote_bit_array(height, round_, type_)
        if ba is not None:
            ba.set_index(index, True)

    def _get_vote_bit_array(self, height: int, round_: int, type_: VoteType) -> BitArray | None:
        """Reference reactor.go getVoteBitArray — find the tracked bit array
        for (height, round, type) across current/last/catchup commits."""
        prs = self.prs
        if prs.height == height:
            if prs.round == round_:
                return prs.prevotes if type_ == VoteType.PREVOTE else prs.precommits
            if prs.catchup_commit_round == round_ and type_ == VoteType.PRECOMMIT:
                return prs.catchup_commit
            if prs.proposal_pol_round == round_ and type_ == VoteType.PREVOTE:
                return prs.proposal_pol
            return None
        if prs.height == height + 1:
            if prs.last_commit_round == round_ and type_ == VoteType.PRECOMMIT:
                return prs.last_commit
            return None
        return None

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        prs = self.prs
        if prs.height == height:
            if prs.prevotes is None:
                prs.prevotes = BitArray(num_validators)
            if prs.precommits is None:
                prs.precommits = BitArray(num_validators)
            if prs.catchup_commit is None:
                prs.catchup_commit = BitArray(num_validators)
            if prs.proposal_pol is None:
                prs.proposal_pol = BitArray(num_validators)
        elif prs.height == height + 1:
            if prs.last_commit is None:
                prs.last_commit = BitArray(num_validators)

    def ensure_catchup_commit_round(self, height: int, round_: int, num_validators: int) -> None:
        """Reference reactor.go:966 — track precommits for a height the peer
        is still on but we have already committed."""
        prs = self.prs
        if prs.height != height:
            return
        if prs.catchup_commit_round == round_:
            return
        prs.catchup_commit_round = round_
        prs.catchup_commit = BitArray(num_validators)

    # -- updates from the peer's messages -----------------------------

    def apply_new_round_step(self, msg: m.NewRoundStepMessage) -> None:
        prs = self.prs
        ph, pr = prs.height, prs.round
        if msg.height < ph or (msg.height == ph and msg.round < pr):
            return
        psc_round = prs.catchup_commit_round
        psc = prs.catchup_commit
        last_precommits = prs.precommits

        prs.height = msg.height
        prs.round = msg.round
        prs.step = RoundStep(msg.step)
        prs.start_time = time.monotonic() - msg.seconds_since_start_time
        if ph != msg.height or pr != msg.round:
            prs.proposal = False
            prs.proposal_block_parts_header = PartSetHeader()
            prs.proposal_block_parts = None
            prs.proposal_pol_round = -1
            prs.proposal_pol = None
            prs.prevotes = None
            prs.precommits = None
        if ph == msg.height and pr != msg.round and msg.round == psc_round:
            # peer caught up to the round we tracked catchup precommits for
            prs.precommits = psc
        if ph != msg.height:
            # shift precommits to LastCommit
            if ph + 1 == msg.height and pr == msg.last_commit_round:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = last_precommits
            else:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = None
            prs.catchup_commit_round = -1
            prs.catchup_commit = None

    def apply_new_valid_block(self, msg: m.NewValidBlockMessage) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.round != msg.round and not msg.is_commit:
            return
        prs.proposal_block_parts_header = msg.block_parts_header
        prs.proposal_block_parts = msg.block_parts

    def apply_proposal_pol(self, msg: m.ProposalPOLMessage) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.proposal_pol_round != msg.proposal_pol_round:
            return
        prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg: m.HasVoteMessage) -> None:
        if self.prs.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def apply_vote_set_bits(self, msg: m.VoteSetBitsMessage, our_votes: BitArray | None) -> None:
        ba = self._get_vote_bit_array(msg.height, msg.round, msg.type)
        if ba is None:
            return
        if our_votes is None:
            ba.update(msg.votes)
        else:
            # votes we have win; for the rest, trust the peer's claim
            other = msg.votes.sub(our_votes)
            ba.update(ba.or_(other))

    # -- vote picking -------------------------------------------------

    async def pick_send_vote(self, votes) -> bool:
        """Reference reactor.go:1031 PickSendVote: pick a random vote the
        peer doesn't have and send it; returns True if one was sent."""
        vote = self.pick_vote_to_send(votes)
        if vote is None:
            return False
        ok = await self.peer.send(VOTE_CHANNEL, m.encode_consensus_message(m.VoteMessage(vote)))
        if ok:
            self.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
        return ok

    def pick_vote_to_send(self, votes) -> Vote | None:
        """votes: VoteSet or Commit (both expose size/bit_array/get_by_index
        semantics — reference VoteSetReader, types/vote_set.go:597)."""
        size = votes.size()
        if size == 0:
            return None
        height, round_, type_ = _votes_hrt(votes)
        # reference VoteSetReader.IsCommit: a Commit, or a precommit VoteSet
        # that reached 2/3 (e.g. rs.last_commit) — the peer may be on a later
        # round than the decision round, so track it as a catchup commit
        is_commit = not isinstance(votes, VoteSet) or (
            votes.type == VoteType.PRECOMMIT and votes.maj23 is not None
        )
        if is_commit:
            self.ensure_catchup_commit_round(height, round_, size)
        self.ensure_vote_bit_arrays(height, size)
        ps_votes = self._get_vote_bit_array(height, round_, type_)
        if ps_votes is None:
            return None
        votes_ba = votes.bit_array() if callable(getattr(votes, "bit_array", None)) else None
        if votes_ba is None:
            return None
        need = votes_ba.sub(ps_votes)
        idx, ok = need.pick_random()
        if not ok:
            return None
        return _votes_get(votes, idx)


def _votes_hrt(votes) -> tuple[int, int, VoteType]:
    if isinstance(votes, VoteSet):
        return votes.height, votes.round, votes.type
    # Commit
    return votes.height(), votes.round(), VoteType.PRECOMMIT


def _votes_get(votes, idx: int):
    if isinstance(votes, VoteSet):
        return votes.get_by_index(idx)
    return votes.precommits[idx]


class ConsensusReactor(BaseReactor):
    traffic_family = "consensus"

    def __init__(self, cs: ConsensusState, fast_sync: bool = False, logger: Logger = NOP) -> None:
        super().__init__("ConsensusReactor")
        self.cs = cs
        self.gossip_sleep = getattr(
            cs.config, "peer_gossip_sleep_duration", PEER_GOSSIP_SLEEP
        )
        self.maj23_sleep = getattr(
            cs.config, "peer_query_maj23_sleep_duration", PEER_QUERY_MAJ23_SLEEP
        )
        self.fast_sync = fast_sync
        self.log = logger
        self._peer_tasks: dict[str, list[asyncio.Task]] = {}
        self._broadcast_queue: asyncio.Queue[tuple[int, bytes]] = asyncio.Queue(maxsize=1000)

    # -- lifecycle ----------------------------------------------------

    async def on_start(self) -> None:
        self._subscribe_to_broadcast_events()
        self.spawn(self._broadcast_routine(), "cons-broadcast")
        if not self.fast_sync:
            await self.cs.start()

    async def on_stop(self) -> None:
        self.cs.event_switch.remove_listener("consensus-reactor")
        for tasks in self._peer_tasks.values():
            for t in tasks:
                t.cancel()
        self._peer_tasks.clear()
        if self.cs.is_running:
            await self.cs.stop()

    async def switch_to_consensus(self, state, blocks_synced: int = 0) -> None:
        """Reference reactor.go:101 SwitchToConsensus — called by the fast
        sync reactor once caught up."""
        self.log.info("switching to consensus")
        self.cs.update_to_state(state)
        self.fast_sync = False
        await self.cs.start()

    # -- event bridge -------------------------------------------------

    def _subscribe_to_broadcast_events(self) -> None:
        es = self.cs.event_switch
        es.add_listener_for_event(
            "consensus-reactor", "new_round_step", self._on_new_round_step
        )
        es.add_listener_for_event("consensus-reactor", "valid_block", self._on_valid_block)
        es.add_listener_for_event("consensus-reactor", "vote", self._on_vote)

    def _enqueue_broadcast(self, ch_id: int, msg_bytes: bytes) -> None:
        try:
            self._broadcast_queue.put_nowait((ch_id, msg_bytes))
        except asyncio.QueueFull:
            self.log.error("consensus broadcast queue full; dropping")

    async def _broadcast_routine(self) -> None:
        while True:
            ch_id, msg_bytes = await self._broadcast_queue.get()
            if self.switch is not None:
                await self.switch.broadcast(ch_id, msg_bytes)

    def _on_new_round_step(self, rs: RoundState) -> None:
        self._enqueue_broadcast(
            STATE_CHANNEL, m.encode_consensus_message(_new_round_step_msg(rs))
        )

    def _on_valid_block(self, rs: RoundState) -> None:
        msg = m.NewValidBlockMessage(
            height=rs.height,
            round=rs.round,
            block_parts_header=rs.proposal_block_parts.header()
            if rs.proposal_block_parts
            else PartSetHeader(),
            block_parts=rs.proposal_block_parts.bit_array()
            if rs.proposal_block_parts
            else BitArray(0),
            is_commit=rs.step == RoundStep.COMMIT,
        )
        self._enqueue_broadcast(STATE_CHANNEL, m.encode_consensus_message(msg))

    def _on_vote(self, vote: Vote) -> None:
        msg = m.HasVoteMessage(
            height=vote.height, round=vote.round, type=vote.type, index=vote.validator_index
        )
        self._enqueue_broadcast(STATE_CHANNEL, m.encode_consensus_message(msg))

    # -- reactor contract ---------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=5, send_queue_capacity=100),
            ChannelDescriptor(
                DATA_CHANNEL, priority=10, send_queue_capacity=100,
                recv_message_capacity=1 << 22,
            ),
            ChannelDescriptor(VOTE_CHANNEL, priority=5, send_queue_capacity=100),
            ChannelDescriptor(VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=2),
        ]

    def classify(self, ch_id: int, msg: bytes) -> str:
        # tags are unique across all four consensus channels; one peek
        return m.TYPE_LABELS.get(msg[0], "other") if msg else "other"

    def init_peer(self, peer) -> None:
        peer.set(PeerState.KEY, PeerState(peer))

    async def add_peer(self, peer) -> None:
        ps: PeerState = peer.get(PeerState.KEY)
        tasks = [
            self.spawn(self._gossip_data_routine(peer, ps), f"gossip-data-{peer.id}"),
            self.spawn(self._gossip_votes_routine(peer, ps), f"gossip-votes-{peer.id}"),
            self.spawn(self._query_maj23_routine(peer, ps), f"query-maj23-{peer.id}"),
        ]
        self._peer_tasks[peer.id] = tasks
        if not self.fast_sync:
            # tell the new peer where we are
            await peer.send(
                STATE_CHANNEL,
                m.encode_consensus_message(_new_round_step_msg(self.cs.rs)),
            )

    async def remove_peer(self, peer, reason) -> None:
        for t in self._peer_tasks.pop(peer.id, []):
            t.cancel()

    async def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        try:
            msg = m.decode_consensus_message(msg_bytes)
            m.validate_consensus_message(msg)
        except Exception as e:
            self.log.error("bad consensus message", peer=peer.id, err=repr(e))
            await self.report(
                peer, PeerBehaviour.bad_message(peer.id, f"consensus: {e!r}")
            )
            return
        ps: PeerState = peer.get(PeerState.KEY)
        if ps is None:
            return

        if ch_id == STATE_CHANNEL:
            await self._receive_state(peer, ps, msg)
        elif ch_id == DATA_CHANNEL:
            if self.fast_sync:
                return
            await self._receive_data(peer, ps, msg)
        elif ch_id == VOTE_CHANNEL:
            if self.fast_sync:
                return
            await self._receive_vote(peer, ps, msg)
        elif ch_id == VOTE_SET_BITS_CHANNEL:
            if self.fast_sync:
                return
            await self._receive_vote_set_bits(peer, ps, msg)

    async def _receive_state(self, peer, ps: PeerState, msg) -> None:
        cs = self.cs
        if isinstance(msg, m.NewRoundStepMessage):
            ps.apply_new_round_step(msg)
        elif isinstance(msg, m.NewValidBlockMessage):
            ps.apply_new_valid_block(msg)
        elif isinstance(msg, m.HasVoteMessage):
            ps.apply_has_vote(msg)
        elif isinstance(msg, m.VoteSetMaj23Message):
            # reference reactor.go:270: respond with our VoteSetBits
            rs = cs.rs
            if rs.height != msg.height or rs.votes is None:
                return
            cs.rs.votes.set_peer_maj23(msg.round, msg.type, peer.id, msg.block_id)
            votes = (
                rs.votes.prevotes(msg.round)
                if msg.type == VoteType.PREVOTE
                else rs.votes.precommits(msg.round)
            )
            our = votes.bit_array_by_block_id(msg.block_id) if votes else None
            resp = m.VoteSetBitsMessage(
                height=msg.height,
                round=msg.round,
                type=msg.type,
                block_id=msg.block_id,
                votes=our if our is not None else BitArray(0),
            )
            await peer.send(VOTE_SET_BITS_CHANNEL, m.encode_consensus_message(resp))

    async def _receive_data(self, peer, ps: PeerState, msg) -> None:
        if isinstance(msg, m.ProposalMessage):
            ps.set_has_proposal(msg.proposal)
            await self.cs.send_peer_msg(msg, peer.id)
        elif isinstance(msg, m.ProposalPOLMessage):
            ps.apply_proposal_pol(msg)
        elif isinstance(msg, m.BlockPartMessage):
            rs = self.cs.rs
            if (
                msg.height == rs.height
                and rs.proposal_block_parts is not None
                and rs.proposal_block_parts.bit_array().get_index(msg.part.index)
            ):
                # part already held: a normal gossip race (two peers both
                # saw the gap), but pure wire waste — count it
                self.note_redundant(peer, "block_part")
            ps.set_has_proposal_block_part(msg.height, msg.round, msg.part.index)
            await self.report(peer, PeerBehaviour.block_part(peer.id))
            await self.cs.send_peer_msg(msg, peer.id)

    async def _receive_vote(self, peer, ps: PeerState, msg) -> None:
        if isinstance(msg, m.VoteMessage):
            cs = self.cs
            rs = cs.rs
            n = rs.validators.size() if rs.validators else 0
            ps.ensure_vote_bit_arrays(rs.height, n)
            ps.ensure_vote_bit_arrays(
                rs.height - 1, rs.last_commit.size() if rs.last_commit else 0
            )
            v = msg.vote
            # fleet-timeline tap: gossip RECEIPT time, per delivering
            # peer — paired with the VoteSet "vote" (counted) event this
            # gives the collector gossip-vs-verify attribution for every
            # vote (the same vote arriving via several peers records one
            # receipt each; only the first COUNTS)
            RECORDER.record(
                "consensus", "vote_recv", height=v.height, round=v.round,
                type=int(v.type), val=v.validator_index, peer=peer.id,
            )
            if v.height == rs.height and rs.votes is not None:
                vs = (
                    rs.votes.prevotes(v.round)
                    if v.type == VoteType.PREVOTE
                    else rs.votes.precommits(v.round)
                )
                if vs is not None and vs.votes_bit_array.get_index(
                    v.validator_index
                ):
                    # already counted via another peer: the redundancy the
                    # gossip amplification factor measures
                    self.note_redundant(peer, "vote")
            ps.set_has_vote(v.height, v.round, v.type, v.validator_index)
            # ADR-039 good behaviour: decodable votes keep the peer's
            # trust metric fed (float ops only on this hot path)
            await self.report(peer, PeerBehaviour.consensus_vote(peer.id))
            await cs.send_peer_msg(msg, peer.id)

    async def _receive_vote_set_bits(self, peer, ps: PeerState, msg) -> None:
        if not isinstance(msg, m.VoteSetBitsMessage):
            return
        rs = self.cs.rs
        our = None
        if rs.height == msg.height and rs.votes is not None:
            votes = (
                rs.votes.prevotes(msg.round)
                if msg.type == VoteType.PREVOTE
                else rs.votes.precommits(msg.round)
            )
            if votes is not None:
                our = votes.bit_array_by_block_id(msg.block_id)
        ps.apply_vote_set_bits(msg, our)

    # -- gossip routines ----------------------------------------------

    async def _gossip_data_routine(self, peer, ps: PeerState) -> None:
        """Reference reactor.go:465 — feed the peer block parts (current
        height) or catch it up from the block store (old heights)."""
        cs = self.cs
        while True:
            rs = cs.rs
            prs = ps.get_round_state()

            # send proposal block parts the peer is missing
            block_parts = rs.proposal_block_parts
            if (
                block_parts is not None
                and rs.height == prs.height
                and rs.round == prs.round
                and prs.proposal_block_parts is not None
                and block_parts.header() == prs.proposal_block_parts_header
            ):
                need = block_parts.bit_array().sub(prs.proposal_block_parts)
                index, ok = need.pick_random()
                if ok and block_parts.get_part(index) is not None:
                    part = block_parts.get_part(index)
                    msg = m.BlockPartMessage(height=rs.height, round=rs.round, part=part)
                    if await peer.send(DATA_CHANNEL, m.encode_consensus_message(msg)):
                        ps.set_has_proposal_block_part(prs.height, prs.round, index)
                        if not (
                            prs.proposal_block_parts is None
                            or prs.proposal_block_parts.get_index(index)
                        ):
                            # the mark didn't take. With message
                            # validation in place the only way here is a
                            # benign race (prs swapped during the awaited
                            # send — e.g. NewValidBlock for a later
                            # round), so don't punish the peer; but DO
                            # yield before re-evaluating, so no state can
                            # ever turn this loop into the soak-found
                            # re-send-forever starvation.
                            await asyncio.sleep(self.gossip_sleep)
                    else:
                        # send refused — above all `not mconn.is_running`
                        # during a peer teardown, which returns False
                        # SYNCHRONOUSLY: without this sleep the loop has
                        # no suspension point at all, and an un-yielding
                        # coroutine starves the whole event loop — it
                        # even blocks the remove_peer() that would cancel
                        # this very task (soak-found: watchdog dumps
                        # showed the loop wedged in this branch's
                        # pick_random; Go's preemptive goroutines never
                        # needed the yield, asyncio does).
                        await asyncio.sleep(self.gossip_sleep)
                    continue

            # catchup: peer is on an older height we have in the store
            if 0 < prs.height < rs.height and prs.height >= cs.block_store.base():
                if await self._gossip_catchup(peer, ps, prs):
                    continue
                await asyncio.sleep(self.gossip_sleep)
                continue

            # send the Proposal (and POL) if the peer doesn't have it
            proposal = rs.proposal
            if rs.height == prs.height and proposal is not None and not prs.proposal:
                msg = m.ProposalMessage(proposal=proposal)
                if await peer.send(DATA_CHANNEL, m.encode_consensus_message(msg)):
                    ps.set_has_proposal(proposal)
                    # use the SNAPSHOT, not live rs: a round change during
                    # the awaited send sets rs.proposal = None in place
                    # (state.py enter_new_round) and a live dereference
                    # would kill this gossip task with AttributeError
                    if proposal.pol_round >= 0 and rs.votes is not None:
                        pol = rs.votes.prevotes(proposal.pol_round)
                        if pol is not None:
                            pol_msg = m.ProposalPOLMessage(
                                height=proposal.height,
                                proposal_pol_round=proposal.pol_round,
                                proposal_pol=pol.bit_array(),
                            )
                            await peer.send(
                                DATA_CHANNEL, m.encode_consensus_message(pol_msg)
                            )
                else:
                    # same synchronous-False teardown race as the part
                    # send above: yield or the retry loop starves the loop
                    await asyncio.sleep(self.gossip_sleep)
                continue

            await asyncio.sleep(self.gossip_sleep)

    async def _gossip_catchup(self, peer, ps: PeerState, prs: PeerRoundState) -> bool:
        """Reference reactor.go:559 gossipDataForCatchup."""
        cs = self.cs
        if prs.proposal_block_parts is None:
            meta = cs.block_store.load_block_meta(prs.height)
            if meta is None:
                return False
            ps.init_proposal_block_parts(meta.block_id.parts)
            return True
        need = BitArray(prs.proposal_block_parts.size).not_().sub(prs.proposal_block_parts)
        index, ok = need.pick_random()
        if not ok:
            return False
        part = cs.block_store.load_block_part(prs.height, index)
        if part is None:
            return False
        msg = m.BlockPartMessage(height=prs.height, round=prs.round, part=part)
        if await peer.send(DATA_CHANNEL, m.encode_consensus_message(msg)):
            ps.set_has_proposal_block_part(prs.height, prs.round, index)
            return True
        return False

    async def _gossip_votes_routine(self, peer, ps: PeerState) -> None:
        """Reference reactor.go:602 — pick one vote the peer needs."""
        cs = self.cs
        while True:
            rs = cs.rs
            prs = ps.get_round_state()
            sent = False

            if rs.height == prs.height:
                sent = await self._gossip_votes_for_height(rs, prs, ps)
            # special: peer is one height behind and wants our last commit
            if (
                not sent
                and prs.height != 0
                and rs.height == prs.height + 1
                and rs.last_commit is not None
            ):
                sent = await ps.pick_send_vote(rs.last_commit)
            # catchup: load the block commit for the peer's height
            if (
                not sent
                and prs.height != 0
                and rs.height >= prs.height + 2
                and prs.height >= cs.block_store.base()
            ):
                commit = cs.block_store.load_block_commit(prs.height)
                if commit is not None:
                    ps.ensure_catchup_commit_round(prs.height, commit.round(), commit.size())
                    ps.ensure_vote_bit_arrays(prs.height, commit.size())
                    sent = await ps.pick_send_vote(commit)

            if not sent:
                await asyncio.sleep(self.gossip_sleep)

    async def _gossip_votes_for_height(self, rs: RoundState, prs: PeerRoundState, ps: PeerState) -> bool:
        """Reference reactor.go:673."""
        if rs.votes is None:
            return False
        # peer's LastCommit precommits
        if prs.step == RoundStep.NEW_HEIGHT and rs.last_commit is not None:
            if await ps.pick_send_vote(rs.last_commit):
                return True
        # POL prevotes for the peer's proposal_pol_round
        if prs.step <= RoundStep.PROPOSE and 0 <= prs.proposal_pol_round:
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and await ps.pick_send_vote(pol):
                return True
        # prevotes for the peer's round
        if prs.step <= RoundStep.PREVOTE_WAIT and 0 <= prs.round <= rs.round:
            pv = rs.votes.prevotes(prs.round)
            if pv is not None and await ps.pick_send_vote(pv):
                return True
        # precommits for the peer's round
        if prs.step <= RoundStep.PRECOMMIT_WAIT and 0 <= prs.round <= rs.round:
            pc = rs.votes.precommits(prs.round)
            if pc is not None and await ps.pick_send_vote(pc):
                return True
        # prevotes for the peer's valid round
        if 0 <= prs.proposal_pol_round:
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and await ps.pick_send_vote(pol):
                return True
        return False

    async def _query_maj23_routine(self, peer, ps: PeerState) -> None:
        """Reference reactor.go:729 — periodically tell the peer which
        block IDs we have seen 2/3 majorities for, so it can prove us wrong
        (fault-tolerance against vote withholding)."""
        cs = self.cs
        while True:
            await asyncio.sleep(self.maj23_sleep)
            rs = cs.rs
            prs = ps.get_round_state()
            if rs.height == prs.height and rs.votes is not None:
                for type_, votes in (
                    (VoteType.PREVOTE, rs.votes.prevotes(prs.round)),
                    (VoteType.PRECOMMIT, rs.votes.precommits(prs.round)),
                ):
                    if votes is None:
                        continue
                    block_id, ok = votes.two_thirds_majority()
                    if not ok:
                        continue
                    msg = m.VoteSetMaj23Message(
                        height=prs.height, round=prs.round, type=type_, block_id=block_id
                    )
                    await peer.send(STATE_CHANNEL, m.encode_consensus_message(msg))
            # catchup hint (reference reactor.go:780): a lagging peer whose
            # decision round we track gets told which block had 2/3 — this
            # lets its VoteSet start counting a Byzantine validator's
            # conflicting precommit toward the decided block
            if (
                prs.catchup_commit_round != -1
                and 0 < prs.height < rs.height
                and prs.height >= cs.block_store.base()
            ):
                commit = cs.block_store.load_block_commit(
                    prs.height
                ) or cs.block_store.load_seen_commit(prs.height)
                if commit is not None and commit.size() > 0:
                    msg = m.VoteSetMaj23Message(
                        height=prs.height,
                        round=commit.round(),
                        type=VoteType.PRECOMMIT,
                        block_id=commit.block_id,
                    )
                    await peer.send(STATE_CHANNEL, m.encode_consensus_message(msg))


def _new_round_step_msg(rs: RoundState) -> m.NewRoundStepMessage:
    return m.NewRoundStepMessage(
        height=rs.height,
        round=rs.round,
        step=rs.step,
        seconds_since_start_time=max(0, int(time.monotonic() - rs.start_time)),
        last_commit_round=rs.last_commit.round if rs.last_commit is not None else -1,
    )
