"""Fast sync v2 — the scheduler data-structure prototype.

Reference parity: blockchain/v2/schedule.go (ADR-043): a pure scheduling
data structure tracking per-height block states (New → Pending → Received
→ Processed) and per-peer states (New → Ready → Removed), with explicit
invariant-checked transitions. The reference shipped only this prototype
(no reactor); mirrored here with the same scope.
"""
from __future__ import annotations

import enum
import time


class BlockState(enum.Enum):
    UNKNOWN = "Unknown"
    NEW = "New"            # known height, no request yet
    PENDING = "Pending"    # requested from a peer
    RECEIVED = "Received"  # block arrived, not yet processed
    PROCESSED = "Processed"


class PeerState(enum.Enum):
    NEW = "New"
    READY = "Ready"
    REMOVED = "Removed"


class ScheduleError(Exception):
    pass


class Schedule:
    """Reference schedule.go `schedule`."""

    def __init__(self, initial_height: int) -> None:
        self.initial_height = initial_height
        self.block_states: dict[int, BlockState] = {}
        self.pending_blocks: dict[int, str] = {}      # height -> peer
        self.pending_time: dict[int, float] = {}
        self.received_blocks: dict[int, str] = {}
        self.peers: dict[str, PeerState] = {}
        self.peer_heights: dict[str, int] = {}
        self.max_height = initial_height - 1

    # -- peers --------------------------------------------------------

    def add_peer(self, peer_id: str) -> None:
        if peer_id in self.peers and self.peers[peer_id] != PeerState.REMOVED:
            raise ScheduleError(f"duplicate peer {peer_id}")
        self.peers[peer_id] = PeerState.NEW

    def touch_peer(self, peer_id: str) -> None:
        if self.peers.get(peer_id) != PeerState.READY:
            raise ScheduleError(f"peer {peer_id} not ready")

    def remove_peer(self, peer_id: str) -> None:
        state = self.peers.get(peer_id)
        if state is None or state == PeerState.REMOVED:
            return
        self.peers[peer_id] = PeerState.REMOVED
        # re-schedule its pending heights; forget its unprocessed blocks
        for h in [h for h, p in self.pending_blocks.items() if p == peer_id]:
            del self.pending_blocks[h]
            self.pending_time.pop(h, None)
            self.block_states[h] = BlockState.NEW
        for h in [h for h, p in self.received_blocks.items() if p == peer_id]:
            del self.received_blocks[h]
            self.block_states[h] = BlockState.NEW
        # shrink the height horizon if this was the tallest peer
        self.peer_heights.pop(peer_id, None)
        new_max = max(
            (
                h
                for p, h in self.peer_heights.items()
                if self.peers.get(p) == PeerState.READY
            ),
            default=self.initial_height - 1,
        )
        if new_max < self.max_height:
            for h in [h for h in self.block_states if h > new_max]:
                if self.block_states[h] != BlockState.PROCESSED:
                    del self.block_states[h]
            self.max_height = new_max

    def set_peer_height(self, peer_id: str, height: int) -> None:
        state = self.peers.get(peer_id)
        if state is None or state == PeerState.REMOVED:
            raise ScheduleError(f"cannot set height for peer {peer_id}")
        self.peers[peer_id] = PeerState.READY
        self.peer_heights[peer_id] = height
        if height > self.max_height:
            for h in range(self.max_height + 1, height + 1):
                if h >= self.initial_height and h not in self.block_states:
                    self.block_states[h] = BlockState.NEW
            self.max_height = height

    def ready_peers(self, min_height: int = 0) -> list[str]:
        return sorted(
            p
            for p, s in self.peers.items()
            if s == PeerState.READY and self.peer_heights.get(p, 0) >= min_height
        )

    # -- block transitions -------------------------------------------

    def get_state_at_height(self, height: int) -> BlockState:
        if height < self.initial_height:
            return BlockState.PROCESSED
        return self.block_states.get(height, BlockState.UNKNOWN)

    def mark_pending(self, peer_id: str, height: int, now: float | None = None) -> None:
        if self.get_state_at_height(height) != BlockState.NEW:
            raise ScheduleError(f"height {height} not New")
        if self.peers.get(peer_id) != PeerState.READY:
            raise ScheduleError(f"peer {peer_id} not ready")
        if self.peer_heights.get(peer_id, 0) < height:
            raise ScheduleError(f"peer {peer_id} too short for {height}")
        self.block_states[height] = BlockState.PENDING
        self.pending_blocks[height] = peer_id
        self.pending_time[height] = now if now is not None else time.monotonic()

    def mark_received(self, peer_id: str, height: int) -> None:
        if self.pending_blocks.get(height) != peer_id:
            raise ScheduleError(f"height {height} not pending from {peer_id}")
        self.block_states[height] = BlockState.RECEIVED
        del self.pending_blocks[height]
        self.pending_time.pop(height, None)
        self.received_blocks[height] = peer_id

    def mark_processed(self, height: int) -> None:
        if self.get_state_at_height(height) != BlockState.RECEIVED:
            raise ScheduleError(f"height {height} not Received")
        self.block_states[height] = BlockState.PROCESSED
        self.received_blocks.pop(height, None)

    # -- queries ------------------------------------------------------

    def next_height_to_schedule(self) -> int | None:
        for h in sorted(self.block_states):
            if self.block_states[h] == BlockState.NEW:
                return h
        return None

    def height_of_first_pending_since(self, cutoff: float) -> list[int]:
        """Heights whose requests have been outstanding since before cutoff
        (stall detection)."""
        return sorted(h for h, t in self.pending_time.items() if t < cutoff)

    def all_blocks_processed(self) -> bool:
        if not self.block_states:
            return False
        return all(s == BlockState.PROCESSED for s in self.block_states.values())
