"""Fast sync v1 — the explicit event-driven FSM refactor.

Reference parity: blockchain/v1/reactor_fsm.go + pool.go (per ADR-040):
the same wire protocol as v0 (status/block request-response), but sync
control flow rewritten as a finite state machine with named states
(unknown → waitForPeer → waitForBlock → finished) and explicit events
(startFSMEv, statusResponseEv, blockResponseEv, processedBlockEv,
makeRequestsEv, peerRemoveEv, stateTimeoutEv), which makes the
sync logic unit-testable without networking — exactly why the reference
rewrote it.

The BlockchainReactorV1 drives the FSM from p2p messages and a process
ticker; verification/apply is shared with v0 (batched commit verify).
"""
from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field

from tendermint_tpu.libs.log import NOP, Logger


class State(enum.Enum):
    UNKNOWN = "unknown"
    WAIT_FOR_PEER = "waitForPeer"
    WAIT_FOR_BLOCK = "waitForBlock"
    FINISHED = "finished"


class Event(enum.Enum):
    START = "startFSMEv"
    STATUS_RESPONSE = "statusResponseEv"
    BLOCK_RESPONSE = "blockResponseEv"
    NO_BLOCK_RESPONSE = "noBlockResponseEv"
    PROCESSED_BLOCK = "processedBlockEv"
    MAKE_REQUESTS = "makeRequestsEv"
    PEER_REMOVE = "peerRemoveEv"
    STATE_TIMEOUT = "stateTimeoutEv"
    STOP = "stopFSMEv"


class FSMError(Exception):
    pass


@dataclass
class BlockData:
    block: object
    peer_id: str


@dataclass
class FSMPeer:
    peer_id: str
    base: int = 0
    height: int = 0
    num_pending: int = 0
    last_touched: float = field(default_factory=time.monotonic)


MAX_PENDING_PER_PEER = 40
PEER_TIMEOUT = 15.0
WAIT_FOR_PEER_TIMEOUT = 3.0


class BcFSM:
    """The sync state machine (reference reactor_fsm.go bcReactorFSM).

    Pure data structure: `handle(event, data)` mutates state and returns a
    list of effects — ("request", height, peer_id) / ("error", peer_id,
    reason) / ("switch_to_consensus",) — the reactor performs IO.
    """

    def __init__(self, start_height: int, logger: Logger = NOP) -> None:
        self.state = State.UNKNOWN
        self.height = start_height  # next height to process
        self.peers: dict[str, FSMPeer] = {}
        self.pending: dict[int, str] = {}  # height -> peer
        self.received: dict[int, BlockData] = {}
        self.max_peer_height = 0
        self.log = logger
        self.blocks_synced = 0
        self._state_start = time.monotonic()

    # -- helpers ------------------------------------------------------

    def _set_state(self, s: State) -> None:
        if s != self.state:
            self.log.debug("fsm transition", frm=self.state.value, to=s.value)
            self.state = s
            self._state_start = time.monotonic()

    def _update_max_peer_height(self) -> None:
        self.max_peer_height = max((p.height for p in self.peers.values()), default=0)

    def _remove_peer(self, peer_id: str, effects: list) -> None:
        if peer_id not in self.peers:
            return
        del self.peers[peer_id]
        self._update_max_peer_height()
        for h in [h for h, p in self.pending.items() if p == peer_id]:
            del self.pending[h]
        for h in [h for h, bd in self.received.items() if bd.peer_id == peer_id]:
            del self.received[h]

    def _make_requests(self, effects: list) -> None:
        """Schedule block requests for a window of heights."""
        window = 600
        for h in range(self.height, min(self.height + window, self.max_peer_height + 1)):
            if h in self.pending or h in self.received:
                continue
            peer = self._pick_peer(h)
            if peer is None:
                break
            self.pending[h] = peer.peer_id
            peer.num_pending += 1
            effects.append(("request", h, peer.peer_id))

    def _pick_peer(self, height: int) -> FSMPeer | None:
        best = None
        for p in self.peers.values():
            if p.base <= height <= p.height and p.num_pending < MAX_PENDING_PER_PEER:
                if best is None or p.num_pending < best.num_pending:
                    best = p
        return best

    def first_two_blocks(self):
        first = self.received.get(self.height)
        second = self.received.get(self.height + 1)
        return first, second

    def is_caught_up(self) -> bool:
        return bool(self.peers) and self.height >= self.max_peer_height

    # -- the transition function --------------------------------------

    def handle(self, ev: Event, **data) -> list:
        effects: list = []
        s = self.state

        if ev == Event.STOP:
            self._set_state(State.FINISHED)
            return effects

        if s == State.UNKNOWN:
            if ev == Event.START:
                self._set_state(State.WAIT_FOR_PEER)
            else:
                raise FSMError(f"event {ev} in state {s}")
            return effects

        if s == State.WAIT_FOR_PEER:
            if ev == Event.STATUS_RESPONSE:
                self._on_status(data, effects)
                if self.max_peer_height >= self.height:
                    self._set_state(State.WAIT_FOR_BLOCK)
                    self._make_requests(effects)
                elif self.is_caught_up():
                    self._set_state(State.FINISHED)
                    effects.append(("switch_to_consensus",))
            elif ev == Event.STATE_TIMEOUT:
                if time.monotonic() - self._state_start > WAIT_FOR_PEER_TIMEOUT and not self.peers:
                    # no peers showed up: keep waiting (the reference errors
                    # out to the switch after a longer timeout)
                    pass
            elif ev == Event.PEER_REMOVE:
                self._remove_peer(data["peer_id"], effects)
            return effects

        if s == State.WAIT_FOR_BLOCK:
            if ev == Event.STATUS_RESPONSE:
                self._on_status(data, effects)
            elif ev == Event.BLOCK_RESPONSE:
                block, peer_id = data["block"], data["peer_id"]
                h = block.header.height
                want = self.pending.get(h)
                if want != peer_id:
                    effects.append(("error", peer_id, f"unsolicited block {h}"))
                else:
                    del self.pending[h]
                    peer = self.peers.get(peer_id)
                    if peer is not None:
                        peer.num_pending = max(0, peer.num_pending - 1)
                        peer.last_touched = time.monotonic()
                    self.received[h] = BlockData(block, peer_id)
            elif ev == Event.NO_BLOCK_RESPONSE:
                peer_id = data["peer_id"]
                effects.append(("error", peer_id, "peer advertised a block it lacks"))
                self._remove_peer(peer_id, effects)
            elif ev == Event.PROCESSED_BLOCK:
                if data.get("err"):
                    # verification failed: drop both involved peers, refetch.
                    # Distinct effect kind (not "error"): the reactor maps it
                    # to the heaviest trust penalty (behaviour bad_block)
                    for h in (self.height, self.height + 1):
                        bd = self.received.pop(h, None)
                        if bd is not None:
                            effects.append(("bad_block", bd.peer_id, "invalid block"))
                            self._remove_peer(bd.peer_id, effects)
                else:
                    self.received.pop(self.height, None)
                    self.height += 1
                    self.blocks_synced += 1
                if self.is_caught_up():
                    self._set_state(State.FINISHED)
                    effects.append(("switch_to_consensus",))
                else:
                    self._make_requests(effects)
            elif ev == Event.MAKE_REQUESTS:
                self._retry_stalled(effects)
                self._make_requests(effects)
            elif ev == Event.PEER_REMOVE:
                self._remove_peer(data["peer_id"], effects)
                if not self.peers:
                    self._set_state(State.WAIT_FOR_PEER)
            elif ev == Event.STATE_TIMEOUT:
                self._retry_stalled(effects)
            return effects

        if s == State.FINISHED:
            return effects
        raise FSMError(f"unhandled state {s}")

    def _on_status(self, data, effects) -> None:
        peer_id = data["peer_id"]
        p = self.peers.get(peer_id)
        if p is None:
            p = FSMPeer(peer_id)
            self.peers[peer_id] = p
        p.base, p.height = data.get("base", 0), data["height"]
        p.last_touched = time.monotonic()
        self._update_max_peer_height()

    def _retry_stalled(self, effects) -> None:
        now = time.monotonic()
        for pid, p in list(self.peers.items()):
            if p.num_pending > 0 and now - p.last_touched > PEER_TIMEOUT:
                effects.append(("error", pid, "fast-sync peer stalled"))
                self._remove_peer(pid, effects)
