"""Fast-sync v1 reactor — drives the BcFSM over the v0 wire protocol.

Reference parity: blockchain/v1/reactor.go — same BlockchainChannel and
messages as v0; sync control flow delegated to the FSM; block
verify+apply (batched commit verification) shared with v0.
"""
from __future__ import annotations

import asyncio

from tendermint_tpu.behaviour import PeerBehaviour
from tendermint_tpu.blockchain.reactor import (
    BC_TYPE_LABELS,
    BLOCKCHAIN_CHANNEL,
    BlockRequestMessage,
    BlockResponseMessage,
    NoBlockResponseMessage,
    StatusRequestMessage,
    StatusResponseMessage,
    decode_bc_message,
    encode_bc_message,
)
from tendermint_tpu.blockchain.v1 import BcFSM, Event, State
from tendermint_tpu.device.priorities import Priority, priority_scope
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.p2p.base_reactor import BaseReactor, ChannelDescriptor
from tendermint_tpu.types import BlockID
from tendermint_tpu.types.validator_set import VerifyError

PROCESS_INTERVAL = 0.01
TICK_INTERVAL = 1.0
STATUS_INTERVAL = 10.0


class BlockchainReactorV1(BaseReactor):
    traffic_family = "blockchain"

    def __init__(self, state, block_exec, block_store, fast_sync: bool, logger: Logger = NOP) -> None:
        super().__init__("BlockchainReactorV1")
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.fast_sync = fast_sync
        self.log = logger
        self.fsm = BcFSM(block_store.height() + 1, logger)

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                BLOCKCHAIN_CHANNEL, priority=10, send_queue_capacity=1000,
                recv_message_capacity=1 << 22,
            )
        ]

    def classify(self, ch_id: int, msg: bytes) -> str:
        return BC_TYPE_LABELS.get(msg[0], "other") if msg else "other"

    async def on_start(self) -> None:
        if self.fast_sync:
            await self._run_effects(self.fsm.handle(Event.START))
            self.spawn(self._process_routine(), "bcv1-process")
            self.spawn(self._tick_routine(), "bcv1-tick")

    async def start_fast_sync(self, state) -> None:
        """State-sync handoff (docs/state_sync.md): re-anchor the FSM on
        the freshly bootstrapped store and start syncing the residual
        heights (the v0 reactor's start_fast_sync contract)."""
        if self.fast_sync and self.fsm.state != State.FINISHED:
            return
        self.state = state
        self.fast_sync = True
        self.fsm = BcFSM(self.block_store.height() + 1, self.log)
        await self._run_effects(self.fsm.handle(Event.START))
        self.spawn(self._process_routine(), "bcv1-process")
        self.spawn(self._tick_routine(), "bcv1-tick")
        if self.switch is not None:
            await self.switch.broadcast(
                BLOCKCHAIN_CHANNEL, encode_bc_message(StatusRequestMessage())
            )

    # -- p2p ----------------------------------------------------------

    async def add_peer(self, peer) -> None:
        await peer.send(
            BLOCKCHAIN_CHANNEL,
            encode_bc_message(
                StatusResponseMessage(self.block_store.base(), self.block_store.height())
            ),
        )

    async def remove_peer(self, peer, reason) -> None:
        if self.fsm.state != State.FINISHED:
            await self._run_effects(self.fsm.handle(Event.PEER_REMOVE, peer_id=peer.id))

    async def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        try:
            msg = decode_bc_message(msg_bytes)
        except Exception as e:
            await self.report(
                peer, PeerBehaviour.bad_message(peer.id, f"blockchain: {e!r}")
            )
            return
        if isinstance(msg, BlockRequestMessage):
            block = self.block_store.load_block(msg.height)
            if block is not None:
                await peer.send(
                    BLOCKCHAIN_CHANNEL, encode_bc_message(BlockResponseMessage(block))
                )
            else:
                await peer.send(
                    BLOCKCHAIN_CHANNEL,
                    encode_bc_message(NoBlockResponseMessage(msg.height)),
                )
            return
        if isinstance(msg, StatusRequestMessage):
            await peer.send(
                BLOCKCHAIN_CHANNEL,
                encode_bc_message(
                    StatusResponseMessage(self.block_store.base(), self.block_store.height())
                ),
            )
            return
        if self.fsm.state == State.FINISHED:
            return
        if isinstance(msg, StatusResponseMessage):
            await self._run_effects(
                self.fsm.handle(
                    Event.STATUS_RESPONSE, peer_id=peer.id, base=msg.base, height=msg.height
                )
            )
        elif isinstance(msg, BlockResponseMessage):
            if self.block_store.height() >= msg.block.header.height:
                # already stored (late or duplicate response): the FSM
                # drops it, but the block's bytes were spent on the wire
                self.note_redundant(peer, "block")
            await self._run_effects(
                self.fsm.handle(Event.BLOCK_RESPONSE, peer_id=peer.id, block=msg.block)
            )
        elif isinstance(msg, NoBlockResponseMessage):
            await self._run_effects(
                self.fsm.handle(Event.NO_BLOCK_RESPONSE, peer_id=peer.id, height=msg.height)
            )

    # -- effects ------------------------------------------------------

    async def _run_effects(self, effects: list) -> None:
        for eff in effects:
            kind = eff[0]
            if kind == "request":
                _, height, peer_id = eff
                peer = self.switch.peers.get(peer_id) if self.switch else None
                if peer is not None:
                    await peer.send(
                        BLOCKCHAIN_CHANNEL, encode_bc_message(BlockRequestMessage(height))
                    )
            elif kind == "bad_block":
                # verification failure: the heaviest trust penalty — a
                # repeat offender gets banned, not just dropped
                _, peer_id, reason = eff
                peer = self.switch.peers.get(peer_id) if self.switch else None
                await self.report(
                    peer, PeerBehaviour.bad_block(peer_id, str(reason)[:120])
                )
            elif kind == "error":
                _, peer_id, reason = eff
                peer = self.switch.peers.get(peer_id) if self.switch else None
                if peer is not None:
                    await self.switch.stop_peer_for_error(peer, reason)
            elif kind == "switch_to_consensus":
                self.log.info(
                    "fast sync v1 complete", height=self.fsm.height,
                    blocks=self.fsm.blocks_synced,
                )
                cons = self.switch.reactor("CONSENSUS") if self.switch else None
                if cons is not None:
                    await cons.switch_to_consensus(self.state, self.fsm.blocks_synced)

    # -- routines -----------------------------------------------------

    async def _process_routine(self) -> None:
        """Verify+apply received block pairs (shared verify path with v0 —
        one batched device verify per commit)."""
        while self.fsm.state != State.FINISHED:
            first, second = self.fsm.first_two_blocks()
            if first is None or second is None:
                await asyncio.sleep(PROCESS_INTERVAL)
                continue
            block = first.block
            first_parts = block.make_part_set()
            first_id = BlockID(block.hash(), first_parts.header())
            err = None
            try:
                # FASTSYNC class: queued behind any concurrent commit
                # verify at the device scheduler, never ahead of it
                with priority_scope(Priority.FASTSYNC):
                    self.state.validators.verify_commit(
                        self.state.chain_id, first_id, block.header.height,
                        second.block.last_commit,
                    )
            except VerifyError as e:
                err = e
                self.log.error("v1 block verify failed", height=block.header.height, err=str(e))
            if err is None:
                self.block_store.save_block(block, first_parts, second.block.last_commit)
                self.state = await self.block_exec.apply_block(self.state, first_id, block)
            await self._run_effects(
                self.fsm.handle(Event.PROCESSED_BLOCK, err=err)
            )

    async def _tick_routine(self) -> None:
        last_status = 0.0
        while self.fsm.state != State.FINISHED:
            await asyncio.sleep(TICK_INTERVAL)
            now = asyncio.get_event_loop().time()
            if now - last_status > STATUS_INTERVAL:
                last_status = now
                if self.switch is not None:
                    await self.switch.broadcast(
                        BLOCKCHAIN_CHANNEL, encode_bc_message(StatusRequestMessage())
                    )
            await self._run_effects(self.fsm.handle(Event.MAKE_REQUESTS))
