"""Fast-sync reactor — BlockchainChannel 0x40.

Reference parity: blockchain/v0/reactor.go:57 — serves BlockRequests from
the store, runs poolRoutine: pull ordered block pairs from the pool, verify
`second.LastCommit` against `first`'s validator set (one TPU batch —
reference's serial hot loop #3, reactor.go:313), ApplyBlock, and
SwitchToConsensus when caught up.
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass

from tendermint_tpu.behaviour import PeerBehaviour
from tendermint_tpu.blockchain import BlockPool
from tendermint_tpu.device.priorities import Priority, priority_scope
from tendermint_tpu.encoding import DecodeError, Reader, Writer
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.p2p.base_reactor import BaseReactor, ChannelDescriptor
from tendermint_tpu.types import BlockID
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.validator_set import verify_commits

BLOCKCHAIN_CHANNEL = 0x40

TRY_SYNC_INTERVAL = 0.01  # reference reactor.go trySyncTicker 10ms
STATUS_UPDATE_INTERVAL = 10.0
SWITCH_TO_CONSENSUS_INTERVAL = 1.0
# verify-ahead window: pending heights whose commits are fused into one
# device batch (per-launch dispatch cost amortizes over the window).
# WINDOW is the floor; the live window grows with the device flush target
# (see _verify_ahead_window) so cross-height packs fill mesh lanes, capped
# to bound the 10ms sync tick's peek cost and the pool's readahead memory.
VERIFY_AHEAD_WINDOW = 16
VERIFY_AHEAD_WINDOW_MAX = 128


@dataclass
class BlockRequestMessage:
    height: int


@dataclass
class BlockResponseMessage:
    block: Block


@dataclass
class NoBlockResponseMessage:
    height: int


@dataclass
class StatusRequestMessage:
    pass


@dataclass
class StatusResponseMessage:
    base: int
    height: int


# tag byte -> traffic-accounting label (wire-efficiency observatory);
# shared by the v0 and v1 reactors, which speak the same codec
BC_TYPE_LABELS: dict[int, str] = {
    1: "block_request",
    2: "block_response",
    3: "no_block_response",
    4: "status_request",
    5: "status_response",
}


def encode_bc_message(msg) -> bytes:
    w = Writer()
    if isinstance(msg, BlockRequestMessage):
        w.u8(1).u64(msg.height)
    elif isinstance(msg, BlockResponseMessage):
        w.u8(2).bytes(msg.block.encode())
    elif isinstance(msg, NoBlockResponseMessage):
        w.u8(3).u64(msg.height)
    elif isinstance(msg, StatusRequestMessage):
        w.u8(4)
    elif isinstance(msg, StatusResponseMessage):
        w.u8(5).u64(msg.base).u64(msg.height)
    else:
        raise TypeError(f"unknown blockchain message {type(msg).__name__}")
    return w.build()


def decode_bc_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == 1:
        msg = BlockRequestMessage(r.u64())
    elif tag == 2:
        msg = BlockResponseMessage(Block.decode(r.bytes()))
    elif tag == 3:
        msg = NoBlockResponseMessage(r.u64())
    elif tag == 4:
        msg = StatusRequestMessage()
    elif tag == 5:
        msg = StatusResponseMessage(r.u64(), r.u64())
    else:
        raise DecodeError(f"unknown blockchain message tag {tag}")
    r.expect_done()
    return msg


class BlockchainReactor(BaseReactor):
    traffic_family = "blockchain"

    def __init__(
        self,
        state,  # state.State snapshot at boot
        block_exec,
        block_store,
        fast_sync: bool,
        logger: Logger = NOP,
    ) -> None:
        super().__init__("BlockchainReactor")
        self.initial_state = state
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.fast_sync = fast_sync
        self.log = logger
        self.pool = BlockPool(
            start_height=block_store.height() + 1,
            send_request=self._send_block_request,
            on_peer_error=self._on_pool_peer_error,
            logger=logger,
        )
        self.blocks_synced = 0
        # verify-ahead caches, keyed (height, block_hash, successor_hash,
        # valset_hash). The verdict is computed from the SUCCESSOR's
        # last_commit, so the successor's identity is part of the key: if
        # block h+1 is replaced in the pool (peer timeout/redo), verdicts
        # computed against the old h+1 must not survive — a stale cached
        # failure would disconnect now-honest senders at the head.
        # Pass/fail is only meaningful under the valset it was checked with;
        # a failed ahead-check is NOT evidence of a bad peer (an intervening
        # block may rotate the validator set), so failures are cached to
        # avoid re-verifying every loop but punished only at the head where
        # the current valset is authoritative. Failures keep str(err) — not
        # the exception, whose __traceback__ would pin the whole
        # verify_commits frame graph across sync ticks — so the
        # head-failure log can name the cause.
        self._verified_ahead: set[tuple[int, bytes, bytes, bytes]] = set()
        self._failed_ahead: dict[tuple[int, bytes, bytes, bytes], str] = {}
        # ValidatorSet.hash() merkle-hashes every validator; memoize per
        # valset object so the 10ms sync tick doesn't recompute it
        self._vs_hash_src: object | None = None
        self._vs_hash = b""

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                BLOCKCHAIN_CHANNEL,
                priority=10,
                send_queue_capacity=1000,
                recv_message_capacity=1 << 22,
            )
        ]

    def classify(self, ch_id: int, msg: bytes) -> str:
        return BC_TYPE_LABELS.get(msg[0], "other") if msg else "other"

    async def on_start(self) -> None:
        if self.fast_sync:
            await self.pool.start()
            self.spawn(self._pool_routine(), "bc-pool-routine")

    async def on_stop(self) -> None:
        if self.pool.is_running:
            await self.pool.stop()

    async def start_fast_sync(self, state) -> None:
        """State-sync handoff (docs/state_sync.md): the store was just
        bootstrapped at a snapshot height — begin fast sync there for the
        residual heights. The node constructed this reactor with
        fast_sync=False so the pool never started at genesis; re-anchor
        it on the bootstrapped store and run the normal pool routine
        (which hands to consensus when caught up)."""
        if self.fast_sync and self.pool.is_running:
            return  # already syncing (double handoff is a no-op)
        self.initial_state = self.state = state
        self.fast_sync = True
        self.pool.height = self.block_store.height() + 1
        self._verified_ahead.clear()
        self._failed_ahead.clear()
        await self.pool.start()
        self.spawn(self._pool_routine(), "bc-pool-routine")
        if self.switch is not None:
            # learn peer ranges NOW instead of waiting out the 10s tick:
            # peers advertise (base, height) and the pool starts fetching
            await self.switch.broadcast(
                BLOCKCHAIN_CHANNEL, encode_bc_message(StatusRequestMessage())
            )

    # -- p2p plumbing -------------------------------------------------

    async def _send_block_request(self, height: int, peer_id: str) -> None:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is None:
            return
        await peer.send(BLOCKCHAIN_CHANNEL, encode_bc_message(BlockRequestMessage(height)))

    async def _on_pool_peer_error(self, peer_id: str, reason) -> None:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is not None:
            await self.switch.stop_peer_for_error(peer, reason)

    async def add_peer(self, peer) -> None:
        # advertise our status; the peer replies with its own so the pool
        # learns its height (reference reactor.go AddPeer)
        await peer.send(
            BLOCKCHAIN_CHANNEL,
            encode_bc_message(
                StatusResponseMessage(self.block_store.base(), self.block_store.height())
            ),
        )

    async def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    async def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        try:
            msg = decode_bc_message(msg_bytes)
        except Exception as e:
            self.log.error("bad blockchain message", peer=peer.id, err=repr(e))
            await self.report(
                peer, PeerBehaviour.bad_message(peer.id, f"blockchain: {e!r}")
            )
            return

        if isinstance(msg, BlockRequestMessage):
            block = self.block_store.load_block(msg.height)
            if block is not None:
                await peer.send(
                    BLOCKCHAIN_CHANNEL, encode_bc_message(BlockResponseMessage(block))
                )
            else:
                await peer.send(
                    BLOCKCHAIN_CHANNEL,
                    encode_bc_message(NoBlockResponseMessage(msg.height)),
                )
        elif isinstance(msg, BlockResponseMessage):
            req = self.pool.requesters.get(msg.block.header.height)
            if req is None or req.block is not None or req.peer_id != peer.id:
                # unsolicited, already-filled, or wrong-peer response: the
                # pool will drop it, but the block's bytes were spent
                self.note_redundant(peer, "block")
            self.pool.add_block(peer.id, msg.block, len(msg_bytes))
        elif isinstance(msg, StatusRequestMessage):
            await peer.send(
                BLOCKCHAIN_CHANNEL,
                encode_bc_message(
                    StatusResponseMessage(self.block_store.base(), self.block_store.height())
                ),
            )
        elif isinstance(msg, StatusResponseMessage):
            self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, NoBlockResponseMessage):
            self.log.debug("peer has no block", peer=peer.id, height=msg.height)

    # -- sync loop ----------------------------------------------------

    async def _pool_routine(self) -> None:
        """Reference reactor.go:211 poolRoutine."""
        last_status = 0.0
        last_switch_check = 0.0
        loop = asyncio.get_event_loop()
        while True:
            now = loop.time()
            if now - last_status > STATUS_UPDATE_INTERVAL:
                last_status = now
                if self.switch is not None:
                    await self.switch.broadcast(
                        BLOCKCHAIN_CHANNEL, encode_bc_message(StatusRequestMessage())
                    )
            if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                last_switch_check = now
                if self.pool.is_caught_up():
                    self.log.info(
                        "fast sync complete", height=self.pool.height,
                        blocks=self.blocks_synced, rate=f"{self.pool.sync_rate():.1f}/s",
                    )
                    await self.pool.stop()
                    cons = self.switch.reactor("CONSENSUS") if self.switch else None
                    if cons is not None:
                        await cons.switch_to_consensus(self.state, self.blocks_synced)
                    return
            if not await self._try_sync_one():
                await asyncio.sleep(TRY_SYNC_INTERVAL)

    def _verify_ahead_window(self) -> int:
        """Heights per verify-ahead flush, sized so one flush carries
        about one synchronous device flush target
        (`crypto.batch.accumulation_hint` — the batch size at which a
        dispatch amortizes its launch, and the mesh plan shards the
        bucket across chips) worth of commit signatures instead of
        whatever happened to arrive. A 64-validator chain on an 8-device
        mesh flushes ~33 heights as ONE mesh-sharded pack; a
        2048-validator chain already fills lanes at the old fixed window.
        NOT stream_flush_hint: that is the routing threshold (8 on a
        local chip), which would keep the window at the floor on exactly
        the hosts that have lanes to fill. Hosts that will never dispatch
        to a device (no accelerator: the serial path gains nothing from a
        bigger window, it only adds event-loop latency and readahead
        memory) keep the old fixed window, as does any process that has
        not loaded ops. Cap bounds peek cost and readahead memory."""
        import os
        import sys

        ops = sys.modules.get("tendermint_tpu.ops")
        if ops is None:
            return VERIFY_AHEAD_WINDOW
        if (
            getattr(ops, "_min_batch_probed", None) is None
            and "TMTPU_MIN_DEVICE_BATCH" not in os.environ
        ):
            # the routing threshold has not been probed yet and reading
            # it would probe NOW — a blocking jit compile + timed device
            # round trips (or a hang on a dead tunnel) on the event
            # loop's 10ms sync tick. The first real verify probes it
            # from the scheduler; until then keep the fixed window.
            return VERIFY_AHEAD_WINDOW
        try:
            if int(ops.effective_min_batch()) >= (1 << 30):
                return VERIFY_AHEAD_WINDOW  # never-device host
        except Exception:  # noqa: BLE001 — a failing probe must not break sync
            return VERIFY_AHEAD_WINDOW
        from tendermint_tpu.crypto.batch import accumulation_hint

        per_commit = max(1, len(self.state.validators))
        # +1: the pair (h, h+1) verifies h from h+1's LastCommit, so a
        # window of W blocks yields W-1 fused commits
        want = -(-accumulation_hint() // per_commit) + 1
        return max(VERIFY_AHEAD_WINDOW, min(VERIFY_AHEAD_WINDOW_MAX, want))

    def _verify_ahead(self, blocks: "list[Block]", vs_hash: bytes) -> None:
        """Fuse the unverified (block, next.last_commit) pairs of the window
        into ONE device batch (hot loop #3 across heights — the reference
        verifies serially per height, reactor.go:313)."""
        entries, keys = [], []
        for blk, nxt in zip(blocks, blocks[1:]):
            key = (blk.header.height, blk.hash(), nxt.hash(), vs_hash)
            if key in self._verified_ahead or key in self._failed_ahead:
                continue
            parts = blk.make_part_set()
            entries.append(
                (
                    self.state.validators,
                    self.state.chain_id,
                    BlockID(blk.hash(), parts.header()),
                    blk.header.height,
                    nxt.last_commit,
                )
            )
            keys.append(key)
        if not entries:
            return
        # catch-up work: the device scheduler must never let this window
        # delay a commit verify on a co-resident validator's hot path
        with priority_scope(Priority.FASTSYNC):
            results = verify_commits(entries)
        for key, err in zip(keys, results):
            if err is None:
                self._verified_ahead.add(key)
            else:
                self._failed_ahead[key] = str(err)
        if len(entries) > 1:
            self.log.debug(
                "verify-ahead batch", heights=len(entries),
                from_height=keys[0][0],
            )

    async def _try_sync_one(self) -> bool:
        """Verify+apply the first block using the second's LastCommit
        (reference reactor.go:271-330). Returns True if a block was applied."""
        blocks = self.pool.peek_window(self._verify_ahead_window())
        if len(blocks) < 2:
            return False
        first, second = blocks[0], blocks[1]
        if self._vs_hash_src is not self.state.validators:
            self._vs_hash_src = self.state.validators
            self._vs_hash = self.state.validators.hash()
        vs_hash = self._vs_hash
        self._verify_ahead(blocks, vs_hash)
        first_parts = first.make_part_set()
        first_id = BlockID(first.hash(), first_parts.header())
        head_key = (first.header.height, first.hash(), second.hash(), vs_hash)
        if head_key not in self._verified_ahead:
            # at the head the current valset IS authoritative: a failure
            # here means a bad block/commit, not a stale-valset artifact
            self.log.error(
                "fast-sync block verify failed", height=first.header.height,
                err=self._failed_ahead.get(head_key, ""),
            )
            # disconnect both senders (reference reactor.go poolRoutine
            # StopPeerForError) — pool removal alone lets a Byzantine peer
            # rejoin on the next status broadcast and stall sync forever.
            # Routed as the heaviest behaviour: repeat offenders get banned
            # and cannot rejoin at all.
            for bad in (
                self.pool.redo_request(first.header.height),
                self.pool.redo_request(first.header.height + 1),
            ):
                if bad is not None and self.switch is not None:
                    await self.report(
                        self.switch.peers.get(bad),
                        PeerBehaviour.bad_block(
                            bad, f"invalid block at height {first.header.height}"
                        ),
                    )
            self._failed_ahead.pop(head_key, None)  # re-verify the redo
            return False
        self.pool.pop_request()
        self.block_store.save_block(first, first_parts, second.last_commit)
        self.state = await self.block_exec.apply_block(self.state, first_id, first)
        self.blocks_synced += 1
        # applying a block can rotate the valset for subsequent heights;
        # cached verdicts under the old valset hash are then unreachable —
        # prune everything below the new sync head (and stale hashes decay
        # naturally because lookups are keyed by the current valset hash)
        if self._verified_ahead or self._failed_ahead:
            floor = self.pool.height
            self._verified_ahead = {
                k for k in self._verified_ahead if k[0] >= floor
            }
            self._failed_ahead = {
                k: e for k, e in self._failed_ahead.items() if k[0] >= floor
            }
        if self.blocks_synced % 100 == 0:
            self.log.info(
                "fast sync progress", height=self.pool.height,
                rate=f"{self.pool.sync_rate():.1f} blocks/s",
            )
        return True
