"""Fast sync — parallel block download, serial verify+apply.

Reference parity: blockchain/v0/pool.go:63 — BlockPool schedules up to
MAX_PENDING_REQUESTS concurrent per-height requesters against peers
advertising sufficient height, monitors per-peer receive rate and evicts
peers that stall (:133), and hands blocks to the reactor strictly in height
order (PeekTwoBlocks/PopRequest, :193).

The verify step is the TPU win: each block's LastCommit is verified as ONE
device batch (types/validator_set.py verify_commit) instead of the
reference's serial loop (types/validator_set.go:609-627), so sync
throughput is bounded by download + ABCI replay, not signature checking.
"""
from __future__ import annotations

import asyncio
import time

from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.types.block import Block

MAX_PENDING_REQUESTS = 600
REQUEST_TIMEOUT = 15.0  # per-block; reference pool.go requestRetrySeconds
MIN_RECV_RATE = 7680  # B/s, reference pool.go:26
PEER_TIMEOUT_CHECK = 1.0


class PoolPeer:
    def __init__(self, peer_id: str, base: int, height: int) -> None:
        self.id = peer_id
        self.base = base
        self.height = height
        self.num_pending = 0
        self._recv_bytes = 0
        self._recv_since = time.monotonic()
        self.did_timeout = False

    def record_recv(self, size: int) -> None:
        self._recv_bytes += size
        self.num_pending = max(0, self.num_pending - 1)
        if self.num_pending == 0:
            self.reset_monitor()  # idle peers aren't judged on stale windows

    def recv_rate(self) -> float:
        dt = time.monotonic() - self._recv_since
        if dt <= 0:
            return float("inf")
        return self._recv_bytes / dt

    def window_age(self) -> float:
        return time.monotonic() - self._recv_since

    def reset_monitor(self) -> None:
        self._recv_bytes = 0
        self._recv_since = time.monotonic()


class Requester:
    """One outstanding block request (reference bpRequester)."""

    def __init__(self, height: int) -> None:
        self.height = height
        self.peer_id: str | None = None
        self.block: Block | None = None
        self.got_block = asyncio.Event()
        self.started_at = time.monotonic()

    def set_block(self, block: Block, peer_id: str) -> bool:
        if self.peer_id != peer_id or self.block is not None:
            return False
        self.block = block
        self.got_block.set()
        return True

    def redo(self) -> None:
        self.peer_id = None
        self.block = None
        self.got_block.clear()
        self.started_at = time.monotonic()


class BlockPool(BaseService):
    """Reference blockchain/v0/pool.go:63."""

    def __init__(
        self,
        start_height: int,
        send_request,  # async (height, peer_id) -> None
        on_peer_error=None,  # async (peer_id, reason) -> None
        logger: Logger = NOP,
    ) -> None:
        super().__init__("BlockPool")
        self.height = start_height  # next height to sync
        self.send_request = send_request
        self.on_peer_error = on_peer_error
        self.log = logger
        self.peers: dict[str, PoolPeer] = {}
        self.requesters: dict[int, Requester] = {}
        self.max_peer_height = 0
        self._started_at = time.monotonic()
        self._num_synced = 0
        self._wake = asyncio.Event()

    async def on_start(self) -> None:
        self.spawn(self._make_requesters_routine(), "pool-requesters")
        self.spawn(self._timeout_routine(), "pool-timeouts")

    # -- peers --------------------------------------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        """Peer advertised its height (StatusResponse)."""
        p = self.peers.get(peer_id)
        if p is None:
            p = PoolPeer(peer_id, base, height)
            self.peers[peer_id] = p
        else:
            p.base, p.height = base, height
        self.max_peer_height = max(self.max_peer_height, height)
        self._wake.set()

    def remove_peer(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)
        # recompute: a stale tall peer would otherwise pin max_peer_height
        # and keep is_caught_up() false forever
        self.max_peer_height = max((p.height for p in self.peers.values()), default=0)
        for req in self.requesters.values():
            if req.peer_id == peer_id and req.block is None:
                req.redo()
        self._wake.set()

    def _pick_peer(self, height: int) -> PoolPeer | None:
        candidates = [
            p
            for p in self.peers.values()
            if p.base <= height <= p.height and not p.did_timeout
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda p: p.num_pending)

    # -- requesters ---------------------------------------------------

    async def _make_requesters_routine(self) -> None:
        """Reference pool.go:108 makeRequestersRoutine."""
        while True:
            next_height = self.height + len(self.requesters)
            if (
                len(self.requesters) >= MAX_PENDING_REQUESTS
                or next_height > self.max_peer_height
            ):
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), 0.25)
                except asyncio.TimeoutError:
                    pass
                continue
            req = Requester(next_height)
            self.requesters[next_height] = req
            await self._assign(req)

    async def _assign(self, req: Requester) -> None:
        peer = self._pick_peer(req.height)
        if peer is None:
            return
        req.peer_id = peer.id
        req.started_at = time.monotonic()
        if peer.num_pending == 0:
            peer.reset_monitor()  # start the stall window at assignment
        peer.num_pending += 1
        await self.send_request(req.height, peer.id)

    async def _timeout_routine(self) -> None:
        """Reference pool.go:133 removeTimedoutPeers + retry unassigned."""
        while True:
            await asyncio.sleep(PEER_TIMEOUT_CHECK)
            now = time.monotonic()
            for peer in list(self.peers.values()):
                # windowed stall check: the window resets whenever the peer
                # drains its pending requests, so only a peer that has been
                # continuously slow *while owing us blocks* for a full
                # timeout period is evicted (reference uses a flowrate
                # monitor's current rate, not a lifetime average)
                if (
                    peer.num_pending > 0
                    and peer.window_age() > REQUEST_TIMEOUT
                    and peer.recv_rate() < MIN_RECV_RATE
                ):
                    peer.did_timeout = True
                    self.log.info("fast-sync peer timed out", peer=peer.id)
                    if self.on_peer_error:
                        await self.on_peer_error(peer.id, "fast-sync timeout")
                    self.remove_peer(peer.id)
            for req in list(self.requesters.values()):
                if req.block is None:
                    if req.peer_id is None:
                        await self._assign(req)
                    elif now - req.started_at > REQUEST_TIMEOUT:
                        req.redo()
                        await self._assign(req)

    # -- block intake -------------------------------------------------

    def add_block(self, peer_id: str, block: Block, size: int) -> None:
        """Reference pool.go:244 AddBlock."""
        req = self.requesters.get(block.header.height)
        if req is None:
            return
        peer = self.peers.get(peer_id)
        if peer is not None:
            peer.record_recv(size)
        req.set_block(block, peer_id)

    def peek_two_blocks(self) -> tuple[Block | None, Block | None]:
        """Reference pool.go:193 — blocks at pool.height and height+1."""
        first = self.requesters.get(self.height)
        second = self.requesters.get(self.height + 1)
        return (
            first.block if first else None,
            second.block if second else None,
        )

    # NOTE: peek_two_blocks is kept for reference-API parity (PeekTwoBlocks)
    # even though the v0 reactor now drives peek_window.

    def peek_window(self, max_blocks: int) -> "list[Block]":
        """Contiguous downloaded blocks from pool.height up (verify-ahead
        window: the reactor batches the commits of every pending pair into
        one device launch instead of one launch per height)."""
        out = []
        for h in range(self.height, self.height + max_blocks):
            req = self.requesters.get(h)
            if req is None or req.block is None:
                break
            out.append(req.block)
        return out

    def pop_request(self) -> None:
        """First block verified+applied: advance (reference PopRequest)."""
        self.requesters.pop(self.height, None)
        self.height += 1
        self._num_synced += 1
        self._wake.set()

    def redo_request(self, height: int) -> str | None:
        """First block failed verification: ban the peers that sent the pair
        (reference pool.go RedoRequest)."""
        req = self.requesters.get(height)
        if req is None:
            return None
        bad = req.peer_id
        if bad is not None:
            self.remove_peer(bad)
        req.redo()
        return bad

    # -- status -------------------------------------------------------

    def is_caught_up(self) -> bool:
        """Reference pool.go:168 IsCaughtUp."""
        if not self.peers:
            return False
        return self.height >= self.max_peer_height

    def sync_rate(self) -> float:
        dt = time.monotonic() - self._started_at
        return self._num_synced / dt if dt > 0 else 0.0
