"""behaviour — peer-behaviour reporting (ADR-039).

Reference parity: behaviour/peer_behaviour.go + reporter.go — reactors
report good/bad peer behaviours through an interface instead of calling
Switch.StopPeerForError directly, decoupling protocol logic from peer
management. The SwitchReporter forwards errors to the switch; the
MockReporter records for tests.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class PeerBehaviour:
    peer_id: str
    reason: str
    is_error: bool

    # constructors matching the reference's behaviour vocabulary
    @classmethod
    def bad_message(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        return cls(peer_id, f"bad message: {explanation}", True)

    @classmethod
    def message_out_of_order(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        return cls(peer_id, f"message out of order: {explanation}", True)

    @classmethod
    def consensus_vote(cls, peer_id: str, explanation: str = "") -> "PeerBehaviour":
        return cls(peer_id, f"consensus vote: {explanation}", False)

    @classmethod
    def block_part(cls, peer_id: str, explanation: str = "") -> "PeerBehaviour":
        return cls(peer_id, f"block part: {explanation}", False)


class Reporter:
    async def report(self, behaviour: PeerBehaviour) -> None:
        raise NotImplementedError


class SwitchReporter(Reporter):
    """Forward error behaviours to the switch (reference reporter.go:17)."""

    def __init__(self, switch) -> None:
        self.switch = switch

    async def report(self, behaviour: PeerBehaviour) -> None:
        peer = self.switch.peers.get(behaviour.peer_id)
        if peer is None:
            return
        if behaviour.is_error:
            await self.switch.stop_peer_for_error(peer, behaviour.reason)


class MockReporter(Reporter):
    """Record behaviours for assertions (reference reporter.go MockReporter)."""

    def __init__(self) -> None:
        self.reports: dict[str, list[PeerBehaviour]] = {}

    async def report(self, behaviour: PeerBehaviour) -> None:
        self.reports.setdefault(behaviour.peer_id, []).append(behaviour)

    def get_behaviours(self, peer_id: str) -> list[PeerBehaviour]:
        return list(self.reports.get(peer_id, []))
