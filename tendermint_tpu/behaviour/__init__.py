"""behaviour — peer-behaviour reporting (ADR-039).

Reference parity: behaviour/peer_behaviour.go + reporter.go — reactors
report good/bad peer behaviours through an interface instead of calling
Switch.StopPeerForError directly, decoupling protocol logic from peer
management. The SwitchReporter forwards behaviours to the switch; the
MockReporter records for tests.

Beyond the reference: every behaviour carries a trust weight, and the
switch feeds each report into the peer's `p2p/trust.py` metric — the
score the ban/accept/dial decisions consult (docs/p2p_resilience.md).
Three independent axes per behaviour:

- `is_error`   — protocol violation worth disconnecting for NOW
                 (the reference's SwitchReporter semantics);
- `is_bad`     — counts AGAINST the trust score (every error is bad,
                 but e.g. unverifiable evidence is bad-not-error:
                 plausibly height skew, not malice — reject the message,
                 keep the peer, remember the smell);
- `weight`     — how much this one event moves the metric (a fabricated
                 block weighs more than a spammy invalid tx).
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class PeerBehaviour:
    peer_id: str
    reason: str
    is_error: bool
    # trust-metric input: weight of the event, and whether it counts as
    # bad. `bad=None` means "bad iff is_error" (the common case).
    weight: float = 1.0
    bad: bool | None = None

    @property
    def is_bad(self) -> bool:
        return self.is_error if self.bad is None else self.bad

    # -- bad behaviours (reference vocabulary + our misbehaviour sources) --

    @classmethod
    def bad_message(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        """Undecodable/invalid frame on any reactor channel."""
        return cls(peer_id, f"bad message: {explanation}", True, weight=3.0)

    @classmethod
    def message_out_of_order(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        return cls(peer_id, f"message out of order: {explanation}", True, weight=1.0)

    @classmethod
    def bad_block(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        """Fast-sync block whose commit failed verification at the head —
        the most expensive lie a peer can tell."""
        return cls(peer_id, f"bad block: {explanation}", True, weight=5.0)

    @classmethod
    def unverifiable_evidence(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        """Evidence we could not verify. Not necessarily Byzantine (height
        skew makes honest evidence unverifiable here), so: no disconnect,
        small trust penalty — a peer that ONLY ever sends these decays."""
        return cls(peer_id, f"unverifiable evidence: {explanation}", False,
                   weight=0.5, bad=True)

    @classmethod
    def tx_flood(cls, peer_id: str, explanation: str = "") -> "PeerBehaviour":
        """Gossiped tx dropped by the per-peer flowrate limiter BEFORE
        CheckTx (docs/tx_ingestion.md). Non-error and lighter than even
        bad_tx: an honest peer relaying a legitimate burst is exactly who
        hits this, so the weight exists only to make a peer whose traffic
        is *persistently* over-limit visible in the trust metric — it can
        never dominate a ban decision on its own."""
        return cls(peer_id, f"tx flood: {explanation}", False, weight=0.05, bad=True)

    @classmethod
    def bad_tx(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        """Gossiped tx rejected by CheckTx: spam pressure, not a protocol
        violation (reference keeps the peer too). Deliberately lighter
        than good_tx: an honest peer relaying txs that a block commit
        races into invalidity must never trend toward a ban — only a
        peer whose traffic is overwhelmingly rejects decays."""
        return cls(peer_id, f"bad tx: {explanation}", False, weight=0.1, bad=True)

    @classmethod
    def bad_chunk(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        """State-sync snapshot chunk that failed its hash or merkle-proof
        check. Chunks are content-addressed (the snapshot manifest pins
        every chunk's sha256), so a mismatch is a fabrication, not drift —
        weighted like a bad block."""
        return cls(peer_id, f"bad chunk: {explanation}", True, weight=5.0)

    @classmethod
    def chunk_timeout(cls, peer_id: str, explanation: str) -> "PeerBehaviour":
        """Chunk request that timed out. Plausibly load or loss, not
        malice: no disconnect, small penalty — a peer that only ever
        stalls restores decays out of the fetch rotation."""
        return cls(peer_id, f"chunk timeout: {explanation}", False,
                   weight=0.5, bad=True)

    # -- good behaviours ---------------------------------------------------

    @classmethod
    def consensus_vote(cls, peer_id: str, explanation: str = "") -> "PeerBehaviour":
        return cls(peer_id, f"consensus vote: {explanation}", False)

    @classmethod
    def block_part(cls, peer_id: str, explanation: str = "") -> "PeerBehaviour":
        return cls(peer_id, f"block part: {explanation}", False)

    @classmethod
    def good_tx(cls, peer_id: str, explanation: str = "") -> "PeerBehaviour":
        return cls(peer_id, f"good tx: {explanation}", False, weight=0.2)


class Reporter:
    async def report(self, behaviour: PeerBehaviour) -> None:
        raise NotImplementedError


class SwitchReporter(Reporter):
    """Forward behaviours to the switch's trust/ban plane (reference
    reporter.go:17, grown from stop-only to score-and-ban)."""

    def __init__(self, switch) -> None:
        self.switch = switch

    async def report(self, behaviour: PeerBehaviour) -> None:
        await self.switch.report_behaviour(behaviour)


class MockReporter(Reporter):
    """Record behaviours for assertions (reference reporter.go MockReporter)."""

    def __init__(self) -> None:
        self.reports: dict[str, list[PeerBehaviour]] = {}

    async def report(self, behaviour: PeerBehaviour) -> None:
        self.reports.setdefault(behaviour.peer_id, []).append(behaviour)

    def get_behaviours(self, peer_id: str) -> list[PeerBehaviour]:
        return list(self.reports.get(peer_id, []))
