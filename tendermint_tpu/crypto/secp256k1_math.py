"""Pure-Python secp256k1 arithmetic — the readable oracle the TPU kernel is
validated against, plus the host-side helpers batch prep needs (pubkey
decompression, ECDSA scalar recovery).

Reference parity: the verification math of crypto/secp256k1 (the reference
delegates to btcec / vendored libsecp256k1; crypto/secp256k1/secp256k1_nocgo.go:21-50).
This mirrors the same equation chain: w = s^-1 mod n, u1 = z*w, u2 = r*w,
R' = u1*G + u2*Q, valid iff R'.x mod n == r. Not constant-time — it only
ever processes public data (signature verification).
"""
from __future__ import annotations

import hashlib

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
HALF_N = N // 2
B = 7

GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

# projective (X, Y, Z); identity = (0, 1, 0)
IDENTITY = (0, 1, 0)
G = (GX, GY, 1)


def point_add(p1, p2):
    """Complete projective addition (Renes-Costello-Batina 2016, Alg 7 for
    a=0, b3=3*7=21) — total: handles doubling and the identity."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    b3 = 3 * B
    t0 = x1 * x2 % P
    t1 = y1 * y2 % P
    t2 = z1 * z2 % P
    t3 = (x1 + y1) * (x2 + y2) % P
    t4 = t0 + t1
    t3 = (t3 - t4) % P
    t4 = (y1 + z1) * (y2 + z2) % P
    x3 = t1 + t2
    t4 = (t4 - x3) % P
    x3 = (x1 + z1) * (x2 + z2) % P
    y3 = t0 + t2
    y3 = (x3 - y3) % P
    x3 = (t0 + t0 + t0) % P
    t2 = b3 * t2 % P
    z3 = (t1 + t2) % P
    t1 = (t1 - t2) % P
    y3 = b3 * y3 % P
    x3_out = (t4 * y3 * -1 + t3 * t1) % P
    y3_out = (y3 * x3 + t1 * z3) % P
    z3_out = (z3 * t4 + x3 * t3) % P
    return (x3_out % P, y3_out % P, z3_out % P)


def point_double(p):
    return point_add(p, p)


def scalar_mult(k: int, p) -> tuple:
    acc = IDENTITY
    while k:
        if k & 1:
            acc = point_add(acc, p)
        p = point_add(p, p)
        k >>= 1
    return acc


def to_affine(p):
    x, y, z = p
    if z == 0:
        return None  # identity
    zi = pow(z, P - 2, P)
    return (x * zi % P, y * zi % P)


def decompress(pub: bytes):
    """33-byte compressed SEC1 point -> (x, y) affine, or None."""
    if len(pub) != 33 or pub[0] not in (2, 3):
        return None
    x = int.from_bytes(pub[1:], "big")
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + B) % P
    y = pow(y2, (P + 1) // 4, P)  # p % 4 == 3
    if y * y % P != y2:
        return None  # not on curve
    if (y & 1) != (pub[0] & 1):
        y = P - y
    return (x, y)


def msg_scalar(msg: bytes) -> int:
    """z = leftmost 256 bits of SHA-256(msg), as ECDSA prescribes."""
    return int.from_bytes(hashlib.sha256(msg).digest(), "big") % N


def _rfc6979_ks(priv: bytes, z: int):
    """RFC 6979 §3.2 deterministic nonce stream (HMAC-SHA256)."""
    import hmac
    import hashlib as _hl

    h1 = z.to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + priv + h1, _hl.sha256).digest()
    v = hmac.new(k, v, _hl.sha256).digest()
    k = hmac.new(k, v + b"\x01" + priv + h1, _hl.sha256).digest()
    v = hmac.new(k, v, _hl.sha256).digest()
    while True:
        v = hmac.new(k, v, _hl.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            yield cand
        k = hmac.new(k, v + b"\x00", _hl.sha256).digest()
        v = hmac.new(k, v, _hl.sha256).digest()


def pub_from_priv(priv: bytes) -> bytes:
    """32-byte privkey -> 33-byte compressed pubkey.

    Dev/bench tool (with `sign` below): NOT constant-time — it exists so
    signed workloads (the transfer app, ingest_bench) can be generated in
    environments without the `cryptography` package. Production keys stay
    on crypto/secp256k1.py's OpenSSL-backed stack."""
    x, y = to_affine(scalar_mult(int.from_bytes(priv, "big") % N, G))
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def sign(priv: bytes, msg: bytes) -> bytes:
    """Deterministic ECDSA (RFC 6979), compact r||s with the low-S rule —
    verifies bit-for-bit on `verify` above, the OpenSSL stack, the native
    batch, and the device kernel. Dev/bench tool (see pub_from_priv)."""
    d = int.from_bytes(priv, "big")
    if not 0 < d < N:
        raise ValueError("privkey scalar out of range")
    z = msg_scalar(msg)
    for k in _rfc6979_ks(priv, z):
        x, _y = to_affine(scalar_mult(k, G))
        r = x % N
        if r == 0:
            continue
        s = pow(k, N - 2, N) * ((z + r * d) % N) % N
        if s == 0:
            continue
        if s > HALF_N:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")
    raise AssertionError("unreachable: RFC 6979 stream exhausted")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Oracle ECDSA verify with the low-S rule — mirrors
    crypto/secp256k1.PubKeySecp256k1.verify bit-for-bit."""
    if len(sig) != 64:
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (0 < r < N and 0 < s <= HALF_N):
        return False
    q = decompress(pub)
    if q is None:
        return False
    w = pow(s, N - 2, N)
    z = msg_scalar(msg)
    u1 = z * w % N
    u2 = r * w % N
    rp = point_add(scalar_mult(u1, G), scalar_mult(u2, (q[0], q[1], 1)))
    aff = to_affine(rp)
    if aff is None:
        return False
    return aff[0] % N == r
