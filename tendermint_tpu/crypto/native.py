"""ctypes binding for the native (C++) batch verify core.

Reference parity: the cgo/nocgo dual build of crypto/secp256k1
(secp256k1_cgo.go / secp256k1_nocgo.go) — the native path is used when the
shared library is available (building it on first use if a toolchain is
present), and everything degrades gracefully to the pure-Python key objects
otherwise. Backend priority in crypto/batch.py: the TPU kernel (registered
by tendermint_tpu.ops) wins for ed25519; this module registers the
secp256k1 backend and serves as the ed25519 fallback for no-TPU builds.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libtmnative.so")

_lib = None
_load_error: str | None = None


def _build() -> bool:
    if not os.path.isdir(_NATIVE_DIR):
        return False
    try:
        env = dict(os.environ)
        # -march=native is safe here (we always build on the machine that
        # will run the .so); the Makefile default stays portable for
        # prebuilt/shared artifacts.
        env.setdefault(
            "CXXFLAGS", "-O3 -march=native -fPIC -std=c++17 -Wall -Wextra"
        )
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=300,
            env=env,
        )
        return os.path.exists(_SO_PATH)
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def load():
    """Load (building if necessary) the shared library; returns None if the
    native path is unavailable."""
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if _load_error is not None:
        return None
    so_exists = os.path.exists(_SO_PATH)
    if so_exists:
        # rebuild a STALE .so (any source newer than it): the library is
        # gitignored, so after a pull the existing binary may silently
        # predate the sources — running verification through old code
        srcs = [
            os.path.join(_NATIVE_DIR, f)
            for f in os.listdir(_NATIVE_DIR)
            if f.endswith((".cpp", ".h"))
        ]
        try:
            so_mtime = os.path.getmtime(_SO_PATH)
            if any(os.path.getmtime(s) > so_mtime for s in srcs):
                _build()  # failure keeps the old .so: degraded, not broken
        except OSError:
            pass
    elif not _build():
        _load_error = "no toolchain / build failed"
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError as e:
        _load_error = str(e)
        return None
    for name, pub_stride in (("tm_ed25519_verify_batch", 32), ("tm_secp256k1_verify_batch", 33)):
        fn = getattr(lib, name)
        fn.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),   # pubs
            ctypes.POINTER(ctypes.c_uint8),   # msgs
            ctypes.POINTER(ctypes.c_uint64),  # offsets
            ctypes.POINTER(ctypes.c_uint8),   # sigs
            ctypes.c_size_t,                  # n
            ctypes.POINTER(ctypes.c_uint8),   # out
        ]
        fn.restype = None
    try:
        mr = lib.tm_merkle_root
        mr.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),   # concatenated items
            ctypes.POINTER(ctypes.c_uint64),  # offsets (n+1)
            ctypes.c_size_t,                  # n
            ctypes.POINTER(ctypes.c_uint8),   # out (32)
        ]
        mr.restype = None
    except AttributeError:
        pass  # stale .so predating the merkle entry; Python path remains
    try:
        prep = lib.tm_ed25519_prepare_batch
        prep.argtypes = [ctypes.POINTER(ctypes.c_uint8)] * 2 + [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
            ctypes.c_size_t,
        ] + [ctypes.POINTER(ctypes.c_uint32)] * 6 + [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        prep.restype = None
    except AttributeError:
        pass  # stale .so predating the prep entry; Python prep path remains
    _lib = lib
    return lib


def _run_batch(fn, pub_stride: int, pubs, msgs, sigs) -> list[bool]:
    n = len(pubs)
    pub_buf = bytearray(n * pub_stride)
    sig_buf = bytearray(n * 64)
    offsets = (ctypes.c_uint64 * (n + 1))()
    bad = set()
    flat = bytearray()
    for i, (p, m, s) in enumerate(zip(pubs, msgs, sigs)):
        if len(p) != pub_stride or len(s) != 64:
            bad.add(i)
            p = b"\x00" * pub_stride
            s = b"\x00" * 64
        pub_buf[i * pub_stride:(i + 1) * pub_stride] = p
        sig_buf[i * 64:(i + 1) * 64] = s
        offsets[i] = len(flat)
        flat.extend(m)
    offsets[n] = len(flat)
    out = (ctypes.c_uint8 * n)()
    msgs_buf = bytes(flat) or b"\x00"
    fn(
        (ctypes.c_uint8 * len(pub_buf)).from_buffer(pub_buf),
        ctypes.cast(ctypes.create_string_buffer(msgs_buf, len(msgs_buf)), ctypes.POINTER(ctypes.c_uint8)),
        offsets,
        (ctypes.c_uint8 * len(sig_buf)).from_buffer(sig_buf),
        n,
        out,
    )
    return [bool(out[i]) and i not in bad for i in range(n)]


def ed25519_verify_batch(pubs, msgs, sigs) -> list[bool]:
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return _run_batch(lib.tm_ed25519_verify_batch, 32, pubs, msgs, sigs)


def secp256k1_verify_batch(pubs, msgs, sigs) -> list[bool]:
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return _run_batch(lib.tm_secp256k1_verify_batch, 33, pubs, msgs, sigs)


def merkle_root(items) -> bytes | None:
    """RFC-6962 tree root over byte slices via the C++ core; None when the
    native library (or a fresh-enough build) is unavailable — callers fall
    back to the Python tree (crypto/merkle.hash_from_byte_slices)."""
    lib = load()
    if lib is None or not hasattr(lib, "tm_merkle_root"):
        return None
    n = len(items)
    offsets = (ctypes.c_uint64 * (n + 1))()
    total = 0
    for i, it in enumerate(items):
        offsets[i] = total
        total += len(it)
    offsets[n] = total
    flat = b"".join(items) or b"\x00"
    out = (ctypes.c_uint8 * 32)()
    lib.tm_merkle_root(
        ctypes.cast(
            ctypes.create_string_buffer(flat, len(flat)),
            ctypes.POINTER(ctypes.c_uint8),
        ),
        offsets,
        n,
        out,
    )
    return bytes(out)


def ed25519_prepare_device_inputs(pubs, msgs, sigs, padded: int):
    """Native host-side batch prep for the TPU kernel (the round-1 Python
    loop in ops/ed25519_batch.prepare_batch was 22us/sig — VERDICT weak #2).

    Writes the kernel wire format directly: the six word-transposed
    (8, padded) int32 planes and the parity row are VIEWS into one
    contiguous (49, padded) packed array (ops/ed25519_batch.py row layout),
    so there is no numpy repack step and the device transfer is a single
    copy. Returns (packed (49, padded) int32, mask (n,) bool) or None when
    the native library is unavailable. Entries with wrong-length pub/sig
    come back mask=False.
    """
    lib = load()
    if lib is None or not hasattr(lib, "tm_ed25519_prepare_batch"):
        return None
    import numpy as np

    n = len(pubs)
    assert padded >= n
    bad = [
        i for i in range(n) if len(pubs[i]) != 32 or len(sigs[i]) != 64
    ]
    if bad:
        zp, zs = b"\x00" * 32, b"\x00" * 64
        badset = set(bad)
        pubs = [zp if i in badset else bytes(pubs[i]) for i in range(n)]
        sigs = [zs if i in badset else bytes(sigs[i]) for i in range(n)]
    pub_cat = b"".join(pubs)
    sig_cat = b"".join(sigs)
    msg_cat = b"".join(msgs)
    offsets = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum(
        np.fromiter((len(m) for m in msgs), dtype=np.uint64, count=n),
        out=offsets[1:],
    )
    from tendermint_tpu.ops.ed25519_batch import (
        ROW_AT, ROW_AX, ROW_AY, ROW_H, ROW_PARITY, ROW_S, ROW_YR, ROWS,
    )

    packed = np.zeros((ROWS, padded), dtype=np.int32)
    out_mask = np.zeros(n, dtype=np.uint8)

    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    def row_ptr(row):  # contiguous view into the packed array
        return packed[row:row + 8].ctypes.data_as(u32p)

    lib.tm_ed25519_prepare_batch(
        ctypes.cast(ctypes.c_char_p(pub_cat), u8p),
        ctypes.cast(ctypes.c_char_p(msg_cat or b"\x00"), u8p),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.cast(ctypes.c_char_p(sig_cat), u8p),
        n,
        padded,
        *[row_ptr(r) for r in (ROW_AX, ROW_AY, ROW_AT, ROW_S, ROW_H, ROW_YR)],
        packed[ROW_PARITY:ROW_PARITY + 1].ctypes.data_as(
            ctypes.POINTER(ctypes.c_int32)
        ),
        out_mask.ctypes.data_as(u8p),
    )
    mask = out_mask.astype(bool)
    if bad:
        mask[bad] = False
    return packed, mask


def register(force: bool = False) -> bool:
    """Register native backends with crypto.batch — for BOTH curves only
    when no richer backend claimed the slot first (unless force). The ops
    backends already route small batches through a probed native-vs-serial
    choice and large ones to the device; overriding them with the raw
    native call would pin every batch to the portable C++ core, which on a
    single-vCPU host is ~2x slower than the serial OpenSSL path."""
    if load() is None:
        return False
    from tendermint_tpu.crypto import batch

    for key_type, fn in (
        ("secp256k1", secp256k1_verify_batch),
        ("ed25519", ed25519_verify_batch),
    ):
        if force or batch.get_backend(key_type) is None:
            batch.register_backend(key_type, fn)
    return True
