"""ASCII armor — OpenPGP-style text encoding of binary blobs.

Reference parity: crypto/armor/armor.go (EncodeArmor/DecodeArmor over
golang.org/x/crypto/openpgp/armor): base64 body with CRC-24 checksum,
header key/value lines, BEGIN/END fencing. Used for exporting keys in a
copy-paste-safe form.
"""
from __future__ import annotations

import base64
import textwrap

CRC24_INIT = 0xB704CE
CRC24_POLY = 0x1864CFB


def _crc24(data: bytes) -> int:
    crc = CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= CRC24_POLY
    return crc & 0xFFFFFF


class ArmorError(Exception):
    pass


def encode_armor(block_type: str, headers: dict[str, str], data: bytes) -> str:
    lines = [f"-----BEGIN {block_type}-----"]
    for k, v in sorted(headers.items()):
        lines.append(f"{k}: {v}")
    lines.append("")
    body = base64.b64encode(data).decode()
    lines.extend(textwrap.wrap(body, 64))
    crc = base64.b64encode(_crc24(data).to_bytes(3, "big")).decode()
    lines.append(f"={crc}")
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(text: str) -> tuple[str, dict[str, str], bytes]:
    """Returns (block_type, headers, data); raises ArmorError."""
    lines = [ln.rstrip("\r") for ln in text.strip().split("\n")]
    if not lines or not lines[0].startswith("-----BEGIN ") or not lines[0].endswith("-----"):
        raise ArmorError("missing BEGIN line")
    block_type = lines[0][len("-----BEGIN "):-len("-----")]
    if lines[-1] != f"-----END {block_type}-----":
        raise ArmorError("missing or mismatched END line")
    headers: dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i].strip():
        if ":" not in lines[i]:
            break  # body began without a blank separator
        k, _, v = lines[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) - 1 and not lines[i].strip():
        i += 1
    body_lines = []
    crc_line = None
    for ln in lines[i:-1]:
        if ln.startswith("="):
            crc_line = ln[1:]
        else:
            body_lines.append(ln.strip())
    try:
        data = base64.b64decode("".join(body_lines), validate=True)
    except Exception as e:
        raise ArmorError(f"bad base64 body: {e}")
    if crc_line is not None:
        try:
            want = int.from_bytes(base64.b64decode(crc_line, validate=True), "big")
        except Exception as e:
            raise ArmorError(f"bad checksum encoding: {e}")
        if _crc24(data) != want:
            raise ArmorError("checksum mismatch")
    return block_type, headers, data
