"""K-of-N threshold multisig pubkeys.

Reference parity: crypto/multisig/threshold_pubkey.go (PubKeyMultisigThreshold
whose VerifyBytes iterates sub-keys against a compact bit array,
threshold_pubkey.go:33), multisignature.go (Multisignature accumulator), and
bitarray/ (CompactBitArray).

Batch-friendliness: `explode` flattens a multisig verification into its
(sub-pubkey, msg, sub-sig) triples so the TPU batch verifier can fold
multisig checks into the same device batch as plain votes (BASELINE.json
config #5: mixed-key 10k-validator streaming AddVote).
"""
from __future__ import annotations

from tendermint_tpu import crypto as _crypto
from tendermint_tpu.crypto import PubKey, sum_truncated
from tendermint_tpu.encoding import Reader, Writer

TYPE = "multisig-threshold"
_TAG = 3


class CompactBitArray:
    """Reference crypto/multisig/bitarray/compact_bit_array.go."""

    __slots__ = ("size", "_elems")

    def __init__(self, size: int) -> None:
        if size < 0:
            raise ValueError("negative size")
        self.size = size
        self._elems = bytearray((size + 7) // 8)

    def get(self, i: int) -> bool:
        if not (0 <= i < self.size):
            return False
        return bool(self._elems[i >> 3] & (1 << (7 - (i & 7))))

    def set(self, i: int, v: bool) -> bool:
        if not (0 <= i < self.size):
            return False
        if v:
            self._elems[i >> 3] |= 1 << (7 - (i & 7))
        else:
            self._elems[i >> 3] &= ~(1 << (7 - (i & 7)))
        return True

    def num_true_before(self, i: int) -> int:
        return sum(1 for j in range(i) if self.get(j))

    def count(self) -> int:
        return self.num_true_before(self.size)

    def encode(self) -> bytes:
        return Writer().u32(self.size).bytes(bytes(self._elems)).build()

    @classmethod
    def read(cls, r: Reader) -> "CompactBitArray":
        size = r.u32()
        elems = r.bytes()
        ba = cls(size)
        if len(elems) != len(ba._elems):
            from tendermint_tpu.encoding import DecodeError

            raise DecodeError("bitarray length mismatch")
        ba._elems = bytearray(elems)
        return ba


class Multisignature:
    """Signature accumulator (reference multisignature.go:13)."""

    def __init__(self, n: int) -> None:
        self.bitarray = CompactBitArray(n)
        self.sigs: list[bytes] = []

    def add_signature_from_pubkey(
        self, sig: bytes, pub: PubKey, keys: list[PubKey]
    ) -> None:
        try:
            index = keys.index(pub)
        except ValueError:
            raise ValueError("pubkey not in multisig key list")
        new_sig_index = self.bitarray.num_true_before(index)
        if self.bitarray.get(index):
            self.sigs[new_sig_index] = sig
        else:
            self.bitarray.set(index, True)
            self.sigs.insert(new_sig_index, sig)

    def encode(self) -> bytes:
        w = Writer().raw(self.bitarray.encode()).u32(len(self.sigs))
        for s in self.sigs:
            w.bytes(s)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "Multisignature":
        r = Reader(data)
        ba = CompactBitArray.read(r)
        nsigs = r.u32()
        sigs = [r.bytes() for _ in range(nsigs)]
        r.expect_done()
        ms = cls(ba.size)
        ms.bitarray = ba
        ms.sigs = sigs
        return ms


class PubKeyMultisigThreshold(PubKey):
    """Reference threshold_pubkey.go:8."""

    TYPE = TYPE

    __slots__ = ("k", "pubkeys")

    def __init__(self, k: int, pubkeys: list[PubKey]) -> None:
        if k <= 0:
            raise ValueError("threshold k must be positive")
        if len(pubkeys) < k:
            raise ValueError("fewer pubkeys than threshold")
        self.k = k
        self.pubkeys = list(pubkeys)

    def bytes(self) -> bytes:
        w = Writer().u32(self.k).u32(len(self.pubkeys))
        for pk in self.pubkeys:
            w.bytes(_crypto.encode_pubkey(pk))
        return w.build()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PubKeyMultisigThreshold":
        r = Reader(raw)
        k = r.u32()
        n = r.u32()
        keys = [_crypto.decode_pubkey(r.bytes()) for _ in range(n)]
        r.expect_done()
        return cls(k, keys)

    def address(self) -> bytes:
        return sum_truncated(self.bytes())

    def explode(
        self, msg: bytes, sig: bytes
    ) -> list[tuple[PubKey, bytes, bytes]] | None:
        """Flatten into sub-key (pub, msg, sig) triples, or None if the
        signature is structurally invalid / below threshold."""
        try:
            ms = Multisignature.decode(sig)
        except Exception:
            return None
        if ms.bitarray.size != len(self.pubkeys):
            return None
        if len(ms.sigs) < self.k:
            return None
        triples = []
        si = 0
        for i, pk in enumerate(self.pubkeys):
            if ms.bitarray.get(i):
                if si >= len(ms.sigs):
                    return None
                triples.append((pk, msg, ms.sigs[si]))
                si += 1
        if si != len(ms.sigs):
            return None
        return triples

    def verify(self, msg: bytes, sig: bytes) -> bool:
        triples = self.explode(msg, sig)
        if triples is None:
            return False
        return all(pk.verify(m, s) for pk, m, s in triples)


_crypto.register_pubkey_type(TYPE, _TAG, PubKeyMultisigThreshold.from_bytes)
