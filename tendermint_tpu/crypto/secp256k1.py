"""secp256k1 ECDSA keys.

Reference parity: crypto/secp256k1/secp256k1.go — 32-byte privkey, 33-byte
compressed pubkey, address = RIPEMD160(SHA256(pubkey)). The reference has a
dual build: pure-Go btcec (secp256k1_nocgo.go:21-50, rejects high-S
malleable signatures) vs cgo libsecp256k1 (secp256k1_cgo.go). Here the
serial path delegates to the `cryptography` package (OpenSSL native code —
the analog of the cgo path); signatures are 64-byte compact r||s with the
same low-S rule enforced on both sign and verify.
"""
from __future__ import annotations

import hashlib
import os

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
)

from tendermint_tpu import crypto as _crypto
from tendermint_tpu.crypto import PrivKey, PubKey

TYPE = "secp256k1"
PUBKEY_SIZE = 33  # compressed
PRIVKEY_SIZE = 32
SIGNATURE_SIZE = 64  # compact r||s
_TAG = 2

# Curve order (for the low-S malleability rule, reference secp256k1_nocgo.go:40-50)
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
HALF_N = N // 2


def _address(pub_bytes: bytes) -> bytes:
    h = hashlib.sha256(pub_bytes).digest()
    r = hashlib.new("ripemd160")
    r.update(h)
    return r.digest()


class PubKeySecp256k1(PubKey):
    TYPE = TYPE

    __slots__ = ("_raw",)

    def __init__(self, raw: bytes) -> None:
        if len(raw) != PUBKEY_SIZE:
            raise ValueError(f"secp256k1 pubkey must be {PUBKEY_SIZE} bytes")
        self._raw = bytes(raw)

    def address(self) -> bytes:
        return _address(self._raw)

    def bytes(self) -> bytes:
        return self._raw

    def verify(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (0 < r < N and 0 < s <= HALF_N):  # reject malleable high-S
            return False
        try:
            pk = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256K1(), self._raw
            )
            pk.verify(encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256()))
            return True
        except (InvalidSignature, ValueError):
            return False


class PrivKeySecp256k1(PrivKey):
    TYPE = TYPE

    __slots__ = ("_raw", "_sk")

    def __init__(self, raw: bytes) -> None:
        if len(raw) != PRIVKEY_SIZE:
            raise ValueError(f"secp256k1 privkey must be {PRIVKEY_SIZE} bytes")
        self._raw = bytes(raw)
        self._sk = ec.derive_private_key(
            int.from_bytes(raw, "big"), ec.SECP256K1()
        )

    def bytes(self) -> bytes:
        return self._raw

    def sign(self, msg: bytes) -> bytes:
        der = self._sk.sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        if s > HALF_N:  # normalize to low-S (reference secp256k1_nocgo.go:30-38)
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> PubKeySecp256k1:
        raw = self._sk.public_key().public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
        )
        return PubKeySecp256k1(raw)


def gen_priv_key(seed: bytes | None = None) -> PrivKeySecp256k1:
    while True:
        raw = hashlib.sha256(seed).digest() if seed is not None else os.urandom(32)
        d = int.from_bytes(raw, "big")
        if 0 < d < N:
            return PrivKeySecp256k1(raw)
        seed = raw  # re-hash until in range (reference GenPrivKey loop)


_crypto.register_pubkey_type(TYPE, _TAG, PubKeySecp256k1)
