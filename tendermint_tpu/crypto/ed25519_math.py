"""Pure-integer edwards25519 curve math (host side).

Used for: pubkey decompression + extended-coordinate caching when building
device batches (ValidatorSet caches decompressed keys), host-side scalar
reduction, and as an independent oracle in tests. The batched hot path lives
in tendermint_tpu/ops (JAX limb arithmetic); signing and one-off verification
go through the `cryptography` library (crypto/ed25519.py).

Curve: -x^2 + y^2 = 1 + d x^2 y^2 over GF(2^255-19), per RFC 8032 §5.1.
"""
from __future__ import annotations

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# Base point (RFC 8032 §5.1): y = 4/5, x recovered with even... x is the
# point with positive (even) x? RFC defines B_x explicitly:
BASE_Y = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """x from y and sign bit; None if y is not on the curve (RFC 8032 §5.1.3)."""
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


BASE_X = _recover_x(BASE_Y, 0)
assert BASE_X is not None

# Extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z.
IDENTITY = (0, 1, 1, 0)
BASE = (BASE_X, BASE_Y, 1, BASE_X * BASE_Y % P)


def point_add(p1, p2):
    """Complete twisted-Edwards addition (RFC 8032 §5.1.4)."""
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_double(p1):
    """Dedicated doubling (RFC 8032 §5.1.4)."""
    x1, y1, z1, _ = p1
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = a + b
    e = h - (x1 + y1) * (x1 + y1)
    g = a - b
    f = c + g
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_neg(p1):
    x, y, z, t = p1
    return (P - x if x else 0, y, z, P - t if t else 0)


def scalar_mult(s: int, p1):
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p1)
        p1 = point_double(p1)
        s >>= 1
    return q


def point_equal(p1, p2) -> bool:
    x1, y1, z1, _ = p1
    x2, y2, z2, _ = p2
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def to_affine(p1):
    x, y, z, _ = p1
    zi = pow(z, P - 2, P)
    return (x * zi % P, y * zi % P)


def compress(p1) -> bytes:
    x, y = to_affine(p1)
    return ((y | ((x & 1) << 255)).to_bytes(32, "little"))


def decompress(data: bytes):
    """Compressed 32-byte point -> extended coords, or None if invalid."""
    if len(data) != 32:
        return None
    n = int.from_bytes(data, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def reduce_scalar(b: bytes) -> int:
    return int.from_bytes(b, "little") % L


def verify_scalar_range(s_bytes: bytes) -> bool:
    """RFC 8032 §5.1.7: reject S >= L (malleability)."""
    return int.from_bytes(s_bytes, "little") < L


def _expand_priv(priv: bytes) -> tuple[int, bytes]:
    """RFC 8032 §5.1.5: seed -> (clamped scalar, prefix)."""
    import hashlib

    h = hashlib.sha512(priv).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def pub_from_priv(priv: bytes) -> bytes:
    """32-byte seed -> compressed public key (RFC 8032 §5.1.5).

    Dev/bench tool (with `sign` below): NOT constant-time — it exists so
    signed workloads (the transfer app, ingest_bench) can be generated in
    environments without the `cryptography` package. Production keys stay
    on crypto/ed25519.py's OpenSSL-backed stack."""
    a, _ = _expand_priv(priv)
    return compress(scalar_mult(a, BASE))


def sign(priv: bytes, msg: bytes) -> bytes:
    """RFC 8032 §5.1.6 deterministic signing (dev/bench tool — see
    pub_from_priv). Output verifies on every path in this repo: the
    `cryptography` stack, the native batch, the device kernel, and
    `verify` below."""
    import hashlib

    a, prefix = _expand_priv(priv)
    pub = compress(scalar_mult(a, BASE))
    r = reduce_scalar(hashlib.sha512(prefix + msg).digest())
    r_enc = compress(scalar_mult(r, BASE))
    k = reduce_scalar(hashlib.sha512(r_enc + pub + msg).digest())
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Reference single verify, used as test oracle (RFC 8032 §5.1.7)."""
    import hashlib

    if len(sig) != 64:
        return False
    a = decompress(pub)
    if a is None:
        return False
    r_bytes, s_bytes = sig[:32], sig[32:]
    if not verify_scalar_range(s_bytes):
        return False
    s = int.from_bytes(s_bytes, "little")
    h = reduce_scalar(hashlib.sha512(r_bytes + pub + msg).digest())
    # [S]B - [h]A == R  <=>  encode([S]B + [h](-A)) == r_bytes
    rp = point_add(scalar_mult(s, BASE), scalar_mult(h, point_neg(a)))
    return compress(rp) == r_bytes
