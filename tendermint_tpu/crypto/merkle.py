"""Merkle trees and proofs.

Reference parity: crypto/merkle/simple_tree.go (simple merkle root over byte
slices), simple_proof.go (`SimpleProof` with aunts), simple_map.go (sorted
KV-pair map hashing for the block header), proof.go (chained
`ProofOperator`/`ProofRuntime` for light-client ABCI query proofs).

This implementation uses RFC-6962 domain separation (0x00 leaf prefix, 0x01
inner prefix) with the largest-power-of-two-less-than split, which hardens
against proof-type confusion; byte compatibility with the reference is not a
goal (different codebase, documented encoding).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

_LEAF_PREFIX = b"\x00"
_INNER_PREFIX = b"\x01"


def _hash(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _hash(_LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _hash(_INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Reference merkle.SimpleHashFromByteSlices (simple_tree.go).

    Trees of 8+ leaves run through the native C++ core (tm_merkle_root,
    native/merkle.cpp — bit-exact, ~20x the Python recursion); smaller
    trees stay in Python where the ctypes marshalling would dominate."""
    n = len(items)
    if n >= 8:
        from tendermint_tpu.crypto import native

        root = native.merkle_root(items)
        if root is not None:
            return root
    return _py_hash_from_byte_slices(items)


def _py_hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Pure-Python tree — the no-native fallback and the parity oracle the
    native core is tested against."""
    n = len(items)
    if n == 0:
        return _hash(b"")
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(
        _py_hash_from_byte_slices(items[:k]), _py_hash_from_byte_slices(items[k:])
    )


@dataclass
class SimpleProof:
    """Reference merkle.SimpleProof (simple_proof.go)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def verify(self, root_hash: bytes, leaf: bytes) -> bool:
        if self.total <= 0 or not (0 <= self.index < self.total):
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = _root_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)
        return computed == root_hash

    def encode(self) -> bytes:
        from tendermint_tpu.encoding import Writer

        w = Writer().u32(self.total).u32(self.index).bytes(self.leaf_hash)
        w.u32(len(self.aunts))
        for a in self.aunts:
            w.bytes(a)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "SimpleProof":
        from tendermint_tpu.encoding import Reader

        r = Reader(data)
        p = cls.read(r)
        r.expect_done()
        return p

    @classmethod
    def read(cls, r) -> "SimpleProof":
        total, index, lh = r.u32(), r.u32(), r.bytes()
        aunts = [r.bytes() for _ in range(r.u32())]
        return cls(total, index, lh, aunts)


def _root_from_aunts(
    index: int, total: int, leaf: bytes, aunts: list[bytes]
) -> bytes | None:
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _root_from_aunts(index, k, leaf, aunts[:-1])
        return None if left is None else inner_hash(left, aunts[-1])
    right = _root_from_aunts(index - k, total - k, leaf, aunts[:-1])
    return None if right is None else inner_hash(aunts[-1], right)


def proofs_from_byte_slices(
    items: list[bytes],
) -> tuple[bytes, list[SimpleProof]]:
    """Root hash + one SimpleProof per item (simple_proof.go SimpleProofsFromByteSlices)."""
    trails, root = _trails_from_byte_slices(items)
    root_hash = root.hash
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(
            SimpleProof(len(items), i, trail.hash, trail.flatten_aunts())
        )
    return root_hash, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes) -> None:
        self.hash = h
        self.parent = None
        self.left = None  # sibling pointers, as in the reference trail nodes
        self.right = None

    def flatten_aunts(self) -> list[bytes]:
        aunts = []
        node = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_byte_slices(items: list[bytes]):
    n = len(items)
    if n == 0:
        return [], _Node(_hash(b""))
    if n == 1:
        node = _Node(leaf_hash(items[0]))
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent, left_root.right = root, right_root
    right_root.parent, right_root.left = root, left_root
    return lefts + rights, root


@dataclass
class RangeProof:
    """Proof that a CONTIGUOUS run of leaves [start, start+count) belongs to
    a simple merkle tree of `total` leaves — the state-sync chunk proof
    (docs/state_sync.md). One proof covers a whole chunk of consecutive
    leaves instead of one SimpleProof per leaf: `aunts` are the roots of
    the maximal subtrees that lie entirely OUTSIDE the range, listed in the
    deterministic pre-order the verification fold consumes them.

    No reference analog (the reference's state sync trusts chunk hashes
    only and re-checks the final state hash); here every chunk is
    independently bound to the verified header's app hash before it is
    applied, so a corrupt chunk can never reach the application.
    """

    total: int
    start: int
    count: int
    aunts: list[bytes] = field(default_factory=list)

    def verify(self, root_hash: bytes, leaves: list[bytes]) -> bool:
        """True iff `leaves` (raw leaf bytes, pre-hash) occupy
        [start, start+count) of a tree whose root is `root_hash`."""
        if self.count != len(leaves) or self.count <= 0:
            return False
        if self.start < 0 or self.start + self.count > self.total:
            return False
        hashes = [leaf_hash(item) for item in leaves]
        state = {"aunt": 0, "leaf": 0, "bad": False}
        end = self.start + self.count

        def fold(lo: int, hi: int) -> bytes:
            if state["bad"]:
                return b""
            if hi <= self.start or lo >= end:
                # subtree entirely outside the range: consume one aunt
                if state["aunt"] >= len(self.aunts):
                    state["bad"] = True
                    return b""
                a = self.aunts[state["aunt"]]
                state["aunt"] += 1
                return a
            if hi - lo == 1:
                h = hashes[state["leaf"]]
                state["leaf"] += 1
                return h
            k = _split_point(hi - lo)
            left = fold(lo, lo + k)
            right = fold(lo + k, hi)
            return inner_hash(left, right)

        computed = fold(0, self.total)
        if state["bad"] or state["aunt"] != len(self.aunts):
            return False  # truncated or padded aunt list
        if state["leaf"] != self.count:
            return False
        return computed == root_hash

    def encode(self) -> bytes:
        from tendermint_tpu.encoding import Writer

        w = Writer().u32(self.total).u32(self.start).u32(self.count)
        w.u32(len(self.aunts))
        for a in self.aunts:
            w.bytes(a)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "RangeProof":
        from tendermint_tpu.encoding import Reader

        r = Reader(data)
        total, start, count = r.u32(), r.u32(), r.u32()
        aunts = [r.bytes() for _ in range(r.u32())]
        r.expect_done()
        return cls(total, start, count, aunts)


def range_proof(
    items: list[bytes],
    start: int,
    count: int,
    subtree_cache: dict[tuple[int, int], bytes] | None = None,
) -> RangeProof:
    """Build the RangeProof for items[start:start+count] (the builder mirrors
    RangeProof.verify's fold, emitting subtree roots where verify will
    consume aunts).

    `subtree_cache` memoizes (lo, hi) -> subtree root across calls. The
    split points depend only on len(items), so proofs for every chunk of
    one snapshot share it: pass one dict per snapshot and the whole set of
    chunk proofs costs one tree pass (O(n) hashing) instead of re-hashing
    the out-of-range subtrees from scratch per chunk (O(n × chunks))."""
    total = len(items)
    if count <= 0 or start < 0 or start + count > total:
        raise ValueError(f"bad range [{start},{start + count}) of {total}")
    end = start + count
    aunts: list[bytes] = []

    def subtree(lo: int, hi: int) -> bytes:
        if subtree_cache is None:
            return _py_hash_from_byte_slices(items[lo:hi])
        h = subtree_cache.get((lo, hi))
        if h is None:
            if hi - lo == 1:
                h = leaf_hash(items[lo])
            else:  # hi > lo always (callers pass non-empty spans)
                k = _split_point(hi - lo)
                h = inner_hash(subtree(lo, lo + k), subtree(lo + k, hi))
            subtree_cache[(lo, hi)] = h
        return h

    def walk(lo: int, hi: int) -> None:
        if hi <= start or lo >= end:
            aunts.append(subtree(lo, hi))
            return
        if hi - lo == 1:
            return  # in-range leaf: verifier recomputes it
        k = _split_point(hi - lo)
        walk(lo, lo + k)
        walk(lo + k, hi)

    walk(0, total)
    return RangeProof(total, start, count, aunts)


# --- simple map (sorted KV hashing, reference simple_map.go) ---------------


def hash_from_map(kvs: dict[str, bytes]) -> bytes:
    """Deterministic hash of string->bytes map: sort keys, hash encoded pairs."""
    from tendermint_tpu.encoding import Writer

    items = []
    for k in sorted(kvs):
        items.append(Writer().str(k).bytes(kvs[k]).build())
    return hash_from_byte_slices(items)


# --- chained proofs (reference proof.go ProofOperator/ProofRuntime) --------


@dataclass
class ProofOp:
    """One verification step; mirrors merkle.ProofOp (proof.go:22)."""

    type: str
    key: bytes
    data: bytes


class ProofOperator:
    def run(self, values: list[bytes]) -> list[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError

    def proof_op(self) -> ProofOp:
        raise NotImplementedError


class SimpleValueOp(ProofOperator):
    """Leaf-value op: proves value at key in a simple merkle tree
    (reference crypto/merkle/proof_simple_value.go)."""

    TYPE = "simple:v"

    def __init__(self, key: bytes, proof: SimpleProof) -> None:
        self.key = key
        self.proof = proof

    def get_key(self) -> bytes:
        return self.key

    def run(self, values: list[bytes]) -> list[bytes]:
        if len(values) != 1:
            raise ValueError("SimpleValueOp expects one value")
        vhash = hashlib.sha256(values[0]).digest()
        from tendermint_tpu.encoding import Writer

        kv = Writer().str(self.key.decode("utf-8", "surrogateescape")).bytes(vhash).build()
        if leaf_hash(kv) != self.proof.leaf_hash:
            raise ValueError("leaf hash mismatch")
        root = _root_from_aunts(
            self.proof.index, self.proof.total, self.proof.leaf_hash, self.proof.aunts
        )
        if root is None:
            raise ValueError("bad aunts")
        return [root]

    def proof_op(self) -> ProofOp:
        from tendermint_tpu.encoding import Writer

        return ProofOp(self.TYPE, self.key, Writer().raw(self.proof.encode()).build())

    @classmethod
    def decode(cls, op: ProofOp) -> "SimpleValueOp":
        return cls(op.key, SimpleProof.decode(op.data))


class ProofRuntime:
    """Registry of op decoders + chained verification (reference proof.go:75)."""

    def __init__(self) -> None:
        self._decoders: dict[str, object] = {}

    def register_op_decoder(self, type_name: str, decoder) -> None:
        self._decoders[type_name] = decoder

    def decode_proof(self, ops: list[ProofOp]) -> list[ProofOperator]:
        out = []
        for op in ops:
            if op.type not in self._decoders:
                raise ValueError(f"unknown proof op type {op.type!r}")
            out.append(self._decoders[op.type](op))
        return out

    def verify_value(
        self, ops: list[ProofOp], root: bytes, keypath: list[bytes], value: bytes
    ) -> bool:
        return self._verify(ops, root, keypath, [value])

    def verify_absence(self, ops: list[ProofOp], root: bytes, keypath: list[bytes]) -> bool:
        return self._verify(ops, root, keypath, [])

    def _verify(
        self, ops: list[ProofOp], root: bytes, keypath: list[bytes], args: list[bytes]
    ) -> bool:
        try:
            operators = self.decode_proof(ops)
            keys = list(keypath)
            for op in operators:
                key = op.get_key()
                if key:
                    if not keys or keys[-1] != key:
                        return False
                    keys.pop()
                args = op.run(args)
            return bool(args) and args[0] == root and not keys
        except Exception:
            return False


def default_proof_runtime() -> ProofRuntime:
    rt = ProofRuntime()
    rt.register_op_decoder(SimpleValueOp.TYPE, SimpleValueOp.decode)
    return rt
