"""XChaCha20-Poly1305 AEAD (24-byte nonces).

Parity with the reference's crypto/xchacha20poly1305/xchachapoly.go:1 —
HChaCha20 subkey derivation (draft-irtf-cfrg-xchacha §2.2) in front of the
IETF ChaCha20-Poly1305 AEAD: the first 16 nonce bytes derive a one-use
subkey, the last 8 become the tail of the 12-byte inner nonce (4 zero-byte
prefix). The long random nonce is what the reference uses it for: safe
random-nonce encryption without a per-key counter.

The 20-round HChaCha20 core runs in pure Python — it is key *derivation*
(one block per seal/open, ~30 µs); the bulk AEAD work is the C-backed
ChaCha20Poly1305 from `cryptography`, mirroring how the reference fronts
golang.org/x/crypto/chacha20poly1305 with its own HChaCha20.
"""
from __future__ import annotations

import struct

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

KEY_SIZE = 32
NONCE_SIZE = 24
TAG_SIZE = 16

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)  # "expand 32-byte k"
_MASK = 0xFFFFFFFF


def _rotl(v: int, c: int) -> int:
    return ((v << c) | (v >> (32 - c))) & _MASK


def _quarter(st: list[int], a: int, b: int, c: int, d: int) -> None:
    st[a] = (st[a] + st[b]) & _MASK
    st[d] = _rotl(st[d] ^ st[a], 16)
    st[c] = (st[c] + st[d]) & _MASK
    st[b] = _rotl(st[b] ^ st[c], 12)
    st[a] = (st[a] + st[b]) & _MASK
    st[d] = _rotl(st[d] ^ st[a], 8)
    st[c] = (st[c] + st[d]) & _MASK
    st[b] = _rotl(st[b] ^ st[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20: (32-byte key, 16-byte nonce) -> 32-byte subkey.

    20 rounds over the ChaCha state; the output is words 0-3 and 12-15
    WITHOUT the feed-forward addition (draft-irtf-cfrg-xchacha §2.2).
    """
    if len(key) != KEY_SIZE:
        raise ValueError("hchacha20: key must be 32 bytes")
    if len(nonce16) != 16:
        raise ValueError("hchacha20: nonce must be 16 bytes")
    st = list(_SIGMA)
    st += list(struct.unpack("<8I", key))
    st += list(struct.unpack("<4I", nonce16))
    for _ in range(10):  # 10 double rounds = 20 rounds
        _quarter(st, 0, 4, 8, 12)
        _quarter(st, 1, 5, 9, 13)
        _quarter(st, 2, 6, 10, 14)
        _quarter(st, 3, 7, 11, 15)
        _quarter(st, 0, 5, 10, 15)
        _quarter(st, 1, 6, 11, 12)
        _quarter(st, 2, 7, 8, 13)
        _quarter(st, 3, 4, 9, 14)
    return struct.pack("<8I", *(st[0:4] + st[12:16]))


class XChaCha20Poly1305:
    """AEAD with 24-byte nonces (reference xchachapoly.go New/Seal/Open)."""

    def __init__(self, key: bytes) -> None:
        if len(key) != KEY_SIZE:
            raise ValueError("xchacha20poly1305: bad key length")
        self._key = bytes(key)

    def _inner(self, nonce: bytes) -> tuple[ChaCha20Poly1305, bytes]:
        if len(nonce) != NONCE_SIZE:
            raise ValueError("xchacha20poly1305: bad nonce length")
        subkey = hchacha20(self._key, nonce[:16])
        return ChaCha20Poly1305(subkey), b"\x00\x00\x00\x00" + nonce[16:]

    def seal(self, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
        """Encrypt+authenticate; returns ciphertext || 16-byte tag."""
        aead, inner_nonce = self._inner(nonce)
        return aead.encrypt(inner_nonce, plaintext, aad or None)

    def open(self, nonce: bytes, ciphertext: bytes, aad: bytes = b"") -> bytes:
        """Verify+decrypt; raises ValueError on forgery (reference returns
        an error from Open — callers treat both uniformly)."""
        aead, inner_nonce = self._inner(nonce)
        try:
            return aead.decrypt(inner_nonce, ciphertext, aad or None)
        except InvalidTag as e:
            raise ValueError("xchacha20poly1305: message authentication failed") from e
