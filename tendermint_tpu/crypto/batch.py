"""BatchVerifier — the first-class batch signature-verification seam.

The reference has *no* batch-verify API anywhere: every hot loop calls
`PubKey.VerifyBytes` one signature at a time under a mutex
(types/vote_set.go:189, types/validator_set.go:609-627,
state/validation.go:99,141, lite/dynamic_verifier.go). This type is the new
framework's replacement seam: accumulation points (VoteSet, Commit verify,
header-chain verify) add (pubkey, msg, sig) triples and flush them through a
pluggable backend — the serial CPU path by default, the JAX/TPU kernel when
registered (tendermint_tpu.ops registers itself on import; see
tendermint_tpu/ops/__init__.py).

Multisig keys are *exploded* into their sub-key triples so mixed
ed25519+secp256k1+multisig batches still verify in as few device launches as
possible (BASELINE.json config #5).
"""
from __future__ import annotations

import os
from typing import Callable, Sequence

from tendermint_tpu.crypto import PubKey
from tendermint_tpu.crypto.multisig import PubKeyMultisigThreshold
from tendermint_tpu.device.priorities import current_priority, priority_scope

# Whole-dispatch bound on the concurrent per-curve group map (ADVICE r4:
# wedged daemon workers are never replaced, so an unbounded wait blocks
# the verify caller forever once the device link dies). Must exceed a
# legitimate cold in-group kernel compile on a loaded host.
_GROUP_TIMEOUT_S = float(os.environ.get("TMTPU_GROUP_TIMEOUT_S", 900.0))

# A backend verifies a homogeneous batch of primitive signatures:
#   fn(pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]) -> list[bool]
Backend = Callable[[Sequence[bytes], Sequence[bytes], Sequence[bytes]], Sequence[bool]]

_BACKENDS: dict[str, Backend] = {}


def register_backend(key_type: str, fn: Backend) -> None:
    _BACKENDS[key_type] = fn


def get_backend(key_type: str) -> Backend | None:
    return _BACKENDS.get(key_type)


def clear_backend(key_type: str) -> None:
    _BACKENDS.pop(key_type, None)


_auto_ops_tried = False
_auto_ops_jobs_seen = 0


def _maybe_register_default_backends(n_jobs: int) -> None:
    """Backends register when `tendermint_tpu.ops` is imported (the node
    does this in its composition root), but standalone consumers — the
    lite proxy, benches, scripts — can forget and silently verify big
    batches one signature at a time (the fast-sync bench lost 40% to
    exactly this). Once enough verification work has flowed through with
    no backend registered — one big batch, or a stream of smaller ones —
    register ops' backends once, via its idempotent register() (NOT the
    import side effect, which is a no-op if ops was imported earlier).
    Genuinely tiny one-off uses never pay the import.
    Set TMTPU_NO_AUTO_OPS=1 to opt out."""
    global _auto_ops_tried, _auto_ops_jobs_seen
    _auto_ops_jobs_seen += n_jobs
    if _auto_ops_tried or (n_jobs < 128 and _auto_ops_jobs_seen < 512):
        return
    import os

    _auto_ops_tried = True
    if os.environ.get("TMTPU_NO_AUTO_OPS"):
        return
    try:
        import tendermint_tpu.ops as _ops

        _ops.register()  # idempotent; honors TMTPU_NO_ACCEL
    except Exception:  # noqa: BLE001 — acceleration is optional
        pass


# optional observability hook: fn(batch_size, seconds)
_metrics_sink = None


def set_metrics_sink(fn) -> None:
    global _metrics_sink
    _metrics_sink = fn


# Streaming-accumulation hint: how many queued signatures make a batch
# worth flushing to the registered backend. The ops package registers a
# probe-driven value (a multiple of the device routing threshold) when a
# device is present; the default suits the CPU paths. Consumers: VoteStream
# (types/vote_set.py) and any bulk-ingest loop that wants to batch.
_accum_hint: Callable[[], int] | None = None


def set_accumulation_hint(fn: Callable[[], int]) -> None:
    global _accum_hint
    _accum_hint = fn


def accumulation_hint() -> int:
    if _accum_hint is not None:
        try:
            return max(1, int(_accum_hint()))
        except Exception:  # noqa: BLE001 — a failing probe must not break ingest
            pass
    return 2048


def stream_flush_hint() -> int:
    """Flush point for ASYNC-streamed accumulation (VoteStream /
    consensus streaming dispatch). The plain accumulation hint targets a
    multiple of the device routing threshold because a synchronous flush
    must amortize its whole launch alone; a streamed flush dispatches
    through the DeviceScheduler's packer, where it coalesces with
    co-resident queued work — so it only needs to cross the scheduler's
    routing threshold (`ops.effective_min_batch`) to fill device lanes.
    Consulted lazily and only when ops is already loaded (the rpc/core
    lazy-module rule: a hint read must never drag jax into a CPU-only
    process); falls back to the plain hint otherwise."""
    import sys

    hint = accumulation_hint()
    ops = sys.modules.get("tendermint_tpu.ops")
    if ops is None:
        return hint
    try:
        emb = int(ops.effective_min_batch())
    except Exception:  # noqa: BLE001 — a failing probe must not break ingest
        return hint
    if emb >= (1 << 30):  # never-device sentinel: no launch to amortize
        return hint
    return max(1, min(hint, emb))


class BatchVerifier:
    """Accumulate signatures, verify them all in grouped batches.

    Usage:
        bv = BatchVerifier()
        for ...: bv.add(pub, msg, sig)
        ok = bv.verify_all()      # list[bool], one per add() call
    """

    def __init__(self) -> None:
        # item = one add() call; job = one primitive signature check
        self._n_items = 0
        self._invalid_items: set[int] = set()
        # key_type -> (item_idx list, pub PubKey list, msg list, sig list)
        self._groups: dict[str, tuple[list, list, list, list]] = {}

    def __len__(self) -> int:
        return self._n_items

    def add(self, pub: PubKey, msg: bytes, sig: bytes) -> int:
        """Queue one signature check; returns its item index."""
        idx = self._n_items
        self._n_items += 1
        if isinstance(pub, PubKeyMultisigThreshold):
            triples = pub.explode(msg, sig)
            if triples is None:
                self._invalid_items.add(idx)
                return idx
            for sub_pub, sub_msg, sub_sig in triples:
                self._enqueue(idx, sub_pub, sub_msg, sub_sig)
        else:
            self._enqueue(idx, pub, msg, sig)
        return idx

    def _enqueue(self, item: int, pub: PubKey, msg: bytes, sig: bytes) -> None:
        g = self._groups.setdefault(pub.TYPE, ([], [], [], []))
        g[0].append(item)
        g[1].append(pub)
        g[2].append(msg)
        g[3].append(sig)

    def verify_all(self) -> list[bool]:
        import time as _time

        from tendermint_tpu.libs import trace as _trace

        with _trace.span("batch_verify", items=self._n_items) as sp:
            return self._verify_all(_time, _trace, sp)

    def _verify_all(self, _time, _trace, sp) -> list[bool]:
        """verify_all body under an open `batch_verify` span `sp`."""
        t0 = _time.monotonic()
        n_jobs = 0
        ok = [True] * self._n_items
        for idx in self._invalid_items:
            ok[idx] = False
        if not _BACKENDS and not _auto_ops_tried:
            _maybe_register_default_backends(
                sum(len(g[0]) for g in self._groups.values())
            )

        # the submitter's device-priority class (consensus commit, fast
        # sync, lite, mempool recheck — device/priorities.py): captured
        # here because the pool workers below do NOT inherit the caller's
        # contextvars, and the scheduler must see the right admission class
        pri = current_priority()
        sp.set(priority=pri.label)

        def run_group(entry):
            key_type, (items, pubs, msgs, sigs) = entry
            backend = _BACKENDS.get(key_type)
            with priority_scope(pri):
                if backend is not None:
                    return backend([p.bytes() for p in pubs], msgs, sigs)
                return [p.verify(m, s) for p, m, s in zip(pubs, msgs, sigs)]

        groups = list(self._groups.items())
        if len(groups) > 1:
            # mixed-curve batches run their per-curve backends
            # CONCURRENTLY: a device-routed ed25519 group spends most of
            # its wall time waiting on the accelerator RPC while a native
            # secp group burns CPU with the GIL released — serializing
            # them (the reference shape: one sig at a time,
            # types/vote_set.go:189) would add the two instead of
            # overlapping them. Single-group batches skip the pool hop.
            from tendermint_tpu.libs.pool import shared_pool

            try:
                # bounded (ADVICE r4): a device-routed group against a
                # wedged tunnel otherwise hangs this caller forever. The
                # budget covers a cold in-group kernel compile; on expiry
                # every group recomputes on the device-free serial path.
                all_results = shared_pool("tmtpu-vgrp", 4).map(
                    run_group, groups, timeout=_GROUP_TIMEOUT_S
                )
            except TimeoutError:
                _trace.DEVICE.record_fallback("group_timeout")
                sp.set(group_timeout=True)
                all_results = [
                    [p.verify(m, s) for p, m, s in zip(pubs_, msgs_, sigs_)]
                    for _, (_, pubs_, msgs_, sigs_) in groups
                ]
        else:
            all_results = [run_group(g) for g in groups]  # 0 or 1 group
        for (_, (items, _p, _m, _s)), results in zip(groups, all_results):
            n_jobs += len(items)
            for item, res in zip(items, results):
                if not res:
                    ok[item] = False
        self._reset()
        secs = _time.monotonic() - t0
        sp.set(jobs=n_jobs, groups=len(groups), ms=round(secs * 1e3, 3))
        if _metrics_sink is not None and n_jobs:
            _metrics_sink(n_jobs, secs)
        return ok

    def _reset(self) -> None:
        self._n_items = 0
        self._invalid_items = set()
        self._groups = {}


def verify_batch(
    triples: Sequence[tuple[PubKey, bytes, bytes]]
) -> list[bool]:
    """One-shot convenience wrapper."""
    bv = BatchVerifier()
    for pub, msg, sig in triples:
        bv.add(pub, msg, sig)
    return bv.verify_all()
