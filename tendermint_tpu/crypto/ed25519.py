"""Ed25519 keys.

Reference parity: crypto/ed25519/ed25519.go — `PrivKeyEd25519 [64]byte`
(seed || pubkey), `PubKeyEd25519 [32]byte`, address = first 20 bytes of
SHA256(pubkey) (ed25519.go:138), Sign/Verify delegate to a vetted library
(there: golang.org/x/crypto/ed25519; here: the `cryptography` package's
OpenSSL-backed implementation for the serial path). The batched path is the
TPU kernel in tendermint_tpu/ops, selected via crypto/batch.py.
"""
from __future__ import annotations

import os

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)

from tendermint_tpu import crypto as _crypto
from tendermint_tpu.crypto import PrivKey, PubKey, sum_truncated

TYPE = "ed25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64  # seed || pubkey, like the reference
SIGNATURE_SIZE = 64
_TAG = 1


class PubKeyEd25519(PubKey):
    TYPE = TYPE

    __slots__ = ("_raw",)

    def __init__(self, raw: bytes) -> None:
        if len(raw) != PUBKEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {PUBKEY_SIZE} bytes")
        self._raw = bytes(raw)

    def address(self) -> bytes:
        return sum_truncated(self._raw)

    def bytes(self) -> bytes:
        return self._raw

    def verify(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        try:
            Ed25519PublicKey.from_public_bytes(self._raw).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False


class PrivKeyEd25519(PrivKey):
    TYPE = TYPE

    __slots__ = ("_raw", "_sk")

    def __init__(self, raw: bytes) -> None:
        if len(raw) != PRIVKEY_SIZE:
            raise ValueError(f"ed25519 privkey must be {PRIVKEY_SIZE} bytes")
        self._raw = bytes(raw)
        self._sk = Ed25519PrivateKey.from_private_bytes(self._raw[:32])

    def bytes(self) -> bytes:
        return self._raw

    def sign(self, msg: bytes) -> bytes:
        return self._sk.sign(msg)

    def pub_key(self) -> PubKeyEd25519:
        return PubKeyEd25519(self._raw[32:])


def gen_priv_key(seed: bytes | None = None) -> PrivKeyEd25519:
    """Reference crypto/ed25519/ed25519.go GenPrivKey (+FromSecret)."""
    if seed is None:
        seed = os.urandom(32)
    elif len(seed) != 32:
        seed = _crypto.sum_sha256(seed)
    sk = Ed25519PrivateKey.from_private_bytes(seed)
    pub = sk.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    return PrivKeyEd25519(seed + pub)


_crypto.register_pubkey_type(TYPE, _TAG, PubKeyEd25519)
