"""Crypto core: key interfaces, hashing, and the batch-verification seam.

Reference parity: crypto/crypto.go:16-33 defines `PubKey{Address, Bytes,
VerifyBytes, Equals}` / `PrivKey{Bytes, Sign, PubKey, Equals}` and tmhash
(SHA256 with a 20-byte truncated form). That one-signature-at-a-time
interface is the exact seam the TPU backend replaces: this package adds a
first-class `BatchVerifier` (crypto/batch.py) with pluggable backends, which
the reference does not have anywhere.

Concrete keys: ed25519 (crypto/ed25519.py), secp256k1 (crypto/secp256k1.py),
k-of-n threshold multisig (crypto/multisig.py).
"""
from __future__ import annotations

import hashlib
from abc import ABC, abstractmethod

ADDRESS_SIZE = 20  # tmhash truncated size (reference crypto/crypto.go:16-20)
HASH_SIZE = 32


def sum_sha256(b: bytes) -> bytes:
    """tmhash.Sum — full 32-byte SHA256 (reference crypto/hash.go)."""
    return hashlib.sha256(b).digest()


def sum_truncated(b: bytes) -> bytes:
    """tmhash.SumTruncated — first 20 bytes of SHA256."""
    return hashlib.sha256(b).digest()[:ADDRESS_SIZE]


class PubKey(ABC):
    """Reference crypto/crypto.go:22-27."""

    TYPE: str = ""

    @abstractmethod
    def address(self) -> bytes: ...

    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def verify(self, msg: bytes, sig: bytes) -> bool: ...

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, PubKey)
            and self.TYPE == other.TYPE
            and self.bytes() == other.bytes()
        )

    def __hash__(self) -> int:
        return hash((self.TYPE, self.bytes()))

    def __repr__(self) -> str:
        return f"PubKey{{{self.TYPE}:{self.bytes().hex()[:16]}…}}"


class PrivKey(ABC):
    """Reference crypto/crypto.go:29-33."""

    TYPE: str = ""

    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abstractmethod
    def pub_key(self) -> PubKey: ...

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, PrivKey)
            and self.TYPE == other.TYPE
            and self.bytes() == other.bytes()
        )

    def __hash__(self) -> int:
        return hash((self.TYPE, self.bytes()))


# --- pubkey type registry --------------------------------------------------
# The reference registers concrete key types with amino names
# ("tendermint/PubKeyEd25519", crypto/ed25519/ed25519.go:21-27). Here the
# registry maps a 1-byte tag + type name to a decoder, used by CBE encoding.

_PUBKEY_TYPES: dict[str, tuple[int, object]] = {}
_PUBKEY_TAGS: dict[int, str] = {}


def register_pubkey_type(type_name: str, tag: int, from_bytes) -> None:
    if type_name in _PUBKEY_TYPES or tag in _PUBKEY_TAGS:
        existing = _PUBKEY_TYPES.get(type_name)
        if existing is not None and existing[0] == tag:
            return  # idempotent re-registration
        raise ValueError(f"pubkey type {type_name}/{tag} already registered")
    _PUBKEY_TYPES[type_name] = (tag, from_bytes)
    _PUBKEY_TAGS[tag] = type_name


def encode_pubkey(pub: PubKey) -> bytes:
    tag, _ = _PUBKEY_TYPES[pub.TYPE]
    from tendermint_tpu.encoding import Writer

    return Writer().u8(tag).bytes(pub.bytes()).build()


def decode_pubkey(data: bytes) -> PubKey:
    from tendermint_tpu.encoding import Reader

    r = Reader(data)
    pub = read_pubkey(r)
    r.expect_done()
    return pub


def read_pubkey(r) -> PubKey:
    tag = r.u8()
    if tag not in _PUBKEY_TAGS:
        from tendermint_tpu.encoding import DecodeError

        raise DecodeError(f"unknown pubkey tag {tag}")
    type_name = _PUBKEY_TAGS[tag]
    _, from_bytes = _PUBKEY_TYPES[type_name]
    return from_bytes(r.bytes())


def pubkey_from_type_and_bytes(type_name: str, raw: bytes) -> PubKey:
    _, from_bytes = _PUBKEY_TYPES[type_name]
    return from_bytes(raw)


# Register the standard key types on import. A host without the
# `cryptography` package still gets the hashing + merkle + ProofOp layer
# (pure hashlib) — the state-sync chunk/proof plumbing and its tests need
# exactly that; anything touching actual keys raises the natural
# ImportError at its own `from tendermint_tpu.crypto import ed25519`
# (the p2p package-lazy-import precedent, docs/p2p_resilience.md).
try:
    from tendermint_tpu.crypto import ed25519 as _ed  # noqa: E402
    from tendermint_tpu.crypto import secp256k1 as _secp  # noqa: E402
    from tendermint_tpu.crypto import multisig as _multisig  # noqa: E402,F401
except ImportError as _e:
    # only the missing `cryptography` package is survivable — any other
    # ImportError (a broken transitive import inside the key modules)
    # must fail HERE, not at the first key decode with "unknown key type"
    if _e.name != "cryptography" and not (_e.name or "").startswith(
        "cryptography."
    ):
        raise

__all__ = [
    "ADDRESS_SIZE",
    "HASH_SIZE",
    "PubKey",
    "PrivKey",
    "sum_sha256",
    "sum_truncated",
    "register_pubkey_type",
    "encode_pubkey",
    "decode_pubkey",
    "read_pubkey",
    "pubkey_from_type_and_bytes",
]
