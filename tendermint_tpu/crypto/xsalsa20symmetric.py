"""XSalsa20-Poly1305 symmetric encryption (pure Python).

Reference parity: crypto/xsalsa20symmetric — secretbox-style
EncryptSymmetric/DecryptSymmetric with a 32-byte key and a random 24-byte
nonce prepended to the ciphertext; used for passphrase-encrypted key
export (with the armor module). The `cryptography` package has no XSalsa20,
so the cipher is implemented here; throughput is irrelevant for key files.
"""
from __future__ import annotations

import os
import struct

from cryptography.hazmat.primitives.poly1305 import Poly1305

NONCE_LEN = 24
KEY_LEN = 32
TAG_LEN = 16


class DecryptError(Exception):
    pass


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _quarter(a, b, c, d):
    b ^= _rotl((a + d) & 0xFFFFFFFF, 7)
    c ^= _rotl((b + a) & 0xFFFFFFFF, 9)
    d ^= _rotl((c + b) & 0xFFFFFFFF, 13)
    a ^= _rotl((d + c) & 0xFFFFFFFF, 18)
    return a, b, c, d


_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)  # "expand 32-byte k"


def _salsa20_rounds(state: list[int], rounds: int = 20) -> list[int]:
    x = list(state)
    for _ in range(rounds // 2):
        # column round
        x[0], x[4], x[8], x[12] = _quarter(x[0], x[4], x[8], x[12])
        x[5], x[9], x[13], x[1] = _quarter(x[5], x[9], x[13], x[1])
        x[10], x[14], x[2], x[6] = _quarter(x[10], x[14], x[2], x[6])
        x[15], x[3], x[7], x[11] = _quarter(x[15], x[3], x[7], x[11])
        # row round
        x[0], x[1], x[2], x[3] = _quarter(x[0], x[1], x[2], x[3])
        x[5], x[6], x[7], x[4] = _quarter(x[5], x[6], x[7], x[4])
        x[10], x[11], x[8], x[9] = _quarter(x[10], x[11], x[8], x[9])
        x[15], x[12], x[13], x[14] = _quarter(x[15], x[12], x[13], x[14])
    return x


def _salsa20_block(key: bytes, nonce16: bytes, counter: int) -> bytes:
    k = struct.unpack("<8I", key)
    n = struct.unpack("<2I", nonce16[:8])
    ctr = (counter & 0xFFFFFFFF, (counter >> 32) & 0xFFFFFFFF)
    state = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        ctr[0], ctr[1], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    x = _salsa20_rounds(state)
    out = [(a + b) & 0xFFFFFFFF for a, b in zip(x, state)]
    return struct.pack("<16I", *out)


def _hsalsa20(key: bytes, nonce16: bytes) -> bytes:
    """Derive a subkey from the first 16 nonce bytes (XSalsa20 extension)."""
    k = struct.unpack("<8I", key)
    n = struct.unpack("<4I", nonce16)
    state = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        n[2], n[3], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    x = _salsa20_rounds(state)
    words = [x[0], x[5], x[10], x[15], x[6], x[7], x[8], x[9]]
    return struct.pack("<8I", *words)


def _xsalsa20_stream(key: bytes, nonce24: bytes, length: int, first_block_skip: int = 0) -> bytes:
    subkey = _hsalsa20(key, nonce24[:16])
    out = bytearray()
    counter = 0
    total = length + first_block_skip
    while len(out) < total:
        out.extend(_salsa20_block(subkey, nonce24[16:] + b"\x00" * 8, counter))
        counter += 1
    return bytes(out[first_block_skip:total])


def encrypt_symmetric(plaintext: bytes, key: bytes, nonce: bytes | None = None) -> bytes:
    """nonce(24) || tag(16) || ciphertext — secretbox layout with the nonce
    prepended (reference EncryptSymmetric)."""
    if len(key) != KEY_LEN:
        raise ValueError("key must be 32 bytes")
    nonce = nonce if nonce is not None else os.urandom(NONCE_LEN)
    if len(nonce) != NONCE_LEN:
        raise ValueError("nonce must be 24 bytes")
    stream = _xsalsa20_stream(key, nonce, 32 + len(plaintext))
    poly_key, ct_stream = stream[:32], stream[32:]
    ct = bytes(p ^ s for p, s in zip(plaintext, ct_stream))
    p = Poly1305(poly_key)
    p.update(ct)
    tag = p.finalize()
    return nonce + tag + ct


def decrypt_symmetric(box: bytes, key: bytes) -> bytes:
    if len(key) != KEY_LEN:
        raise ValueError("key must be 32 bytes")
    if len(box) < NONCE_LEN + TAG_LEN:
        raise DecryptError("ciphertext too short")
    nonce, tag, ct = box[:NONCE_LEN], box[NONCE_LEN:NONCE_LEN + TAG_LEN], box[NONCE_LEN + TAG_LEN:]
    stream = _xsalsa20_stream(key, nonce, 32 + len(ct))
    poly_key, ct_stream = stream[:32], stream[32:]
    p = Poly1305(poly_key)
    p.update(ct)
    try:
        p.verify(tag)
    except Exception:
        raise DecryptError("authentication failed")
    return bytes(c ^ s for c, s in zip(ct, ct_stream))
