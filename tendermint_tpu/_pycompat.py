"""Python 3.10 compatibility backports.

The codebase is written against the Python 3.11 asyncio idiom —
``async with asyncio.timeout(t): ...`` — across the RPC server/client,
tools, proc-testnet scenarios, and the test suite, but pyproject declares
``requires-python = ">=3.10"`` and some containers run 3.10, where
``asyncio.timeout`` does not exist: every node-level call site died with
``AttributeError: module 'asyncio' has no attribute 'timeout'``.

``install()`` (called from the package ``__init__``) backports it onto
the asyncio module so every call site — library code, tests, and
subprocess-spawned nodes — keeps the 3.11 spelling. On 3.11+ it is a
no-op.

The backport raises ``_CompatTimeoutError``, which subclasses BOTH the
builtin ``TimeoutError`` and ``asyncio.TimeoutError``: on 3.10 those are
disjoint types (unified only in 3.11), and call sites here catch
sometimes one, sometimes the other.
"""
from __future__ import annotations

import asyncio


class _CompatTimeoutError(TimeoutError, asyncio.TimeoutError):
    pass


class _Timeout:
    """Minimal ``asyncio.timeout`` semantics: cancel the enclosing task
    when the deadline passes, convert that cancellation into a
    TimeoutError at the context boundary.

    External-cancel discipline (the uncancel()-counting behaviour of the
    real 3.11 implementation, approximated): the deadline callback
    REFUSES to claim expiry when the task already has a cancellation
    pending — an external cancel (service stop) that arrived first
    always propagates as CancelledError, never resurrected into a
    TimeoutError handler. Once expiry IS claimed, the resulting
    CancelledError is converted whether or not it still carries our
    sentinel message: cancellation crossing a task boundary (a timed-out
    body awaiting `gather(...)` or a child task) arrives with empty args
    on 3.10, and must still surface as TimeoutError."""

    _SENTINEL = "tendermint_tpu._pycompat.timeout"

    def __init__(self, delay: float | None) -> None:
        self._delay = delay
        self._expired = False
        self._handle = None
        self._task = None

    async def __aenter__(self) -> "_Timeout":
        if self._delay is not None:
            loop = asyncio.get_running_loop()
            self._task = asyncio.current_task()
            self._handle = loop.call_later(self._delay, self._on_timeout)
        return self

    def _cancel_pending(self) -> bool:
        """True when the task already has a cancellation in flight that
        is NOT ours (3.10 internals: an undelivered `_must_cancel`, or a
        cancelled future the task is awaiting)."""
        t = self._task
        if getattr(t, "_must_cancel", False):
            return True
        fw = getattr(t, "_fut_waiter", None)
        return fw is not None and fw.cancelled()

    def _on_timeout(self) -> None:
        if self._task is None or self._task.done() or self._cancel_pending():
            return
        self._expired = True
        self._task.cancel(self._SENTINEL)

    async def __aexit__(self, exc_type, exc, tb):
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if (
            self._expired
            and exc_type is asyncio.CancelledError
            and (not exc.args or exc.args[0] == self._SENTINEL)
        ):
            raise _CompatTimeoutError() from exc
        return False


def _timeout(delay: float | None) -> _Timeout:
    return _Timeout(delay)


def install() -> None:
    if not hasattr(asyncio, "timeout"):
        asyncio.timeout = _timeout  # type: ignore[attr-defined]
