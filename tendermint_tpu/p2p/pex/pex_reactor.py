"""PEX reactor: peer address gossip + outbound connection maintenance.

Reference parity: p2p/pex/pex_reactor.go — channel 0x00; inbound peers may
send one address request per interval (rate limited); `ensure_peers` routine
dials from the address book (biased toward vetted addresses) while below the
outbound target; seed mode answers requests then disconnects.
"""
from __future__ import annotations

import asyncio
import time

from tendermint_tpu.behaviour import PeerBehaviour
from tendermint_tpu.encoding import DecodeError, Reader, Writer
from tendermint_tpu.p2p.base_reactor import BaseReactor, ChannelDescriptor
from tendermint_tpu.p2p.netaddress import AddressError, NetAddress
from tendermint_tpu.p2p.pex.addrbook import AddrBook

PEX_CHANNEL = 0x00

_MSG_REQUEST = 0
_MSG_ADDRS = 1

ENSURE_PEERS_INTERVAL = 30.0
MIN_REQUEST_INTERVAL = 60.0  # per-peer inbound request rate limit
MAX_ADDRS_PER_MSG = 100


def encode_request() -> bytes:
    return Writer().u8(_MSG_REQUEST).build()


def encode_addrs(addrs: list[NetAddress]) -> bytes:
    w = Writer().u8(_MSG_ADDRS).u32(len(addrs))
    for a in addrs:
        w.str(str(a))
    return w.build()


def decode_pex_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == _MSG_REQUEST:
        r.expect_done()
        return ("request", None)
    if tag == _MSG_ADDRS:
        n = r.u32()
        if n > MAX_ADDRS_PER_MSG:
            raise DecodeError(f"too many addrs ({n})")
        addrs = [NetAddress.parse(r.str()) for _ in range(n)]
        r.expect_done()
        return ("addrs", addrs)
    raise DecodeError(f"unknown pex message tag {tag}")


class PexReactor(BaseReactor):
    traffic_family = "pex"

    def __init__(
        self,
        book: AddrBook,
        seed_mode: bool = False,
        ensure_interval: float = ENSURE_PEERS_INTERVAL,
    ) -> None:
        super().__init__(name="PEX")
        self.book = book
        self.seed_mode = seed_mode
        self.ensure_interval = ensure_interval
        self._last_request_from: dict[str, float] = {}
        self._requested_of: set[str] = set()

    def get_channels(self):
        return [ChannelDescriptor(id=PEX_CHANNEL, priority=1,
                                  recv_message_capacity=64 * 1024)]

    def classify(self, ch_id: int, msg: bytes) -> str:
        if msg:
            if msg[0] == _MSG_REQUEST:
                return "request"
            if msg[0] == _MSG_ADDRS:
                return "addrs"
        return "other"

    async def on_start(self) -> None:
        self.spawn(self._ensure_peers_routine(), "pex-ensure")

    async def on_stop(self) -> None:
        self.book.save()

    async def add_peer(self, peer) -> None:
        if peer.socket_addr is not None and peer.outbound:
            self.book.mark_good(peer.socket_addr)
        if peer.outbound:
            # inbound peers could lie about being short on addresses; only
            # ask peers we chose to dial (reference pex_reactor.go AddPeer)
            await self._request_addrs(peer)
        elif peer.socket_addr is not None and peer.socket_addr.id:
            self.book.add_address(
                peer.socket_addr, src=peer.socket_addr, src_id=peer.id
            )

    async def remove_peer(self, peer, reason) -> None:
        self._last_request_from.pop(peer.id, None)
        self._requested_of.discard(peer.id)

    async def _request_addrs(self, peer) -> None:
        if peer.id in self._requested_of:
            return
        self._requested_of.add(peer.id)
        await peer.send(PEX_CHANNEL, encode_request())

    async def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        try:
            kind, payload = decode_pex_message(msg_bytes)
        except (DecodeError, AddressError) as e:
            await self.report(peer, PeerBehaviour.bad_message(peer.id, f"pex: {e}"))
            return
        if kind == "request":
            now = time.monotonic()
            last = self._last_request_from.get(peer.id)
            if last is not None and now - last < MIN_REQUEST_INTERVAL:
                await self.report(
                    peer,
                    PeerBehaviour.message_out_of_order(
                        peer.id, "pex request rate exceeded"
                    ),
                )
                return
            self._last_request_from[peer.id] = now
            # seeds answer crawls with a controlled new/old mix (reference
            # pex_reactor.go SendAddrs + GetSelectionWithBias)
            sel = (
                self.book.get_selection_with_bias(30)
                if self.seed_mode
                else self.book.get_selection()
            )
            await peer.send(PEX_CHANNEL, encode_addrs(sel))
            if self.seed_mode:
                await self.switch.stop_peer_gracefully(peer)
        else:  # addrs
            if peer.id not in self._requested_of:
                # unsolicited addrs are dropped whole: everything in the
                # message was wire waste
                self.note_redundant(peer, "addrs")
                await self.report(
                    peer,
                    PeerBehaviour.message_out_of_order(
                        peer.id, "unsolicited pex addrs"
                    ),
                )
                return
            self._requested_of.discard(peer.id)
            for addr in payload:
                # src = the peer that told us: keys the hashed-bucket
                # placement so one source group maps to few buckets
                self.book.add_address(addr, src=peer.socket_addr, src_id=peer.id)

    async def _ensure_peers_routine(self) -> None:
        while True:
            try:
                await self._ensure_peers()
            except Exception as e:  # keep the maintenance loop alive
                self.logger.debug("ensure_peers: %s", e)
            await asyncio.sleep(self.ensure_interval)

    async def _ensure_peers(self) -> None:
        out, _ = self.switch.num_peers()
        need = self.switch.max_outbound_peers - out
        if need <= 0:
            return
        connected = {p.id for p in self.switch.peers.list()} | {self.switch.node_id()}
        to_dial = []
        for _ in range(need * 2):
            addr = self.book.pick_address(exclude=connected)
            if addr is None:
                break
            connected.add(addr.id)
            to_dial.append(addr)
            if len(to_dial) >= need:
                break
        if to_dial:
            await self.switch.dial_peers_async(to_dial)
