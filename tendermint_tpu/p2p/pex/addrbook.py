"""Address book: hashed-bucket peer address manager.

Reference parity: p2p/pex/addrbook.go (btcd lineage) — addresses live in
256 "new" buckets (heard about) and 64 "old" buckets (vetted: we connected
at least once), 64 entries each. Placement is keyed by a per-book random
key and the /16 network group:

  new bucket = H(key + group(addr) + group(src)) % 32 -> H(key + group(src)
               + that) % 256   (addrbook.go:731 calcNewBucket)
  old bucket = H(key + addr) % 4 -> H(key + group(addr) + that) % 64
               (addrbook.go:750 calcOldBucket)

so one source group can influence at most 32 of the 256 new buckets and an
address group at most 4 of the 64 old buckets — the eclipse-resistance
property a flat dict cannot give. A new address may be added from up to 4
sources (maxNewBucketsPerAddress, probabilistically decayed); full new
buckets expire bad entries then the oldest (expireNew, addrbook.go:674);
promoting into a full old bucket demotes that bucket's oldest back to a new
bucket (moveToOld, addrbook.go:692).
"""
from __future__ import annotations

import hashlib
import json
import math
import os
import random
import time
from dataclasses import dataclass, field

from tendermint_tpu.p2p.bans import BanTable
from tendermint_tpu.p2p.netaddress import NetAddress

# reference p2p/pex/params.go
NEW_BUCKET_COUNT = 256
NEW_BUCKET_SIZE = 64
NEW_BUCKETS_PER_GROUP = 32
OLD_BUCKET_COUNT = 64
OLD_BUCKET_SIZE = 64
OLD_BUCKETS_PER_GROUP = 4
MAX_NEW_BUCKETS_PER_ADDRESS = 4
NEED_ADDRESS_THRESHOLD = 1000
GET_SELECTION_PERCENT = 23
MIN_GET_SELECTION = 32
MAX_GET_SELECTION = 250
NUM_MISSING_DAYS = 7
NUM_RETRIES = 3
MAX_FAILURES = 10
MIN_BAD_DAYS = 7

BUCKET_TYPE_NEW = 1
BUCKET_TYPE_OLD = 2


def _double_sha256(data: bytes) -> bytes:
    return hashlib.sha256(hashlib.sha256(data).digest()).digest()


@dataclass
class _KnownAddress:
    addr: NetAddress
    src: NetAddress | None = None
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket_type: int = BUCKET_TYPE_NEW
    buckets: list = field(default_factory=list)

    @property
    def is_old(self) -> bool:
        return self.bucket_type == BUCKET_TYPE_OLD

    def is_bad(self, now: float) -> bool:
        """Reference known_address.go:99 isBad.

        `now` is REQUIRED and must come from the owning book's clock
        (``book.now()``): timestamps here live on that injectable
        monotonic clock, so a defaulted ``time.monotonic()`` would
        silently compare against the wrong timeline whenever a fake
        clock is injected.
        """
        if self.is_old:
            return False
        if self.last_attempt == 0.0:
            # never attempted (epoch sentinel): same verdict the
            # wall-clock epoch-0 value used to get ("not seen in a week").
            # Negative values are fine — a restored entry older than the
            # process's monotonic origin — and use the normal math below.
            return True
        if self.last_attempt > now - 60:
            return False  # attempted in the last minute
        if self.last_attempt < now - NUM_MISSING_DAYS * 86400:
            return True  # not seen in a week
        if self.last_success == 0.0 and self.attempts >= NUM_RETRIES:
            return True  # never succeeded
        if (
            self.last_success < now - MIN_BAD_DAYS * 86400
            and self.attempts >= MAX_FAILURES
        ):
            return True
        return False

    def to_json(self) -> dict:
        return {
            "addr": str(self.addr),
            "src": str(self.src) if self.src else "",
            "attempts": self.attempts,
            "last_attempt": self.last_attempt,
            "last_success": self.last_success,
            "bucket_type": self.bucket_type,
            "buckets": self.buckets,
        }

    @classmethod
    def from_json(cls, d: dict) -> "_KnownAddress":
        return cls(
            addr=NetAddress.parse(d["addr"]),
            src=NetAddress.parse(d["src"]) if d.get("src") else None,
            attempts=d.get("attempts", 0),
            last_attempt=d.get("last_attempt", 0.0),
            last_success=d.get("last_success", 0.0),
            bucket_type=d.get("bucket_type", BUCKET_TYPE_NEW),
            buckets=list(d.get("buckets", [])),
        )


class AddrBook:
    """In-memory timestamps (`last_attempt`/`last_success`) live on an
    injectable MONOTONIC clock: backoff and staleness math must not
    jump when NTP slews the wall clock (tmlint TM201 class of bug).
    Wall time appears only in the persisted JSON, where it is both
    human-readable and meaningful across restarts; save/load convert
    between the two clocks, preserving ages."""

    def __init__(self, file_path: str | None = None, our_ids: set[str] | None = None,
                 routability_strict: bool = False,
                 clock=None, wall=None):
        self.file_path = file_path
        self.our_ids = our_ids or set()
        self.routability_strict = routability_strict
        self._clock = clock or time.monotonic  # interval/backoff math
        self._wall = wall or time.time  # persisted, human-readable fields
        self.key = os.urandom(12).hex()  # bucket-placement key
        self._lookup: dict[str, _KnownAddress] = {}  # node_id -> entry
        self._new: list[dict[str, _KnownAddress]] = [
            {} for _ in range(NEW_BUCKET_COUNT)
        ]
        self._old: list[dict[str, _KnownAddress]] = [
            {} for _ in range(OLD_BUCKET_COUNT)
        ]
        self.n_new = 0
        self.n_old = 0
        # behaviour-scored bans (docs/p2p_resilience.md, p2p/bans.py):
        # kept OUTSIDE the buckets (a ban survives the entry being
        # evicted) and persisted in the book's JSON with wall-clock
        # expiries, so a banned garbage peer stays banned — with its
        # REMAINING time — across a restart.
        self._ban_table = BanTable(clock=self._clock, our_ids=self.our_ids)
        if file_path and os.path.exists(file_path):
            self.load(file_path)

    def __len__(self) -> int:
        return self.n_new + self.n_old

    # --- clocks -----------------------------------------------------------

    def now(self) -> float:
        """Current time on the book's monotonic clock (what every
        `last_attempt`/`last_success` in memory is compared against)."""
        return self._clock()

    def _mono_to_wall(self, t: float) -> float:
        # only exact 0.0 is the "never" sentinel — NEGATIVE monotonic
        # values are legitimate (restored entries older than this
        # process's clock origin) and must keep their age on save
        return 0.0 if t == 0.0 else self._wall() - (self._clock() - t)

    def _wall_to_mono(self, t: float) -> float:
        # clamp: a stored timestamp "from the future" (clock skew across
        # restarts) must not become newer than now on the monotonic clock
        return 0.0 if t == 0.0 else self._clock() - max(0.0, self._wall() - t)

    # --- bucket placement (reference addrbook.go:731-767) ----------------

    def group_key(self, addr: NetAddress) -> str:
        """/16 network group for IPv4, host otherwise (addrbook.go:771;
        "local"/"unroutable" classes only matter with routability_strict)."""
        parts = addr.host.split(".")
        if len(parts) == 4 and all(p.isdigit() and int(p) < 256 for p in parts):
            if self.routability_strict and (
                parts[0] == "127" or parts[0] == "10" or addr.host == "0.0.0.0"
            ):
                return "local"
            return f"{parts[0]}.{parts[1]}"
        return addr.host

    def _calc_new_bucket(self, addr: NetAddress, src: NetAddress | None) -> int:
        key = self.key.encode()
        src_group = self.group_key(src if src is not None else addr).encode()
        h1 = _double_sha256(key + self.group_key(addr).encode() + src_group)
        h64 = int.from_bytes(h1[:8], "big") % NEW_BUCKETS_PER_GROUP
        h2 = _double_sha256(key + src_group + h64.to_bytes(8, "big"))
        return int.from_bytes(h2[:8], "big") % NEW_BUCKET_COUNT

    def _calc_old_bucket(self, addr: NetAddress) -> int:
        key = self.key.encode()
        h1 = _double_sha256(key + str(addr).encode())
        h64 = int.from_bytes(h1[:8], "big") % OLD_BUCKETS_PER_GROUP
        h2 = _double_sha256(
            key + self.group_key(addr).encode() + h64.to_bytes(8, "big")
        )
        return int.from_bytes(h2[:8], "big") % OLD_BUCKET_COUNT

    # --- bucket mutation --------------------------------------------------

    def _add_to_new_bucket(self, ka: _KnownAddress, idx: int) -> None:
        """Reference addrbook.go:469."""
        if ka.is_old:
            return
        if idx in ka.buckets:
            return
        if len(self._new[idx]) >= NEW_BUCKET_SIZE:
            self._expire_new(idx)
        if not ka.buckets:
            self.n_new += 1
            self._lookup[ka.addr.id] = ka
        ka.buckets.append(idx)
        self._new[idx][ka.addr.id] = ka

    def _add_to_old_bucket(self, ka: _KnownAddress, idx: int) -> bool:
        """Reference addrbook.go:502 — False when the bucket is full."""
        if ka.buckets:
            return False
        if len(self._old[idx]) >= OLD_BUCKET_SIZE:
            return False
        self._old[idx][ka.addr.id] = ka
        ka.buckets = [idx]
        self.n_old += 1
        self._lookup[ka.addr.id] = ka
        return True

    def _remove_from_bucket(self, ka: _KnownAddress, idx: int) -> None:
        bucket = self._old[idx] if ka.is_old else self._new[idx]
        bucket.pop(ka.addr.id, None)
        if idx in ka.buckets:
            ka.buckets.remove(idx)
        if not ka.buckets:
            self._lookup.pop(ka.addr.id, None)
            if ka.is_old:
                self.n_old -= 1
            else:
                self.n_new -= 1

    def _remove_from_all_buckets(self, ka: _KnownAddress) -> None:
        for idx in list(ka.buckets):
            self._remove_from_bucket(ka, idx)

    def _pick_oldest(self, buckets, idx: int) -> _KnownAddress | None:
        bucket = buckets[idx]
        oldest = None
        for ka in bucket.values():
            if oldest is None or ka.last_attempt < oldest.last_attempt:
                oldest = ka
        return oldest

    def _expire_new(self, idx: int) -> None:
        """Reference addrbook.go:674 — drop a bad entry, else the oldest."""
        for ka in list(self._new[idx].values()):
            if ka.is_bad(self._clock()):
                self._remove_from_bucket(ka, idx)
                return
        oldest = self._pick_oldest(self._new, idx)
        if oldest is not None:
            self._remove_from_bucket(oldest, idx)

    def _move_to_old(self, ka: _KnownAddress) -> None:
        """Reference addrbook.go:692 — promote; a full old bucket demotes
        its oldest entry back to a new bucket."""
        if ka.is_old:
            return
        self._remove_from_all_buckets(ka)
        ka.bucket_type = BUCKET_TYPE_OLD
        idx = self._calc_old_bucket(ka.addr)
        if not self._add_to_old_bucket(ka, idx):
            oldest = self._pick_oldest(self._old, idx)
            if oldest is not None:
                self._remove_from_bucket(oldest, idx)
                oldest.bucket_type = BUCKET_TYPE_NEW
                oldest.buckets = []
                self._add_to_new_bucket(
                    oldest, self._calc_new_bucket(oldest.addr, oldest.src)
                )
            self._add_to_old_bucket(ka, idx)

    # --- public API -------------------------------------------------------

    def add_address(
        self, addr: NetAddress, src: NetAddress | None = None, src_id: str = ""
    ) -> bool:
        """Record a heard-about address (reference addrbook.go:587
        addAddress). Returns True if the book gained a new entry."""
        if not addr.id or addr.id in self.our_ids or addr.port == 0:
            return False
        if src is None and src_id:
            src = NetAddress(src_id, addr.host, addr.port)
        ka = self._lookup.get(addr.id)
        if ka is not None:
            if ka.is_old:
                return False
            # a reappearing unvetted node may have moved: refresh endpoint
            if ka.addr != addr:
                ka.addr = addr
            # already in max new buckets, or probabilistic decay
            if len(ka.buckets) >= MAX_NEW_BUCKETS_PER_ADDRESS:
                return False
            if random.randrange(2 * len(ka.buckets)) != 0:
                return False
        else:
            ka = _KnownAddress(addr=addr, src=src, last_attempt=self._clock())
        before = addr.id in self._lookup
        # bucket keyed by THIS call's reporting source (addrbook.go:640):
        # each new reporter can land the address in a different new bucket,
        # which is where the multi-source redundancy comes from
        self._add_to_new_bucket(ka, self._calc_new_bucket(addr, src))
        return not before

    def remove_address(self, addr: NetAddress) -> None:
        ka = self._lookup.get(addr.id)
        if ka is not None:
            self._remove_from_all_buckets(ka)

    def mark_attempt(self, addr: NetAddress) -> None:
        ka = self._lookup.get(addr.id)
        if ka is not None:
            ka.attempts += 1
            ka.last_attempt = self._clock()

    def mark_good(self, addr: NetAddress) -> None:
        """Successful connection: reset counters and promote to old
        (reference MarkGood -> moveToOld)."""
        ka = self._lookup.get(addr.id)
        if ka is None:
            if not addr.id or addr.id in self.our_ids or addr.port == 0:
                return
            ka = _KnownAddress(addr=addr, last_attempt=self._clock())
            self._add_to_new_bucket(ka, self._calc_new_bucket(addr, None))
        now = self._clock()
        ka.attempts = 0
        ka.last_attempt = now
        ka.last_success = now
        if not ka.is_old:
            self._move_to_old(ka)

    def mark_bad(self, addr: NetAddress) -> None:
        self.remove_address(addr)

    def need_more_addrs(self) -> bool:
        return len(self) < NEED_ADDRESS_THRESHOLD

    def pick_address(self, new_bias_pct: int = 30, exclude: set[str] | None = None
                     ) -> NetAddress | None:
        """Random address to dial: random non-empty bucket, then random
        entry, sqrt-weighted between old and new by the bias (reference
        addrbook.go:249 PickAddress; `exclude` is our addition for the
        dialing loop, handled by restricting to available buckets)."""
        exclude = exclude or set()
        new_bias_pct = max(0, min(100, new_bias_pct))
        now = self._clock()
        # buckets that still contain a non-excluded candidate
        avail_new: dict[int, list] = {}
        avail_old: dict[int, list] = {}
        n_new_avail = n_old_avail = 0
        for ka in self._lookup.values():
            if ka.addr.id in exclude or self.is_banned(ka.addr.id, now):
                continue
            tgt = avail_old if ka.is_old else avail_new
            tgt.setdefault(ka.buckets[0] if ka.buckets else 0, []).append(ka)
            if ka.is_old:
                n_old_avail += 1
            else:
                n_new_avail += 1
        if n_new_avail + n_old_avail == 0:
            return None
        old_cor = math.sqrt(n_old_avail) * (100.0 - new_bias_pct)
        new_cor = math.sqrt(n_new_avail) * new_bias_pct
        pick_old = (new_cor + old_cor) * random.random() < old_cor
        if pick_old and not avail_old:
            pick_old = False
        if not pick_old and not avail_new:
            pick_old = True
        buckets = avail_old if pick_old else avail_new
        bucket = random.choice(list(buckets.values()))
        return random.choice(bucket).addr

    def get_selection(self, max_n: int = MAX_GET_SELECTION) -> list[NetAddress]:
        """Random subset for a PEX response (reference GetSelection:
        23% of the book, clamped to [32, 250])."""
        size = len(self)
        if size == 0:
            return []
        n = max(min(MIN_GET_SELECTION, size), size * GET_SELECTION_PERCENT // 100)
        n = min(n, max_n, MAX_GET_SELECTION)
        now = self._clock()
        # banned addresses are not vouched for to other peers
        addrs = [
            ka.addr for ka in self._lookup.values()
            if not self.is_banned(ka.addr.id, now)
        ]
        random.shuffle(addrs)
        return addrs[:n]

    def get_selection_with_bias(self, new_bias_pct: int = 30) -> list[NetAddress]:
        """Reference GetSelectionWithBias (addrbook.go:384) — seed nodes
        answer crawls with a controlled new/old mix."""
        size = len(self)
        if size == 0:
            return []
        new_bias_pct = max(0, min(100, new_bias_pct))
        n = max(min(MIN_GET_SELECTION, size), size * GET_SELECTION_PERCENT // 100)
        n = min(n, MAX_GET_SELECTION)
        required_new = max(n * new_bias_pct // 100, n - self.n_old)
        new_addrs = [
            ka.addr for b in self._new for ka in b.values()
        ]
        old_addrs = [
            ka.addr for b in self._old for ka in b.values()
        ]
        random.shuffle(new_addrs)
        random.shuffle(old_addrs)
        sel = new_addrs[:required_new]
        sel += old_addrs[: n - len(sel)]
        if len(sel) < n:  # not enough old: top up with more new
            sel += new_addrs[required_new : required_new + n - len(sel)]
        return sel

    def is_good(self, addr: NetAddress) -> bool:
        ka = self._lookup.get(addr.id)
        return bool(ka and ka.is_old)

    # --- bans (delegated to the shared BanTable policy) -------------------

    def ban(self, node_id: str, duration: float, reason: str = "") -> float:
        return self._ban_table.ban(node_id, duration, reason)

    def unban(self, node_id: str) -> None:
        self._ban_table.unban(node_id)

    def is_banned(self, node_id: str, now: float | None = None) -> bool:
        return self._ban_table.is_banned(node_id, now)

    def bans(self) -> list[dict]:
        return self._ban_table.bans()

    # --- persistence ------------------------------------------------------

    def save(self, path: str | None = None) -> None:
        path = path or self.file_path
        if not path:
            return
        addrs = []
        for ka in self._lookup.values():
            d = ka.to_json()
            # persisted timestamps are wall time: readable by operators
            # and still meaningful after a restart (monotonic isn't)
            d["last_attempt"] = self._mono_to_wall(ka.last_attempt)
            d["last_success"] = self._mono_to_wall(ka.last_success)
            addrs.append(d)
        # live bans persist with wall-clock expiry (mirrors the timestamp
        # treatment above: readable, and the REMAINING ban time survives
        # a restart instead of resetting or evaporating)
        bans = [
            {
                "id": node_id,
                "expires": self._mono_to_wall(b["expires"]),
                "reason": b["reason"],
                "count": b["count"],
            }
            for node_id, b in self._ban_table.live().items()
        ]
        doc = {"key": self.key, "addrs": addrs, "bans": bans}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def load(self, path: str) -> None:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        self.key = doc.get("key", self.key)
        for b in doc.get("bans", []):
            # a ban expiry is a FUTURE timestamp: _wall_to_mono clamps the
            # future to "now" (right for ages, wrong here) — convert the
            # REMAINING time instead (expired-while-down bans drop out)
            self._ban_table.restore(
                b.get("id", ""),
                float(b.get("expires", 0.0)) - self._wall(),
                b.get("reason", ""),
                int(b.get("count", 1)),
            )
        for d in doc.get("addrs", []):
            ka = _KnownAddress.from_json(d)
            if ka.addr.id in self.our_ids:
                continue
            # stored wall timestamps -> this process's monotonic clock,
            # preserving each entry's age
            ka.last_attempt = self._wall_to_mono(ka.last_attempt)
            ka.last_success = self._wall_to_mono(ka.last_success)
            # stored indices come from an untrusted file: out-of-range ones
            # (corruption, changed bucket-count params) are re-derived
            buckets = [
                idx
                for idx in ka.buckets
                if isinstance(idx, int)
                and 0 <= idx < (OLD_BUCKET_COUNT if ka.is_old else NEW_BUCKET_COUNT)
            ]
            ka.buckets = []
            if ka.is_old:
                restored = False
                for idx in buckets[:1] or [self._calc_old_bucket(ka.addr)]:
                    restored = self._add_to_old_bucket(ka, idx)
                if not restored:
                    ka.bucket_type = BUCKET_TYPE_NEW
                    self._add_to_new_bucket(
                        ka, self._calc_new_bucket(ka.addr, ka.src)
                    )
            else:
                for idx in buckets or [self._calc_new_bucket(ka.addr, ka.src)]:
                    self._add_to_new_bucket(ka, idx)
