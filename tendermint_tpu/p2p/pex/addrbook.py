"""Address book: known peer addresses with quality tracking.

Reference parity: p2p/pex/addrbook.go — file-backed book of peer addresses
split into "new" (heard about) and "old" (vetted: we connected at least once)
buckets, with attempt counting, bias-toward-vetted random picking for dialing,
and random selections for PEX responses. The reference's 256/64 hashed bucket
scheme exists to bound memory and resist address-flooding; here the same
goals are met with two flat dicts capped in size (the eviction policy —
drop the unvetted address with the most failed dial attempts — matches the
reference's spirit without the per-bucket bookkeeping).
"""
from __future__ import annotations

import json
import os
import random
import time
from dataclasses import dataclass, field

from tendermint_tpu.p2p.netaddress import NetAddress

MAX_NEW_ADDRS = 1024
MAX_OLD_ADDRS = 512
GET_SELECTION_MAX = 32


@dataclass
class _KnownAddress:
    addr: NetAddress
    src_id: str = ""
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    is_old: bool = False  # vetted: connected successfully at least once

    def to_json(self) -> dict:
        return {
            "addr": str(self.addr),
            "src_id": self.src_id,
            "attempts": self.attempts,
            "last_attempt": self.last_attempt,
            "last_success": self.last_success,
            "is_old": self.is_old,
        }

    @classmethod
    def from_json(cls, d: dict) -> "_KnownAddress":
        return cls(
            addr=NetAddress.parse(d["addr"]),
            src_id=d.get("src_id", ""),
            attempts=d.get("attempts", 0),
            last_attempt=d.get("last_attempt", 0.0),
            last_success=d.get("last_success", 0.0),
            is_old=d.get("is_old", False),
        )


class AddrBook:
    def __init__(self, file_path: str | None = None, our_ids: set[str] | None = None):
        self._addrs: dict[str, _KnownAddress] = {}  # node_id -> entry
        self.file_path = file_path
        self.our_ids = our_ids or set()
        if file_path and os.path.exists(file_path):
            self.load(file_path)

    def __len__(self) -> int:
        return len(self._addrs)

    def add_address(self, addr: NetAddress, src_id: str = "") -> bool:
        """Record a heard-about address; returns True if newly added."""
        if not addr.id or addr.id in self.our_ids or addr.port == 0:
            return False
        known = self._addrs.get(addr.id)
        if known is not None:
            if not known.is_old:
                known.addr = addr  # refresh endpoint for unvetted entries
            return False
        self._evict_if_full()
        self._addrs[addr.id] = _KnownAddress(addr=addr, src_id=src_id)
        return True

    def _evict_if_full(self) -> None:
        new = [k for k in self._addrs.values() if not k.is_old]
        if len(new) >= MAX_NEW_ADDRS:
            victim = max(new, key=lambda k: k.attempts)
            del self._addrs[victim.addr.id]

    def remove_address(self, addr: NetAddress) -> None:
        self._addrs.pop(addr.id, None)

    def mark_attempt(self, addr: NetAddress) -> None:
        k = self._addrs.get(addr.id)
        if k is not None:
            k.attempts += 1
            k.last_attempt = time.time()

    def mark_good(self, addr: NetAddress) -> None:
        """Successful connection: promote to the vetted ("old") set."""
        k = self._addrs.get(addr.id)
        if k is None:
            if not addr.id or addr.id in self.our_ids or addr.port == 0:
                return
            k = _KnownAddress(addr=addr)
            self._addrs[addr.id] = k
        k.attempts = 0
        k.last_success = time.time()
        k.is_old = True
        old = [a for a in self._addrs.values() if a.is_old]
        if len(old) > MAX_OLD_ADDRS:
            victim = min(old, key=lambda a: a.last_success)
            del self._addrs[victim.addr.id]

    def mark_bad(self, addr: NetAddress) -> None:
        self.remove_address(addr)

    def pick_address(self, new_bias_pct: int = 30, exclude: set[str] | None = None
                     ) -> NetAddress | None:
        """Random address to dial; biased toward vetted addresses
        (reference addrbook.go PickAddress: bias is % chance of a new addr)."""
        exclude = exclude or set()
        cands = [k for k in self._addrs.values() if k.addr.id not in exclude]
        if not cands:
            return None
        new = [k for k in cands if not k.is_old]
        old = [k for k in cands if k.is_old]
        pool = new if (not old or (new and random.random() * 100 < new_bias_pct)) else old
        return random.choice(pool).addr if pool else None

    def get_selection(self, max_n: int = GET_SELECTION_MAX) -> list[NetAddress]:
        """Random subset for a PEX response."""
        addrs = [k.addr for k in self._addrs.values()]
        random.shuffle(addrs)
        return addrs[:max_n]

    def is_good(self, addr: NetAddress) -> bool:
        k = self._addrs.get(addr.id)
        return bool(k and k.is_old)

    # --- persistence -----------------------------------------------------

    def save(self, path: str | None = None) -> None:
        path = path or self.file_path
        if not path:
            return
        doc = {"addrs": [k.to_json() for k in self._addrs.values()]}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def load(self, path: str) -> None:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        for d in doc.get("addrs", []):
            k = _KnownAddress.from_json(d)
            if k.addr.id not in self.our_ids:
                self._addrs[k.addr.id] = k
