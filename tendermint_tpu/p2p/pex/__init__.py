"""Peer exchange (PEX) + address book."""
from __future__ import annotations

from tendermint_tpu.p2p.pex.addrbook import AddrBook
from tendermint_tpu.p2p.pex.pex_reactor import PexReactor, PEX_CHANNEL

__all__ = ["AddrBook", "PexReactor", "PEX_CHANNEL"]
