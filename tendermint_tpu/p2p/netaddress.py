"""Network addresses: `id@host:port`.

Reference parity: p2p/netaddress.go — addresses carry the expected node ID so
dialing can authenticate the remote identity after the SecretConnection
handshake.
"""
from __future__ import annotations

from dataclasses import dataclass


class AddressError(Exception):
    pass


@dataclass(frozen=True)
class NetAddress:
    id: str  # hex node ID ("" if unknown)
    host: str
    port: int

    def __str__(self) -> str:
        hp = f"{self.host}:{self.port}"
        return f"{self.id}@{hp}" if self.id else hp

    def dial_string(self) -> str:
        return f"{self.host}:{self.port}"

    @classmethod
    def parse(cls, s: str) -> "NetAddress":
        node_id = ""
        rest = s
        if "@" in s:
            node_id, rest = s.split("@", 1)
            node_id = node_id.lower()
            if len(node_id) != 40 or any(c not in "0123456789abcdef" for c in node_id):
                raise AddressError(f"bad node id in address {s!r}")
        if ":" not in rest:
            raise AddressError(f"missing port in address {s!r}")
        host, port_s = rest.rsplit(":", 1)
        try:
            port = int(port_s)
        except ValueError as e:
            raise AddressError(f"bad port in address {s!r}") from e
        if not (0 <= port <= 65535):
            raise AddressError(f"port out of range in address {s!r}")
        if not host:
            raise AddressError(f"missing host in address {s!r}")
        return cls(node_id, host, port)
