"""Transport: TCP listen/dial → SecretConnection upgrade → NodeInfo handshake.

Reference parity: p2p/transport.go:125 (MultiplexTransport) — accept and dial
produce authenticated, version-checked connections; filters reject duplicate
or unwanted peers before the Switch sees them (transport.go:82).
"""
from __future__ import annotations

import asyncio

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn.secret_connection import HandshakeError, SecretConnection
from tendermint_tpu.p2p.key import NodeKey, node_id_from_pubkey
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.node_info import NodeInfo, NodeInfoError

HANDSHAKE_TIMEOUT = 20.0


class TransportError(Exception):
    pass


class RejectedError(TransportError):
    """Peer failed authentication/compatibility/filter checks."""


class Transport(BaseService):
    """Owns the listener; produces (SecretConnection, NodeInfo, NetAddress)
    triples through an accept queue."""

    def __init__(
        self,
        node_key: NodeKey,
        node_info: NodeInfo,
        conn_filters=None,  # [async (host) -> None or raise RejectedError]
        handshake_timeout: float = HANDSHAKE_TIMEOUT,
    ) -> None:
        super().__init__(name="Transport")
        self.node_key = node_key
        self.node_info = node_info
        self.conn_filters = conn_filters or []
        self.handshake_timeout = handshake_timeout
        self._server: asyncio.base_events.Server | None = None
        self._accepted: asyncio.Queue = asyncio.Queue(32)
        self.listen_addr: NetAddress | None = None

    async def listen(self, addr: NetAddress) -> None:
        if not self._started:
            await self.start()  # ensure stop() reaches on_stop and closes us
        self._server = await asyncio.start_server(
            self._handle_inbound, addr.host, addr.port
        )
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        self.listen_addr = NetAddress(self.node_key.id(), host, port)
        # Advertise the actual bound port (addr.port may have been 0).
        self.node_info.listen_addr = f"{host}:{port}"
        self.logger.info("listening on %s", self.listen_addr)

    async def _handle_inbound(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peerhost = writer.get_extra_info("peername")
        try:
            for f in self.conn_filters:
                await f(peerhost[0] if peerhost else "")
            conn, ni = await asyncio.wait_for(
                self._upgrade(reader, writer, expected_id=""),
                self.handshake_timeout,
            )
        except Exception as e:
            self.logger.debug("inbound rejected from %s: %s", peerhost, e)
            writer.close()
            return
        # Dialable address for the peer: its socket IP + its self-advertised
        # listen port (the ephemeral source port is useless for dialing;
        # reference p2p uses NodeInfo.ListenAddr the same way). Port 0 means
        # "not dialable" and is rejected by the addr book.
        port = 0
        try:
            port = NetAddress.parse(f"{ni.node_id}@{ni.listen_addr}").port
        except Exception:
            pass
        addr = NetAddress(ni.node_id, peerhost[0] if peerhost else "", port)
        await self._accepted.put((conn, ni, addr))

    async def accept(self):
        """Next authenticated inbound connection: (conn, node_info, addr)."""
        return await self._accepted.get()

    async def dial(self, addr: NetAddress):
        """Dial, upgrade, handshake; returns (conn, node_info)."""
        reader, writer = await asyncio.open_connection(addr.host, addr.port)
        try:
            return await asyncio.wait_for(
                self._upgrade(reader, writer, expected_id=addr.id),
                self.handshake_timeout,
            )
        except Exception:
            writer.close()
            raise

    async def _upgrade(self, reader, writer, expected_id: str):
        try:
            conn = await SecretConnection.make(reader, writer, self.node_key.priv_key)
        except (HandshakeError, asyncio.IncompleteReadError, OSError) as e:
            raise RejectedError(f"secret handshake failed: {e}") from e

        remote_id = node_id_from_pubkey(conn.remote_pubkey)
        if expected_id and remote_id != expected_id:
            raise RejectedError(
                f"dialed {expected_id} but authenticated {remote_id}"
            )
        if remote_id == self.node_key.id():
            raise RejectedError("connected to self")

        # NodeInfo exchange over the encrypted channel.
        await conn.write(self.node_info.encode())
        await conn.drain()
        try:
            ni = NodeInfo.decode(await conn.read_msg())
            ni.validate()
            self.node_info.compatible_with(ni)
        except NodeInfoError as e:
            raise RejectedError(f"incompatible peer: {e}") from e
        if ni.node_id != remote_id:
            raise RejectedError(
                f"node info ID {ni.node_id} != authenticated {remote_id}"
            )
        return conn, ni

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # close any accepted-but-undrained connections
        while not self._accepted.empty():
            conn, _, _ = self._accepted.get_nowait()
            conn.close()
