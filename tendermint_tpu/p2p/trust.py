"""Peer trust metric — EWMA of good/bad events over time intervals.

Reference parity: p2p/trust/metric.go — a sliding-interval metric mixing a
proportional component (fraction of good events in recent history) with a
derivative component, weighted ~0.8/0.2 (reference defaults), plus
p2p/trust/store.go — a persistent store of metric values per peer with
periodic saving.
"""
from __future__ import annotations

import json
import math
import os
import time

# reference metric.go defaults
DEFAULT_PROPORTIONAL_WEIGHT = 0.8
DEFAULT_INTEGRAL_WEIGHT = 0.2
MAX_HISTORY = 16
INTERVAL_SECONDS = 10.0


class TrustMetric:
    """Tracks good/bad events in the current interval; history of interval
    scores feeds the aggregate value in [0, 1] (reference metric.go:14)."""

    def __init__(
        self,
        proportional_weight: float = DEFAULT_PROPORTIONAL_WEIGHT,
        integral_weight: float = DEFAULT_INTEGRAL_WEIGHT,
        max_history: int = MAX_HISTORY,
        interval: float = INTERVAL_SECONDS,
        now=time.monotonic,
    ) -> None:
        self.pw = proportional_weight
        self.iw = integral_weight
        self.max_history = max_history
        self.interval = interval
        self._now = now
        self.good = 0.0
        self.bad = 0.0
        self.history: list[float] = []
        self._interval_start = now()
        self.paused = False
        # lifetime accumulators (this process): the ban decision requires
        # a minimum total_bad so one unlucky frame can tank the SCORE
        # without triggering a ban (docs/p2p_resilience.md)
        self.total_good = 0.0
        self.total_bad = 0.0

    def good_event(self, weight: float = 1.0) -> None:
        self._tick()
        self.paused = False
        self.good += weight
        self.total_good += weight

    def bad_event(self, weight: float = 1.0) -> None:
        self._tick()
        self.paused = False
        self.bad += weight
        self.total_bad += weight

    def pause(self) -> None:
        """Stop counting elapsed empty intervals against the peer
        (reference metric.go Pause)."""
        self.paused = True

    def _tick(self) -> None:
        """Roll over any completed intervals into history."""
        now = self._now()
        while now - self._interval_start >= self.interval:
            self._interval_start += self.interval
            score = self._interval_score()
            self.good = 0.0
            self.bad = 0.0
            if not self.paused or score is not None:
                self.history.append(1.0 if score is None else score)
                del self.history[: -self.max_history]

    def _interval_score(self) -> float | None:
        total = self.good + self.bad
        if total == 0:
            return None  # empty interval: neutral
        return self.good / total

    def _history_value(self) -> float:
        """Recency-weighted mean of history (reference weights via fading)."""
        if not self.history:
            return 1.0
        num = 0.0
        den = 0.0
        for i, v in enumerate(reversed(self.history)):
            w = math.pow(0.8, i)  # newer intervals matter more
            num += w * v
            den += w
        return num / den

    def trust_value(self) -> float:
        """Current trust in [0, 1]."""
        self._tick()
        cur = self._interval_score()
        hist = self._history_value()
        if cur is None:
            cur = hist
        r = self.pw * cur + self.iw * hist
        # derivative penalty: current worse than history hits immediately
        d = cur - hist
        if d < 0:
            r += d * 0.5
        return max(0.0, min(1.0, r))

    def trust_score(self) -> int:
        """0-100 integer (reference TrustScore)."""
        return int(round(self.trust_value() * 100))


class TrustMetricStore:
    """Per-peer metrics with JSON persistence (reference store.go).

    Bounded: a public node sees an open-ended stream of freshly minted
    node ids (handshakes are cheap), so the in-memory map caps at
    `max_metrics` — when full, PAUSED (disconnected) metrics with the
    least interesting reputation (highest trust, least bad history) are
    evicted first; live peers and known offenders are never displaced by
    strangers. Persistence mirrors that: near-perfect scores carry no
    information (a fresh metric starts at 1.0) and are not written, so
    the JSON holds only peers with an actual track record.
    """

    # trust values at/above this are indistinguishable from "never seen"
    UNINFORMATIVE = 0.95

    def __init__(self, file_path: str | None = None,
                 max_metrics: int = 10_000, **metric_kwargs) -> None:
        self.file_path = file_path
        self.max_metrics = max_metrics
        self.metric_kwargs = metric_kwargs
        self.metrics: dict[str, TrustMetric] = {}
        self._saved_scores: dict[str, float] = {}
        if file_path and os.path.exists(file_path):
            try:
                with open(file_path) as f:
                    self._saved_scores = json.load(f)
            except (OSError, ValueError):
                self._saved_scores = {}

    def get_peer_trust_metric(self, peer_id: str) -> TrustMetric:
        tm = self.metrics.get(peer_id)
        if tm is None:
            if len(self.metrics) >= self.max_metrics:
                self._evict_one()
            tm = TrustMetric(**self.metric_kwargs)
            saved = self._saved_scores.get(peer_id)
            if saved is not None:
                tm.history = [saved]
            self.metrics[peer_id] = tm
        return tm

    def _evict_one(self) -> None:
        """Drop the least informative DISCONNECTED metric: highest trust,
        fewest bad events. Falls back to the globally least-bad entry if
        everything is somehow live (cap misconfigured below peer count)."""
        candidates = [
            (tm.total_bad, -tm._history_value(), pid)
            for pid, tm in self.metrics.items()
            if tm.paused
        ] or [
            (tm.total_bad, -tm._history_value(), pid)
            for pid, tm in self.metrics.items()
        ]
        candidates.sort()
        self.metrics.pop(candidates[0][2], None)

    def peer_disconnected(self, peer_id: str) -> None:
        tm = self.metrics.get(peer_id)
        if tm is not None:
            tm.pause()

    def save(self) -> None:
        if not self.file_path:
            return
        scores = {
            pid: v for pid, v in self._saved_scores.items()
            if v < self.UNINFORMATIVE
        }
        for pid, tm in self.metrics.items():
            v = tm.trust_value()
            if v < self.UNINFORMATIVE:
                scores[pid] = v
            else:
                scores.pop(pid, None)  # reputation re-earned: forget
        tmp = self.file_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(scores, f)
        os.replace(tmp, self.file_path)

    def size(self) -> int:
        return len(self.metrics)
