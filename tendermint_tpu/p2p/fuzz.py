"""Network fault injection.

Reference parity: p2p/fuzz.go:14 — FuzzedConnection probabilistically delays
or drops reads/writes, used to shake out reactor assumptions about timing and
delivery. Wraps any SecretConnection-shaped object (write/drain/read_msg/
close).
"""
from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass


@dataclass
class FuzzConfig:
    prob_drop_rw: float = 0.2  # chance a message write is silently dropped
    prob_delay: float = 0.2  # chance an op is delayed
    max_delay: float = 0.3  # seconds
    seed: int | None = None
    # grace period before any fault fires (reference FuzzConnAfter,
    # p2p/test_util.go:232 uses 10s): lets the NodeInfo handshake and
    # reactor init land on a clean link so fuzz exercises steady-state
    # gossip, not connection setup
    start_after: float = 0.0


class FuzzedConnection:
    def __init__(self, conn, config: FuzzConfig | None = None) -> None:
        self._conn = conn
        self.config = config or FuzzConfig()
        self._rng = random.Random(self.config.seed)
        self._armed_at = (
            asyncio.get_event_loop().time() + self.config.start_after
        )

    @property
    def remote_pubkey(self):
        return self._conn.remote_pubkey

    def _active(self) -> bool:
        return asyncio.get_event_loop().time() >= self._armed_at

    async def _maybe_delay(self) -> None:
        if self._active() and self._rng.random() < self.config.prob_delay:
            await asyncio.sleep(self._rng.random() * self.config.max_delay)

    async def write(self, data: bytes) -> None:
        await self._maybe_delay()
        if self._active() and self._rng.random() < self.config.prob_drop_rw:
            return  # dropped on the floor
        await self._conn.write(data)

    async def drain(self) -> None:
        await self._conn.drain()

    async def read_msg(self) -> bytes:
        await self._maybe_delay()
        return await self._conn.read_msg()

    def close(self) -> None:
        self._conn.close()
