"""Per-(peer, channel, message-type) traffic ledger — the wire-efficiency
observatory's accounting core (docs/observability.md "Wire efficiency").

Every message the switch sends or routes is attributed here: the peer it
crossed, the channel byte, the message type (decoded cheaply at the
reactor boundary by each reactor's `classify(ch_id, msg)` tag peek), the
direction, and its payload bytes. Reactors additionally report
*redundant* deliveries — a vote already counted, a block part already
held, a tx already in the dedup cache, a duplicate snapshot chunk — so
gossip amplification (delivered ÷ useful) is measurable per fleet.

The ledger is per-Switch (never process-global): in-process meshes and
benches run several switches on one loop, and a shared ledger would
blend their flows. Every mutation stamps a strictly-increasing `seq`
from one counter, so `snapshot(since_seq)` returns only the series that
changed after a cursor — the recorder-style incremental-read contract
the `debug_traffic` RPC route and the fleet collector ride.
"""
from __future__ import annotations

import itertools


class TrafficLedger:
    """Cumulative message/byte counters keyed
    (peer_id, channel, type, direction) plus redundant-delivery counters
    keyed (peer_id, reactor, kind). Single-threaded by construction (all
    taps run on the node's event loop)."""

    def __init__(self) -> None:
        self._seq = itertools.count(1)
        self.last_seq = 0
        # (peer_id, ch_id, mtype, direction) -> [msgs, bytes, seq]
        self._series: dict[tuple[str, int, str, str], list] = {}
        # (peer_id, reactor, kind) -> [count, seq]
        self._redundant: dict[tuple[str, str, str], list] = {}

    def note_msg(self, peer_id: str, ch_id: int, mtype: str,
                 direction: str, nbytes: int) -> None:
        """Attribute one whole message (chunked or not — the caller taps
        at the message boundary, so a multi-packet message counts once)."""
        seq = next(self._seq)
        self.last_seq = seq
        row = self._series.get((peer_id, ch_id, mtype, direction))
        if row is None:
            self._series[(peer_id, ch_id, mtype, direction)] = [1, nbytes, seq]
        else:
            row[0] += 1
            row[1] += nbytes
            row[2] = seq

    def note_redundant(self, peer_id: str, reactor: str, kind: str,
                       n: int = 1) -> None:
        seq = next(self._seq)
        self.last_seq = seq
        row = self._redundant.get((peer_id, reactor, kind))
        if row is None:
            self._redundant[(peer_id, reactor, kind)] = [n, seq]
        else:
            row[0] += n
            row[1] = seq

    def snapshot(self, since_seq: int = 0) -> dict:
        """Per-peer cumulative snapshots of every series that changed
        after `since_seq` (0 = everything). Values are cumulative, not
        deltas — a reader that missed polls still converges by replacing
        each (channel, type, dir) row with the newest one it sees."""
        peers: dict[str, dict] = {}

        def peer_entry(pid: str) -> dict:
            return peers.setdefault(pid, {"series": [], "redundant": []})

        for (pid, ch_id, mtype, direction), row in self._series.items():
            if row[2] <= since_seq:
                continue
            peer_entry(pid)["series"].append({
                "channel": ch_id, "type": mtype, "dir": direction,
                "msgs": row[0], "bytes": row[1], "seq": row[2],
            })
        for (pid, reactor, kind), row in self._redundant.items():
            if row[1] <= since_seq:
                continue
            peer_entry(pid)["redundant"].append({
                "reactor": reactor, "kind": kind,
                "count": row[0], "seq": row[1],
            })
        return {"seq": self.last_seq, "peers": peers}

    def totals(self) -> dict:
        """Whole-ledger rollup: per-direction msgs/bytes and the summed
        redundant count — the cheap health view."""
        out = {
            "sent_msgs": 0, "sent_bytes": 0,
            "recv_msgs": 0, "recv_bytes": 0,
            "redundant": 0,
        }
        for (_pid, _ch, _mt, direction), row in self._series.items():
            if direction == "sent":
                out["sent_msgs"] += row[0]
                out["sent_bytes"] += row[1]
            else:
                out["recv_msgs"] += row[0]
                out["recv_bytes"] += row[1]
        for row in self._redundant.values():
            out["redundant"] += row[0]
        return out
