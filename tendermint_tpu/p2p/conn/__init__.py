"""Connection substrate: SecretConnection (authenticated encryption) and
MConnection (channel multiplexing) — reference p2p/conn/.

Lazy exports (PEP 562, like the p2p package itself): MConnection is pure
asyncio, and importing it must not drag the `cryptography`-backed
SecretConnection in on hosts without the crypto package.
"""
from __future__ import annotations

import importlib

_EXPORTS = {
    "SecretConnection": "tendermint_tpu.p2p.conn.secret_connection",
    "MConnection": "tendermint_tpu.p2p.conn.connection",
    "ChannelStatus": "tendermint_tpu.p2p.conn.connection",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)
