"""Connection substrate: SecretConnection (authenticated encryption) and
MConnection (channel multiplexing) — reference p2p/conn/."""
from __future__ import annotations

from tendermint_tpu.p2p.conn.secret_connection import SecretConnection
from tendermint_tpu.p2p.conn.connection import MConnection, ChannelStatus

__all__ = ["SecretConnection", "MConnection", "ChannelStatus"]
