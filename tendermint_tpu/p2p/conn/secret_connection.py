"""SecretConnection: authenticated encryption for peer links.

Reference parity: p2p/conn/secret_connection.go:49 — Station-to-Station
protocol: X25519 ephemeral Diffie-Hellman (:253,381), HKDF key derivation
(:346), ChaCha20-Poly1305 AEAD framing, and an ed25519 signature over the
derived challenge authenticating each peer's long-lived node key (:405,419).
Low-order DH result rejection (:335) is handled by the `cryptography`
library, which raises on an all-zero shared secret.

Wire format: 32-byte ephemeral pubkeys in the clear, then fixed-size sealed
frames: plaintext = u32 BE payload length + payload, zero-padded to
DATA_MAX_SIZE + 4; ciphertext = plaintext + 16-byte Poly1305 tag. Fixed-size
frames avoid leaking message lengths (same rationale as the reference's
1044-byte frames). Nonces are 96-bit little-endian counters, one counter per
direction.
"""
from __future__ import annotations

import asyncio
import struct

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.encoding import Reader, Writer

DATA_MAX_SIZE = 1024
_FRAME_SIZE = DATA_MAX_SIZE + 4
_SEALED_SIZE = _FRAME_SIZE + 16
_HKDF_INFO = b"TMTPU_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"

# Ceiling on a single length-prefixed message. The length prefix comes from
# the (authenticated but untrusted) remote peer; without a cap it could claim
# 4 GiB and force unbounded buffering before MConnection's per-channel
# recv_message_capacity is ever consulted.
MAX_MSG_SIZE = 8 * 1024 * 1024


class HandshakeError(Exception):
    pass


class _NonceCounter:
    __slots__ = ("_n",)

    def __init__(self) -> None:
        self._n = 0

    def next(self) -> bytes:
        n = self._n
        self._n += 1
        if self._n >= 1 << 64:
            raise OverflowError("nonce counter exhausted")
        return b"\x00\x00\x00\x00" + struct.pack("<Q", n)


class SecretConnection:
    """Encrypted, peer-authenticated byte stream over an asyncio socket."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_aead: ChaCha20Poly1305,
        recv_aead: ChaCha20Poly1305,
        remote_pubkey: ed25519.PubKeyEd25519,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._send_aead = send_aead
        self._recv_aead = recv_aead
        self._send_nonce = _NonceCounter()
        self._recv_nonce = _NonceCounter()
        self._recv_buf = bytearray()
        self.remote_pubkey = remote_pubkey

    @classmethod
    async def make(
        cls,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        priv_key: ed25519.PrivKeyEd25519,
    ) -> "SecretConnection":
        """Run the handshake as either dialer or acceptor (symmetric)."""
        eph_priv = X25519PrivateKey.generate()
        loc_eph_pub = eph_priv.public_key().public_bytes_raw()
        writer.write(loc_eph_pub)
        await writer.drain()
        rem_eph_pub = await reader.readexactly(32)

        try:
            shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(rem_eph_pub))
        except ValueError as e:
            raise HandshakeError(f"bad ephemeral key: {e}") from e

        # Key schedule: the party with the lexicographically smaller ephemeral
        # pubkey receives with key1/sends with key2; the other side mirrors
        # (reference secret_connection.go:346-376).
        okm = HKDF(
            algorithm=hashes.SHA256(), length=96, salt=None, info=_HKDF_INFO
        ).derive(shared)
        key1, key2, challenge = okm[:32], okm[32:64], okm[64:]
        if loc_eph_pub < rem_eph_pub:
            recv_key, send_key = key1, key2
        elif loc_eph_pub > rem_eph_pub:
            recv_key, send_key = key2, key1
        else:
            raise HandshakeError("identical ephemeral keys (reflection attack)")

        conn = cls(
            reader,
            writer,
            ChaCha20Poly1305(send_key),
            ChaCha20Poly1305(recv_key),
            remote_pubkey=None,  # set below after authentication
        )

        # Authenticate over the encrypted channel: sign the shared challenge
        # with the long-lived node key (reference :405,419).
        sig = priv_key.sign(challenge)
        w = Writer()
        w.bytes(priv_key.pub_key().bytes())
        w.bytes(sig)
        await conn.write(w.build())
        await conn.drain()

        auth = await conn.read_msg()
        r = Reader(auth)
        rem_pub_raw = r.bytes()
        rem_sig = r.bytes()
        r.expect_done()
        rem_pub = ed25519.PubKeyEd25519(rem_pub_raw)
        if not rem_pub.verify(challenge, rem_sig):
            raise HandshakeError("challenge signature verification failed")
        conn.remote_pubkey = rem_pub
        return conn

    # --- encrypted byte stream -------------------------------------------

    async def write(self, data: bytes) -> None:
        """Send as a length-prefixed message (one or more sealed frames)."""
        msg = struct.pack(">I", len(data)) + data
        for off in range(0, len(msg), _FRAME_SIZE):
            frame = msg[off : off + _FRAME_SIZE].ljust(_FRAME_SIZE, b"\x00")
            sealed = self._send_aead.encrypt(self._send_nonce.next(), frame, None)
            self._writer.write(sealed)

    async def drain(self) -> None:
        await self._writer.drain()

    async def _read_frame(self) -> bytes:
        sealed = await self._reader.readexactly(_SEALED_SIZE)
        try:
            return self._recv_aead.decrypt(self._recv_nonce.next(), sealed, None)
        except InvalidTag as e:
            raise HandshakeError("frame authentication failed") from e

    async def read_msg(self) -> bytes:
        """Receive one length-prefixed message."""
        while len(self._recv_buf) < 4:
            self._recv_buf += await self._read_frame()
        (n,) = struct.unpack(">I", self._recv_buf[:4])
        if n > MAX_MSG_SIZE:
            raise HandshakeError(f"message length {n} exceeds cap {MAX_MSG_SIZE}")
        while len(self._recv_buf) < 4 + n:
            self._recv_buf += await self._read_frame()
        msg = bytes(self._recv_buf[4 : 4 + n])
        # Each message starts on a frame boundary; drop its frames, padding
        # included, so the buffer stays frame-aligned.
        frames = (4 + n + _FRAME_SIZE - 1) // _FRAME_SIZE
        del self._recv_buf[: frames * _FRAME_SIZE]
        return msg

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass
