"""MConnection: N logical channels multiplexed over one encrypted link.

Reference parity: p2p/conn/connection.go:74 — per-channel priority send
queues drained by a single send routine (least recently-sent/priority ratio
first, :405), a recv routine reassembling chunked messages per channel
(:539), ping/pong keepalive with a pong timeout, flow-rate metering, and
`ChannelDescriptor{ID, Priority, SendQueueCapacity, RecvMessageCapacity}`
(:696). Packet framing rides the SecretConnection's length-prefixed message
layer instead of amino `PacketMsg` (:884).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass

from tendermint_tpu.encoding import DecodeError, Reader, Writer
from tendermint_tpu.libs.flowrate import Monitor
from tendermint_tpu.libs.service import BaseService

_PKT_PING = 0
_PKT_PONG = 1
_PKT_MSG = 2

MAX_PACKET_PAYLOAD = 1024


@dataclass
class MConnConfig:
    send_rate: float = 5 * 1024 * 1024  # bytes/sec (config/config.go:473)
    recv_rate: float = 5 * 1024 * 1024
    max_packet_payload: int = MAX_PACKET_PAYLOAD
    flush_throttle: float = 0.1
    ping_interval: float = 60.0
    pong_timeout: float = 45.0
    send_timeout: float = 10.0


@dataclass
class ChannelStatus:
    id: int
    send_queue_size: int
    priority: int
    recently_sent: int


class _Channel:
    def __init__(self, desc, max_payload: int) -> None:
        self.desc = desc
        self.queue: asyncio.Queue[bytes] = asyncio.Queue(desc.send_queue_capacity)
        self.recving = bytearray()
        self.recently_sent = 0
        self.sending: bytes | None = None  # message currently being chunked
        self.sent_offset = 0
        self.max_payload = max_payload
        # packet-layer traffic accounting (wire-efficiency observatory):
        # packets vs messages separates chunking cost from payload volume
        self.sent_msgs = 0
        self.sent_bytes = 0  # payload only; framing on the MConnection
        self.sent_packets = 0
        self.recv_msgs = 0
        self.recv_bytes = 0
        self.recv_packets = 0

    def is_send_pending(self) -> bool:
        return self.sending is not None or not self.queue.empty()

    def next_packet(self) -> tuple[bytes, bool]:
        """Pop up to max_payload bytes of the in-flight message; returns
        (chunk, eof)."""
        if self.sending is None:
            self.sending = self.queue.get_nowait()
            self.sent_offset = 0
        chunk = self.sending[self.sent_offset : self.sent_offset + self.max_payload]
        self.sent_offset += len(chunk)
        eof = self.sent_offset >= len(self.sending)
        if eof:
            self.sending = None
            self.sent_offset = 0
        return chunk, eof


class MConnection(BaseService):
    """One peer link: channels in, packets out (and back)."""

    def __init__(
        self,
        conn,  # SecretConnection-like: write/drain/read_msg/close
        chan_descs,
        on_receive,  # async (ch_id: int, msg: bytes) -> None
        on_error,  # async (exc: Exception) -> None
        config: MConnConfig | None = None,
    ) -> None:
        super().__init__(name="MConn")
        self.config = config or MConnConfig()
        self._conn = conn
        self._channels = {
            d.id: _Channel(d, self.config.max_packet_payload) for d in chan_descs
        }
        self._on_receive = on_receive
        self._on_error = on_error
        self._send_wake = asyncio.Event()
        self._pong_pending = 0
        self._last_pong = time.monotonic()
        self._last_flush = time.monotonic()
        self._send_monitor = Monitor()
        self._recv_monitor = Monitor()
        self._errored = False
        # link-level overhead accounting: framing = every wire byte that
        # is not channel payload (packet tags + headers + ping/pong), and
        # the cumulative time the send routine slept in the flowrate
        # throttle — the two costs goodput numbers must subtract
        self.sent_framing_bytes = 0
        self.recv_framing_bytes = 0
        self.throttle_wait_s = 0.0

    async def on_start(self) -> None:
        self.spawn(self._send_routine(), "mconn-send")
        self.spawn(self._recv_routine(), "mconn-recv")
        self.spawn(self._ping_routine(), "mconn-ping")

    async def on_stop(self) -> None:
        self._conn.close()

    # --- sending ---------------------------------------------------------

    async def send(self, ch_id: int, msg: bytes) -> bool:
        """Queue msg on channel; False if unknown channel or queue full past
        the timeout (reference connection.go Send)."""
        ch = self._channels.get(ch_id)
        if ch is None or not self.is_running:
            return False
        try:
            await asyncio.wait_for(ch.queue.put(msg), self.config.send_timeout)
        except asyncio.TimeoutError:
            return False
        self._send_wake.set()
        return True

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        ch = self._channels.get(ch_id)
        if ch is None or not self.is_running:
            return False
        try:
            ch.queue.put_nowait(msg)
        except asyncio.QueueFull:
            return False
        self._send_wake.set()
        return True

    def _pick_channel(self) -> _Channel | None:
        """Least recently_sent/priority ratio among channels with data
        (reference connection.go:405 sendPacketMsg)."""
        best, best_ratio = None, None
        for ch in self._channels.values():
            if not ch.is_send_pending():
                continue
            ratio = ch.recently_sent / max(ch.desc.priority, 1)
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    async def _send_routine(self) -> None:
        try:
            while True:
                await self._send_wake.wait()
                self._send_wake.clear()
                while True:
                    if self._pong_pending:
                        self._pong_pending -= 1
                        pong = Writer().u8(_PKT_PONG).build()
                        await self._write_packet(pong)
                        self.sent_framing_bytes += len(pong)
                        continue
                    ch = self._pick_channel()
                    if ch is None:
                        break
                    chunk, eof = ch.next_packet()
                    w = Writer().u8(_PKT_MSG).u8(ch.desc.id).bool(eof).bytes(chunk)
                    pkt = w.build()
                    await self._write_packet(pkt)
                    ch.recently_sent += len(chunk)
                    ch.sent_packets += 1
                    ch.sent_bytes += len(chunk)
                    self.sent_framing_bytes += len(pkt) - len(chunk)
                    if eof:
                        ch.sent_msgs += 1
                    # flush-throttled mid-burst drain (connection.go:74
                    # flushThrottle, default 100ms): a long burst flushes
                    # every flush_throttle seconds — batching writes —
                    # while bounding how stale buffered packets can get
                    now = time.monotonic()
                    if now - self._last_flush >= self.config.flush_throttle:
                        await self._conn.drain()
                        self._last_flush = now
                await self._conn.drain()
                self._last_flush = time.monotonic()
                # decay so bursts don't starve low-priority channels forever
                for c in self._channels.values():
                    c.recently_sent = int(c.recently_sent * 0.8)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            await self._fail(e)

    async def _write_packet(self, pkt: bytes) -> None:
        # flowrate cap (config/config.go:473 SendRate, default 5 MB/s):
        # wait until the token bucket admits the packet, so sustained
        # throughput converges on send_rate instead of oscillating. When
        # the configured rate is so low that one packet exceeds a full
        # window of credit, admit at a full bucket — the debt recorded by
        # update() still paces the long-run rate — so progress is always
        # made (a send_rate below ~1 KB/s must throttle, never wedge).
        rate = self.config.send_rate
        if rate > 0:
            target = min(len(pkt), max(1, int(rate * self._send_monitor.window)))
            while True:
                allowed = self._send_monitor.limit(len(pkt), rate)
                if allowed >= target:
                    break
                wait = (target - allowed) / rate
                self.throttle_wait_s += wait
                await asyncio.sleep(wait)
        await self._conn.write(pkt)
        self._send_monitor.update(len(pkt))

    # --- receiving -------------------------------------------------------

    async def _recv_routine(self) -> None:
        try:
            while True:
                pkt = await self._conn.read_msg()
                self._recv_monitor.update(len(pkt))
                r = Reader(pkt)
                tag = r.u8()
                if tag == _PKT_PING:
                    self.recv_framing_bytes += len(pkt)
                    self._pong_pending += 1
                    self._send_wake.set()
                elif tag == _PKT_PONG:
                    self.recv_framing_bytes += len(pkt)
                    self._last_pong = time.monotonic()
                elif tag == _PKT_MSG:
                    ch_id = r.u8()
                    eof = r.bool()
                    data = r.bytes()
                    ch = self._channels.get(ch_id)
                    if ch is None:
                        raise DecodeError(f"packet on unknown channel {ch_id:#x}")
                    ch.recv_packets += 1
                    ch.recv_bytes += len(data)
                    self.recv_framing_bytes += len(pkt) - len(data)
                    ch.recving += data
                    if len(ch.recving) > ch.desc.recv_message_capacity:
                        raise DecodeError(
                            f"message on channel {ch_id:#x} exceeds capacity "
                            f"{ch.desc.recv_message_capacity}"
                        )
                    if eof:
                        ch.recv_msgs += 1
                        msg = bytes(ch.recving)
                        ch.recving.clear()
                        await self._on_receive(ch_id, msg)
                else:
                    raise DecodeError(f"unknown packet tag {tag}")
        except asyncio.CancelledError:
            raise
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            await self._fail(e)
        except Exception as e:
            await self._fail(e)

    async def _ping_routine(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.config.ping_interval)
                ping = Writer().u8(_PKT_PING).build()
                await self._write_packet(ping)
                self.sent_framing_bytes += len(ping)
                await self._conn.drain()
                await asyncio.sleep(self.config.pong_timeout)
                if time.monotonic() - self._last_pong > (
                    self.config.ping_interval + self.config.pong_timeout
                ):
                    await self._fail(TimeoutError("pong timeout"))
                    return
        except asyncio.CancelledError:
            raise
        except Exception as e:
            await self._fail(e)

    async def _fail(self, e: Exception) -> None:
        if self._errored:
            return
        self._errored = True
        self.logger.debug("connection failed: %s", e)
        try:
            await self._on_error(e)
        except Exception:
            pass

    def status(self) -> list[ChannelStatus]:
        return [
            ChannelStatus(
                id=ch.desc.id,
                send_queue_size=ch.queue.qsize(),
                priority=ch.desc.priority,
                recently_sent=ch.recently_sent,
            )
            for ch in self._channels.values()
        ]

    def traffic_snapshot(self) -> dict:
        """Packet-layer accounting for debug_traffic: per-channel
        msgs/packets/payload-bytes both ways plus queue depth, and the
        link-level framing/throttle/utilization costs."""
        return {
            "channels": {
                f"{ch.desc.id:#04x}": {
                    "sent_msgs": ch.sent_msgs,
                    "sent_packets": ch.sent_packets,
                    "sent_bytes": ch.sent_bytes,
                    "recv_msgs": ch.recv_msgs,
                    "recv_packets": ch.recv_packets,
                    "recv_bytes": ch.recv_bytes,
                    "send_queue_size": ch.queue.qsize(),
                    "send_queue_capacity": ch.desc.send_queue_capacity,
                }
                for ch in self._channels.values()
            },
            "sent_framing_bytes": self.sent_framing_bytes,
            "recv_framing_bytes": self.recv_framing_bytes,
            "throttle_wait_s": round(self.throttle_wait_s, 6),
            "send_utilization": round(
                self._send_monitor.utilization(self.config.send_rate), 4
            ),
            "recv_utilization": round(
                self._recv_monitor.utilization(self.config.recv_rate), 4
            ),
        }
