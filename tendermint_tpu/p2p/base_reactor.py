"""Reactor contract.

Reference parity: p2p/base_reactor.go — a Reactor owns a set of channels on
the Switch and reacts to peer lifecycle + messages:
`{GetChannels, InitPeer, AddPeer, RemovePeer, Receive}`.
"""
from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.libs.service import BaseService


@dataclass(frozen=True)
class ChannelDescriptor:
    """Reference p2p/conn/connection.go:696."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 100
    recv_message_capacity: int = 1 << 20


class BaseReactor(BaseService):
    #: short family label for traffic accounting — the {reactor} label on
    #: tm_p2p_redundant_received_total and the ledger's redundancy key
    traffic_family = "other"

    def __init__(self, name: str) -> None:
        super().__init__(name=name)
        self.switch = None  # set by Switch.add_reactor
        self._redundant_ctrs: dict[str, object] = {}

    def set_switch(self, switch) -> None:
        self.switch = switch

    async def report(self, peer, behaviour) -> None:
        """Route a behaviour/PeerBehaviour into the switch's peer-quality
        plane (trust score, bans, disconnect — ADR-039). Falls back to the
        legacy stop-on-error contract for stub switches in tests that only
        implement `stop_peer_for_error`."""
        sw = self.switch
        if sw is None:
            return
        report_behaviour = getattr(sw, "report_behaviour", None)
        if report_behaviour is not None:
            await report_behaviour(behaviour, peer=peer)
        elif behaviour.is_error and peer is not None:
            await sw.stop_peer_for_error(peer, behaviour.reason)

    def classify(self, ch_id: int, msg: bytes) -> str:
        """Cheap message-type label for the (peer, channel, type) traffic
        rollup — typically one tag-byte peek, never a full decode. Must
        not raise on garbage: unknown frames are 'other' (the decode path
        reports them as behaviour, not the accountant)."""
        return "other"

    def note_redundant(self, peer, kind: str, n: int = 1) -> None:
        """Report a delivery that carried nothing new (vote already
        counted, block part already held, tx already cached...). Feeds
        the switch's traffic ledger and the redundant-received counter;
        a no-op under stub switches without the traffic plane."""
        sw = self.switch
        if sw is None or n <= 0:
            return
        ledger = getattr(sw, "traffic", None)
        if ledger is not None:
            pid = peer.id if peer is not None else "?"
            ledger.note_redundant(pid, self.traffic_family, kind, n)
        m = getattr(sw, "metrics", None)
        if m is not None:
            ctr = self._redundant_ctrs.get(kind)
            if ctr is None:
                ctr = m.redundant_received_total.bind(
                    reactor=self.traffic_family, kind=kind
                )
                self._redundant_ctrs[kind] = ctr
            ctr.inc(n)

    def get_channels(self) -> list[ChannelDescriptor]:
        return []

    def init_peer(self, peer) -> None:
        """Called before the peer starts; install per-peer state."""

    async def add_peer(self, peer) -> None:
        """Called once the peer is started."""

    async def remove_peer(self, peer, reason) -> None:
        pass

    async def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        pass
