"""UPnP NAT traversal — discover an internet gateway and map a port.

Reference parity: p2p/upnp (Discover, AddPortMapping, DeletePortMapping,
GetExternalAddress) used by `tendermint probe_upnp` and optional laddr
mapping. SSDP discovery over UDP multicast + SOAP control over HTTP, all
stdlib; everything degrades to UPnPError on networks without a gateway.
"""
from __future__ import annotations

import re
import socket
import urllib.request
from dataclasses import dataclass

SSDP_ADDR = ("239.255.255.250", 1900)
ST = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
SERVICE_TYPES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


class UPnPError(Exception):
    pass


@dataclass
class Gateway:
    control_url: str
    service_type: str
    local_ip: str


def discover(timeout: float = 3.0) -> Gateway:
    """SSDP M-SEARCH for an internet gateway (reference upnp.Discover)."""
    msg = (
        "M-SEARCH * HTTP/1.1\r\n"
        f"HOST: {SSDP_ADDR[0]}:{SSDP_ADDR[1]}\r\n"
        'MAN: "ssdp:discover"\r\n'
        "MX: 2\r\n"
        f"ST: {ST}\r\n\r\n"
    ).encode()
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    try:
        s.sendto(msg, SSDP_ADDR)
        data, addr = s.recvfrom(4096)
        local_ip = _local_ip_towards(addr[0])
    except OSError as e:
        raise UPnPError(f"no UPnP gateway responded: {e}") from e
    finally:
        s.close()
    m = re.search(rb"(?i)location:\s*(\S+)", data)
    if not m:
        raise UPnPError("SSDP response without LOCATION")
    location = m.group(1).decode()
    return _parse_device(location, local_ip)


def _local_ip_towards(remote: str) -> str:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((remote, 1900))
        return s.getsockname()[0]
    finally:
        s.close()


def _parse_device(location: str, local_ip: str) -> Gateway:
    with urllib.request.urlopen(location, timeout=5) as resp:
        xml = resp.read().decode("utf-8", "replace")
    for st in SERVICE_TYPES:
        pat = (
            rf"<serviceType>{re.escape(st)}</serviceType>.*?"
            rf"<controlURL>([^<]+)</controlURL>"
        )
        m = re.search(pat, xml, re.S)
        if m:
            control = m.group(1)
            if not control.startswith("http"):
                base = re.match(r"(https?://[^/]+)", location).group(1)
                control = base + control
            return Gateway(control, st, local_ip)
    raise UPnPError("gateway has no WAN connection service")


def _soap(gw: Gateway, action: str, body_xml: str) -> str:
    envelope = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        f"<s:Body><u:{action} xmlns:u=\"{gw.service_type}\">{body_xml}"
        f"</u:{action}></s:Body></s:Envelope>"
    ).encode()
    req = urllib.request.Request(
        gw.control_url,
        data=envelope,
        headers={
            "Content-Type": 'text/xml; charset="utf-8"',
            "SOAPAction": f'"{gw.service_type}#{action}"',
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.read().decode("utf-8", "replace")
    except OSError as e:
        raise UPnPError(f"SOAP {action} failed: {e}") from e


def get_external_address(gw: Gateway) -> str:
    xml = _soap(gw, "GetExternalIPAddress", "")
    m = re.search(r"<NewExternalIPAddress>([^<]+)</NewExternalIPAddress>", xml)
    if not m:
        raise UPnPError("no external address in response")
    return m.group(1)


def add_port_mapping(
    gw: Gateway, external_port: int, internal_port: int,
    protocol: str = "TCP", description: str = "tendermint-tpu", lease: int = 0,
) -> None:
    body = (
        "<NewRemoteHost></NewRemoteHost>"
        f"<NewExternalPort>{external_port}</NewExternalPort>"
        f"<NewProtocol>{protocol}</NewProtocol>"
        f"<NewInternalPort>{internal_port}</NewInternalPort>"
        f"<NewInternalClient>{gw.local_ip}</NewInternalClient>"
        "<NewEnabled>1</NewEnabled>"
        f"<NewPortMappingDescription>{description}</NewPortMappingDescription>"
        f"<NewLeaseDuration>{lease}</NewLeaseDuration>"
    )
    _soap(gw, "AddPortMapping", body)


def delete_port_mapping(gw: Gateway, external_port: int, protocol: str = "TCP") -> None:
    body = (
        "<NewRemoteHost></NewRemoteHost>"
        f"<NewExternalPort>{external_port}</NewExternalPort>"
        f"<NewProtocol>{protocol}</NewProtocol>"
    )
    _soap(gw, "DeletePortMapping", body)


def probe(timeout: float = 3.0) -> dict:
    """Reference `tendermint probe_upnp`: capabilities report."""
    gw = discover(timeout)
    out = {"gateway": gw.control_url, "local_ip": gw.local_ip}
    try:
        out["external_ip"] = get_external_address(gw)
    except UPnPError as e:
        out["external_ip_error"] = str(e)
    return out
