"""Switch: peer lifecycle + reactor message routing + peer quality.

Reference parity: p2p/switch.go:67 — owns the transport, the peer set, and
all reactors. `add_reactor` claims channel IDs (switch.go:154); `broadcast`
fans out to every peer (switch.go:258); dial/accept routines add peers with
retry + exponential backoff for persistent peers (switch.go:362,572).

Peer quality (docs/p2p_resilience.md): reactors route misbehaviour through
`behaviour/` reports into the per-peer `p2p/trust.py` metric; the switch
bans peers whose score crosses the threshold (persisted in the PEX address
book so bans survive restart), rejects banned peers on accept AND dial,
and heals lost links through the unified `p2p/dialer.py` backoff dialer —
persistent peers are never permanently abandoned.
"""
from __future__ import annotations

import asyncio
import random
import time

from typing import TYPE_CHECKING

from tendermint_tpu.behaviour import PeerBehaviour
from tendermint_tpu.libs.recorder import RECORDER
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.bans import BanTable
from tendermint_tpu.p2p.dialer import Dialer
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.traffic import TrafficLedger
from tendermint_tpu.p2p.trust import TrustMetricStore

if TYPE_CHECKING:  # Transport pulls the crypto stack; keep it type-only
    from tendermint_tpu.p2p.transport import Transport

# legacy fast-phase constants, now interpreted by p2p/dialer.py (the old
# _reconnect_routine stopped FOR GOOD after MAX_RECONNECT_ATTEMPTS — the
# dialer's slow phase continues persistent peers unboundedly instead)
RECONNECT_BASE_DELAY = 1.0
RECONNECT_MAX_DELAY = 300.0
MAX_RECONNECT_ATTEMPTS = 20

# behaviour-scored banning defaults (config p2p.* overrides via the node)
BAN_THRESHOLD_SCORE = 20  # trust_score() in [0, 100]
BAN_MIN_BAD_WEIGHT = 6.0  # accumulated bad weight before a ban can fire
BAN_DURATION = 300.0  # seconds; repeat offenders double (addrbook.ban)


class SwitchError(Exception):
    pass


class PeerSet:
    def __init__(self) -> None:
        self._by_id: dict[str, Peer] = {}

    def add(self, peer: Peer) -> None:
        if peer.id in self._by_id:
            raise SwitchError(f"duplicate peer {peer.id}")
        self._by_id[peer.id] = peer

    def remove(self, peer: Peer) -> bool:
        return self._by_id.pop(peer.id, None) is not None

    def has(self, peer_id: str) -> bool:
        return peer_id in self._by_id

    def get(self, peer_id: str) -> Peer | None:
        return self._by_id.get(peer_id)

    def list(self) -> list[Peer]:
        return list(self._by_id.values())

    def __len__(self) -> int:
        return len(self._by_id)


class Switch(BaseService):
    def __init__(
        self,
        transport: Transport,
        max_inbound_peers: int = 40,
        max_outbound_peers: int = 10,
        fuzz_config=None,  # p2p.fuzz.FuzzConfig | None (config.p2p.test_fuzz)
        fault_control: bool = False,  # config.p2p.test_fault_control
        trust_store: TrustMetricStore | None = None,
        ban_threshold: int = BAN_THRESHOLD_SCORE,
        ban_min_bad_weight: float = BAN_MIN_BAD_WEIGHT,
        ban_duration: float = BAN_DURATION,
        max_concurrent_dials: int = 8,
    ) -> None:
        super().__init__(name="Switch")
        self.transport = transport
        self.fuzz_config = fuzz_config
        self.fault_control = fault_control
        self.peers = PeerSet()
        self.reactors: dict[str, object] = {}
        self._chan_descs: list = []
        self._reactors_by_ch: dict[int, object] = {}
        self.max_inbound_peers = max_inbound_peers
        self.max_outbound_peers = max_outbound_peers
        self._dialing: set[str] = set()
        self._persistent_addrs: dict[str, NetAddress] = {}
        self.addr_book = None  # optional, set by PEX wiring
        self._metrics = None
        # wire-efficiency observatory: per-switch ledger of
        # (peer, channel, message-type, direction) message/byte counters
        # plus redundant deliveries; surfaced by the debug_traffic route
        self.traffic = TrafficLedger()
        self._recv_msg_ctrs: dict[tuple[int, str], tuple] = {}
        # (peer_id, ch_id) -> monotonic t0 when the send queue was first
        # seen saturated; cleared when it drains (sendq_stall_age)
        self._sendq_sat: dict[tuple[str, int], float] = {}
        # peer-quality plane: every behaviour report lands in the trust
        # store; the ban decision needs BOTH a below-threshold score and
        # enough accumulated bad weight (one unlucky frame disconnects
        # but does not ban)
        self.trust_store = trust_store or TrustMetricStore()
        self.ban_threshold = ban_threshold
        self.ban_min_bad_weight = ban_min_bad_weight
        self.ban_duration = ban_duration
        # backend when addr_book is None (tests, ad-hoc meshes): same
        # shared BanTable policy, monotonic clock, no persistence
        self._local_bans = BanTable()
        # unified self-healing dialer: one backoff policy for persistent
        # reconnects AND PEX-discovered addresses
        self.dialer = Dialer(
            self._dial_attempt,
            has_peer=self.peers.has,
            is_banned=self.is_banned,
            spawn=self.spawn,
            is_running=lambda: self.is_running,
            base_delay=RECONNECT_BASE_DELAY,
            fast_attempts=MAX_RECONNECT_ATTEMPTS,
            slow_interval=RECONNECT_MAX_DELAY,
            max_concurrent=max_concurrent_dials,
        )

    @property
    def metrics(self):
        """libs/metrics.P2PMetrics | None, set by the node when Prometheus
        is on; propagated to each Peer (per-channel byte counters) and to
        the dialer (dial attempt/failure counters)."""
        return self._metrics

    @metrics.setter
    def metrics(self, m) -> None:
        self._metrics = m
        self.dialer.metrics = m

    def node_id(self) -> str:
        return self.transport.node_key.id()

    # --- reactors --------------------------------------------------------

    def add_reactor(self, name: str, reactor) -> None:
        for d in reactor.get_channels():
            if d.id in self._reactors_by_ch:
                raise SwitchError(f"channel {d.id:#x} already claimed")
            self._reactors_by_ch[d.id] = reactor
            self._chan_descs.append(d)
        self.reactors[name] = reactor
        reactor.set_switch(self)

    def reactor(self, name: str):
        return self.reactors.get(name)

    # --- lifecycle -------------------------------------------------------

    async def on_start(self) -> None:
        for reactor in self.reactors.values():
            await reactor.start()
        self.spawn(self._accept_routine(), "switch-accept")

    async def on_stop(self) -> None:
        for peer in self.peers.list():
            await self._stop_and_remove(peer, "switch stopping")
        for reactor in self.reactors.values():
            await reactor.stop()
        await self.transport.stop()
        # trust-store persistence is the injecting owner's duty (the node
        # saves it on stop); the self-created fallback store has no file

    async def _accept_routine(self) -> None:
        while True:
            conn, ni, addr = await self.transport.accept()
            inbound = sum(1 for p in self.peers.list() if not p.outbound)
            if inbound >= self.max_inbound_peers:
                self.logger.debug("rejecting inbound %s: at capacity", ni.node_id)
                conn.close()
                continue
            try:
                await self._add_peer(conn, ni, outbound=False, socket_addr=addr)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # any failure (reactor add_peer bug included) must not kill
                # the accept loop — the node would stop taking inbound peers
                self.logger.debug("inbound peer rejected: %s", e)
                conn.close()

    # --- peer quality: trust, behaviours, bans ---------------------------

    def _ban_backend(self):
        return self.addr_book if self.addr_book is not None else self._local_bans

    def is_banned(self, peer_id: str) -> bool:
        return self._ban_backend().is_banned(peer_id)

    def _refresh_ban_gauge(self) -> int:
        """bans() prunes expired entries as a side effect, so this keeps
        the gauge honest wherever the ban set is touched or read."""
        n = len(self._ban_backend().bans())
        if self.metrics is not None:
            self.metrics.banned_peers.set(n)
        return n

    def trust_score(self, peer_id: str) -> int:
        return self.trust_store.get_peer_trust_metric(peer_id).trust_score()

    async def report_behaviour(self, behaviour: PeerBehaviour, peer=None) -> None:
        """The ADR-039 sink: feed the trust metric, ban on threshold
        crossing, disconnect on error behaviours. Reactors reach this via
        `BaseReactor.report` (behaviour.SwitchReporter forwards here)."""
        pid = behaviour.peer_id
        tm = self.trust_store.get_peer_trust_metric(pid)
        if behaviour.is_bad:
            tm.bad_event(behaviour.weight)
        else:
            tm.good_event(behaviour.weight)
        if not behaviour.is_bad:
            return  # good events are the hot path: no recording, no checks
        score = tm.trust_score()
        RECORDER.record(
            "p2p", "behaviour", peer=pid, reason=behaviour.reason[:120],
            weight=behaviour.weight, score=score,
        )
        if self.metrics is not None:
            self.metrics.behaviour_bad_total.inc()
        if peer is None:
            peer = self.peers.get(pid)
        if (
            score < self.ban_threshold
            and tm.total_bad >= self.ban_min_bad_weight
            and not self.is_banned(pid)
        ):
            await self.ban_peer(pid, f"trust score {score} < {self.ban_threshold}"
                                     f" ({behaviour.reason[:80]})")
        elif behaviour.is_error and peer is not None:
            await self.stop_peer_for_error(peer, behaviour.reason)

    async def ban_peer(self, peer_id: str, reason: str) -> None:
        """Ban + disconnect. The ban lives in the address book (persisted
        across restarts with its remaining time) or the local fallback."""
        applied = self._ban_backend().ban(peer_id, self.ban_duration, reason)
        score = self.trust_score(peer_id)
        RECORDER.record(
            "p2p", "peer_banned", peer=peer_id, duration_s=round(applied, 1),
            score=score, reason=str(reason)[:200],
        )
        if self.metrics is not None:
            self.metrics.peer_bans_total.inc()
        self._refresh_ban_gauge()
        self.logger.info("banned peer %s for %.0fs: %s", peer_id, applied, reason)
        peer = self.peers.get(peer_id)
        if peer is not None:
            await self.stop_peer_for_error(peer, f"banned: {reason}")

    def unban_peer(self, peer_id: str) -> None:
        self._ban_backend().unban(peer_id)
        self._refresh_ban_gauge()

    # --- dialing ---------------------------------------------------------

    async def dial_peers_async(
        self, addrs: list[NetAddress], persistent: bool = False
    ) -> None:
        for addr in addrs:
            if persistent and addr.id:
                self._persistent_addrs[addr.id] = addr
            self.dialer.schedule(addr, persistent)

    async def _dial_attempt(self, addr: NetAddress, persistent: bool) -> bool:
        """One dial + add-peer attempt with addr-book bookkeeping; returns
        True on success (or if already connected/dialing)."""
        key = addr.id or addr.dial_string()
        if key in self._dialing or (addr.id and self.peers.has(addr.id)):
            return True
        from tendermint_tpu.p2p.transport import RejectedError

        self._dialing.add(key)
        try:
            # jitter so a restarted network doesn't dial in lockstep
            await asyncio.sleep(random.random() * 0.05)
            conn, ni = await self.transport.dial(addr)
            await self._add_peer(
                conn, ni, outbound=True, persistent=persistent, socket_addr=addr
            )
            if self.addr_book is not None:
                self.addr_book.mark_good(addr)
            return True
        except (OSError, RejectedError, SwitchError, asyncio.TimeoutError) as e:
            self.logger.debug("dial %s failed: %s", addr, e)
            if self.addr_book is not None:
                self.addr_book.mark_attempt(addr)
            return False
        finally:
            self._dialing.discard(key)

    # --- peer management -------------------------------------------------

    async def _add_peer(
        self, conn, ni, outbound: bool, persistent: bool = False, socket_addr=None
    ) -> Peer:
        if ni.node_id == self.node_id():
            raise SwitchError("self connection")
        if self.is_banned(ni.node_id):
            # the quality gate: banned peers are refused on accept AND
            # dial until the ban decays (reference ADR-039 direction)
            RECORDER.record(
                "p2p", "banned_reject", peer=ni.node_id, outbound=outbound,
            )
            raise SwitchError(f"peer {ni.node_id} is banned")
        if self.peers.has(ni.node_id):
            raise SwitchError(f"already connected to {ni.node_id}")
        persistent = persistent or ni.node_id in self._persistent_addrs
        if self.fuzz_config is not None:
            # config.p2p.test_fuzz (reference p2p/test_util.go:229-232):
            # wrap the authenticated conn so every peer link drops/delays
            # probabilistically AFTER the start_after grace
            from tendermint_tpu.p2p.fuzz import FuzzedConnection

            conn = FuzzedConnection(conn, self.fuzz_config)
        if self.fault_control:
            # nemesis plane (config.p2p.test_fault_control): per-link
            # runtime faults keyed by the remote peer id, outermost so a
            # partition blackholes the link below any fuzz layer
            from tendermint_tpu.libs.fault import FaultedConnection

            conn = FaultedConnection(conn, ni.node_id)
        peer = Peer(
            conn,
            ni,
            self._chan_descs,
            on_receive=self._route_receive,
            on_error=self._on_peer_error,
            outbound=outbound,
            persistent=persistent,
            socket_addr=socket_addr,
        )
        peer.metrics = self.metrics  # per-channel byte counters from byte 0
        peer.traffic = self.traffic  # (peer, channel, type) rollup
        peer.classify = self._classify  # reactor-boundary type decoder
        for reactor in self.reactors.values():
            reactor.init_peer(peer)
        self.peers.add(peer)
        try:
            await peer.start()
            for reactor in self.reactors.values():
                await reactor.add_peer(peer)
        except Exception:
            self.peers.remove(peer)
            await peer.stop()
            raise
        # a live link stops the empty-interval decay of the trust history
        self.trust_store.get_peer_trust_metric(peer.id).good_event(0.0)
        RECORDER.record("p2p", "peer_connected", peer=peer.id, outbound=outbound)
        if self.metrics is not None:
            self.metrics.peers.set(len(self.peers))
        self.logger.info("added peer %s (%s)", peer, "out" if outbound else "in")
        return peer

    def _classify(self, ch_id: int, msg: bytes) -> str:
        """Message-type label via the owning reactor's classify hook — a
        tag-byte peek, not a decode (the traffic plane must stay cheap)."""
        reactor = self._reactors_by_ch.get(ch_id)
        if reactor is None:
            return "other"
        return reactor.classify(ch_id, msg)

    def _account_receive(self, ch_id: int, peer: Peer, msg: bytes) -> None:
        mtype = self._classify(ch_id, msg)
        self.traffic.note_msg(peer.id, ch_id, mtype, "recv", len(msg))
        if self._metrics is not None:
            pair = self._recv_msg_ctrs.get((ch_id, mtype))
            if pair is None:
                labels = {"channel": f"{ch_id:#04x}", "type": mtype}
                pair = (
                    self._metrics.msg_received_total.bind(**labels),
                    self._metrics.msg_received_bytes.bind(**labels),
                )
                self._recv_msg_ctrs[(ch_id, mtype)] = pair
            pair[0].inc()
            pair[1].inc(len(msg))

    async def _route_receive(self, ch_id: int, peer: Peer, msg: bytes) -> None:
        # account before dispatch so even rejected/garbage frames show up
        # in the wire ledger — they cost bandwidth whether or not a
        # reactor accepts them
        self._account_receive(ch_id, peer, msg)
        reactor = self._reactors_by_ch.get(ch_id)
        if reactor is None:
            await self.report_behaviour(
                PeerBehaviour.bad_message(
                    peer.id, f"msg on unclaimed channel {ch_id:#x}"
                ),
                peer=peer,
            )
            return
        await reactor.receive(ch_id, peer, msg)

    async def _on_peer_error(self, peer: Peer, e: Exception) -> None:
        await self.stop_peer_for_error(peer, e)

    async def stop_peer_for_error(self, peer: Peer, reason) -> None:
        if not self.peers.has(peer.id):
            return
        RECORDER.record("p2p", "peer_error", peer=peer.id, err=str(reason)[:200])
        self.logger.info("stopping peer %s: %s", peer, reason)
        await self._stop_and_remove(peer, reason)
        if peer.persistent and self.is_running:
            addr = self._persistent_addrs.get(peer.id) or peer.socket_addr
            if addr is not None and addr.id:
                self.dialer.schedule(addr, persistent=True)

    async def stop_peer_gracefully(self, peer: Peer) -> None:
        await self._stop_and_remove(peer, "graceful stop")

    async def _stop_and_remove(self, peer: Peer, reason) -> None:
        self.peers.remove(peer)
        # stop charging elapsed empty intervals against a peer we are no
        # longer connected to (reference trust store PeerDisconnected)
        self.trust_store.peer_disconnected(peer.id)
        RECORDER.record("p2p", "peer_disconnected", peer=peer.id,
                        reason=str(reason)[:200])
        if self.metrics is not None:
            self.metrics.peers.set(len(self.peers))
        await peer.stop()
        for reactor in self.reactors.values():
            await reactor.remove_peer(peer, reason)

    # --- messaging -------------------------------------------------------

    async def broadcast(self, ch_id: int, msg: bytes) -> None:
        """Fan out to all peers (reference switch.go:258); failures are the
        peer's problem, not the broadcaster's."""
        await asyncio.gather(
            *(p.send(ch_id, msg) for p in self.peers.list()),
            return_exceptions=True,
        )

    def num_peers(self) -> tuple[int, int]:
        out = sum(1 for p in self.peers.list() if p.outbound)
        return out, len(self.peers) - out

    # --- wire-efficiency observatory -------------------------------------

    def sendq_stall_age(self, now: float | None = None) -> float:
        """Longest time (s) any peer channel's send queue has stayed
        saturated, 0.0 when none is. Lazy scan: called by health() and the
        1 Hz gauge sampler, so a stall older than TMTPU_SENDQ_STALL_S
        degrades health without a dedicated watcher task."""
        now = time.monotonic() if now is None else now
        live: set[tuple[str, int]] = set()
        for p in self.peers.list():
            for ch in p.mconn._channels.values():
                cap = ch.desc.send_queue_capacity
                if cap > 0 and ch.queue.qsize() >= cap:
                    key = (p.id, ch.desc.id)
                    live.add(key)
                    self._sendq_sat.setdefault(key, now)
        for key in list(self._sendq_sat):
            if key not in live:
                del self._sendq_sat[key]
        if not self._sendq_sat:
            return 0.0
        return max(now - t0 for t0 in self._sendq_sat.values())

    def sample_traffic_gauges(self) -> None:
        """Feed the send-queue depth and flowrate-utilization gauges from
        each live MConnection; driven by the node's 1 Hz metrics sampler.
        Also advances the sendq-stall tracker so health() sees stalls even
        between its own polls."""
        self.sendq_stall_age()
        m = self._metrics
        if m is None:
            return
        for p in self.peers.list():
            mc = p.mconn
            pid = p.id[:8]
            for ch in mc._channels.values():
                m.send_queue_depth.set(
                    ch.queue.qsize(), peer=pid, channel=f"{ch.desc.id:#04x}"
                )
            m.flowrate_utilization.set(
                round(mc._send_monitor.utilization(mc.config.send_rate), 4),
                peer=pid, direction="send",
            )
            m.flowrate_utilization.set(
                round(mc._recv_monitor.utilization(mc.config.recv_rate), 4),
                peer=pid, direction="recv",
            )

    def traffic_conn_snapshot(self) -> dict:
        """Per-peer packet-layer accounting (framing overhead, throttle
        wait, queue depths, utilization) for debug_traffic."""
        return {p.id: p.mconn.traffic_snapshot() for p in self.peers.list()}

    # --- introspection (debug_p2p route) ---------------------------------

    def quality_snapshot(self) -> dict:
        """Trust scores, live bans, and dialer state for debug_p2p."""
        scores = {
            pid: tm.trust_score()
            for pid, tm in self.trust_store.metrics.items()
        }
        self._refresh_ban_gauge()  # debug_p2p reads re-sync expiry
        return {
            "peers": [
                {
                    "id": p.id,
                    "outbound": p.outbound,
                    "persistent": p.persistent,
                    "trust_score": scores.get(p.id, 100),
                }
                for p in self.peers.list()
            ],
            "trust": scores,
            "bans": self._ban_backend().bans(),
            "ban_threshold": self.ban_threshold,
            "dialer": self.dialer.snapshot(),
        }
