"""Switch: peer lifecycle + reactor message routing.

Reference parity: p2p/switch.go:67 — owns the transport, the peer set, and
all reactors. `add_reactor` claims channel IDs (switch.go:154); `broadcast`
fans out to every peer (switch.go:258); dial/accept routines add peers with
retry + exponential backoff for persistent peers (switch.go:362,572).
"""
from __future__ import annotations

import asyncio
import random

from tendermint_tpu.libs.recorder import RECORDER
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.transport import RejectedError, Transport

RECONNECT_BASE_DELAY = 1.0
RECONNECT_MAX_DELAY = 300.0
MAX_RECONNECT_ATTEMPTS = 20


class SwitchError(Exception):
    pass


class PeerSet:
    def __init__(self) -> None:
        self._by_id: dict[str, Peer] = {}

    def add(self, peer: Peer) -> None:
        if peer.id in self._by_id:
            raise SwitchError(f"duplicate peer {peer.id}")
        self._by_id[peer.id] = peer

    def remove(self, peer: Peer) -> bool:
        return self._by_id.pop(peer.id, None) is not None

    def has(self, peer_id: str) -> bool:
        return peer_id in self._by_id

    def get(self, peer_id: str) -> Peer | None:
        return self._by_id.get(peer_id)

    def list(self) -> list[Peer]:
        return list(self._by_id.values())

    def __len__(self) -> int:
        return len(self._by_id)


class Switch(BaseService):
    def __init__(
        self,
        transport: Transport,
        max_inbound_peers: int = 40,
        max_outbound_peers: int = 10,
        fuzz_config=None,  # p2p.fuzz.FuzzConfig | None (config.p2p.test_fuzz)
        fault_control: bool = False,  # config.p2p.test_fault_control
    ) -> None:
        super().__init__(name="Switch")
        self.transport = transport
        self.fuzz_config = fuzz_config
        self.fault_control = fault_control
        self.peers = PeerSet()
        self.reactors: dict[str, object] = {}
        self._chan_descs: list = []
        self._reactors_by_ch: dict[int, object] = {}
        self.max_inbound_peers = max_inbound_peers
        self.max_outbound_peers = max_outbound_peers
        self._dialing: set[str] = set()
        self._reconnecting: set[str] = set()
        self._persistent_addrs: dict[str, NetAddress] = {}
        self.addr_book = None  # optional, set by PEX wiring
        # libs/metrics.P2PMetrics | None, set by the node when Prometheus
        # is on; propagated to each Peer for per-channel byte counters
        self.metrics = None

    def node_id(self) -> str:
        return self.transport.node_key.id()

    # --- reactors --------------------------------------------------------

    def add_reactor(self, name: str, reactor) -> None:
        for d in reactor.get_channels():
            if d.id in self._reactors_by_ch:
                raise SwitchError(f"channel {d.id:#x} already claimed")
            self._reactors_by_ch[d.id] = reactor
            self._chan_descs.append(d)
        self.reactors[name] = reactor
        reactor.set_switch(self)

    def reactor(self, name: str):
        return self.reactors.get(name)

    # --- lifecycle -------------------------------------------------------

    async def on_start(self) -> None:
        for reactor in self.reactors.values():
            await reactor.start()
        self.spawn(self._accept_routine(), "switch-accept")

    async def on_stop(self) -> None:
        for peer in self.peers.list():
            await self._stop_and_remove(peer, "switch stopping")
        for reactor in self.reactors.values():
            await reactor.stop()
        await self.transport.stop()

    async def _accept_routine(self) -> None:
        while True:
            conn, ni, addr = await self.transport.accept()
            inbound = sum(1 for p in self.peers.list() if not p.outbound)
            if inbound >= self.max_inbound_peers:
                self.logger.debug("rejecting inbound %s: at capacity", ni.node_id)
                conn.close()
                continue
            try:
                await self._add_peer(conn, ni, outbound=False, socket_addr=addr)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # any failure (reactor add_peer bug included) must not kill
                # the accept loop — the node would stop taking inbound peers
                self.logger.debug("inbound peer rejected: %s", e)
                conn.close()

    # --- dialing ---------------------------------------------------------

    async def dial_peers_async(
        self, addrs: list[NetAddress], persistent: bool = False
    ) -> None:
        for addr in addrs:
            if persistent and addr.id:
                self._persistent_addrs[addr.id] = addr
            self.spawn(self._dial_one(addr, persistent), f"dial-{addr.id[:8]}")

    async def _dial_one(self, addr: NetAddress, persistent: bool) -> None:
        ok = await self._dial_attempt(addr, persistent)
        if not ok and persistent:
            self._schedule_reconnect(addr)

    async def _dial_attempt(self, addr: NetAddress, persistent: bool) -> bool:
        """One dial + add-peer attempt with addr-book bookkeeping; returns
        True on success (or if already connected/dialing)."""
        key = addr.id or addr.dial_string()
        if key in self._dialing or (addr.id and self.peers.has(addr.id)):
            return True
        self._dialing.add(key)
        try:
            # jitter so a restarted network doesn't dial in lockstep
            await asyncio.sleep(random.random() * 0.05)
            conn, ni = await self.transport.dial(addr)
            await self._add_peer(
                conn, ni, outbound=True, persistent=persistent, socket_addr=addr
            )
            if self.addr_book is not None:
                self.addr_book.mark_good(addr)
            return True
        except (OSError, RejectedError, SwitchError, asyncio.TimeoutError) as e:
            self.logger.debug("dial %s failed: %s", addr, e)
            if self.addr_book is not None:
                self.addr_book.mark_attempt(addr)
            return False
        finally:
            self._dialing.discard(key)

    def _schedule_reconnect(self, addr: NetAddress) -> None:
        if addr.id in self._reconnecting or not self.is_running:
            return
        self._reconnecting.add(addr.id)
        self.spawn(self._reconnect_routine(addr), f"reconnect-{addr.id[:8]}")

    async def _reconnect_routine(self, addr: NetAddress) -> None:
        """Exponential backoff redial for persistent peers
        (reference switch.go:362 reconnectToPeer)."""
        try:
            delay = RECONNECT_BASE_DELAY
            for _ in range(MAX_RECONNECT_ATTEMPTS):
                await asyncio.sleep(delay * (1 + random.random() * 0.1))
                if not self.is_running or self.peers.has(addr.id):
                    return
                if await self._dial_attempt(addr, persistent=True):
                    return
                delay = min(delay * 2, RECONNECT_MAX_DELAY)
            self.logger.info("gave up reconnecting to %s", addr)
        finally:
            self._reconnecting.discard(addr.id)

    # --- peer management -------------------------------------------------

    async def _add_peer(
        self, conn, ni, outbound: bool, persistent: bool = False, socket_addr=None
    ) -> Peer:
        if ni.node_id == self.node_id():
            raise SwitchError("self connection")
        if self.peers.has(ni.node_id):
            raise SwitchError(f"already connected to {ni.node_id}")
        persistent = persistent or ni.node_id in self._persistent_addrs
        if self.fuzz_config is not None:
            # config.p2p.test_fuzz (reference p2p/test_util.go:229-232):
            # wrap the authenticated conn so every peer link drops/delays
            # probabilistically AFTER the start_after grace
            from tendermint_tpu.p2p.fuzz import FuzzedConnection

            conn = FuzzedConnection(conn, self.fuzz_config)
        if self.fault_control:
            # nemesis plane (config.p2p.test_fault_control): per-link
            # runtime faults keyed by the remote peer id, outermost so a
            # partition blackholes the link below any fuzz layer
            from tendermint_tpu.libs.fault import FaultedConnection

            conn = FaultedConnection(conn, ni.node_id)
        peer = Peer(
            conn,
            ni,
            self._chan_descs,
            on_receive=self._route_receive,
            on_error=self._on_peer_error,
            outbound=outbound,
            persistent=persistent,
            socket_addr=socket_addr,
        )
        peer.metrics = self.metrics  # per-channel byte counters from byte 0
        for reactor in self.reactors.values():
            reactor.init_peer(peer)
        self.peers.add(peer)
        try:
            await peer.start()
            for reactor in self.reactors.values():
                await reactor.add_peer(peer)
        except Exception:
            self.peers.remove(peer)
            await peer.stop()
            raise
        RECORDER.record("p2p", "peer_connected", peer=peer.id, outbound=outbound)
        if self.metrics is not None:
            self.metrics.peers.set(len(self.peers))
        self.logger.info("added peer %s (%s)", peer, "out" if outbound else "in")
        return peer

    async def _route_receive(self, ch_id: int, peer: Peer, msg: bytes) -> None:
        reactor = self._reactors_by_ch.get(ch_id)
        if reactor is None:
            await self.stop_peer_for_error(peer, f"msg on unclaimed channel {ch_id:#x}")
            return
        await reactor.receive(ch_id, peer, msg)

    async def _on_peer_error(self, peer: Peer, e: Exception) -> None:
        await self.stop_peer_for_error(peer, e)

    async def stop_peer_for_error(self, peer: Peer, reason) -> None:
        if not self.peers.has(peer.id):
            return
        RECORDER.record("p2p", "peer_error", peer=peer.id, err=str(reason)[:200])
        self.logger.info("stopping peer %s: %s", peer, reason)
        await self._stop_and_remove(peer, reason)
        if peer.persistent and self.is_running:
            addr = self._persistent_addrs.get(peer.id) or peer.socket_addr
            if addr is not None and addr.id:
                self._schedule_reconnect(addr)

    async def stop_peer_gracefully(self, peer: Peer) -> None:
        await self._stop_and_remove(peer, "graceful stop")

    async def _stop_and_remove(self, peer: Peer, reason) -> None:
        self.peers.remove(peer)
        RECORDER.record("p2p", "peer_disconnected", peer=peer.id,
                        reason=str(reason)[:200])
        if self.metrics is not None:
            self.metrics.peers.set(len(self.peers))
        await peer.stop()
        for reactor in self.reactors.values():
            await reactor.remove_peer(peer, reason)

    # --- messaging -------------------------------------------------------

    async def broadcast(self, ch_id: int, msg: bytes) -> None:
        """Fan out to all peers (reference switch.go:258); failures are the
        peer's problem, not the broadcaster's."""
        await asyncio.gather(
            *(p.send(ch_id, msg) for p in self.peers.list()),
            return_exceptions=True,
        )

    def num_peers(self) -> tuple[int, int]:
        out = sum(1 for p in self.peers.list() if p.outbound)
        return out, len(self.peers) - out
