"""In-process p2p test harness.

Reference parity: p2p/test_util.go:75,97 — MakeConnectedSwitches builds N
switches and fully connects them. Here switches listen on 127.0.0.1 ephemeral
ports and dial each other over real sockets (the reference uses net.Pipe;
loopback TCP exercises the same code path and stays asyncio-native).
"""
from __future__ import annotations

import asyncio

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import Transport


def make_node_info(node_key: NodeKey, channels: bytes, network: str = "test-chain") -> NodeInfo:
    return NodeInfo(
        node_id=node_key.id(),
        listen_addr="127.0.0.1:0",
        network=network,
        version="dev",
        channels=channels,
        moniker=f"test-{node_key.id()[:8]}",
    )


async def make_switch(reactors: dict[str, object], network: str = "test-chain") -> Switch:
    """One switch with the given reactors, listening on an ephemeral port."""
    node_key = NodeKey(ed25519.gen_priv_key())
    channels = bytes(
        d.id for r in reactors.values() for d in r.get_channels()
    )
    transport = Transport(node_key, make_node_info(node_key, channels, network))
    sw = Switch(transport)
    for name, r in reactors.items():
        sw.add_reactor(name, r)
    await transport.listen(NetAddress("", "127.0.0.1", 0))
    return sw


async def make_connected_switches(
    n: int, reactor_factory, network: str = "test-chain"
) -> list[Switch]:
    """N started switches, fully connected (each i dials all j > i).
    reactor_factory(i) -> dict[str, Reactor]."""
    switches = []
    for i in range(n):
        sw = await make_switch(reactor_factory(i), network)
        await sw.start()
        switches.append(sw)
    for i, sw in enumerate(switches):
        addrs = [switches[j].transport.listen_addr for j in range(i + 1, n)]
        await sw.dial_peers_async(addrs)
    await wait_for_peers(switches, n - 1)
    return switches


async def wait_for_peers(switches, want: int, timeout: float = 10.0) -> None:
    async def _all_connected():
        while any(len(sw.peers) < want for sw in switches):
            await asyncio.sleep(0.02)

    await asyncio.wait_for(_all_connected(), timeout)


async def stop_switches(switches) -> None:
    for sw in switches:
        await sw.stop()
