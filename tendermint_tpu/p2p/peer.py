"""Peer: one connected remote node.

Reference parity: p2p/peer.go — wraps the MConnection, carries the remote
NodeInfo, a key-value store for per-peer reactor state (Set/Get), and
send/try_send routed by channel ID.
"""
from __future__ import annotations

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn.connection import MConnection, MConnConfig
from tendermint_tpu.p2p.node_info import NodeInfo


class Peer(BaseService):
    def __init__(
        self,
        conn,  # SecretConnection (already handshaked)
        node_info: NodeInfo,
        chan_descs,
        on_receive,  # async (ch_id, peer, msg) -> None
        on_error,  # async (peer, exc) -> None
        outbound: bool,
        persistent: bool = False,
        mconfig: MConnConfig | None = None,
        socket_addr=None,
    ) -> None:
        super().__init__(name=f"Peer:{node_info.node_id[:8]}")
        self.node_info = node_info
        self.outbound = outbound
        self.persistent = persistent
        self.socket_addr = socket_addr  # NetAddress dialed/accepted from
        self._data: dict[str, object] = {}
        # libs/metrics.P2PMetrics | None, set by the switch: per-channel
        # byte counters at the message layer (reference p2p/peer.go wraps
        # onReceive/send the same way). Counters are bound per channel on
        # first use so the per-message cost is one dict-get + add.
        self.metrics = None
        self._send_ctrs: dict[int, object] = {}
        self._recv_ctrs: dict[int, object] = {}

        async def _recv(ch_id: int, msg: bytes) -> None:
            if self.metrics is not None:
                self._count(
                    self._recv_ctrs, self.metrics.peer_receive_bytes_total,
                    ch_id, len(msg),
                )
            await on_receive(ch_id, self, msg)

        async def _err(e: Exception) -> None:
            await on_error(self, e)

        self.mconn = MConnection(conn, chan_descs, _recv, _err, mconfig)

    @property
    def id(self) -> str:
        return self.node_info.node_id

    async def on_start(self) -> None:
        await self.mconn.start()

    async def on_stop(self) -> None:
        await self.mconn.stop()

    @staticmethod
    def _count(cache: dict, counter, ch_id: int, n: int) -> None:
        ctr = cache.get(ch_id)
        if ctr is None:
            ctr = counter.bind(channel=f"{ch_id:#04x}")
            cache[ch_id] = ctr
        ctr.inc(n)

    async def send(self, ch_id: int, msg: bytes) -> bool:
        ok = await self.mconn.send(ch_id, msg)
        if ok and self.metrics is not None:
            self._count(self._send_ctrs, self.metrics.peer_send_bytes_total,
                        ch_id, len(msg))
        return ok

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        ok = self.mconn.try_send(ch_id, msg)
        if ok and self.metrics is not None:
            self._count(self._send_ctrs, self.metrics.peer_send_bytes_total,
                        ch_id, len(msg))
        return ok

    def set(self, key: str, value) -> None:
        self._data[key] = value

    def get(self, key: str):
        return self._data.get(key)

    def __repr__(self) -> str:
        d = "out" if self.outbound else "in"
        return f"Peer{{{self.id[:12]} {d}}}"
