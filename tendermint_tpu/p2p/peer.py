"""Peer: one connected remote node.

Reference parity: p2p/peer.go — wraps the MConnection, carries the remote
NodeInfo, a key-value store for per-peer reactor state (Set/Get), and
send/try_send routed by channel ID.
"""
from __future__ import annotations

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn.connection import MConnection, MConnConfig
from tendermint_tpu.p2p.node_info import NodeInfo


class Peer(BaseService):
    def __init__(
        self,
        conn,  # SecretConnection (already handshaked)
        node_info: NodeInfo,
        chan_descs,
        on_receive,  # async (ch_id, peer, msg) -> None
        on_error,  # async (peer, exc) -> None
        outbound: bool,
        persistent: bool = False,
        mconfig: MConnConfig | None = None,
        socket_addr=None,
    ) -> None:
        super().__init__(name=f"Peer:{node_info.node_id[:8]}")
        self.node_info = node_info
        self.outbound = outbound
        self.persistent = persistent
        self.socket_addr = socket_addr  # NetAddress dialed/accepted from
        self._data: dict[str, object] = {}
        # libs/metrics.P2PMetrics | None, set by the switch: per-channel
        # byte counters at the message layer (reference p2p/peer.go wraps
        # onReceive/send the same way). Counters are bound per channel on
        # first use so the per-message cost is one dict-get + add.
        self.metrics = None
        self._send_ctrs: dict[int, object] = {}
        self._recv_ctrs: dict[int, object] = {}
        # wire-efficiency observatory, set by the switch alongside
        # metrics: the per-switch TrafficLedger and the reactor-boundary
        # classify dispatcher (ch_id, msg) -> message-type label. The
        # send side is attributed here (the only place that sees every
        # outbound message); the receive side rolls up in the switch's
        # _route_receive, which already resolves the reactor.
        self.traffic = None
        self.classify = None
        self._send_msg_ctrs: dict[tuple[int, str], tuple] = {}

        async def _recv(ch_id: int, msg: bytes) -> None:
            if self.metrics is not None:
                self._count(
                    self._recv_ctrs, self.metrics.peer_receive_bytes_total,
                    ch_id, len(msg),
                )
            await on_receive(ch_id, self, msg)

        async def _err(e: Exception) -> None:
            await on_error(self, e)

        self.mconn = MConnection(conn, chan_descs, _recv, _err, mconfig)

    @property
    def id(self) -> str:
        return self.node_info.node_id

    async def on_start(self) -> None:
        await self.mconn.start()

    async def on_stop(self) -> None:
        await self.mconn.stop()

    @staticmethod
    def _count(cache: dict, counter, ch_id: int, n: int) -> None:
        ctr = cache.get(ch_id)
        if ctr is None:
            ctr = counter.bind(channel=f"{ch_id:#04x}")
            cache[ch_id] = ctr
        ctr.inc(n)

    def _account_send(self, ch_id: int, msg: bytes) -> None:
        if self.traffic is None and self.metrics is None:
            return
        mtype = self.classify(ch_id, msg) if self.classify is not None else "other"
        if self.traffic is not None:
            self.traffic.note_msg(self.id, ch_id, mtype, "sent", len(msg))
        if self.metrics is not None:
            self._count(self._send_ctrs, self.metrics.peer_send_bytes_total,
                        ch_id, len(msg))
            pair = self._send_msg_ctrs.get((ch_id, mtype))
            if pair is None:
                labels = {"channel": f"{ch_id:#04x}", "type": mtype}
                pair = (
                    self.metrics.msg_sent_total.bind(**labels),
                    self.metrics.msg_sent_bytes.bind(**labels),
                )
                self._send_msg_ctrs[(ch_id, mtype)] = pair
            pair[0].inc()
            pair[1].inc(len(msg))

    async def send(self, ch_id: int, msg: bytes) -> bool:
        ok = await self.mconn.send(ch_id, msg)
        if ok:
            self._account_send(ch_id, msg)
        return ok

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        ok = self.mconn.try_send(ch_id, msg)
        if ok:
            self._account_send(ch_id, msg)
        return ok

    def set(self, key: str, value) -> None:
        self._data[key] = value

    def get(self, key: str):
        return self._data.get(key)

    def __repr__(self) -> str:
        d = "out" if self.outbound else "in"
        return f"Peer{{{self.id[:12]} {d}}}"
