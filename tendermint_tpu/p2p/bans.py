"""BanTable — the one implementation of the peer-ban policy.

Used by the PEX address book (persistent bans, book clock) and by
switches without an address book (local, monotonic clock). One place
owns the escalation rule — duration doubles per offence, capped at a
day — the lazy expiry pruning, and the listing shape, so the two
backends can never diverge (docs/p2p_resilience.md).
"""
from __future__ import annotations

import time

BAN_CAP_SECONDS = 86400.0  # one day


class BanTable:
    def __init__(self, clock=None, our_ids: set[str] | None = None) -> None:
        self._clock = clock or time.monotonic
        self.our_ids = our_ids if our_ids is not None else set()
        self._bans: dict[str, dict] = {}
        # repeat-offender memory outliving individual ban windows (session
        # only — the persisted trust scores are the durable reputation)
        self._counts: dict[str, int] = {}

    def ban(self, node_id: str, duration: float, reason: str = "") -> float:
        """Ban `node_id` for `duration` seconds; repeated bans double the
        effective duration (reputation decay has to be re-earned). Returns
        the applied duration."""
        if not node_id or node_id in self.our_ids:
            return 0.0
        count = self._counts.get(node_id, 0) + 1
        self._counts[node_id] = count
        applied = min(duration * (2 ** (count - 1)), BAN_CAP_SECONDS)
        self._bans[node_id] = {
            "expires": self._clock() + applied,
            "reason": reason[:200],
            "count": count,
        }
        return applied

    def unban(self, node_id: str) -> None:
        self._bans.pop(node_id, None)

    def is_banned(self, node_id: str, now: float | None = None) -> bool:
        b = self._bans.get(node_id)
        if b is None:
            return False
        if (self._clock() if now is None else now) >= b["expires"]:
            # expired bans are pruned; `_counts` keeps the escalation
            # memory and the trust metric keeps the longer reputation
            self._bans.pop(node_id, None)
            return False
        return True

    def bans(self) -> list[dict]:
        """Live bans (debug_p2p): [{id, remaining_s, reason, count}]."""
        now = self._clock()
        out = []
        for node_id in list(self._bans):
            b = self._bans.get(node_id)
            if b is None or now >= b["expires"]:
                self._bans.pop(node_id, None)
                continue
            out.append({
                "id": node_id,
                "remaining_s": round(b["expires"] - now, 1),
                "reason": b["reason"],
                "count": b["count"],
            })
        return out

    def live(self) -> dict[str, dict]:
        """Unexpired raw entries (persistence): id -> {expires(mono),
        reason, count}."""
        now = self._clock()
        return {
            node_id: b
            for node_id, b in self._bans.items()
            if b["expires"] > now
        }

    def restore(self, node_id: str, remaining: float, reason: str,
                count: int) -> None:
        """Re-create a ban with `remaining` seconds left (load path)."""
        if not node_id or node_id in self.our_ids or remaining <= 0:
            return
        count = max(1, count)
        self._bans[node_id] = {
            "expires": self._clock() + min(remaining, BAN_CAP_SECONDS),
            "reason": str(reason)[:200],
            "count": count,
        }
        self._counts[node_id] = max(self._counts.get(node_id, 0), count)
