"""Node identity key.

Reference parity: p2p/key.go — a node's ID is the hex of the address of its
ed25519 public key (address = first 20 bytes of SHA256(pubkey), same rule as
validator addresses). The key persists as a JSON file.
"""
from __future__ import annotations

import json
import os

from tendermint_tpu.crypto import PubKey
from tendermint_tpu.crypto import ed25519


def node_id_from_pubkey(pub: PubKey) -> str:
    return pub.address().hex()


class NodeKey:
    """Persistent ed25519 identity for the p2p layer."""

    def __init__(self, priv_key: ed25519.PrivKeyEd25519) -> None:
        self.priv_key = priv_key

    @property
    def pub_key(self) -> ed25519.PubKeyEd25519:
        return self.priv_key.pub_key()

    def id(self) -> str:
        return node_id_from_pubkey(self.pub_key)

    def save_as(self, path: str) -> None:
        doc = {"priv_key": {"type": "ed25519", "value": self.priv_key.bytes().hex()}}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        raw = bytes.fromhex(doc["priv_key"]["value"])
        return cls(ed25519.PrivKeyEd25519(raw))

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            return cls.load(path)
        nk = cls(ed25519.gen_priv_key())
        nk.save_as(path)
        return nk
