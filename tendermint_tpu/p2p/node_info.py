"""NodeInfo: identity + capability advertisement exchanged at handshake.

Reference parity: p2p/node_info.go — DefaultNodeInfo{ProtocolVersion, ID,
ListenAddr, Network, Version, Channels, Moniker, Other{TxIndex, RPCAddress}}
with CompatibleWith (same network, shared protocol block version, at least one
common channel) and Validate rules.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.encoding import Reader, Writer

MAX_NUM_CHANNELS = 16
MAX_MONIKER_LEN = 64


class NodeInfoError(Exception):
    pass


@dataclass
class ProtocolVersion:
    p2p: int = 1
    block: int = 1
    app: int = 0


@dataclass
class NodeInfo:
    node_id: str
    listen_addr: str
    network: str  # chain ID
    version: str
    channels: bytes  # one byte per advertised channel ID
    moniker: str = ""
    protocol_version: ProtocolVersion = field(default_factory=ProtocolVersion)
    tx_index: str = "on"
    rpc_address: str = ""

    def validate(self) -> None:
        if len(self.node_id) != 40:
            raise NodeInfoError(f"invalid node ID {self.node_id!r}")
        if len(self.channels) > MAX_NUM_CHANNELS:
            raise NodeInfoError(f"too many channels ({len(self.channels)})")
        if len(set(self.channels)) != len(self.channels):
            raise NodeInfoError("duplicate channel IDs")
        if len(self.moniker) > MAX_MONIKER_LEN:
            raise NodeInfoError("moniker too long")

    def compatible_with(self, other: "NodeInfo") -> None:
        """Raise NodeInfoError unless the peers can talk (reference
        p2p/node_info.go CompatibleWith)."""
        if self.protocol_version.block != other.protocol_version.block:
            raise NodeInfoError(
                f"block protocol mismatch: {self.protocol_version.block} vs "
                f"{other.protocol_version.block}"
            )
        if self.network != other.network:
            raise NodeInfoError(f"network mismatch: {self.network} vs {other.network}")
        if self.channels and other.channels:
            if not set(self.channels) & set(other.channels):
                raise NodeInfoError("no common channels")

    def encode(self) -> bytes:
        w = Writer()
        w.u64(self.protocol_version.p2p)
        w.u64(self.protocol_version.block)
        w.u64(self.protocol_version.app)
        w.str(self.node_id)
        w.str(self.listen_addr)
        w.str(self.network)
        w.str(self.version)
        w.bytes(self.channels)
        w.str(self.moniker)
        w.str(self.tx_index)
        w.str(self.rpc_address)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "NodeInfo":
        r = Reader(data)
        pv = ProtocolVersion(r.u64(), r.u64(), r.u64())
        ni = cls(
            node_id=r.str(),
            listen_addr=r.str(),
            network=r.str(),
            version=r.str(),
            channels=r.bytes(),
            moniker=r.str(),
            protocol_version=pv,
            tx_index=r.str(),
            rpc_address=r.str(),
        )
        r.expect_done()
        return ni
