"""p2p — the distributed communication backend (host-side).

Reference parity: p2p/ — Switch (switch.go:67), Reactor contract
(base_reactor.go), MultiplexTransport (transport.go:125), MConnection
multiplexed channels (conn/connection.go:74), SecretConnection authenticated
encryption (conn/secret_connection.go:49), PEX/addrbook (pex/).

Per SURVEY.md §2.3 the consensus gossip network stays host-side (TCP between
mutually untrusting machines); ICI/collectives are used only inside the batch
signature-verification data plane (tendermint_tpu.parallel). Everything here
is asyncio-native: goroutine-per-peer in the reference maps to task-per-peer.
"""
from __future__ import annotations

from tendermint_tpu.p2p.key import NodeKey, node_id_from_pubkey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.base_reactor import BaseReactor, ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Switch

__all__ = [
    "NodeKey",
    "node_id_from_pubkey",
    "NodeInfo",
    "NetAddress",
    "BaseReactor",
    "ChannelDescriptor",
    "Peer",
    "Switch",
]
