"""p2p — the distributed communication backend (host-side).

Reference parity: p2p/ — Switch (switch.go:67), Reactor contract
(base_reactor.go), MultiplexTransport (transport.go:125), MConnection
multiplexed channels (conn/connection.go:74), SecretConnection authenticated
encryption (conn/secret_connection.go:49), PEX/addrbook (pex/).

Per SURVEY.md §2.3 the consensus gossip network stays host-side (TCP between
mutually untrusting machines); ICI/collectives are used only inside the batch
signature-verification data plane (tendermint_tpu.parallel). Everything here
is asyncio-native: goroutine-per-peer in the reference maps to task-per-peer.
"""
from __future__ import annotations

import importlib

# Lazy exports (PEP 562): `from tendermint_tpu.p2p import Switch` still
# works, but importing a crypto-free submodule (trust, dialer, netaddress,
# pex.addrbook) no longer drags the `cryptography`-backed key/transport
# stack in — those modules must stay importable on hosts without the
# crypto package (the libs/fault.py precedent).
_EXPORTS = {
    "NodeKey": "tendermint_tpu.p2p.key",
    "node_id_from_pubkey": "tendermint_tpu.p2p.key",
    "NodeInfo": "tendermint_tpu.p2p.node_info",
    "NetAddress": "tendermint_tpu.p2p.netaddress",
    "BaseReactor": "tendermint_tpu.p2p.base_reactor",
    "ChannelDescriptor": "tendermint_tpu.p2p.base_reactor",
    "Peer": "tendermint_tpu.p2p.peer",
    "Switch": "tendermint_tpu.p2p.switch",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)
