"""Unified self-healing dialer — ONE backoff policy for every outbound dial.

Before this module the node had two dialing planes with different (and
partly broken) policies: the switch's `_reconnect_routine` gave up on
persistent peers after a fixed attempt cap (reference switch.go:362 has a
second, slower, *much longer* phase precisely so validators are never
permanently abandoned), and the PEX `ensure_peers` loop fired one-shot
dials with no backoff at all, so a flapping network redialed dead
addresses every sweep. Both now route here.

Policy (reference switch.go reconnectToPeer, :362):

- fast phase — jittered exponential backoff from `base_delay` doubling to
  `max_delay`, for up to `fast_attempts` attempts;
- slow phase — persistent peers only: UNBOUNDED further attempts every
  `slow_interval` (jittered). A validator peer is never abandoned; a
  transient (PEX-discovered) address is dropped after `transient_attempts`
  and left to the address book's staleness machinery;
- banned targets are not dialed: transient addresses are dropped, while
  persistent peers sleep a slow interval and re-check (the ban may have
  been an operator action or a decayed misunderstanding — a validator
  peer must come back once the ban expires);
- at most `max_concurrent` dial attempts run at once, and consecutive
  attempt *starts* are spaced `min_gap` apart — a restarted 100-node net
  churning all its links must not stampede the event loop (dial
  throttling under churn).

Every transition is a flight-recorder event (`p2p dial/dial_backoff/
dial_gave_up`) so a postmortem can see exactly why a link stayed down.
The dialer spawns its loops through the owning service's `spawn`, so
switch stop cancels them.
"""
from __future__ import annotations

import asyncio
import random
import time

from tendermint_tpu.libs.recorder import RECORDER

FAST_BASE_DELAY = 1.0
FAST_MAX_DELAY = 30.0
FAST_ATTEMPTS = 20
SLOW_INTERVAL = 300.0
TRANSIENT_ATTEMPTS = 3
MAX_CONCURRENT_DIALS = 8
MIN_DIAL_GAP = 0.05
JITTER = 0.2  # +- fraction applied to every sleep


class Dialer:
    """Owns one redial loop per target address.

    `dial_attempt(addr, persistent) -> bool` performs one dial + add-peer
    attempt (the switch's `_dial_attempt`); `has_peer(peer_id) -> bool`
    and `is_banned(peer_id) -> bool` gate attempts; `spawn` registers the
    loop task with the owning service; `is_running()` ends loops at
    shutdown.
    """

    def __init__(
        self,
        dial_attempt,
        *,
        has_peer,
        is_banned,
        spawn,
        is_running,
        base_delay: float = FAST_BASE_DELAY,
        max_delay: float = FAST_MAX_DELAY,
        fast_attempts: int = FAST_ATTEMPTS,
        slow_interval: float = SLOW_INTERVAL,
        transient_attempts: int = TRANSIENT_ATTEMPTS,
        max_concurrent: int = MAX_CONCURRENT_DIALS,
        min_gap: float = MIN_DIAL_GAP,
        metrics=None,  # libs/metrics.P2PMetrics | None
    ) -> None:
        self._dial_attempt = dial_attempt
        self._has_peer = has_peer
        self._is_banned = is_banned
        self._spawn = spawn
        self._is_running = is_running
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.fast_attempts = fast_attempts
        self.slow_interval = slow_interval
        self.transient_attempts = transient_attempts
        self.min_gap = min_gap
        self.metrics = metrics
        self._sem = asyncio.Semaphore(max(1, max_concurrent))
        self._next_start = 0.0  # monotonic; global inter-dial-start gap
        self._loops: dict[str, asyncio.Task] = {}
        self._persistent: dict[str, bool] = {}  # live loops' persistence
        # live introspection for debug_p2p: id -> {phase, attempts, next_in}
        self._state: dict[str, dict] = {}

    # -- public API --------------------------------------------------------

    def schedule(self, addr, persistent: bool = False) -> None:
        """Ensure a dial loop exists for `addr`. A live loop dedupes the
        call — EXCEPT that a transient loop is upgraded when the new
        request is persistent (a PEX sweep may race the node's own
        persistent-peer dial for the same address; the configured
        validator peer must never inherit give-up-after-3 semantics)."""
        key = addr.id or addr.dial_string()
        t = self._loops.get(key)
        if t is not None and not t.done():
            if not persistent or self._persistent.get(key, False):
                return
            t.cancel()  # upgrade: restart the loop with persistent policy
        self._persistent[key] = persistent
        self._loops[key] = self._spawn(
            self._dial_loop(key, addr, persistent), f"dial-{key[:8]}"
        )

    def cancel(self, peer_id: str) -> None:
        t = self._loops.pop(peer_id, None)
        if t is not None and not t.done():
            t.cancel()
        self._persistent.pop(peer_id, None)
        self._state.pop(peer_id, None)

    def snapshot(self) -> dict:
        """Live per-target dial state (debug_p2p)."""
        now = time.monotonic()
        out = {}
        for key, st in self._state.items():
            d = dict(st)
            due = d.pop("due", None)
            if due is not None:
                d["next_in_s"] = round(max(0.0, due - now), 3)
            out[key] = d
        return out

    # -- internals ---------------------------------------------------------

    def _jitter(self, t: float) -> float:
        return t * (1.0 + random.uniform(-JITTER, JITTER))

    async def _throttle(self) -> float:
        """Space dial starts `min_gap` apart globally; returns the wait
        actually imposed. Single event loop: the read-modify below has no
        suspension point, so no lock is needed."""
        now = time.monotonic()
        wait = max(0.0, self._next_start - now)
        self._next_start = max(now, self._next_start) + self.min_gap
        if wait > 0:
            await asyncio.sleep(wait)
        return wait

    async def _attempt(self, addr, persistent: bool) -> bool:
        async with self._sem:
            await self._throttle()
            m = self.metrics
            if m is not None:
                m.dials_total.inc()
            ok = await self._dial_attempt(addr, persistent)
            if not ok and m is not None:
                m.dial_failures_total.inc()
            return ok

    async def _dial_loop(self, key: str, addr, persistent: bool) -> None:
        attempts = 0
        delay = self.base_delay
        give_up_after = None if persistent else self.transient_attempts
        try:
            while self._is_running():
                if addr.id and self._has_peer(addr.id):
                    return
                if addr.id and self._is_banned(addr.id):
                    if not persistent:
                        RECORDER.record("p2p", "dial_gave_up", peer=key,
                                        attempts=attempts, reason="banned")
                        return
                    # persistent: sleep a slow interval and re-check — the
                    # ban decays, the validator link must come back
                    sleep_for = self._jitter(self.slow_interval)
                    self._state[key] = {
                        "phase": "banned", "attempts": attempts,
                        "persistent": persistent,
                        "due": time.monotonic() + sleep_for,
                    }
                    await asyncio.sleep(sleep_for)
                    continue
                self._state[key] = {
                    "phase": "dialing", "attempts": attempts,
                    "persistent": persistent,
                }
                if await self._attempt(addr, persistent):
                    RECORDER.record("p2p", "dial", peer=key, ok=True,
                                    attempts=attempts + 1)
                    return
                attempts += 1
                if give_up_after is not None and attempts >= give_up_after:
                    RECORDER.record("p2p", "dial_gave_up", peer=key,
                                    attempts=attempts, reason="transient")
                    return
                if attempts >= self.fast_attempts:
                    phase, sleep_for = "slow", self._jitter(self.slow_interval)
                else:
                    phase, sleep_for = "fast", self._jitter(delay)
                    delay = min(delay * 2, self.max_delay)
                RECORDER.record("p2p", "dial_backoff", peer=key, phase=phase,
                                attempts=attempts, next_s=round(sleep_for, 2))
                self._state[key] = {
                    "phase": phase, "attempts": attempts,
                    "persistent": persistent,
                    "due": time.monotonic() + sleep_for,
                }
                await asyncio.sleep(sleep_for)
        finally:
            t = self._loops.get(key)
            if t is not None and t is asyncio.current_task():
                # an upgraded loop's cancelled predecessor must not tear
                # down its successor's bookkeeping
                self._loops.pop(key, None)
                self._persistent.pop(key, None)
                self._state.pop(key, None)
