"""Configuration.

Reference parity: config/config.go:59 — Config of 9 sections (Base, RPC,
P2P, Mempool, FastSync, Consensus, TxIndex, Instrumentation); all consensus
timeouts including the per-round linear growth (config.go:796-811);
TOML-template persistence is replaced by JSON (config.json) with identical
precedence: flags > env > file > defaults.
"""
from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field, replace


@dataclass
class BaseConfig:
    chain_id: str = ""
    moniker: str = "node"
    fast_sync: bool = True
    db_backend: str = "sqlite"
    log_level: str = "info"
    proxy_app: str = "kvstore"
    abci: str = "local"  # local | socket | grpc (reference config.go ABCI)
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    genesis_file: str = "config/genesis.json"
    filter_peers: bool = False
    prof_laddr: str = ""


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    grpc_laddr: str = ""
    unsafe: bool = False
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit: float = 10.0
    # Per-client broadcast_tx_* flowrate ceiling (txs/s per remote host;
    # 0 = unlimited). Over-limit calls get a structured "rate-limited"
    # JSONRPC error instead of queueing unboundedly (docs/tx_ingestion.md).
    tx_rate_limit: float = 0.0
    # burst credit as a multiple of tx_rate_limit (token-bucket depth)
    tx_rate_burst: float = 2.0


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    flush_throttle_timeout: float = 0.1
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5120000
    recv_rate: int = 5120000
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: str = ""
    allow_duplicate_ip: bool = False
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0
    # Peer-quality plane (docs/p2p_resilience.md): behaviour reports feed
    # a per-peer trust metric (p2p/trust.py); a peer whose score crosses
    # ban_threshold (0-100) after accumulating ban_min_bad_weight of bad
    # behaviour is banned for ban_duration seconds (doubling for repeat
    # offenders, persisted in the address book across restarts). The
    # trust scores themselves persist in trust_file.
    trust_file: str = "data/peer_trust.json"
    ban_threshold: int = 20
    ban_min_bad_weight: float = 6.0
    ban_duration: float = 300.0
    # Unified self-healing dialer (p2p/dialer.py): at most this many dial
    # attempts in flight at once (churn throttling).
    max_concurrent_dials: int = 8
    test_fuzz: bool = False
    # Nemesis fault control (libs/fault.py): wrap every peer link in a
    # runtime-controllable fault injector driven by the `debug_fault`
    # RPC route (partition / asymmetric delay / drop, and device-breaker
    # tripping). Test harness only — leave off in production.
    test_fault_control: bool = False


@dataclass
class MempoolConfig:
    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = ""
    size: int = 5000
    max_txs_bytes: int = 1073741824
    cache_size: int = 10000
    max_tx_bytes: int = 1048576
    # Batched admission (docs/tx_ingestion.md): incoming txs park in an
    # ingest bucket that flushes as ONE CheckTxBatch round trip when it
    # crosses the streaming flush hint or after batch_window seconds.
    # batch_max pins the bucket high-water explicitly (0 = consult the
    # hint, capped at 4096). batch=False restores per-tx admission.
    batch: bool = True
    batch_window: float = 0.002
    batch_max: int = 0
    # Per-peer gossip tx-rate ceiling (txs/s; 0 = unlimited): over-limit
    # gossip is dropped before CheckTx and feeds the behaviour plane with
    # a non-error weight — an honest burst never trends toward a ban.
    gossip_tx_rate: float = 0.0


@dataclass
class FastSyncConfig:
    version: str = "v0"


@dataclass
class StateSyncConfig:
    """State sync (docs/state_sync.md, reference config.go StateSyncConfig):
    bootstrap a fresh node from an app-state snapshot discovered over p2p
    instead of replaying the chain — O(state), not O(history). The target
    header is verified by light-client bisection against `rpc_servers`
    (device batches at LITE priority); every chunk carries a merkle proof
    to that header's app hash, so a corrupt chunk can never apply. Only
    an EMPTY node state-syncs; a restarted node falls through to fast
    sync. Serving (answering peers' snapshot/chunk requests) is always on
    — `enable` arms only the restore side."""

    enable: bool = False
    # comma-separated `host:port` JSON-RPC endpoints used by the light
    # client for header verification (at least one required to sync)
    rpc_servers: str = ""
    # light-client trust anchor: first-contact header (height, hex block
    # hash). 0/"" = trust-on-first-use of the current head — fine for lab
    # nets, pin both in production.
    trust_height: int = 0
    trust_hash: str = ""
    # how long to collect snapshot advertisements before picking one
    discovery_time: float = 3.0
    # per-request chunk fetch timeout; a peer that times out is retried
    # elsewhere and behaviour-scored
    chunk_request_timeout: float = 10.0
    # parallel chunk fetchers (applies stay strictly in order)
    chunk_fetchers: int = 4


@dataclass
class ConsensusConfig:
    wal_path: str = "data/cs.wal/wal"
    # timeouts in seconds (reference config.go:730-824, ms there)
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    peer_gossip_sleep_duration: float = 0.1
    peer_query_maj23_sleep_duration: float = 2.0
    # vote micro-batching (SURVEY §7 hard part b): when a gossip burst is in
    # flight, wait up to this long for more votes so one device batch
    # verifies them all; 0 disables the wait (singletons never wait).
    vote_batch_window: float = 0.0015
    # Hard ceiling on adaptive accumulation: while votes KEEP ARRIVING and
    # the batch is under the backend's accumulation hint, the micro-batcher
    # extends the wait window-by-window up to this total — so a 10k-
    # validator vote storm crosses the device routing threshold instead of
    # serializing as sub-threshold windows (r2 VERDICT weak #3). An idle
    # queue stops the accumulation after one empty window, so small nets
    # pay at most one extra window of latency.
    vote_batch_max_window: float = 0.012
    vote_batch_cap: int = 4096
    # Streaming vote-verification pipeline (docs/vote_pipeline.md): vote
    # groups of at least vote_stream_min signatures verify OFF the
    # consensus loop (DeviceScheduler submit at CONSENSUS class) while the
    # next gossip window ingests; verdict application is a completion
    # stage with serial-equivalent semantics. vote_stream_inflight bounds
    # the pipeline depth (2 = classic double buffering). vote_stream_async
    # = False restores the fully synchronous verify.
    vote_stream_async: bool = True
    vote_stream_min: int = 8
    vote_stream_inflight: int = 2

    def propose_timeout(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote_timeout(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit_timeout(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_

    def commit_time(self) -> float:
        return self.timeout_commit


@dataclass
class DeviceConfig:
    # Device-mesh dispatch (docs/device_scheduler.md "Mesh dispatch"):
    # how many devices the DeviceScheduler's packed batches shard across.
    # 0 = auto (all visible devices), 1 = single-device dispatch
    # bit-for-bit as before, N >= 2 = at most N (clamped to the largest
    # power of two that the visible devices cover). The TMTPU_MESH env
    # var overrides this at runtime.
    mesh: int = 0


@dataclass
class TxIndexConfig:
    indexer: str = "kv"  # kv | null


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    namespace: str = "tendermint"
    # Event-loop liveness watchdog (libs/watchdog.py — the deadlock-mutex
    # analog, SURVEY §5): ping every `watchdog_interval` s, dump all task
    # + thread stacks when unserviced for `watchdog_grace` s. 0 = off.
    watchdog_interval: float = 0.0
    watchdog_grace: float = 10.0
    # Consensus timeline tracing (libs/trace.py): one trace per height
    # with per-step + device spans, served by the debug_consensus_trace
    # RPC route. Default-off — the disabled path adds no measurable
    # overhead to the verify hot loop.
    tracing: bool = False
    # completed height traces kept in memory for debug_consensus_trace
    trace_ring: int = 64
    # non-empty = also export every completed trace as one JSONL line
    # through a rotating autofile.Group at this path (relative to root)
    trace_jsonl_file: str = ""
    # Flight recorder (libs/recorder.py): bounded black-box event ring,
    # always on (appends are one GIL-atomic deque op). Dumps — on watchdog
    # stall, task crash, SIGUSR1, and stop-after-crash — are appended as
    # JSONL to this rotating file next to the trace export; empty disables
    # dumping (the ring and the debug_flight_recorder route stay live).
    flight_recorder_ring: int = 4096
    flight_recorder_dump_file: str = "data/flight_recorder.jsonl"
    # Transaction lifecycle tracing (libs/txlife.py): per-tx stage
    # timestamps (rpc_received → … → committed), hash-sampled so every
    # node samples the SAME txs and the fleet collector can stitch one
    # tx across nodes. Default-off; when off every tap is one boolean.
    # TMTPU_TXLIFE_SAMPLE overrides both knobs (>0 enables at that
    # rate, 0 forces off). Served by tx_status / debug_tx_lifecycle.
    txlife: bool = False
    txlife_sample: int = 16  # keep 1 tx in N (1 = every tx)
    txlife_ring: int = 8192  # flat stage-event ring (cursor protocol)
    # JSONL dump sink (rotating autofile.Group, dumped on node stop and
    # SIGUSR1 when the plane is armed); empty disables dumping
    txlife_dump_file: str = "data/tx_lifecycle.jsonl"


@dataclass
class Config:
    root_dir: str = "."
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    fast_sync: FastSyncConfig = field(default_factory=FastSyncConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)

    # -- path helpers -------------------------------------------------------

    def _abs(self, p: str) -> str:
        return p if os.path.isabs(p) else os.path.join(self.root_dir, p)

    @property
    def genesis_path(self) -> str:
        return self._abs(self.base.genesis_file)

    @property
    def priv_validator_key_path(self) -> str:
        return self._abs(self.base.priv_validator_key_file)

    @property
    def priv_validator_state_path(self) -> str:
        return self._abs(self.base.priv_validator_state_file)

    @property
    def node_key_path(self) -> str:
        return self._abs(self.base.node_key_file)

    @property
    def db_dir(self) -> str:
        return self._abs("data")

    @property
    def wal_path(self) -> str:
        return self._abs(self.consensus.wal_path)

    def validate_basic(self) -> None:
        for name, section in (
            ("consensus", self.consensus),
            ("p2p", self.p2p),
            ("mempool", self.mempool),
        ):
            for k, v in asdict(section).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool) and v < -1:
                    raise ValueError(f"config {name}.{k} must be >= -1, got {v}")

    # -- persistence --------------------------------------------------------

    def save(self, path: str | None = None) -> None:
        path = path or os.path.join(self.root_dir, "config", "config.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        d = asdict(self)
        d.pop("root_dir")
        with open(path, "w") as f:
            json.dump(d, f, indent=1, sort_keys=True)

    @classmethod
    def load(cls, root_dir: str) -> "Config":
        path = os.path.join(root_dir, "config", "config.json")
        cfg = cls(root_dir=root_dir)
        if os.path.exists(path):
            with open(path) as f:
                d = json.load(f)
            cfg = cls(
                root_dir=root_dir,
                base=BaseConfig(**d.get("base", {})),
                rpc=RPCConfig(**d.get("rpc", {})),
                p2p=P2PConfig(**d.get("p2p", {})),
                mempool=MempoolConfig(**d.get("mempool", {})),
                fast_sync=FastSyncConfig(**d.get("fast_sync", {})),
                statesync=StateSyncConfig(**d.get("statesync", {})),
                consensus=ConsensusConfig(**d.get("consensus", {})),
                device=DeviceConfig(**d.get("device", {})),
                tx_index=TxIndexConfig(**d.get("tx_index", {})),
                instrumentation=InstrumentationConfig(**d.get("instrumentation", {})),
            )
        return cfg


def make_test_config(root_dir: str) -> Config:
    """Fast timeouts for in-process tests (reference config.ResetTestRoot)."""
    cfg = Config(root_dir=root_dir)
    cfg.base.db_backend = "mem"
    cfg.consensus = ConsensusConfig(
        wal_path="data/cs.wal/wal",
        timeout_propose=0.4,
        timeout_propose_delta=0.1,
        timeout_prevote=0.2,
        timeout_prevote_delta=0.1,
        timeout_precommit=0.2,
        timeout_precommit_delta=0.1,
        timeout_commit=0.1,
        skip_timeout_commit=True,
        peer_gossip_sleep_duration=0.01,
        peer_query_maj23_sleep_duration=0.25,
    )
    # every test node runs the loop watchdog (SURVEY §5 deadlock tooling:
    # the reference runs all tests under -race + a deadlock mutex; here a
    # stalled loop dumps task stacks instead of timing out opaquely)
    cfg.instrumentation.watchdog_interval = 2.0
    cfg.instrumentation.watchdog_grace = 30.0
    os.makedirs(os.path.join(root_dir, "data"), exist_ok=True)
    os.makedirs(os.path.join(root_dir, "config"), exist_ok=True)
    return cfg
