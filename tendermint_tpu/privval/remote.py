"""Remote signer protocol — keep validator keys in a separate process (KMS).

Reference parity: privval/messages.go (req/resp union),
privval/signer_client.go:14,91 (validator side), signer_server.go (KMS
side), signer_listener_endpoint.go:18,155 (the validator LISTENS on
priv_validator_laddr and the KMS DIALS IN, with ping keepalive and
reconnect). Framing: u32 length prefix + CBE tagged union. Transport: tcp
(optionally upgraded to a SecretConnection) or unix socket.
"""
from __future__ import annotations

import asyncio

from tendermint_tpu.encoding import DecodeError, Reader, Writer
from tendermint_tpu.libs.log import NOP, Logger
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.types.priv_validator import PrivValidator
from tendermint_tpu.types.vote import Proposal, Vote
from tendermint_tpu.crypto import ed25519

PING_INTERVAL = 10.0
READ_TIMEOUT = 5.0

# message tags
_PUBKEY_REQ = 1
_PUBKEY_RESP = 2
_SIGN_VOTE_REQ = 3
_SIGNED_VOTE_RESP = 4
_SIGN_PROPOSAL_REQ = 5
_SIGNED_PROPOSAL_RESP = 6
_PING_REQ = 7
_PING_RESP = 8
_ERROR_RESP = 9


class RemoteSignerError(Exception):
    pass


def _frame(payload: bytes) -> bytes:
    return Writer().u32(len(payload)).raw(payload).build()


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(4)
    n = int.from_bytes(hdr, "big")
    if n > (1 << 20):
        raise DecodeError(f"remote signer frame too large: {n}")
    return await reader.readexactly(n)


def encode_signer_message(tag: int, chain_id: str = "", msg=None, err: str = "") -> bytes:
    w = Writer().u8(tag)
    if tag in (_SIGN_VOTE_REQ, _SIGN_PROPOSAL_REQ):
        w.str(chain_id).bytes(msg.encode())
    elif tag == _SIGNED_VOTE_RESP or tag == _SIGNED_PROPOSAL_RESP:
        w.bytes(msg.encode())
    elif tag == _PUBKEY_RESP:
        w.bytes(msg.bytes())
    elif tag == _ERROR_RESP:
        w.str(err)
    return w.build()


def decode_signer_message(data: bytes):
    """Returns (tag, payload) where payload depends on tag."""
    r = Reader(data)
    tag = r.u8()
    if tag in (_SIGN_VOTE_REQ, _SIGN_PROPOSAL_REQ):
        chain_id = r.str()
        raw = r.bytes()
        obj = Vote.decode(raw) if tag == _SIGN_VOTE_REQ else Proposal.decode(raw)
        r.expect_done()
        return tag, (chain_id, obj)
    if tag in (_SIGNED_VOTE_RESP, _SIGNED_PROPOSAL_RESP):
        raw = r.bytes()
        obj = Vote.decode(raw) if tag == _SIGNED_VOTE_RESP else Proposal.decode(raw)
        r.expect_done()
        return tag, obj
    if tag == _PUBKEY_RESP:
        pk = ed25519.PubKeyEd25519(r.bytes())
        r.expect_done()
        return tag, pk
    if tag == _ERROR_RESP:
        err = r.str()
        r.expect_done()
        return tag, err
    if tag in (_PUBKEY_REQ, _PING_REQ, _PING_RESP):
        r.expect_done()
        return tag, None
    raise DecodeError(f"unknown signer message tag {tag}")


class SignerListenerEndpoint(BaseService):
    """Validator side: listens on an address, accepts ONE signer connection
    at a time, and exposes a request/response API (reference
    signer_listener_endpoint.go:18)."""

    def __init__(self, host: str, port: int, logger: Logger = NOP) -> None:
        super().__init__("SignerListenerEndpoint")
        self.host, self.port = host, port
        self.log = logger
        self._server: asyncio.Server | None = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._connected = asyncio.Event()
        self._io_lock = asyncio.Lock()

    @property
    def listen_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)

    async def on_stop(self) -> None:
        if self._writer is not None:
            self._writer.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        if self._connected.is_set():
            writer.close()  # single active signer connection
            return
        self.log.info("remote signer connected")
        self._reader, self._writer = reader, writer
        self._connected.set()

    async def wait_for_conn(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._connected.wait(), timeout)

    async def request(self, payload: bytes) -> tuple[int, object]:
        """Send one framed request, wait for the framed response."""
        async with self._io_lock:
            if not self._connected.is_set():
                raise RemoteSignerError("no signer connected")
            try:
                self._writer.write(_frame(payload))
                await self._writer.drain()
                resp = await asyncio.wait_for(_read_frame(self._reader), READ_TIMEOUT)
            except (asyncio.IncompleteReadError, asyncio.TimeoutError, OSError) as e:
                self._connected.clear()
                raise RemoteSignerError(f"signer connection failed: {e!r}") from e
        tag, obj = decode_signer_message(resp)
        if tag == _ERROR_RESP:
            raise RemoteSignerError(str(obj))
        return tag, obj


class SignerClient(PrivValidator):
    """The PrivValidator the node uses when keys are remote (reference
    signer_client.go:91). Synchronous interface over the async endpoint —
    consensus calls sign_vote/sign_proposal from within the event loop, so
    these are async-under-the-hood via the endpoint's request()."""

    def __init__(self, endpoint: SignerListenerEndpoint) -> None:
        self.endpoint = endpoint
        self._pub_key = None

    async def fetch_pub_key(self):
        tag, pk = await self.endpoint.request(encode_signer_message(_PUBKEY_REQ))
        if tag != _PUBKEY_RESP:
            raise RemoteSignerError(f"unexpected response tag {tag}")
        self._pub_key = pk
        return pk

    def get_pub_key(self):
        if self._pub_key is None:
            raise RemoteSignerError("pub key not fetched yet (call fetch_pub_key)")
        return self._pub_key

    async def sign_vote_async(self, chain_id: str, vote: Vote) -> Vote:
        tag, v = await self.endpoint.request(
            encode_signer_message(_SIGN_VOTE_REQ, chain_id, vote)
        )
        if tag != _SIGNED_VOTE_RESP:
            raise RemoteSignerError(f"unexpected response tag {tag}")
        return v

    async def sign_proposal_async(self, chain_id: str, proposal: Proposal) -> Proposal:
        tag, p = await self.endpoint.request(
            encode_signer_message(_SIGN_PROPOSAL_REQ, chain_id, proposal)
        )
        if tag != _SIGNED_PROPOSAL_RESP:
            raise RemoteSignerError(f"unexpected response tag {tag}")
        return p

    async def ping(self) -> None:
        tag, _ = await self.endpoint.request(encode_signer_message(_PING_REQ))
        if tag != _PING_RESP:
            raise RemoteSignerError(f"unexpected ping response tag {tag}")

    # sync PrivValidator interface: only usable via the async variants;
    # consensus detects and awaits these (see ConsensusState.sign_vote).
    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        raise RemoteSignerError("use sign_vote_async for remote signers")

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        raise RemoteSignerError("use sign_proposal_async for remote signers")


class SignerServer(BaseService):
    """KMS side: dials the validator and serves signing requests from a
    local PrivValidator (reference signer_server.go + signer_dialer_endpoint).
    """

    def __init__(
        self, host: str, port: int, pv: PrivValidator, logger: Logger = NOP,
        retry_interval: float = 0.5, max_retries: int = 20,
    ) -> None:
        super().__init__("SignerServer")
        self.host, self.port = host, port
        self.pv = pv
        self.log = logger
        self.retry_interval = retry_interval
        self.max_retries = max_retries

    async def on_start(self) -> None:
        for attempt in range(self.max_retries):
            try:
                reader, writer = await asyncio.open_connection(self.host, self.port)
                break
            except OSError:
                await asyncio.sleep(self.retry_interval)
        else:
            raise RemoteSignerError(f"cannot reach validator at {self.host}:{self.port}")
        self._writer = writer
        self.spawn(self._serve(reader, writer), "signer-serve")

    async def on_stop(self) -> None:
        self._writer.close()

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        while True:
            try:
                req = await _read_frame(reader)
            except (asyncio.IncompleteReadError, OSError):
                self.log.info("validator connection closed")
                return
            writer.write(_frame(self._handle(req)))
            await writer.drain()

    def _handle(self, req: bytes) -> bytes:
        """Reference signer_requestHandler.go DefaultValidationRequestHandler."""
        try:
            tag, payload = decode_signer_message(req)
            if tag == _PUBKEY_REQ:
                return encode_signer_message(_PUBKEY_RESP, msg=self.pv.get_pub_key())
            if tag == _PING_REQ:
                return encode_signer_message(_PING_RESP)
            if tag == _SIGN_VOTE_REQ:
                chain_id, vote = payload
                signed = self.pv.sign_vote(chain_id, vote)
                return encode_signer_message(_SIGNED_VOTE_RESP, msg=signed)
            if tag == _SIGN_PROPOSAL_REQ:
                chain_id, proposal = payload
                signed = self.pv.sign_proposal(chain_id, proposal)
                return encode_signer_message(_SIGNED_PROPOSAL_RESP, msg=signed)
            return encode_signer_message(_ERROR_RESP, err=f"unexpected request tag {tag}")
        except Exception as e:
            return encode_signer_message(_ERROR_RESP, err=str(e))
