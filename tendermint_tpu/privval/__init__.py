"""privval — validator key management with double-sign protection.

Reference parity: privval/file.go:137 — FilePV is a key file plus a
last-sign-state file; it refuses to sign if (height, round, step)
regresses, and allows re-signing only of a message identical to the last
one except for its timestamp (:86,282-361,379). The last-sign-state file is
the anti-double-sign checkpoint and is fsynced before the signature is
returned (sign-then-persist would allow double signing across a crash).

The remote-signer protocol lives in tendermint_tpu/privval/remote.py.
"""
from __future__ import annotations

import json
import os
import tempfile

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.types.priv_validator import PrivValidator
from tendermint_tpu.types.vote import Proposal, Vote

# sign-state steps (reference privval/file.go:41-45)
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_TYPE_TO_STEP = {1: STEP_PREVOTE, 2: STEP_PRECOMMIT}


class DoubleSignError(Exception):
    pass


def _atomic_write(path: str, data: bytes) -> None:
    """Write + sync + rename so the file is never half-written. fdatasync
    (data + size metadata — everything needed to read it back) rather than
    full fsync: the last-sign state is written 3x per height on the sign
    path, and the timestamp journal write is pure overhead there."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-privval-")
    try:
        os.write(fd, data)
        os.fdatasync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)


class FilePVKey:
    """Reference privval/file.go FilePVKey."""

    def __init__(self, priv_key: ed25519.PrivKeyEd25519) -> None:
        self.priv_key = priv_key
        self.pub_key = priv_key.pub_key()
        self.address = self.pub_key.address()

    def save(self, path: str) -> None:
        doc = {
            "address": self.address.hex(),
            "pub_key": self.pub_key.bytes().hex(),
            "priv_key": self.priv_key.bytes().hex(),
        }
        _atomic_write(path, json.dumps(doc, indent=2).encode())

    @classmethod
    def load(cls, path: str) -> "FilePVKey":
        with open(path, "rb") as f:
            doc = json.loads(f.read())
        key = cls(ed25519.PrivKeyEd25519(bytes.fromhex(doc["priv_key"])))
        if key.pub_key.bytes().hex() != doc["pub_key"]:
            raise ValueError(f"corrupt key file {path}: pub_key mismatch")
        return key


class FilePVLastSignState:
    """Reference privval/file.go:69-135 FilePVLastSignState."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.height = 0
        self.round = 0
        self.step = 0
        self.signature: bytes = b""
        self.sign_bytes: bytes = b""
        if os.path.exists(path):
            with open(path, "rb") as f:
                doc = json.loads(f.read())
            self.height = doc["height"]
            self.round = doc["round"]
            self.step = doc["step"]
            self.signature = bytes.fromhex(doc.get("signature", ""))
            self.sign_bytes = bytes.fromhex(doc.get("sign_bytes", ""))

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Reference file.go:86 CheckHRS. Returns True if (H,R,S) equals the
        last signed (H,R,S) AND we have the last signature — the caller must
        then verify the message differs only by timestamp. Raises on any
        regression."""
        if self.height > height:
            raise DoubleSignError(f"height regression: {self.height} > {height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}: {self.round} > {round_}"
                )
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at {height}/{round_}: {self.step} > {step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no last signature to compare against")
                    return True
        return False

    def save(self, height: int, round_: int, step: int, signature: bytes, sign_bytes: bytes) -> None:
        self.height = height
        self.round = round_
        self.step = step
        self.signature = signature
        self.sign_bytes = sign_bytes
        doc = {
            "height": height,
            "round": round_,
            "step": step,
            "signature": signature.hex(),
            "sign_bytes": sign_bytes.hex(),
        }
        _atomic_write(self.path, json.dumps(doc, indent=2).encode())


def _same_except_timestamp(last: bytes, new: bytes, chain_id: str) -> tuple[bool, int]:
    """Reference file.go:379 checkVotesOnlyDifferByTimestamp. The CBE
    canonical layout (types/vote.py canonical_*_sign_bytes) ends with
    `timestamp u64 | chain_id (u32 len + utf8)`, so the timestamp sits 8
    bytes before the chain-id suffix. Returns (same_otherwise,
    last_timestamp_ns)."""
    suffix = 4 + len(chain_id.encode("utf-8"))
    ts_start = len(last) - suffix - 8
    if len(last) != len(new) or ts_start < 0:
        return False, 0
    if last[:ts_start] != new[:ts_start] or last[ts_start + 8:] != new[ts_start + 8:]:
        return False, 0
    return True, int.from_bytes(last[ts_start:ts_start + 8], "big")


class FilePV(PrivValidator):
    """Reference privval/file.go:137."""

    def __init__(self, key: FilePVKey, last_sign_state: FilePVLastSignState, key_path: str) -> None:
        self.key = key
        self.last_sign_state = last_sign_state
        self.key_path = key_path

    @classmethod
    def generate(cls, key_path: str, state_path: str) -> "FilePV":
        key = FilePVKey(ed25519.gen_priv_key())
        key.save(key_path)
        pv = cls(key, FilePVLastSignState(state_path), key_path)
        pv.last_sign_state.save(0, 0, 0, b"", b"")
        return pv

    @classmethod
    def load(cls, key_path: str, state_path: str) -> "FilePV":
        return cls(FilePVKey.load(key_path), FilePVLastSignState(state_path), key_path)

    @classmethod
    def load_or_generate(cls, key_path: str, state_path: str) -> "FilePV":
        if os.path.exists(key_path):
            return cls.load(key_path, state_path)
        return cls.generate(key_path, state_path)

    def get_pub_key(self):
        return self.key.pub_key

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        """Reference file.go:282 signVote."""
        step = _VOTE_TYPE_TO_STEP[int(vote.type)]
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(vote.height, vote.round, step)
        sb = vote.sign_bytes(chain_id)
        if same_hrs:
            if sb == lss.sign_bytes:
                return vote.with_signature(lss.signature)
            same, last_ts = _same_except_timestamp(lss.sign_bytes, sb, chain_id)
            if same:
                # re-sign the old message (old timestamp) — reference :331
                from dataclasses import replace

                old_vote = replace(vote, timestamp=last_ts)
                return old_vote.with_signature(lss.signature)
            raise DoubleSignError(
                f"conflicting vote data at {vote.height}/{vote.round}/{step}"
            )
        sig = self.key.priv_key.sign(sb)
        lss.save(vote.height, vote.round, step, sig, sb)  # persist BEFORE returning
        return vote.with_signature(sig)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        """Reference file.go:336 signProposal."""
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(proposal.height, proposal.round, STEP_PROPOSE)
        sb = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sb == lss.sign_bytes:
                return proposal.with_signature(lss.signature)
            same, last_ts = _same_except_timestamp(lss.sign_bytes, sb, chain_id)
            if same:
                from dataclasses import replace

                old = replace(proposal, timestamp=last_ts)
                return old.with_signature(lss.signature)
            raise DoubleSignError(
                f"conflicting proposal data at {proposal.height}/{proposal.round}"
            )
        sig = self.key.priv_key.sign(sb)
        lss.save(proposal.height, proposal.round, STEP_PROPOSE, sig, sb)
        return proposal.with_signature(sig)

    def reset(self) -> None:
        """Unsafe: wipe the sign state (reference ResetAll; only for
        unsafe_reset_all)."""
        self.last_sign_state.save(0, 0, 0, b"", b"")
