"""tendermint-tpu: TPU-native BFT state-machine replication.

Importing any submodule runs the Python 3.10 compatibility shims first
(_pycompat installs an ``asyncio.timeout`` backport on interpreters that
predate it) so the 3.11 asyncio idiom used throughout the codebase works
everywhere ``requires-python`` allows.
"""
from tendermint_tpu import _pycompat

_pycompat.install()
