"""BlockStore — blocks, parts, and commits on disk.

Reference parity: store/store.go — per height: BlockMeta, the block's parts,
the block commit (LastCommit of the next block) and the SeenCommit (the +2/3
precommits this node actually saw). Keys are prefixed, height big-endian so
prefix iteration is ordered.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass

from tendermint_tpu.encoding import Reader, Writer
from tendermint_tpu.libs.db import DB
from tendermint_tpu.types import Block, BlockID, Commit, Part, PartSet
from tendermint_tpu.types.block import Header


@dataclass
class BlockMeta:
    """Reference types/block_meta.go."""

    block_id: BlockID
    header: Header
    block_size: int
    num_txs: int

    def encode(self) -> bytes:
        w = Writer()
        self.block_id.encode_into(w)
        w.bytes(self.header.encode()).u64(self.block_size).u64(self.num_txs)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "BlockMeta":
        r = Reader(data)
        bid = BlockID.read(r)
        header = Header.decode(r.bytes())
        size = r.u64()
        ntxs = r.u64()
        r.expect_done()
        return cls(bid, header, size, ntxs)


def _h(height: int) -> bytes:
    return struct.pack(">Q", height)


class BlockStore:
    def __init__(self, db: DB) -> None:
        self._db = db

    def height(self) -> int:
        raw = self._db.get(b"BS:height")
        return struct.unpack(">Q", raw)[0] if raw else 0

    def base(self) -> int:
        raw = self._db.get(b"BS:base")
        return struct.unpack(">Q", raw)[0] if raw else 0

    def save_block(self, block: Block, parts: PartSet, seen_commit: Commit) -> None:
        """Reference store/store.go SaveBlock."""
        height = block.header.height
        if height != self.height() + 1 and self.height() != 0:
            raise ValueError(
                f"cannot save block at height {height}, store is at {self.height()}"
            )
        if not parts.is_complete():
            raise ValueError("cannot save block with incomplete part set")
        meta = BlockMeta(
            BlockID(block.hash(), parts.header()),
            block.header,
            len(block.encode()),
            len(block.data.txs),
        )
        self._db.set(b"BS:meta:" + _h(height), meta.encode())
        for i in range(parts.total):
            part = parts.get_part(i)
            self._db.set(b"BS:part:" + _h(height) + struct.pack(">I", i), part.encode())
        if block.last_commit is not None:
            self._db.set(b"BS:commit:" + _h(height - 1), block.last_commit.encode())
        self._db.set(b"BS:seen:" + _h(height), seen_commit.encode())
        if self.base() == 0:
            self._db.set(b"BS:base", _h(height))
        self._db.set_sync(b"BS:height", _h(height))

    def load_block_meta(self, height: int) -> BlockMeta | None:
        raw = self._db.get(b"BS:meta:" + _h(height))
        return BlockMeta.decode(raw) if raw else None

    def load_block(self, height: int) -> Block | None:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        data = []
        for i in range(meta.block_id.parts.total):
            raw = self._db.get(b"BS:part:" + _h(height) + struct.pack(">I", i))
            if raw is None:
                return None
            data.append(Part.decode(raw).bytes_)
        return Block.decode(b"".join(data))

    def load_block_part(self, height: int, index: int) -> Part | None:
        raw = self._db.get(b"BS:part:" + _h(height) + struct.pack(">I", index))
        return Part.decode(raw) if raw else None

    def load_block_commit(self, height: int) -> Commit | None:
        """The canonical commit for block at `height` (stored in block
        height+1's LastCommit)."""
        raw = self._db.get(b"BS:commit:" + _h(height))
        return Commit.decode(raw) if raw else None

    def load_seen_commit(self, height: int) -> Commit | None:
        raw = self._db.get(b"BS:seen:" + _h(height))
        return Commit.decode(raw) if raw else None

    # -- state-sync support (reference store.go SaveSeenCommit + the v0.34
    # statesync bootstrap, and PruneBlocks for ResponseCommit.retain_height)

    def bootstrap(self, height: int, commit: Commit) -> None:
        """Anchor an EMPTY store at a snapshot height: the node holds the
        verified commit FOR `height` but no blocks at or below it — fast
        sync resumes at height+1 and save_block's contiguity check passes.
        Refused on a store with real history (bootstrap is a fresh-replica
        operation; overwriting live blocks would corrupt them). A store
        holding only a previous bootstrap anchor — no block meta at its
        height — may be re-anchored: that is the restart-after-crash shape
        of a state sync that died between bootstrap and the state save."""
        old = self.height()
        if old != 0:
            if self._db.get(b"BS:meta:" + _h(old)) is not None:
                raise ValueError(
                    f"cannot bootstrap at {height}: store already at {old}"
                )
            self._db.delete(b"BS:commit:" + _h(old))
            self._db.delete(b"BS:seen:" + _h(old))
        self._db.set(b"BS:commit:" + _h(height), commit.encode())
        self._db.set(b"BS:seen:" + _h(height), commit.encode())
        self._db.set(b"BS:base", _h(height + 1))
        self._db.set_sync(b"BS:height", _h(height))

    def prune(self, retain_height: int) -> int:
        """Delete blocks below `retain_height` (meta, parts, commits, seen),
        advancing base — the store-side half of ResponseCommit.retain_height.
        The current height is never pruned. Returns the number of heights
        removed."""
        base = self.base()
        top = min(retain_height, self.height())
        if base == 0 or top <= base:
            return 0
        pruned = 0
        for h in range(base, top):
            meta = self.load_block_meta(h)
            if meta is not None:
                for i in range(meta.block_id.parts.total):
                    self._db.delete(b"BS:part:" + _h(h) + struct.pack(">I", i))
                self._db.delete(b"BS:meta:" + _h(h))
            self._db.delete(b"BS:commit:" + _h(h))
            self._db.delete(b"BS:seen:" + _h(h))
            pruned += 1
        self._db.set_sync(b"BS:base", _h(top))
        return pruned
