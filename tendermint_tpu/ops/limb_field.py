"""Generic batched prime-field arithmetic in 12-bit limbs, Mosaic-friendly.

Factory producing the list-of-vregs field ops (see ops/pallas_verify.py's
layout rationale) for ANY modulus p with 2^255 <= p < 2^264 whose fold
constant K = 2^264 mod p has few nonzero base-4096 digits — true for the
pseudo-Mersenne primes of ed25519 (K = 9728) and secp256k1
(K = 2^40 + 250112). A field element is a python list of NLIMB int32
arrays of identical shape; in-kernel each limb is one (8, 128) vreg.

The carry/bound discipline mirrors ops/field.py: weakly-reduced "class R"
values between ops, 2 wide passes + digit-fold + 4 narrow passes per
multiply, value-tested on adversarial loose inputs (tests/test_ops_secp.py,
tests/test_ops_verify.py pattern).
"""
from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import jax.numpy as jnp

from tendermint_tpu.ops.limbs import LIMB_BITS, LIMB_MASK, NLIMB


def _digits_of(v: int) -> list[tuple[int, int]]:
    """Nonzero base-2^12 digits of v as (limb_index, digit)."""
    out = []
    k = 0
    while v:
        d = v & LIMB_MASK
        if d:
            out.append((k, d))
        v >>= LIMB_BITS
        k += 1
    return out


def _limbs_of(v: int) -> list[int]:
    return [(v >> (LIMB_BITS * k)) & LIMB_MASK for k in range(NLIMB)]


def _make_bias(p: int) -> list[int]:
    """A multiple of p in non-canonical digits, every limb large enough to
    dominate a class-R operand (same construction as ops/field._make_bias,
    with the shift sized so 2^shift * p just fits the 264-bit capacity)."""
    v = (1 << (NLIMB * LIMB_BITS - p.bit_length())) * p
    digits = [(v >> (LIMB_BITS * i)) & LIMB_MASK for i in range(NLIMB)]
    mins = [1 << 15] + [1 << 14] * (NLIMB - 2) + [0]
    for i in range(NLIMB - 2, -1, -1):
        while digits[i] < mins[i]:
            digits[i] += 1 << LIMB_BITS
            digits[i + 1] -= 1
    assert all(d >= 0 for d in digits), digits
    assert sum(d << (LIMB_BITS * i) for i, d in enumerate(digits)) == v
    return [2 * d for d in digits]


@dataclass
class FieldOps:
    p: int
    fold_digits: list  # [(limb_index, digit)] of K = 2^264 mod p
    bias: list
    negp: list = dc_field(default_factory=list)

    def limbs_of(self, v: int) -> list[int]:
        return _limbs_of(v % self.p)

    def const(self, v: int, like):
        return [jnp.full_like(like, c) for c in self.limbs_of(v)]

    # -- carries ---------------------------------------------------------

    def _fold_into(self, rows, cc, src_weight: int):
        """rows[src_weight + i] += digit_i * cc for K's digits (rows must be
        long enough)."""
        for k, d in self.fold_digits:
            i = src_weight + k
            rows[i] = cc * d if rows[i] is None else rows[i] + cc * d
        return rows

    def carry(self, c):
        """One vectorized carry pass over NLIMB rows with top fold."""
        cc = [x >> LIMB_BITS for x in c]
        lo = [x & LIMB_MASK for x in c]
        out = [lo[0]] + [lo[k] + cc[k - 1] for k in range(1, NLIMB)]
        for k, d in self.fold_digits:
            out[k] = out[k] + cc[NLIMB - 1] * d
        return out

    # -- mul/square ------------------------------------------------------

    def _tail(self, c):
        """Reduce 44 product columns -> class R (2 wide passes, two-level
        digit fold, 4 narrow passes)."""
        n2 = 2 * NLIMB
        for _ in range(2):
            cc = [x >> LIMB_BITS for x in c]
            lo = [x & LIMB_MASK for x in c]
            c = [lo[0]] + [lo[k] + cc[k - 1] for k in range(1, n2 - 1)] + [
                lo[n2 - 1] + cc[n2 - 2] + (cc[n2 - 1] << LIMB_BITS)
            ]
        # first-level fold: c[22+j] (weight 2^(264+12j)) scatters K's digits
        # into limbs j..j+max_digit; digits past limb 21 land in extra rows
        max_k = self.fold_digits[-1][0]
        ext: list = [None] * (NLIMB + max_k)
        for k in range(NLIMB):
            ext[k] = c[k]
        for j in range(NLIMB):
            hi = c[NLIMB + j]
            for k, d in self.fold_digits:
                i = j + k
                ext[i] = hi * d if ext[i] is None else ext[i] + hi * d
        # second-level fold: rows 22..22+max_k-1 are small; fold them back
        d2 = ext[:NLIMB]
        for j in range(max_k):
            hi = ext[NLIMB + j]
            if hi is None:
                continue
            for k, d in self.fold_digits:
                d2[j + k] = d2[j + k] + hi * d
        for _ in range(4):
            d2 = self.carry(d2)
        return d2

    def mul(self, a, b):
        n2 = 2 * NLIMB
        c = [None] * n2
        for i in range(NLIMB):
            ai = a[i]
            for j in range(NLIMB):
                k = i + j
                t = ai * b[j]
                c[k] = t if c[k] is None else c[k] + t
        c[n2 - 1] = jnp.zeros_like(a[0])
        return self._tail(c)

    def sq(self, a):
        n2 = 2 * NLIMB
        c = [None] * n2
        for i in range(NLIMB):
            ai = a[i]
            for j in range(i + 1, NLIMB):
                k = i + j
                t = ai * a[j]
                c[k] = t if c[k] is None else c[k] + t
        for k in range(n2):
            if c[k] is not None:
                c[k] = c[k] + c[k]
        for i in range(NLIMB):
            k = 2 * i
            t = a[i] * a[i]
            c[k] = t if c[k] is None else c[k] + t
        c[n2 - 1] = jnp.zeros_like(a[0])
        return self._tail(c)

    # -- add/sub/select --------------------------------------------------

    def add(self, a, b):
        return self.carry([x + y for x, y in zip(a, b)])

    def sub(self, a, b):
        return self.carry([x + (bk - y) for x, y, bk in zip(a, b, self.bias)])

    def sel(self, cond, a, b):
        return [jnp.where(cond, x, y) for x, y in zip(a, b)]

    def mul_small(self, a, m: int):
        """a * m for a small python int (m * classR limb must fit int32)."""
        return self.carry(self.carry([x * m for x in a]))

    # -- canonicalize / compare ------------------------------------------

    def _seq_carry(self, a, topfold: bool):
        a = list(a)
        for k in range(NLIMB - 1):
            cc = a[k] >> LIMB_BITS
            a[k] = a[k] & LIMB_MASK
            a[k + 1] = a[k + 1] + cc
        if topfold:
            cc = a[NLIMB - 1] >> LIMB_BITS
            a[NLIMB - 1] = a[NLIMB - 1] & LIMB_MASK
            for k, d in self.fold_digits:
                a[k] = a[k] + cc * d
        return a

    def canon(self, a):
        """Exact canonical digits of (a mod p), in [0, p).

        top_bits = ceil(log2 p): 255 for 2^255-19, 256 for secp256k1's
        2^256-2^32-977. Bits >= top_bits fold via 2^top_bits mod p (small
        for both); the result is < 2^top_bits < 2p, so ONE conditional
        subtract of p finishes."""
        top_bits = self.p.bit_length()
        top_limb_bits = top_bits - LIMB_BITS * (NLIMB - 1)  # bits in limb 21
        c_small = (1 << top_bits) % self.p
        a = self.carry(self.carry(a))
        a = self._seq_carry(a, True)
        a = self._seq_carry(a, True)
        for _ in range(2):
            hi = a[NLIMB - 1] >> top_limb_bits
            a = list(a)
            a[NLIMB - 1] = a[NLIMB - 1] & ((1 << top_limb_bits) - 1)
            for k, d in _digits_of(c_small):
                a[k] = a[k] + hi * d
            a = self._seq_carry(a, False)
        t = [x + nk for x, nk in zip(a, self.negp)]
        for k in range(NLIMB - 1):
            cc = t[k] >> LIMB_BITS
            t[k] = t[k] & LIMB_MASK
            t[k + 1] = t[k + 1] + cc
        overflow = t[NLIMB - 1] >> LIMB_BITS
        t[NLIMB - 1] = t[NLIMB - 1] & LIMB_MASK
        return self.sel(overflow > 0, t, a)

    def eq(self, a, b):
        """Canonical-digit equality; inputs must be canonical."""
        from functools import reduce

        return reduce(jnp.logical_and, [x == y for x, y in zip(a, b)])

    def is_zero(self, a):
        """a == 0 for canonical digits."""
        from functools import reduce

        return reduce(jnp.logical_and, [x == 0 for x in a])


NWORDS = 8


def digit_at(w_rows, d):
    """2-bit digit d (traced scalar) of scalars packed in 8 little-endian
    int32 word arrays. Computed arithmetically — Mosaic cannot lower a
    dynamic_slice over a per-digit array inside the loop. All int32: the
    arithmetic shift's sign extension only reaches bits >= 2 even at the
    maximum shift of 30, and `& 3` discards them."""
    wi = d // 16
    sh = 2 * (d % 16)
    acc = w_rows[0]
    for k in range(1, NWORDS):
        acc = jnp.where(wi == k, w_rows[k], acc)
    return (acc >> sh) & 3


def words_to_limbs(w_rows):
    """8 little-endian int32 word arrays -> 22-limb field element, full
    256-bit range. The arithmetic right shift sign-extends, so (a) where a
    limb straddles a word boundary the low word's field is masked to its
    true width before OR-ing the high word's bits, and (b) the top limb is
    masked to its 4 true bits — word 7's sign bit IS bit 255, which
    secp256k1 coordinates can set."""
    limbs = []
    for k in range(NLIMB):
        lo_bit = LIMB_BITS * k
        a, s = lo_bit // 32, lo_bit % 32
        v = w_rows[a] >> s
        if s > 32 - LIMB_BITS:
            if a + 1 < NWORDS:
                v = (v & ((1 << (32 - s)) - 1)) | (w_rows[a + 1] << (32 - s))
            else:
                v = v & ((1 << (32 - s)) - 1)
        limbs.append(v & LIMB_MASK)
    return limbs


def make_field(p: int) -> FieldOps:
    assert 2**254 < p < 2**264
    k = (1 << (NLIMB * LIMB_BITS)) % p
    fold_digits = _digits_of(k)
    ops = FieldOps(p=p, fold_digits=fold_digits, bias=_make_bias(p))
    ops.negp = _limbs_of((1 << (NLIMB * LIMB_BITS)) - p)
    return ops
