"""TPU kernel package — batched signature verification.

Importing this package registers the JAX ed25519 batch backend into
tendermint_tpu.crypto.batch, replacing the serial per-signature loop for
batches of at least MIN_DEVICE_BATCH signatures (smaller batches stay on the
CPU serial path: a single OpenSSL verify is ~50µs, well under a device
launch, which matters for consensus hot loop #1 where votes arrive one at a
time — see SURVEY.md §3.2).

Set TMTPU_NO_ACCEL=1 to disable the device backend entirely (the analog of
the reference's cgo/nocgo dual build, crypto/secp256k1/secp256k1_cgo.go).
"""
from __future__ import annotations

import os

# The device batch paths lean on the host crypto stack throughout (serial
# CPU fallback, breaker drain verifies, parity oracles, key handling):
# without the `cryptography` package the ops package cannot produce
# correct verdicts. Declare the dependency at import so it fails HERE —
# `tendermint_tpu.crypto` itself now imports crypto-free (the hash/merkle
# /proof layer state sync needs, docs/state_sync.md), which would
# otherwise let an ops import "succeed" and die mid-verify.
from tendermint_tpu.crypto import ed25519 as _host_ed25519  # noqa: F401

MIN_DEVICE_BATCH = int(os.environ.get("TMTPU_MIN_DEVICE_BATCH", "8"))

_min_batch_probed: int | None = None

# Serial OpenSSL-backed verify cost per signature: the break-even unit the
# dispatch probe divides by.
_SERIAL_VERIFY_S = 120e-6


def _threshold_for_dispatch(dispatch_s: float) -> int:
    """Measured device round-trip cost -> routing threshold: batches at or
    above it win on device. A ~1ms local chip stays at the MIN_DEVICE_BATCH
    floor (8); a ~65ms tunnel yields ~540; clamped at 4096 so a pathological
    probe can never push everything onto the serial path."""
    return min(4096, max(MIN_DEVICE_BATCH, int(dispatch_s / _SERIAL_VERIFY_S)))


def effective_min_batch() -> int:
    """Routing threshold between the serial/native CPU path and the device.

    A local chip dispatches in ~1ms, so tiny batches still win on device;
    behind a high-latency link (the axon tunnel round trip is ~65ms) the
    break-even moves up. Probed once: the threshold is the measured
    round-trip cost divided by ~120us (the serial OpenSSL per-signature
    cost), clamped to [MIN_DEVICE_BATCH, 4096] — a 65ms link yields ~540,
    a local chip stays at the floor. TMTPU_MIN_DEVICE_BATCH always wins
    when set.

    With NO accelerator (jax backend == cpu) the "device" kernel is the
    XLA:CPU lowering of the limb-arithmetic Straus loop — measured ~30x
    SLOWER per signature than the serial OpenSSL path on a 1-vCPU host
    (it exists for testing, not speed) — so routing returns never-device
    and every batch takes the native/serial CPU paths, mirroring the
    reference's nocgo build (crypto/secp256k1/secp256k1_nocgo.go:21).
    """
    global _min_batch_probed
    if "TMTPU_MIN_DEVICE_BATCH" in os.environ:
        return MIN_DEVICE_BATCH
    if _min_batch_probed is not None:
        return _min_batch_probed
    _min_batch_probed = MIN_DEVICE_BATCH
    try:
        import time

        import jax
        import numpy as np

        if jax.default_backend() == "cpu":
            _min_batch_probed = 1 << 30  # no accelerator: CPU paths win
            return _min_batch_probed
        dev = jax.devices()[0]
        f = jax.jit(lambda x: x + 1)
        np.asarray(f(jax.device_put(np.arange(8), dev)))  # compile
        t0 = time.perf_counter()
        np.asarray(f(jax.device_put(np.full(8, 3), dev)))
        dispatch_s = time.perf_counter() - t0
        _min_batch_probed = _threshold_for_dispatch(dispatch_s)
    except Exception:  # noqa: BLE001 — no device: serial fallback anyway
        pass
    return _min_batch_probed


def serial_verify(pub_cls, pubs, msgs, sigs):
    """One-at-a-time verification with per-signature error isolation — the
    small-batch and no-device path for every curve."""
    out = []
    for p, m, s in zip(pubs, msgs, sigs):
        try:
            out.append(pub_cls(bytes(p)).verify(m, s))
        except ValueError:
            out.append(False)
    return out


# Sub-device-threshold batches have two CPU paths: the C++ batch core
# (threads across cores; portable field arithmetic, ~270us/sig/core) and
# the serial loop over the OpenSSL-backed key objects (~120us/sig, one
# core). Which wins is machine-dependent — the C++ path needs >= ~2-3
# cores to beat OpenSSL's faster per-op code (on a 1-vCPU host it LOSES
# 2x) — so the choice is probed once per curve with real signatures.
_small_choice: dict[str, str] = {}


def _probe_small_path(curve: str, native_fn, serial_fn, sample) -> str:
    """Pick native vs serial by timing both on a real sample, best of two
    runs each (the native core spawns its worker threads per call, so the
    first run carries startup noise; best-of-two measures steady cost at a
    representative sub-threshold batch size). ~50 ms once per curve, on the
    first sub-threshold verification of the process."""
    choice = _small_choice.get(curve)
    if choice is not None:
        return choice
    import time

    try:
        pubs, msgs, sigs = sample()

        def best_of_two(fn):
            t0 = time.perf_counter()
            ok = fn(pubs, msgs, sigs)
            t1 = time.perf_counter()
            fn(pubs, msgs, sigs)
            t2 = time.perf_counter()
            return min(t1 - t0, t2 - t1), ok

        t_native, ok_n = best_of_two(native_fn)
        t_serial, ok_s = best_of_two(serial_fn)
        if not all(ok_s):
            # the serial path mis-verified a known-good sample: never
            # select the path that just failed — prefer native if IT
            # verified, else fall through to serial anyway (it keeps
            # per-signature error isolation; nothing better exists)
            choice = "native" if all(ok_n) else "serial"
        else:
            choice = (
                "native" if all(ok_n) and t_native <= t_serial else "serial"
            )
    except Exception:  # noqa: BLE001 — native missing/broken: serial path
        choice = "serial"
    _small_choice[curve] = choice
    return choice


def _ed25519_sample():
    from tendermint_tpu.utils import make_sig_batch

    return make_sig_batch(64, msg_prefix=b"probe ")


def _secp256k1_sample():
    from tendermint_tpu.crypto import secp256k1 as sk

    priv = sk.gen_priv_key(seed=b"small-path probe")
    pub = priv.pub_key().bytes()
    msgs_ = [b"probe %d" % i for i in range(64)]
    return [pub] * 64, msgs_, [priv.sign(m) for m in msgs_]


def _curve_spec(curve: str):
    """(pub_cls, native batch fn, probe sample) per curve — the one place
    the small-path machinery differs between ed25519 and secp256k1 (the
    probe/try-native/serial skeleton below used to be duplicated)."""
    from tendermint_tpu.crypto import native

    if curve == "ed25519":
        from tendermint_tpu.crypto.ed25519 import PubKeyEd25519

        return PubKeyEd25519, native.ed25519_verify_batch, _ed25519_sample
    from tendermint_tpu.crypto.secp256k1 import PubKeySecp256k1

    return PubKeySecp256k1, native.secp256k1_verify_batch, _secp256k1_sample


def _small_verify(curve, pubs, msgs, sigs):
    """Sub-threshold host verification, shared skeleton for both curves:
    probe native-vs-serial once per curve, prefer the winner, degrade to
    the serial loop (per-signature error isolation) on native failure."""
    pub_cls, native_fn, sample = _curve_spec(curve)

    def serial(p, m, s):
        return serial_verify(pub_cls, p, m, s)

    if _probe_small_path(curve, native_fn, serial, sample) == "native":
        try:
            return native_fn(pubs, msgs, sigs)
        except (RuntimeError, OSError):
            pass
    return serial(pubs, msgs, sigs)


def _ed25519_small(pubs, msgs, sigs):
    return _small_verify("ed25519", pubs, msgs, sigs)


def _secp256k1_small(pubs, msgs, sigs):
    return _small_verify("secp256k1", pubs, msgs, sigs)


# The registered crypto.batch backends submit through the process-wide
# DeviceScheduler (tendermint_tpu/device/): one admission queue + packer
# + breaker for every subsystem's signatures. The scheduler keeps the
# measured routing (scheduler.verify runs sub-threshold batches on the
# host paths above, inline on the submitting thread) and dispatches
# device-bound work by priority class (device/priorities.py contextvar:
# consensus commit > fast sync > lite > mempool recheck).


def _ed25519_backend(pubs, msgs, sigs):
    from tendermint_tpu.device import get_scheduler

    return get_scheduler().verify("ed25519", pubs, msgs, sigs)


def _secp256k1_backend(pubs, msgs, sigs):
    from tendermint_tpu.device import get_scheduler

    return get_scheduler().verify("secp256k1", pubs, msgs, sigs)


def _accumulation_hint() -> int:
    """Streaming flush point: far enough past the routing threshold that a
    flush amortizes its launch over several thresholds' worth of work (a
    sub-threshold flush would serialize behind the dispatch floor), floor
    2048 so CPU/local hosts still batch big enough to beat per-call
    overhead. The never-device sentinel (no accelerator) must NOT leak
    into the hint — with no launch to amortize, the floor is the right
    flush point and auto-flush must keep working."""
    t = effective_min_batch()
    if t >= 1 << 30:
        return 2048
    return max(8 * t, 2048)


def register() -> bool:
    """Register device-backed batch verification. Returns True if enabled."""
    if os.environ.get("TMTPU_NO_ACCEL"):
        return False
    from tendermint_tpu.crypto import batch

    batch.register_backend("ed25519", _ed25519_backend)
    batch.register_backend("secp256k1", _secp256k1_backend)
    batch.set_accumulation_hint(_accumulation_hint)
    return True


register()
