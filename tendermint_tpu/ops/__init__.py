"""TPU kernel package — batched signature verification.

Importing this package registers the JAX ed25519 batch backend into
tendermint_tpu.crypto.batch, replacing the serial per-signature loop for
batches of at least MIN_DEVICE_BATCH signatures (smaller batches stay on the
CPU serial path: a single OpenSSL verify is ~50µs, well under a device
launch, which matters for consensus hot loop #1 where votes arrive one at a
time — see SURVEY.md §3.2).

Set TMTPU_NO_ACCEL=1 to disable the device backend entirely (the analog of
the reference's cgo/nocgo dual build, crypto/secp256k1/secp256k1_cgo.go).
"""
from __future__ import annotations

import os

MIN_DEVICE_BATCH = int(os.environ.get("TMTPU_MIN_DEVICE_BATCH", "8"))

_min_batch_probed: int | None = None


def effective_min_batch() -> int:
    """Routing threshold between the serial/native CPU path and the device.

    A local chip dispatches in ~1ms, so tiny batches still win on device;
    behind a high-latency link (the axon tunnel round trip is ~70ms) small
    batches are far faster on the threaded native path. Probed once: if a
    trivial pre-compiled dispatch takes >10ms, the threshold rises to 2048
    (~where device throughput overtakes native latency at ~30k sigs/s).
    TMTPU_MIN_DEVICE_BATCH always wins when set.
    """
    global _min_batch_probed
    if "TMTPU_MIN_DEVICE_BATCH" in os.environ:
        return MIN_DEVICE_BATCH
    if _min_batch_probed is not None:
        return _min_batch_probed
    _min_batch_probed = MIN_DEVICE_BATCH
    try:
        import time

        import jax
        import numpy as np

        if jax.default_backend() == "cpu":
            return _min_batch_probed
        dev = jax.devices()[0]
        f = jax.jit(lambda x: x + 1)
        np.asarray(f(jax.device_put(np.arange(8), dev)))  # compile
        t0 = time.perf_counter()
        np.asarray(f(jax.device_put(np.full(8, 3), dev)))
        if time.perf_counter() - t0 > 0.010:
            _min_batch_probed = max(MIN_DEVICE_BATCH, 2048)
    except Exception:  # noqa: BLE001 — no device: serial fallback anyway
        pass
    return _min_batch_probed


def serial_verify(pub_cls, pubs, msgs, sigs):
    """One-at-a-time verification with per-signature error isolation — the
    small-batch and no-device path for every curve."""
    out = []
    for p, m, s in zip(pubs, msgs, sigs):
        try:
            out.append(pub_cls(bytes(p)).verify(m, s))
        except ValueError:
            out.append(False)
    return out


def _ed25519_backend(pubs, msgs, sigs):
    if len(pubs) < effective_min_batch():
        from tendermint_tpu.crypto import native
        from tendermint_tpu.crypto.ed25519 import PubKeyEd25519

        try:  # threaded C++ batch first: ~50x the serial-Python loop
            return native.ed25519_verify_batch(pubs, msgs, sigs)
        except (RuntimeError, OSError):
            return serial_verify(PubKeyEd25519, pubs, msgs, sigs)
    from tendermint_tpu.ops import ed25519_batch

    return ed25519_batch.verify_batch(pubs, msgs, sigs)


def _secp256k1_backend(pubs, msgs, sigs):
    if len(pubs) < effective_min_batch():
        from tendermint_tpu.crypto import native
        from tendermint_tpu.crypto.secp256k1 import PubKeySecp256k1

        try:
            return native.secp256k1_verify_batch(pubs, msgs, sigs)
        except (RuntimeError, OSError):
            return serial_verify(PubKeySecp256k1, pubs, msgs, sigs)
    from tendermint_tpu.ops import secp_batch

    return secp_batch.verify_batch(pubs, msgs, sigs)


def register() -> bool:
    """Register device-backed batch verification. Returns True if enabled."""
    if os.environ.get("TMTPU_NO_ACCEL"):
        return False
    from tendermint_tpu.crypto import batch

    batch.register_backend("ed25519", _ed25519_backend)
    batch.register_backend("secp256k1", _secp256k1_backend)
    return True


register()
