"""TPU kernel package — batched signature verification.

Importing this package registers the JAX ed25519 batch backend into
tendermint_tpu.crypto.batch, replacing the serial per-signature loop for
batches of at least MIN_DEVICE_BATCH signatures (smaller batches stay on the
CPU serial path: a single OpenSSL verify is ~50µs, well under a device
launch, which matters for consensus hot loop #1 where votes arrive one at a
time — see SURVEY.md §3.2).

Set TMTPU_NO_ACCEL=1 to disable the device backend entirely (the analog of
the reference's cgo/nocgo dual build, crypto/secp256k1/secp256k1_cgo.go).
"""
from __future__ import annotations

import os

MIN_DEVICE_BATCH = int(os.environ.get("TMTPU_MIN_DEVICE_BATCH", "8"))


def _ed25519_backend(pubs, msgs, sigs):
    if len(pubs) < MIN_DEVICE_BATCH:
        from tendermint_tpu.crypto.ed25519 import PubKeyEd25519

        out = []
        for p, m, s in zip(pubs, msgs, sigs):
            try:
                out.append(PubKeyEd25519(bytes(p)).verify(m, s))
            except ValueError:
                out.append(False)
        return out
    from tendermint_tpu.ops import ed25519_batch

    return ed25519_batch.verify_batch(pubs, msgs, sigs)


def register() -> bool:
    """Register device-backed batch verification. Returns True if enabled."""
    if os.environ.get("TMTPU_NO_ACCEL"):
        return False
    from tendermint_tpu.crypto import batch

    batch.register_backend("ed25519", _ed25519_backend)
    return True


register()
