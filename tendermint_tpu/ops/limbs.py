"""Host-side limb packing for the TPU field arithmetic.

Field elements of GF(2^255-19) are represented on device as 22 limbs of 12
bits in int32, limb-major: shape (22, B) so the batch dimension maps to TPU
vector lanes (128-wide) and limbs to sublanes. 22*12 = 264 bits of capacity;
values are kept weakly reduced (see ops/field.py for the bound contracts).

These helpers convert between Python ints / little-endian byte strings and
the packed numpy arrays, vectorized over the batch.
"""
from __future__ import annotations

import numpy as np

NLIMB = 22
LIMB_BITS = 12
LIMB_MASK = (1 << LIMB_BITS) - 1


def ints_to_limbs(vals: list[int]) -> np.ndarray:
    """Pack non-negative ints < 2^264 into a (22, B) int32 limb array."""
    if not vals:
        return np.zeros((NLIMB, 0), dtype=np.int32)
    buf = b"".join(v.to_bytes(33, "little") for v in vals)
    b = np.frombuffer(buf, dtype=np.uint8).reshape(len(vals), 33).astype(np.int32)
    trip = b.reshape(len(vals), 11, 3)
    lo = trip[:, :, 0] | ((trip[:, :, 1] & 0xF) << 8)
    hi = (trip[:, :, 1] >> 4) | (trip[:, :, 2] << 4)
    limbs = np.stack([lo, hi], axis=2).reshape(len(vals), NLIMB)
    return np.ascontiguousarray(limbs.T)


def limbs_to_ints(arr) -> list[int]:
    """Inverse of ints_to_limbs; accepts any (22, B) integer array (limbs may
    be loose, i.e. larger than 12 bits — weights still apply)."""
    a = np.asarray(arr, dtype=np.int64)
    out = []
    for col in range(a.shape[1]):
        v = 0
        for k in range(NLIMB - 1, -1, -1):
            v = (v << LIMB_BITS) + int(a[k, col])
        out.append(v)
    return out


def int_to_limb_column(v: int) -> np.ndarray:
    """(22, 1) column for module-level constants."""
    return ints_to_limbs([v])


def scalars_to_bits(vals: list[int], nbits: int = 253) -> np.ndarray:
    """Pack scalars (< 2^nbits) into a (nbits, B) int32 bit array,
    little-endian bit order (bits[i] = bit i)."""
    if not vals:
        return np.zeros((nbits, 0), dtype=np.int32)
    buf = b"".join(v.to_bytes(32, "little") for v in vals)
    b = np.frombuffer(buf, dtype=np.uint8).reshape(len(vals), 32)
    bits = np.unpackbits(b, axis=1, bitorder="little")[:, :nbits]
    return np.ascontiguousarray(bits.T.astype(np.int32))
