"""Batched GF(2^255-19) arithmetic in 12-bit limbs on int32 — the TPU hot core.

Design notes (why this shape):
- TPUs have no big-int and no cheap int64 multiply; int32 multiply on the VPU
  is the primitive. 12-bit limbs make every schoolbook partial product fit
  comfortably in int32: partials are <= ~2^26 and a 22-term accumulation plus
  fold stays under ~6e8 < 2^31 (bound analysis below, checked by
  tests/test_field_bounds.py with an interval tracker).
- Arrays are limb-major (22, B): the batch dimension B maps to the 128-wide
  TPU vector lanes, limbs to sublanes; every op is static-shape, branch-free
  and identical across lanes — exactly what XLA wants under jit.
- Between operations values are kept *weakly reduced* ("class R": limb0 <=
  ~24k, limbs 1..21 <= ~4120) using a fixed number of vectorized carry
  passes; exact canonicalization (unique digits of a mod p) happens once per
  verify, at the final compare, using short unrolled sequential carries.

p = 2^255 - 19;  2^264 == 19 * 2^9 == 9728 (mod p) is the fold constant:
carries out of limb 21 (weight 2^264) re-enter at limb 0 multiplied by 9728.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops.limbs import LIMB_BITS, LIMB_MASK, NLIMB, int_to_limb_column

P = 2**255 - 19
FOLD = 19 << (NLIMB * LIMB_BITS - 255)  # 2^264 mod p = 9728


def _make_bias() -> np.ndarray:
    """A multiple of p in non-canonical digits, every limb large enough to
    dominate a class-R operand, so sub(a, b) = a + BIAS - b never goes
    negative limb-wise. Built from 2^9 * p (fits 22 digits exactly), digits
    rebalanced by borrowing, then doubled so the top limb has headroom."""
    v = (1 << 9) * P
    digits = [(v >> (LIMB_BITS * i)) & LIMB_MASK for i in range(NLIMB)]
    mins = [1 << 15] + [1 << 14] * (NLIMB - 2) + [0]
    for i in range(NLIMB - 2, -1, -1):
        while digits[i] < mins[i]:
            digits[i] += 1 << LIMB_BITS
            digits[i + 1] -= 1
    assert all(d >= 0 for d in digits), digits
    assert sum(d << (LIMB_BITS * i) for i, d in enumerate(digits)) == v
    digits = [2 * d for d in digits]  # top limb >= ~8170 > any class-R limb
    return np.array(digits, dtype=np.int32).reshape(NLIMB, 1)


BIAS = _make_bias()
P_LIMBS = int_to_limb_column(P)
NEGP_LIMBS = int_to_limb_column((1 << (NLIMB * LIMB_BITS)) - P)  # 2^264 - p


def carry_pass(c):
    """One vectorized carry pass over (22, B) with top fold into limb 0."""
    c = jnp.asarray(c)
    cc = c >> LIMB_BITS
    c = c & LIMB_MASK
    c = c.at[1:].add(cc[:-1])
    c = c.at[0].add(cc[NLIMB - 1] * FOLD)
    return c


def mul(a, b):
    """Batched field multiply: (22,B) x (22,B) -> (22,B), class R out.

    Schoolbook partial products accumulated by limb weight into a (44, B)
    array, two wide carry passes (top limb kept unmasked so no carry is ever
    lost), fold of the high half with 2^264 == 9728 (mod p), then four
    narrow passes back to class R.
    """
    parts = [
        jnp.pad(a[i][None, :] * b, ((i, NLIMB - i), (0, 0))) for i in range(NLIMB)
    ]
    c = parts[0]
    for p_ in parts[1:]:
        c = c + p_  # (44, B); limb 43 is 0 until carries arrive
    for _ in range(2):
        cc = c >> LIMB_BITS
        lo = c & LIMB_MASK
        lo = lo.at[1:].add(cc[:-1])
        # top limb accumulates: restore its masked-off high bits
        lo = lo.at[-1].add(cc[-1] << LIMB_BITS)
        c = lo
    d = c[:NLIMB] + FOLD * c[NLIMB:]
    for _ in range(4):
        d = carry_pass(d)
    return d


def square(a):
    return mul(a, a)


def add(a, b):
    return carry_pass(a + b)


def sub(a, b):
    return carry_pass(a + (jnp.asarray(BIAS) - b))


def select(cond, a, b):
    """Per-batch-element select: cond (B,), a/b (22, B)."""
    return jnp.where(cond[None, :] != 0, a, b)


def pow2k(a, k: int):
    return jax.lax.fori_loop(0, k, lambda _, x: square(x), a)


def inv(a):
    """a^(p-2) via the standard 25519 addition chain (254 squarings, 11
    multiplies), with squaring runs as fori_loops to keep the graph small."""
    t0 = square(a)  # 2
    t1 = square(square(t0))  # 8
    t1 = mul(a, t1)  # 9
    t0 = mul(t0, t1)  # 11
    t2 = square(t0)  # 22
    t1 = mul(t1, t2)  # 2^5 - 1
    t2 = pow2k(t1, 5)
    t1 = mul(t2, t1)  # 2^10 - 1
    t2 = pow2k(t1, 10)
    t2 = mul(t2, t1)  # 2^20 - 1
    t3 = pow2k(t2, 20)
    t2 = mul(t3, t2)  # 2^40 - 1
    t2 = pow2k(t2, 10)
    t1 = mul(t2, t1)  # 2^50 - 1
    t2 = pow2k(t1, 50)
    t2 = mul(t2, t1)  # 2^100 - 1
    t3 = pow2k(t2, 100)
    t2 = mul(t3, t2)  # 2^200 - 1
    t2 = pow2k(t2, 50)
    t1 = mul(t2, t1)  # 2^250 - 1
    t1 = pow2k(t1, 5)
    return mul(t1, t0)  # 2^255 - 21 = p - 2


def _seq_carry(a, topfold: bool):
    """Exact sequential carry over 22 limbs (unrolled; 21 static steps).
    With topfold, the limb-21 carry re-enters limb 0 via the 9728 fold;
    without, limb 21 must be known small enough not to carry."""
    for k in range(NLIMB - 1):
        cc = a[k] >> LIMB_BITS
        a = a.at[k].set(a[k] & LIMB_MASK)
        a = a.at[k + 1].add(cc)
    if topfold:
        cc = a[NLIMB - 1] >> LIMB_BITS
        a = a.at[NLIMB - 1].set(a[NLIMB - 1] & LIMB_MASK)
        a = a.at[0].add(cc * FOLD)
    return a


def canonicalize(a):
    """Exact canonical digits of (a mod p), in [0, p). Runs once per verify
    (final encode-and-compare), so the unrolled sequential carries are cheap
    relative to the 253-iteration scalar-mult loop."""
    a = jnp.asarray(a)
    a = carry_pass(carry_pass(a))  # shrink class R to near-canonical
    a = _seq_carry(a, topfold=True)
    a = _seq_carry(a, topfold=True)  # settles: all limbs canonical, V < 2^264
    # fold bits >= 255: V = hi*2^255 + lo == 19*hi + lo (mod p); twice
    for _ in range(2):
        hi = a[NLIMB - 1] >> 3
        a = a.at[NLIMB - 1].set(a[NLIMB - 1] & 0x7)
        a = a.at[0].add(hi * 19)
        a = _seq_carry(a, topfold=False)
    # now V < 2^255: one conditional subtract of p, computed as the 264-bit
    # add V + (2^264 - p); carry out of limb 21 <=> V >= p
    t = a + jnp.asarray(NEGP_LIMBS)
    overflow = jnp.zeros_like(a[0])
    for k in range(NLIMB - 1):
        cc = t[k] >> LIMB_BITS
        t = t.at[k].set(t[k] & LIMB_MASK)
        t = t.at[k + 1].add(cc)
    overflow = t[NLIMB - 1] >> LIMB_BITS
    t = t.at[NLIMB - 1].set(t[NLIMB - 1] & LIMB_MASK)
    return jnp.where(overflow[None, :] > 0, t, a)


def eq(a, b):
    """Canonical-digit equality -> (B,) bool. Inputs must be canonical."""
    return jnp.all(a == b, axis=0)


def is_odd(a):
    """Parity of a canonical element -> (B,) int32 in {0,1}."""
    return a[0] & 1
