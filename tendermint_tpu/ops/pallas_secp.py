"""Pallas TPU kernel for batched secp256k1 ECDSA verification.

R' = [u1]G + [u2]Q by joint radix-4 Straus (128 iterations of 2 doubles +
1 complete add against a 16-entry table), over GF(2^256 - 2^32 - 977) in
the 12-bit-limb list-of-vregs layout of ops/limb_field.py (see
ops/pallas_verify.py for the layout rationale — every op is a whole
(8, 128) vector register).

Point arithmetic uses the COMPLETE projective a=0 formulas of
Renes-Costello-Batina 2016 (Alg 7 add, Alg 9 double, b3 = 21): total on
all inputs including identity and P == Q, so the constant-shape loop needs
no branches and adversarially-crafted (u1, u2) cannot hit an exceptional
case. Verdict: R' valid iff Z' != 0 and X' == t*Z' for t in {r, r+n}
(x mod n == r admits both representatives when r + n < p).

Replaces: /root/reference/crypto/secp256k1/secp256k1_nocgo.go:21-50 (and
the vendored libsecp256k1's verify on the cgo path) — the reference
verifies one signature at a time; this verifies a whole commit's worth per
launch. Oracle: crypto/secp256k1_math.py + native/secp256k1.cpp.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tendermint_tpu.crypto import secp256k1_math as sm
from tendermint_tpu.ops.limb_field import (
    NWORDS,
    digit_at,
    make_field,
    words_to_limbs,
)

TILE = 1024
SUB, LANE = 8, 128
NDIGITS = 128  # 256-bit scalars, 2-bit joint digits

F = make_field(sm.P)
B3 = 3 * sm.B  # 21


# ------------------------------------------------------------------- curve
# Points: 3-tuples (X, Y, Z) of field elements, projective; (0, 1, 0) = O.


def padd(p1, p2):
    """Complete projective addition (RCB16 Alg 7, a=0)."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    t0 = F.mul(x1, x2)
    t1 = F.mul(y1, y2)
    t2 = F.mul(z1, z2)
    t3 = F.mul(F.add(x1, y1), F.add(x2, y2))
    t3 = F.sub(t3, F.add(t0, t1))          # X1Y2 + X2Y1
    t4 = F.mul(F.add(y1, z1), F.add(y2, z2))
    t4 = F.sub(t4, F.add(t1, t2))          # Y1Z2 + Y2Z1
    t5 = F.mul(F.add(x1, z1), F.add(x2, z2))
    t5 = F.sub(t5, F.add(t0, t2))          # X1Z2 + X2Z1
    x3 = F.add(F.add(t0, t0), t0)          # 3*X1X2
    t2 = F.mul_small(t2, B3)               # b3*Z1Z2
    z3 = F.add(t1, t2)                     # Y1Y2 + b3Z1Z2
    t1 = F.sub(t1, t2)                     # Y1Y2 - b3Z1Z2
    y3 = F.mul_small(t5, B3)               # b3*(X1Z2+X2Z1)
    xo = F.sub(F.mul(t3, t1), F.mul(t4, y3))
    yo = F.add(F.mul(y3, x3), F.mul(t1, z3))
    zo = F.add(F.mul(z3, t4), F.mul(x3, t3))
    return (xo, yo, zo)


def pdbl(p):
    """Complete projective doubling (RCB16 Alg 9, a=0)."""
    x, y, z = p
    t0 = F.sq(y)
    z3 = F.add(F.add(t0, t0), F.add(t0, t0))
    z3 = F.add(z3, z3)                     # 8Y^2
    t1 = F.mul(y, z)
    t2 = F.mul_small(F.sq(z), B3)          # b3*Z^2
    x3 = F.mul(t2, z3)
    y3 = F.add(t0, t2)
    z3 = F.mul(t1, z3)
    t1 = F.add(t2, t2)
    t2 = F.add(t1, t2)
    t0 = F.sub(t0, t2)                     # Y^2 - 3*b3*Z^2
    y3 = F.add(x3, F.mul(t0, y3))
    m = F.mul(t0, F.mul(x, y))
    x3 = F.add(m, m)
    return (x3, y3, z3)


def psel(cond, a, b):
    return tuple(F.sel(cond, x, y) for x, y in zip(a, b))


def _sel2(b0, b1, e0, e1, e2, e3):
    lo = psel(b0, e1, e0)
    hi = psel(b0, e3, e2)
    return psel(b1, hi, lo)


# -------------------------------------------- compile-time [i]G constants

_G_MULTS = [
    sm.IDENTITY,
    sm.G,
    sm.to_affine(sm.point_double(sm.G)) + (1,),
    sm.to_affine(sm.scalar_mult(3, sm.G)) + (1,),
]


def _const_pt(pt, like):
    return tuple(F.const(c, like) for c in pt)


# ------------------------------------------------------------- the kernel


def verify_tile(u1, u2, qx, qy, t1, t2):
    """Per-tile verification as a pure array function.

    u1/u2/qx/qy/t1/t2: (NWORDS, *S) int32 little-endian words. Returns
    (*S,) int32 verdicts. (No parity/y check: ECDSA's verdict depends only
    on x(R').)
    """
    u1_r = [u1[i] for i in range(NWORDS)]
    u2_r = [u2[i] for i in range(NWORDS)]
    like = u1_r[0]

    q = (
        words_to_limbs([qx[i] for i in range(NWORDS)]),
        words_to_limbs([qy[i] for i in range(NWORDS)]),
        F.const(1, like),
    )

    # 16-entry table [i]G + [j]Q (i = u1 digit, j = u2 digit)
    g_pts = [_const_pt(pt, like) for pt in _G_MULTS]
    q2 = pdbl(q)
    q3 = padd(q2, q)
    q_pts = [None, q, q2, q3]
    table = []
    for i in range(4):
        for j in range(4):
            if j == 0:
                table.append(g_pts[i])
            elif i == 0:
                table.append(q_pts[j])
            else:
                table.append(padd(g_pts[i], q_pts[j]))
    ident = _const_pt(sm.IDENTITY, like)

    def body(it, p):
        d = NDIGITS - 1 - it
        sd = digit_at(u1_r, d)
        hd = digit_at(u2_r, d)
        s0, s1 = (sd & 1) != 0, (sd >> 1) != 0
        h0, h1 = (hd & 1) != 0, (hd >> 1) != 0
        rows = [
            _sel2(h0, h1, table[4 * i + 0], table[4 * i + 1],
                  table[4 * i + 2], table[4 * i + 3])
            for i in range(4)
        ]
        entry = _sel2(s0, s1, rows[0], rows[1], rows[2], rows[3])
        r = padd(pdbl(pdbl(p)), entry)
        return tuple(tuple(e) for e in r)

    p0 = tuple(tuple(e) for e in ident)
    rx, ry, rz = (list(e) for e in jax.lax.fori_loop(0, NDIGITS, body, p0))

    cz = F.canon(rz)
    cx = F.canon(rx)
    t1_fe = words_to_limbs([t1[i] for i in range(NWORDS)])
    t2_fe = words_to_limbs([t2[i] for i in range(NWORDS)])
    m1 = F.canon(F.mul(t1_fe, rz))
    m2 = F.canon(F.mul(t2_fe, rz))
    ok = (~F.is_zero(cz)) & (F.eq(cx, m1) | F.eq(cx, m2))
    return ok.astype(jnp.int32)


def _verify_tile_kernel(sigs_ref, keys_ref, out_ref):
    sigs = sigs_ref[:]  # (SIG_ROWS, SUB, LANE): u1, u2, t1, t2
    keys = keys_ref[:]  # (KEY_ROWS, SUB, LANE): Qx, Qy

    out_ref[:] = verify_tile(
        sigs[0:NWORDS], sigs[NWORDS:2 * NWORDS],
        keys[0:NWORDS], keys[NWORDS:2 * NWORDS],
        sigs[2 * NWORDS:3 * NWORDS], sigs[3 * NWORDS:4 * NWORDS],
    )


@jax.jit
def secp_verify_xla(sigs, keys):
    """XLA (non-Pallas) variant of `secp_verify_kernel`: identical (32, B)
    sigs + (16, B) keys wire blocks in, (B,) bool out, but `verify_tile`
    runs as a plain array program — no Mosaic. TPU-TARGET ONLY in
    practice: the 12-bit-limb program (16-entry table of complete RCB
    adds + 128-iteration loop) is pathological for XLA:CPU's scalar
    codegen — >18 min compile measured on the CI host, vs ~1 min for
    Mosaic. It exists as the A/B variant and Mosaic-regression fallback
    on real TPU; non-TPU meshes use the host-callback body instead
    (parallel/sharded.py, secp_batch.host_verify_blocks). Reference
    analog: /root/reference/crypto/secp256k1/secp256k1_nocgo.go:21-50."""
    from tendermint_tpu.ops.secp_batch import KEY_ROWS, SIG_ROWS

    assert sigs.shape[0] == SIG_ROWS and keys.shape[0] == KEY_ROWS
    ok = verify_tile(
        sigs[0:NWORDS], sigs[NWORDS:2 * NWORDS],
        keys[0:NWORDS], keys[NWORDS:2 * NWORDS],
        sigs[2 * NWORDS:3 * NWORDS], sigs[3 * NWORDS:4 * NWORDS],
    )
    return ok != 0


@partial(jax.jit, static_argnames=("interpret",))
def secp_verify_kernel(sigs, keys, interpret: bool = False):
    """Batched ECDSA verify: sigs (32, B) + keys (16, B) wire blocks in,
    (B,) bool out (two arguments so the valset-dependent Q block can stay
    device-resident). B is padded on device to a TILE multiple; padded
    lanes compute garbage verdicts that are sliced off (complete formulas:
    junk inputs cannot fault)."""
    from tendermint_tpu.ops.secp_batch import KEY_ROWS, SIG_ROWS

    b = sigs.shape[1]
    padded = -(-b // TILE) * TILE
    pad = padded - b
    if pad:
        sigs = jnp.pad(sigs, ((0, 0), (0, pad)))
        keys = jnp.pad(keys, ((0, 0), (0, pad)))
    sigs = sigs.reshape(SIG_ROWS, padded // LANE, LANE)
    keys = keys.reshape(KEY_ROWS, padded // LANE, LANE)

    grid = (padded // TILE,)
    out = pl.pallas_call(
        _verify_tile_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((SIG_ROWS, SUB, LANE), lambda i: (0, i, 0)),
            pl.BlockSpec((KEY_ROWS, SUB, LANE), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((SUB, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded // LANE, LANE), jnp.int32),
        interpret=interpret,
    )(sigs, keys)
    return out.reshape(-1)[:b] != 0
