"""AOT pre-baking of TPU executables WITHOUT a live device.

The round-2..4 postmortems all have one shape: the tunnel to the real TPU
answers rarely and briefly, and the first device action of a cold process
is a 100+-second XLA:TPU compile — so short windows bank nothing. This
module removes the compile from the window entirely:

- ``libtpu`` is installed locally (compile-only use is supported via PJRT
  topology descriptions), so ``jax.jit(...).lower(...).compile()`` against
  a ``jax.experimental.topologies`` description runs the REAL XLA:TPU +
  Mosaic compiler on this host with no device and no tunnel.
- The compiled executable is serialized (``jax.experimental
  .serialize_executable``) and cached on disk, keyed by kernel-source
  hash + jax/libtpu versions + bucket.
- On a live device, ``load_verify_fn`` deserializes the executable into
  the real client — an upload, not a compile — so the first verify of a
  tunnel window costs seconds, not minutes.

Version skew between the local compiler (libtpu 0.0.34 here) and the
device runtime is handled by treating every load failure as a cache miss:
callers fall through to the export-blob/jit path exactly as before.

Bake offline:  JAX_PLATFORMS=cpu python -m tendermint_tpu.ops.aot [bucket ...]

The topology name targets the tunnel device (``TPU v5 lite`` = v5e; the
2x2 topology is the smallest the local libtpu accepts — executables are
compiled single-device against its device 0, which matches the 1-chip
client's device id).

Reference anchor: this replaces the warm-up cost in front of the batched
commit-verify loop at /root/reference/types/validator_set.go:591-633.
"""
from __future__ import annotations

import json
import os
import sys

TOPOLOGY = "v5e:2x2"
_DEVICE_KIND = "TPU v5 lite"


def _aot_dir() -> str:
    from tendermint_tpu.ops import kcache

    return os.path.join(kcache._CACHE_DIR, "aot")


def _versions() -> str:
    import jax

    try:
        from importlib.metadata import version

        ltv = version("libtpu")
    except Exception:  # noqa: BLE001 — absent metadata just widens the key
        ltv = "unknown"
    return f"jax{jax.__version__}_libtpu{ltv}"


def _path(kname: str, bucket: int) -> str:
    from tendermint_tpu.ops import kcache

    return os.path.join(
        _aot_dir(),
        f"ed25519_verify_{kname}_{bucket}_{kcache._source_version()}"
        f"_{_versions()}.aotexec",
    )


def _secp_version() -> str:
    import hashlib

    from tendermint_tpu.ops import pallas_secp, secp_batch

    h = hashlib.sha256()
    for m in (pallas_secp, secp_batch):
        with open(m.__file__, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _secp_path(bucket: int) -> str:
    from tendermint_tpu.ops import kcache  # noqa: F401 — cache dir init

    return os.path.join(
        _aot_dir(),
        f"secp_verify_{bucket}_{_secp_version()}_{_versions()}.aotexec",
    )


def topology_sharding():
    """SingleDeviceSharding on device 0 of the local compile-only v5e
    topology — the target every artifact in this cache is baked for."""
    from jax.experimental import topologies
    from jax.sharding import SingleDeviceSharding

    topo = topologies.get_topology_desc(TOPOLOGY, "tpu")
    return SingleDeviceSharding(topo.devices[0])


def artifact_path(tag: str) -> str:
    """Cache path for a caller-tagged artifact (e.g. the device-time
    K-repeat programs). The tag must already encode any source-version
    dependence; jax/libtpu versions are appended here."""
    return os.path.join(_aot_dir(), f"{tag}_{_versions()}.aotexec")


def _kernel_plain(kname: str):
    """The un-jitted (keys, sigs) -> verdicts callable for a kernel name
    (re-jitted here with explicit shardings for the topology compile)."""
    if kname == "pallas":
        from tendermint_tpu.ops import pallas_verify

        def fn(keys, sigs):
            return pallas_verify.pallas_verify_kernel.__wrapped__(keys, sigs)

        return fn
    from tendermint_tpu.ops import ed25519_batch

    return ed25519_batch.verify_kernel.__wrapped__


def _mesh_path(kname: str, bucket: int, mesh_n: int) -> str:
    from tendermint_tpu.ops import kcache

    return os.path.join(
        _aot_dir(),
        f"ed25519_verify_mesh{mesh_n}_{kname}_{bucket}"
        f"_{kcache._source_version()}_{_versions()}.aotexec",
    )


def topology_mesh(mesh_n: int):
    """A `mesh_n`-device batch mesh over the local compile-only topology
    (None when the topology has fewer devices): the target the mesh-
    sharded executables are baked for. The scheduler's dispatch plan
    (device/mesh.py) shards packed buckets over exactly this axis."""
    from jax.experimental import topologies

    from tendermint_tpu.parallel import sharded as shard_mod

    topo = topologies.get_topology_desc(TOPOLOGY, "tpu")
    if len(topo.devices) < mesh_n:
        return None
    return shard_mod.make_batch_mesh(topo.devices[:mesh_n])


def bake(
    buckets, kernels=("pallas", "xla"), secp: bool = True, mesh_sizes=()
) -> list[str]:
    """Compile + serialize each (kernel, bucket) against the local v5e
    topology — single-device executables, plus batch-sharded mesh
    executables for each size in `mesh_sizes` (AOT_r05 topology bake:
    the 2x2 topology offers 4 devices, so mesh sizes 2 and 4 bake here;
    larger slices bake on a host whose libtpu accepts their topology).
    Returns the list of paths written. Requires NO device: run under
    JAX_PLATFORMS=cpu so jax never dials the tunnel."""
    import jax
    from jax.experimental import serialize_executable, topologies
    from jax.sharding import SingleDeviceSharding

    from tendermint_tpu.ops import ed25519_batch, kcache

    topo = topologies.get_topology_desc(TOPOLOGY, "tpu")
    sharding = SingleDeviceSharding(topo.devices[0])
    written = []
    for b in sorted({min(int(b), kcache.MAX_BUCKET) for b in buckets}):
        ks, ss = kcache._input_shapes(b)
        for kname in kernels:
            if kname == "xla" and b > 4096:
                # the XLA kernel's serialized executable grows with the
                # bucket (119 MB at 2048 vs pallas's constant ~20 MB —
                # pallas streams grid tiles); at stream shapes the blob
                # would cost more tunnel time to upload than it saves,
                # and pallas is the preferred TPU kernel anyway
                continue
            if _bake_one(
                _path(kname, b), _kernel_plain(kname), (ks, ss), sharding,
                f"{kname} bucket {b}",
            ):
                written.append(_path(kname, b))
        if secp:
            _bake_secp(b, sharding)
        for mesh_n in sorted({int(m) for m in mesh_sizes if int(m) >= 2}):
            p = _bake_mesh(b, mesh_n)
            if p is not None:
                written.append(p)
    return written


def _bake_mesh(bucket: int, mesh_n: int) -> str | None:
    """Bake the batch-sharded verify executable for one (bucket, mesh)
    pair: the preferred TPU kernel jitted with the same matched
    NamedSharding in/out specs + donated sig block the live mesh plan
    uses (parallel/sharded.py), compiled against the topology mesh. The
    bucket must divide over the mesh — guaranteed for the power-of-two
    sizes device/mesh.py resolves."""
    from tendermint_tpu.ops import kcache
    from tendermint_tpu.parallel import sharded as shard_mod

    if bucket % mesh_n:
        print(
            f"bake SKIPPED mesh{mesh_n} bucket {bucket}: not divisible",
            file=sys.stderr,
        )
        return None
    mesh = topology_mesh(mesh_n)
    if mesh is None:
        print(
            f"bake SKIPPED mesh{mesh_n}: topology {TOPOLOGY} has too few "
            f"devices",
            file=sys.stderr,
        )
        return None
    kname, _ = kcache._kernel_for("tpu")
    path = _mesh_path(kname, bucket, mesh_n)
    ks, ss = kcache._input_shapes(bucket)

    def jitted():
        # bake EXACTLY the program the live mesh plan runs: the shard_map-
        # wrapped stream verifier (a Mosaic kernel cannot be GSPMD-
        # partitioned by a bare pjit — it must stay inside the shard_map)
        return shard_mod.build_stream_verifier(mesh, donate=True).jitted

    ok = _bake_one_jitted(
        path, jitted, (ks, ss), f"mesh{mesh_n} {kname} bucket {bucket}"
    )
    return path if ok else None


def _bake_one_jitted(path: str, make_jitted, arg_shapes, label: str) -> bool:
    """Like `_bake_one` but for a caller-jitted program (mesh bakes carry
    their own shardings; re-wrapping them in a SingleDeviceSharding jit
    would defeat the point)."""
    from jax.experimental import serialize_executable

    if os.path.exists(path):
        return False
    try:
        compiled = make_jitted().lower(*arg_shapes).compile()
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        _write(path, payload, in_tree, out_tree)
        print(
            f"baked {label}: {os.path.getsize(path):,} bytes",
            file=sys.stderr,
            flush=True,
        )
        return True
    except Exception as e:  # noqa: BLE001 — bake the rest anyway
        print(f"bake FAILED {label}: {e!r}", file=sys.stderr, flush=True)
        return False


def _bake_one(path: str, plain_fn, arg_shapes, sharding, label: str) -> bool:
    """Compile `plain_fn` at `arg_shapes` against the topology sharding,
    serialize, and atomically persist to `path`. Best-effort: a failure is
    logged and skipped (bake the rest). Returns True when newly written."""
    import jax
    from jax.experimental import serialize_executable

    if os.path.exists(path):
        return False
    try:
        jitted = jax.jit(
            plain_fn,
            in_shardings=tuple(sharding for _ in arg_shapes),
            out_shardings=sharding,
        )
        compiled = jitted.lower(*arg_shapes).compile()
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        _write(path, payload, in_tree, out_tree)
        print(
            f"baked {label}: {os.path.getsize(path):,} bytes",
            file=sys.stderr,
            flush=True,
        )
        return True
    except Exception as e:  # noqa: BLE001 — bake the rest anyway
        print(f"bake FAILED {label}: {e!r}", file=sys.stderr, flush=True)
        return False


def _bake_secp(bucket: int, sharding) -> None:
    """Bake the secp256k1 verify kernel for one bucket (best-effort: the
    kernel is TPU-only; a lowering failure just means no AOT entry)."""
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.ops import pallas_secp, secp_batch

    ss = jax.ShapeDtypeStruct((secp_batch.SIG_ROWS, bucket), jnp.int32)
    ks = jax.ShapeDtypeStruct((secp_batch.KEY_ROWS, bucket), jnp.int32)

    def plain(sigs, keys):
        return pallas_secp.secp_verify_kernel.__wrapped__(sigs, keys)

    _bake_one(_secp_path(bucket), plain, (ss, ks), sharding,
              f"secp bucket {bucket}")


# -- on-disk format ----------------------------------------------------------
#
# Two files per artifact: `<path>` holds the RAW serialized-executable
# bytes exactly as XLA produced them, and `<path>.tree.json` is a JSON
# sidecar describing the call-signature pytrees. The previous format was
# one pickle of (payload, in_tree, out_tree) — but unpickling a cache
# file is an arbitrary-code-execution surface (ROADMAP item 1 / ADVICE):
# anyone who can write to the cache dir owns the process at the next
# load. Raw bytes + JSON can encode no behaviour; a legacy pickle file
# simply has no sidecar and is a cache miss (re-bake to migrate).


def _sidecar(path: str) -> str:
    return path + ".tree.json"


def _treedef_to_spec(treedef):
    """PyTreeDef -> JSON-able spec. Only the stdlib containers jax call
    signatures are made of (tuple/list/dict/None + leaves) are supported;
    anything else fails the bake loudly rather than silently pickling."""
    import jax

    leaf = object()  # unique marker: None is itself a pytree node in jax
    skeleton = jax.tree_util.tree_unflatten(
        treedef, [leaf] * treedef.num_leaves
    )

    def conv(obj):
        if obj is leaf:
            return "*"
        if isinstance(obj, tuple):
            if hasattr(obj, "_fields"):  # namedtuple: distinct treedef
                raise ValueError("unsupported pytree node namedtuple")
            return {"t": [conv(x) for x in obj]}
        if isinstance(obj, list):
            return {"l": [conv(x) for x in obj]}
        if isinstance(obj, dict):
            if not all(isinstance(k, str) for k in obj):
                raise ValueError("unsupported pytree: non-string dict key")
            return {"d": {k: conv(v) for k, v in obj.items()}}
        if obj is None:
            return {"n": True}  # structural None node (zero leaves)
        raise ValueError(f"unsupported pytree node {type(obj).__name__}")

    return conv(skeleton)


def _spec_to_treedef(spec):
    """JSON spec -> PyTreeDef (inverse of `_treedef_to_spec`)."""
    import jax

    def conv(s):
        if s == "*":
            return 0  # any non-container object is a leaf
        if isinstance(s, dict):
            if "t" in s:
                return tuple(conv(x) for x in s["t"])
            if "l" in s:
                return [conv(x) for x in s["l"]]
            if "d" in s:
                return {k: conv(v) for k, v in s["d"].items()}
            if "n" in s:
                return None
        raise ValueError(f"bad tree spec {s!r}")

    return jax.tree_util.tree_structure(conv(spec))


def _write(path: str, payload: bytes, in_tree, out_tree) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    spec = json.dumps({
        "format": 1,
        "in_tree": _treedef_to_spec(in_tree),
        "out_tree": _treedef_to_spec(out_tree),
    })
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
    # sidecar last: a crash in between leaves payload-without-sidecar,
    # which the loader treats as a miss
    stmp = _sidecar(path) + f".tmp{os.getpid()}"
    with open(stmp, "w", encoding="utf-8") as f:
        f.write(spec)
    os.replace(stmp, _sidecar(path))


def _load(path: str):
    """Deserialize one cached executable into the live client; returns the
    jax.stages.Compiled or None. Any failure (missing file/sidecar,
    version skew, client without deserialize support) is a cache miss."""
    try:
        with open(_sidecar(path), encoding="utf-8") as f:
            spec = json.load(f)
        with open(path, "rb") as f:
            payload = f.read()
    except (OSError, ValueError):
        # missing sidecar also covers legacy pickle-era artifacts, which
        # are deliberately never unpickled
        return None
    try:
        in_tree = _spec_to_treedef(spec["in_tree"])
        out_tree = _spec_to_treedef(spec["out_tree"])
    except (KeyError, TypeError, ValueError):
        return None
    try:
        import jax

        dev = jax.devices()[0]
        if dev.device_kind != _DEVICE_KIND:
            # executables are target-specific; don't rely on the client
            # rejecting a wrong-generation binary — a skewed accept would
            # run a wrong-target program undetected
            print(
                f"aot: skipping {path} — baked for {_DEVICE_KIND!r}, "
                f"device is {dev.device_kind!r}",
                file=sys.stderr,
            )
            return None
        from jax.experimental import serialize_executable

        return serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree, backend=dev.client
        )
    except Exception as e:  # noqa: BLE001 — runtime/compiler skew: miss
        print(f"aot load failed ({path}): {e!r}", file=sys.stderr)
        return None


def load_verify_fn(bucket: int):
    """Pre-baked ed25519 verify executable for the preferred kernel on the
    live TPU client, or None. Tries the preferred kernel first, then the
    other one (a baked-but-unpreferred kernel still beats a cold compile)."""
    from tendermint_tpu.ops import kcache

    preferred, _ = kcache._kernel_for("tpu")
    for kname in (preferred, "xla" if preferred == "pallas" else "pallas"):
        if os.environ.get("TMTPU_KERNEL") and kname != preferred:
            break  # an explicit kernel choice must not silently switch
        compiled = _load(_path(kname, bucket))
        if compiled is not None:
            print(
                f"aot: loaded pre-baked {kname} executable, bucket {bucket}",
                file=sys.stderr,
            )
            return lambda keys, sigs: compiled(keys, sigs)
    return None


def load_secp_fn(bucket: int):
    """Pre-baked secp verify executable on the live client, or None."""
    compiled = _load(_secp_path(bucket))
    if compiled is None:
        return None
    return lambda sigs, keys: compiled(sigs, keys)


def load_mesh_verify_fn(bucket: int, mesh_n: int):
    """Pre-baked batch-sharded ed25519 verify executable for one
    (bucket, mesh size) on the live client, or None. The live mesh must
    match the baked device count; a mismatch (or any deserialize failure)
    is a cache miss and the caller keeps its jit program."""
    import jax

    from tendermint_tpu.ops import kcache

    if len(jax.devices()) < mesh_n:
        return None
    kname, _ = kcache._kernel_for("tpu")
    compiled = _load(_mesh_path(kname, bucket, mesh_n))
    if compiled is None:
        return None
    print(
        f"aot: loaded pre-baked mesh{mesh_n} {kname} executable, "
        f"bucket {bucket}",
        file=sys.stderr,
    )
    return lambda keys, sigs: compiled(keys, sigs)


if __name__ == "__main__":
    # bake must never dial the tunnel: force CPU before jax initializes.
    # The env var alone is NOT enough — the axon plugin registers itself
    # regardless and a dead tunnel hangs backend init for ~25 min before
    # erroring; the config update is the authoritative override
    # (tests/conftest.py pattern).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax as _jax

    _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    args = sys.argv[1:]
    mesh_sizes: list[int] = []
    if "--mesh" in args:
        # bake batch-sharded executables too: every power-of-two mesh the
        # topology covers (2 and 4 on the default 2x2)
        args.remove("--mesh")
        mesh_sizes = [2, 4]
    wanted = [int(a) for a in args] or [128, 1024, 2048, 12288, 131072]
    paths = bake(wanted, mesh_sizes=mesh_sizes)
    print(f"baked {len(paths)} new executables under {_aot_dir()}")
