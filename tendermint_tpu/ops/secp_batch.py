"""Batched secp256k1 ECDSA verification — host-side batch builder.

TPU replacement for the reference's serial secp256k1 verify
(crypto/secp256k1/secp256k1_nocgo.go:21-50; vendored libsecp256k1 on the
cgo path). Work split mirrors ops/ed25519_batch.py:

- Host (cheap, per signature): parse r||s, range + low-S checks, z =
  SHA-256(msg) mod n, w = s^-1 mod n, u1 = z*w, u2 = r*w (all mod-n bigint,
  ~2us/sig), pubkey decompression (cached — validator keys are stable), and
  the two device compare targets r and r+n (x mod n == r admits both).
- Device (the FLOPs): R' = [u1]G + [u2]Q by joint radix-4 Straus over
  complete projective a=0 formulas; valid iff Z' != 0 and X' == t*Z' for a
  target t. See ops/pallas_secp.py.

Wire format: ONE (48, B) int32 array per batch — six (8, B) little-endian
word planes stacked (~192 B/signature). A single array means a single
host->device transfer per batch: on a tunneled/remote device every
separate `device_put` pays a full RPC round trip (see ops/ed25519_batch.py
— same design, measured there). The per-signature planes (u1, u2, t1, t2)
come first and the pubkey planes (Qx, Qy) last, so `split()` yields the
two as zero-copy views and a stable valset's key block stays
device-resident between batches, exactly like the ed25519 path.
"""
from __future__ import annotations

import os

import numpy as np

from tendermint_tpu.crypto import secp256k1_math as sm
from tendermint_tpu.device import profiler as _profiler
from tendermint_tpu.device import scheduler as _dsched
from tendermint_tpu.device.priorities import current_priority as _current_priority
from tendermint_tpu.libs import trace as _trace

NWORDS = 8
# Packed wire-format rows: sig-dependent planes then the pubkey planes.
ROW_U1, ROW_U2, ROW_T1, ROW_T2, ROW_QX, ROW_QY = (8 * k for k in range(6))
ROWS = 48
SIG_ROWS = 32   # u1, u2, t1, t2
KEY_ROWS = 16   # Qx, Qy


def split(packed):
    """(48, B) packed -> (sigs (32, B), keys (16, B)) zero-copy row views."""
    return packed[:SIG_ROWS], packed[SIG_ROWS:]


class _PubkeyCache:
    """pubkey bytes -> (2, 8) uint32 words of Q affine (x, y), LRU-bounded."""

    def __init__(self, maxsize: int = 65536) -> None:
        self._d: dict[bytes, np.ndarray | None] = {}
        self._maxsize = maxsize

    def get(self, pub: bytes) -> np.ndarray | None:
        if pub in self._d:
            return self._d[pub]
        pt = sm.decompress(pub)
        if pt is None:
            entry = None
        else:
            buf = b"".join(v.to_bytes(32, "little") for v in pt)
            entry = np.frombuffer(buf, dtype=np.uint32).reshape(2, NWORDS).copy()
        if len(self._d) >= self._maxsize:
            self._d.pop(next(iter(self._d)))
        self._d[pub] = entry
        return entry


_cache = _PubkeyCache()



# one bucketing policy and one device-key-cache type for both curves
from tendermint_tpu.ops.ed25519_batch import (  # noqa: E402
    _DeviceKeyCache,
    _pad_to_bucket,
)

_dev_keys = _DeviceKeyCache()  # content-addressed device-resident Q blocks


def prepare_batch(pubs, msgs, sigs, min_bucket: int = 128):
    """Returns (packed (48, B) int32 array | None, valid_mask).

    valid_mask marks signatures already known invalid from structural checks
    (bad lengths, r/s out of range, high-S, bad pubkey) — final False.
    """
    n = len(pubs)
    mask = np.ones(n, dtype=bool)
    u1_w = np.zeros((n, NWORDS), dtype=np.uint32)
    u2_w = np.zeros((n, NWORDS), dtype=np.uint32)
    qx_w = np.zeros((n, NWORDS), dtype=np.uint32)
    qy_w = np.zeros((n, NWORDS), dtype=np.uint32)
    t1_w = np.zeros((n, NWORDS), dtype=np.uint32)
    t2_w = np.zeros((n, NWORDS), dtype=np.uint32)
    parsed: list[tuple[int, int, int] | None] = [None] * n  # (r, s, i)
    for i in range(n):
        pub, msg, sig = pubs[i], msgs[i], sigs[i]
        if len(pub) != 33 or len(sig) != 64:
            mask[i] = False
            continue
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (0 < r < sm.N and 0 < s <= sm.HALF_N):
            mask[i] = False
            continue
        entry = _cache.get(bytes(pub))
        if entry is None:
            mask[i] = False
            continue
        qx_w[i], qy_w[i] = entry
        parsed[i] = (r, s)
    if not mask.any():
        return None, mask
    # Montgomery batch inversion: ONE mod-n inverse for the whole batch +
    # 3 multiplies per signature (the per-signature Fermat pow was
    # ~150us/sig — the whole point of batching lost to host prep)
    idxs = [i for i in range(n) if parsed[i] is not None]
    prefix = []
    acc = 1
    for i in idxs:
        prefix.append(acc)
        acc = acc * parsed[i][1] % sm.N
    inv_acc = pow(acc, -1, sm.N)
    inv_s: dict[int, int] = {}
    for j in range(len(idxs) - 1, -1, -1):
        i = idxs[j]
        inv_s[i] = inv_acc * prefix[j] % sm.N
        inv_acc = inv_acc * parsed[i][1] % sm.N
    for i in idxs:
        r, _s = parsed[i]
        w = inv_s[i]
        z = sm.msg_scalar(msgs[i])
        u1 = z * w % sm.N
        u2 = r * w % sm.N
        u1_w[i] = np.frombuffer(u1.to_bytes(32, "little"), dtype=np.uint32)
        u2_w[i] = np.frombuffer(u2.to_bytes(32, "little"), dtype=np.uint32)
        t1_w[i] = np.frombuffer(r.to_bytes(32, "little"), dtype=np.uint32)
        # x mod n == r also matches x == r + n (only when it stays < p)
        t2 = r + sm.N if r + sm.N < sm.P else r
        t2_w[i] = np.frombuffer(t2.to_bytes(32, "little"), dtype=np.uint32)
    padded = _pad_to_bucket(n, min_bucket)
    packed = np.zeros((ROWS, padded), dtype=np.int32)
    for row, a in (
        (ROW_U1, u1_w), (ROW_U2, u2_w), (ROW_T1, t1_w),
        (ROW_T2, t2_w), (ROW_QX, qx_w), (ROW_QY, qy_w),
    ):
        packed[row:row + NWORDS, :n] = a.T.view(np.int32)
    return packed, mask


def _device_fn():
    """Mosaic kernel on TPU; None elsewhere — on CPU the serial OpenSSL
    path is faster than a jitted limb kernel AND skips a multi-minute
    XLA-CPU compile, mirroring the reference's cgo/nocgo duality
    (secp256k1_cgo.go / secp256k1_nocgo.go)."""
    import jax

    if jax.default_backend() != "tpu":
        return None
    from tendermint_tpu.ops import pallas_secp

    if os.environ.get("TMTPU_NO_AOT_CACHE"):
        return _profiler.wrap("secp_verify", pallas_secp.secp_verify_kernel)

    timed_kernel = _profiler.wrap("secp_verify", pallas_secp.secp_verify_kernel)

    def dispatch(sigs, keys):
        # per-bucket pre-baked executable (ops/aot.py) when one exists —
        # an upload instead of a cold-window compile; the jit kernel is
        # the fallback for unbaked shapes and load failures
        b = int(sigs.shape[1])
        fn = _aot_fns.get(b, _AOT_UNTRIED)
        if fn is _AOT_UNTRIED:
            try:
                from tendermint_tpu.ops import aot

                fn = aot.load_secp_fn(b)
            except Exception:  # noqa: BLE001 — AOT layer is best-effort
                fn = None
            if fn is not None:
                # pre-baked executable: an upload, not a compile
                _profiler.PROFILER.record_cache_hit("secp_verify", "aot")
            _aot_fns[b] = fn
        if fn is not None:
            return fn(sigs, keys)
        return timed_kernel(sigs, keys)

    return dispatch


_AOT_UNTRIED = object()
_aot_fns: dict[int, object] = {}

# Multi-device dispatch (SURVEY §7: both curves shard across chips).
# Mesh routing is owned by device/mesh.py (config/env-driven TMTPU_MESH
# plan, shared with ed25519): it keeps this curve's gate — TPU only by
# default, because on a CPU host the serial OpenSSL path beats a jitted
# limb kernel (see _device_fn) — with TMTPU_SECP_MESH=1 forcing it on
# for the virtual-mesh routing tests and dryruns.
_sharded = None  # (fn, NamedSharding, mesh size) | None, rebuilt on change


def _multi_device_fn():
    from tendermint_tpu.device import mesh as dmesh

    n = dmesh.mesh_size("secp256k1")
    if n < 2:
        return None, None
    global _sharded
    if _sharded is None or _sharded[2] != n:
        built = dmesh.build_plan("secp256k1", n)
        if built is None:
            return None, None
        _sharded = (built[0], built[1], n)
    return _sharded[0], _sharded[1]


def invalidate_mesh_plan() -> None:
    """Drop every cache bound to the current device layout (see
    ed25519_batch.invalidate_mesh_plan — called by device/mesh.reset()
    on a layout change)."""
    global _sharded
    _sharded = None
    _dev_keys._d.clear()


def host_verify_blocks(sigs_blk, keys_blk) -> np.ndarray:
    """Reference-semantics verification of packed wire blocks on the HOST
    (python ints, crypto/secp256k1_math): sigs (32, B) + keys (16, B)
    int32 word planes in, (B,) bool out — the exact verdict contract of
    `pallas_secp.secp_verify_kernel`/`secp_verify_xla`, computed without
    any device program. Used as the per-shard body on non-TPU meshes
    (ops/pallas_secp.py documents why the limb kernels are not viable on
    XLA:CPU) and usable as an oracle anywhere. All-zero (padded) lanes
    yield False, matching the kernels' garbage-lane contract."""
    sigs_w = np.ascontiguousarray(np.asarray(sigs_blk)).view(np.uint32)
    keys_w = np.ascontiguousarray(np.asarray(keys_blk)).view(np.uint32)
    b = sigs_w.shape[1]
    out = np.zeros(b, dtype=bool)

    def word_int(plane, col):
        return int.from_bytes(plane[:, col].astype("<u4").tobytes(), "little")

    for i in range(b):
        u1 = word_int(sigs_w[0:NWORDS], i)
        u2 = word_int(sigs_w[NWORDS:2 * NWORDS], i)
        t1 = word_int(sigs_w[2 * NWORDS:3 * NWORDS], i)
        t2 = word_int(sigs_w[3 * NWORDS:4 * NWORDS], i)
        qx = word_int(keys_w[0:NWORDS], i)
        qy = word_int(keys_w[NWORDS:2 * NWORDS], i)
        r = sm.point_add(
            sm.scalar_mult(u1, sm.G), sm.scalar_mult(u2, (qx, qy, 1))
        )
        x, _, z = r
        if z % sm.P == 0:
            continue
        out[i] = x % sm.P in (t1 * z % sm.P, t2 * z % sm.P)
    return out


def _serial_verify(pubs, msgs, sigs) -> list[bool]:
    from tendermint_tpu import ops
    from tendermint_tpu.crypto.secp256k1 import PubKeySecp256k1

    return ops.serial_verify(PubKeySecp256k1, pubs, msgs, sigs)


def verify_batch(pubs, msgs, sigs) -> list[bool]:
    """DEPRECATED direct entry — thin compatibility wrapper.

    Submits through the process-wide DeviceScheduler admission queue
    (tendermint_tpu/device/) at the caller's priority class; on the
    scheduler's own dispatch thread it runs the real dispatch body (tmlint
    TM501 flags new direct calls outside tendermint_tpu/device/)."""
    if _dsched.in_dispatch():
        return _verify_batch_local(pubs, msgs, sigs)
    return _dsched.get_scheduler().submit_sync(
        "secp256k1", pubs, msgs, sigs
    ).result()


def _verify_batch_local(pubs, msgs, sigs) -> list[bool]:
    """Full batched verification: host prep + one device launch per chunk.
    Scheduler-dispatch body (callers go through `verify_batch`).

    Chunk launches are dispatched asynchronously and collected at the end
    (one device transfer + one execute each — see ed25519_batch for the
    dispatch-cost rationale). Consults the dispatching scheduler's
    wedged-device circuit breaker — both curves dispatch over the same
    link, through the same queue — and records the same `secp_batch`
    device span + DEVICE telemetry."""
    from tendermint_tpu.ops import kcache

    n = len(pubs)
    fn = _device_fn()
    mfn, sharding = _multi_device_fn()
    if fn is None and mfn is None:
        # no secp device kernel: serial path, and crucially WITHOUT
        # consulting the breaker — allow() claims the one half-open probe
        # per retry window, and a caller that can never reach the device
        # must not starve ed25519's actual recovery probe
        return _serial_verify(pubs, msgs, sigs)
    if not _dsched.active_breaker().allow():
        _trace.DEVICE.record_fallback("breaker_open", curve="secp256k1")
        with _trace.span("secp_cpu_fallback", batch_size=n, reason="breaker_open"):
            return _serial_verify(pubs, msgs, sigs)
    with _trace.span("secp_batch", batch_size=n) as sp:
        return _verify_batch_device(pubs, msgs, sigs, n, fn, mfn, sharding, kcache, sp)


def _verify_batch_device(pubs, msgs, sigs, n, fn, mfn, sharding, kcache, sp) -> list[bool]:
    """verify_batch body under an open `secp_batch` span `sp`."""
    import time as _time

    breaker = _dsched.active_breaker()
    t_dispatch0 = _time.monotonic()
    pending: list[tuple[int, int, object, np.ndarray]] = []
    out = np.zeros(n, dtype=bool)
    for lo in range(0, n, kcache.MAX_BUCKET):
        hi = min(lo + kcache.MAX_BUCKET, n)
        packed, mask = prepare_batch(pubs[lo:hi], msgs[lo:hi], sigs[lo:hi])
        if packed is None:
            continue
        _trace.DEVICE.record_dispatch(
            int(mask.sum()), packed.shape[1], curve="secp256k1"
        )
        sp.set(bucket=packed.shape[1])
        sigs_np, keys_np = split(packed)
        import jax

        dev_out = None
        from_sharded = False
        if mfn is not None:
            try:
                keys_dev = _dev_keys.get(
                    pubs[lo:hi], keys_np, sharding, cacheable=bool(mask.all())
                )
                dev_out = mfn(jax.device_put(sigs_np, sharding), keys_dev)
            except Exception:  # noqa: BLE001 — a sharding/mesh/transfer
                # failure is not a kernel failure: degrade to the
                # single-device path (or serial below)
                dev_out = None
            if dev_out is not None:
                from_sharded = True
                # outside the dispatch try: a throwing telemetry sink
                # must not discard the completed mesh result
                try:
                    _trace.DEVICE.record_mesh_dispatch(
                        int(mask.sum()), packed.shape[1],
                        int(sharding.mesh.size), curve="secp256k1",
                    )
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
        if dev_out is None and fn is not None:
            try:
                # placement is part of the key-cache key, so this lookup
                # serves the default-placed block — never the mesh-placed
                # one a failed sharded attempt above may have cached
                keys_dev = _dev_keys.get(
                    pubs[lo:hi], keys_np, cacheable=bool(mask.all())
                )
                # commit both args: a committed/uncommitted mix is a
                # separate jit cache key and re-traces the kernel (see
                # ed25519_batch)
                dev_out = fn(jax.device_put(sigs_np), keys_dev)
            except Exception:  # noqa: BLE001 — kernel failure degrades to
                # serial, never breaks verification
                dev_out = None
        if dev_out is None:
            out[lo:hi] = _serial_verify(pubs[lo:hi], msgs[lo:hi], sigs[lo:hi])
            continue
        try:
            # cumulative waste ledger (device/profiler); the priority
            # class resolves under the lead request's contextvars
            _profiler.PROFILER.record_padding(
                int(mask.sum()), packed.shape[1],
                cls=_current_priority().label,
                shards=int(sharding.mesh.size) if from_sharded else 1,
            )
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        pending.append((lo, hi, dev_out, mask))
    # concurrent, BOUNDED fetches (the scheduler's pool): a wedged device
    # link degrades every chunk to the serial path instead of blocking
    # the caller forever
    fetch_verdicts = _dsched.fetch_verdicts

    sp.set(chunks=len(pending),
           dispatch_ms=round((_time.monotonic() - t_dispatch0) * 1e3, 3))
    t_fetch0 = _time.monotonic()
    fetched = fetch_verdicts([p[2] for p in pending])
    fetch_s = _time.monotonic() - t_fetch0
    sp.set(fetch_ms=round(fetch_s * 1e3, 3))
    timed_out = False
    for (lo, hi, _, mask), got in zip(pending, fetched):
        if isinstance(got, Exception):
            if isinstance(got, TimeoutError):
                timed_out = True
                _trace.DEVICE.record_fallback("fetch_timeout", curve="secp256k1")
            else:
                _trace.DEVICE.record_fallback("kernel_error", curve="secp256k1")
            out[lo:hi] = _serial_verify(pubs[lo:hi], msgs[lo:hi], sigs[lo:hi])
        else:
            out[lo:hi] = got[: hi - lo] & mask
    if pending:
        # occupancy: dispatch-to-last-verdict wall span, chunks in flight
        _trace.DEVICE.record_busy(
            (_time.monotonic() - t_dispatch0), queue_depth=len(pending)
        )
    if timed_out:
        breaker.trip()
        _trace.DEVICE.record_timeout(curve="secp256k1")
        sp.set(timeout=True)
    elif pending:
        breaker.reset()
        _trace.DEVICE.record_fetch(fetch_s, curve="secp256k1")
    else:
        # nothing dispatched: return the claimed half-open probe unused
        breaker.release_probe()
    return out.tolist()
