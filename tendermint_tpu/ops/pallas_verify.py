"""Pallas TPU kernel for batched Ed25519 verification.

Same math as ops/ed25519_batch.verify_kernel (radix-4 joint Straus over
GF(2^255-19) in 12-bit limbs), but compiled as ONE Mosaic kernel per batch
tile: the 127-iteration loop, its 16-entry table, and every field
intermediate stay in VMEM for the whole verification instead of
round-tripping HBM between XLA fusions. The field primitives here are
written Mosaic-friendly — carries and limb shifts as concatenations, no
pads or scatters.

Falls back transparently: ops/__init__ prefers this kernel when pallas
lowers on the current backend, else the XLA kernel.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tendermint_tpu.ops import curve, field
from tendermint_tpu.ops.ed25519_batch import NDIGITS, NWORDS, _B_MULT_CACHED, _B_MULT_POINTS
from tendermint_tpu.ops.limbs import LIMB_BITS, LIMB_MASK, NLIMB

TILE = 128  # batch lanes per program instance

FOLD = field.FOLD

# Pallas kernels cannot capture (or create) non-scalar constants — every
# curve/field constant is packed into ONE (22, 40) int32 operand, column
# layout: 0 BIAS | 1 NEGP | 2 2d | 3 one | 4-7 identity(x,y,z,t) |
# 8-23 [i]B points (4 coords each) | 24-39 [i]B cached forms.


def _build_const_cols():
    import numpy as np

    cols = [field.BIAS, field.NEGP_LIMBS, curve._D2, curve._ONE]
    cols += list(curve.IDENTITY)
    for p in _B_MULT_POINTS:
        cols += list(p)
    for p in _B_MULT_CACHED:
        cols += list(p)
    return np.concatenate([np.asarray(c, dtype=np.int32).reshape(NLIMB, 1) for c in cols], axis=1)


CONST_COLS = _build_const_cols()
_C_BIAS, _C_NEGP, _C_D2, _C_ONE, _C_IDENT, _C_BPTS, _C_BCACHED = 0, 1, 2, 3, 4, 8, 24

# set per-trace by the kernel body (tracing is single-threaded)
_CST = None


def _col(j):
    return _CST[:, j:j + 1]


# ------------------------------------------------------------- field (tile)


def _carry(c):
    """One carry pass with top fold (concat form of field.carry_pass)."""
    cc = c >> LIMB_BITS
    lo = c & LIMB_MASK
    return lo + jnp.concatenate([cc[-1:] * FOLD, cc[:-1]], axis=0)


def fmul(a, b):
    """(22,T) x (22,T) -> (22,T), class-R out (mirrors field.mul).

    The accumulator is (44, T) — row 43 exists solely to receive the carry
    out of row 42 during the wide passes. (A 43-row variant that kept row 42
    unmasked overflowed int32 at the FOLD multiply for class-R inputs, where
    limb 21 can reach ~4120: 4120^2 * 9728 > 2^31. Canonical inputs hid the
    bug because a canonical limb 21 is <= 7.)"""
    rows = []
    for k in range(2 * NLIMB - 1):
        acc = None
        for i in range(max(0, k - NLIMB + 1), min(NLIMB - 1, k) + 1):
            t = a[i:i + 1] * b[k - i:k - i + 1]
            acc = t if acc is None else acc + t
        rows.append(acc)
    zero1 = jnp.zeros_like(rows[0])
    c = jnp.concatenate(rows + [zero1], axis=0)  # (44, T)
    for _ in range(2):
        cc = c >> LIMB_BITS
        lo = c & LIMB_MASK
        lo = lo + jnp.concatenate([zero1, cc[:-1]], axis=0)
        # top row accumulates: restore its masked-off high bits
        c = jnp.concatenate([lo[:-1], lo[-1:] + (cc[-1:] << LIMB_BITS)], axis=0)
    d = c[:NLIMB] + FOLD * c[NLIMB:]
    for _ in range(4):
        d = _carry(d)
    return d


def fsq(a):
    return fmul(a, a)


def fadd(a, b):
    return _carry(a + b)


def fsub(a, b):
    return _carry(a + (_col(_C_BIAS) - b))


def fsel(cond, a, b):
    """cond (1,T) int32 -> select between (22,T) arrays."""
    return jnp.where(cond != 0, a, b)


def _pow2k(a, k):
    return jax.lax.fori_loop(0, k, lambda _, x: fsq(x), a)


def finv(a):
    t0 = fsq(a)
    t1 = fsq(fsq(t0))
    t1 = fmul(a, t1)
    t0 = fmul(t0, t1)
    t2 = fsq(t0)
    t1 = fmul(t1, t2)
    t2 = _pow2k(t1, 5); t1 = fmul(t2, t1)
    t2 = _pow2k(t1, 10); t2 = fmul(t2, t1)
    t3 = _pow2k(t2, 20); t2 = fmul(t3, t2)
    t2 = _pow2k(t2, 10); t1 = fmul(t2, t1)
    t2 = _pow2k(t1, 50); t2 = fmul(t2, t1)
    t3 = _pow2k(t2, 100); t2 = fmul(t3, t2)
    t2 = _pow2k(t2, 50); t1 = fmul(t2, t1)
    t1 = _pow2k(t1, 5)
    return fmul(t1, t0)


def _concat_rows(parts):
    """concatenate, dropping zero-row operands (Mosaic rejects (0, T)
    vector types that XLA silently folds away)."""
    parts = [p for p in parts if p.shape[0] > 0]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def _seq_carry(a, topfold: bool):
    for k in range(NLIMB - 1):
        cc = a[k:k + 1] >> LIMB_BITS
        a = _concat_rows(
            [a[:k], a[k:k + 1] & LIMB_MASK, a[k + 1:k + 2] + cc, a[k + 2:]]
        )
    if topfold:
        cc = a[-1:] >> LIMB_BITS
        a = _concat_rows([a[:1] + cc * FOLD, a[1:-1], a[-1:] & LIMB_MASK])
    return a


def fcanon(a):
    """Exact canonical digits (mirrors field.canonicalize)."""
    a = _carry(_carry(a))
    a = _seq_carry(a, True)
    a = _seq_carry(a, True)
    for _ in range(2):
        hi = a[-1:] >> 3
        a = jnp.concatenate([a[:1] + hi * 19, a[1:-1], a[-1:] & 0x7], axis=0)
        a = _seq_carry(a, False)
    t = a + _col(_C_NEGP)
    for k in range(NLIMB - 1):
        cc = t[k:k + 1] >> LIMB_BITS
        t = _concat_rows(
            [t[:k], t[k:k + 1] & LIMB_MASK, t[k + 1:k + 2] + cc, t[k + 2:]]
        )
    overflow = t[-1:] >> LIMB_BITS
    t = jnp.concatenate([t[:-1], t[-1:] & LIMB_MASK], axis=0)
    return jnp.where(overflow > 0, t, a)


# ------------------------------------------------------------- curve (tile)

def to_cached(p):
    x, y, z, t = p
    d2 = jnp.broadcast_to(_col(_C_D2), t.shape)
    return (fsub(y, x), fadd(y, x), fmul(t, d2), fadd(z, z))


def add_cached(p, q):
    x, y, z, t = p
    ymx, ypx, t2d, z2 = q
    a = fmul(fsub(y, x), ymx)
    b = fmul(fadd(y, x), ypx)
    c = fmul(t, t2d)
    d = fmul(z, z2)
    e = fsub(b, a)
    f = fsub(d, c)
    g = fadd(d, c)
    h = fadd(b, a)
    return (fmul(e, f), fmul(g, h), fmul(f, g), fmul(e, h))


def pdouble(p):
    x, y, z, _ = p
    a = fsq(x)
    b = fsq(y)
    zz = fsq(z)
    c = fadd(zz, zz)
    h = fadd(a, b)
    e = fsub(h, fsq(fadd(x, y)))
    g = fsub(a, b)
    f = fadd(c, g)
    return (fmul(e, f), fmul(g, h), fmul(f, g), fmul(e, h))


def csel(cond, a, b):
    return tuple(fsel(cond, x, y) for x, y in zip(a, b))


def _sel2(b0, b1, e0, e1, e2, e3):
    lo = csel(b0, e1, e0)
    hi = csel(b0, e3, e2)
    return csel(b1, hi, lo)


# ------------------------------------------------------------- the kernel


def _words_to_limbs(w):
    """(8, T) int32 -> (22, T), all-int32 (Mosaic rejects uint ops): the
    arithmetic right shift sign-extends, so when the limb straddles a word
    boundary the low word's field is masked to its true width before OR-ing
    in the high word's bits."""
    limbs = []
    for k in range(NLIMB):
        lo_bit = LIMB_BITS * k
        a, s = lo_bit // 32, lo_bit % 32
        v = w[a:a + 1] >> s
        if s > 32 - LIMB_BITS and a + 1 < NWORDS:
            v = (v & ((1 << (32 - s)) - 1)) | (w[a + 1:a + 2] << (32 - s))
        limbs.append(v & LIMB_MASK)
    return jnp.concatenate(limbs, axis=0)


def _word_rows(w):
    """(8, T) int32 -> list of 8 (1, T) int32 rows (static slices)."""
    return [w[i:i + 1] for i in range(NWORDS)]


def _digit_at(w_rows, d):
    """2-bit digit d (traced scalar) of scalars packed in 8 int32 rows.

    Mosaic cannot lower a dynamic_slice over a (127, T) digit array inside
    the loop (the round-1 dead-code failure mode), so the digit is computed
    arithmetically: one-hot select of the word row (8 static rows, scalar
    conditions) followed by a variable shift. All int32: the arithmetic
    shift's sign extension only reaches bits >= 2 even at the maximum shift
    of 30, and `& 3` discards them.
    """
    wi = d // 16
    sh = 2 * (d % 16)
    acc = w_rows[0]
    for k in range(1, NWORDS):
        acc = jnp.where(wi == k, w_rows[k], acc)
    return (acc >> sh) & 3


def _bcol(j, t):
    return jnp.broadcast_to(_col(j), (NLIMB, t))


def _verify_tile_kernel(cst_ref, ax_ref, ay_ref, at_ref, s_ref, h_ref, yr_ref, par_ref, out_ref):
    out_ref[:] = verify_tile(
        cst_ref[:], ax_ref[:], ay_ref[:], at_ref[:], s_ref[:], h_ref[:],
        yr_ref[:], par_ref[:],
    )


def verify_tile(cst, ax, ay, at, s, h, yr, par):
    """The whole per-tile verification as a pure array function: (22, NC)
    constants + (8, T) word arrays + (1, T) parity -> (1, T) int32 verdicts.
    The Pallas kernel wraps this with ref loads/stores; tests jit it directly
    on CPU to validate the math without the (slow) Pallas interpreter."""
    global _CST
    _CST = cst
    t = ax.shape[1]
    one = _bcol(_C_ONE, t)
    neg_a = (_words_to_limbs(ax), _words_to_limbs(ay), one,
             _words_to_limbs(at))
    s_rows = _word_rows(s)
    h_rows = _word_rows(h)

    # 16-entry table [i]B + [j](-A)
    b_pts = [
        tuple(_bcol(_C_BPTS + 4 * i + j, t) for j in range(4)) for i in range(4)
    ]
    b_cached = [
        tuple(_bcol(_C_BCACHED + 4 * i + j, t) for j in range(4)) for i in range(4)
    ]
    ca1 = to_cached(neg_a)
    a2 = pdouble(neg_a)
    a3 = add_cached(a2, ca1)
    a_pts = [None, neg_a, a2, a3]
    table = []
    for s2 in range(4):
        for h2 in range(4):
            if h2 == 0:
                table.append(b_cached[s2])
            elif s2 == 0:
                table.append(to_cached(a_pts[h2]))
            else:
                table.append(to_cached(add_cached(a_pts[h2], b_cached[s2])))

    p0 = tuple(_bcol(_C_IDENT + j, t) for j in range(4))

    def body(i, p):
        d = NDIGITS - 1 - i
        sd = _digit_at(s_rows, d)
        hd = _digit_at(h_rows, d)
        s0, s1 = sd & 1, sd >> 1
        h0, h1 = hd & 1, hd >> 1
        rows = [
            _sel2(h0, h1, table[4 * s2 + 0], table[4 * s2 + 1],
                  table[4 * s2 + 2], table[4 * s2 + 3])
            for s2 in range(4)
        ]
        entry = _sel2(s0, s1, rows[0], rows[1], rows[2], rows[3])
        return add_cached(pdouble(pdouble(p)), entry)

    rp = jax.lax.fori_loop(0, NDIGITS, body, p0)

    x, y, z, _ = rp
    zi = finv(z)
    xa = fcanon(fmul(x, zi))
    ya = fcanon(fmul(y, zi))
    y_r = fcanon(_words_to_limbs(yr))
    y_eq = jnp.all(ya == y_r, axis=0, keepdims=True)
    par_ok = (xa[0:1] & 1) == par
    return (y_eq & par_ok).astype(jnp.int32)


@partial(jax.jit, static_argnames=("interpret",))
def pallas_verify_kernel(a_x_w, a_y_w, a_t_w, s_w, h_w, yr_w, x_parity,
                         interpret: bool = False):
    """Drop-in for ed25519_batch.verify_kernel: same inputs, (B,) bool out.
    B must be a multiple of TILE (prepare_batch buckets guarantee it for
    min_bucket >= TILE). interpret=True runs the Pallas interpreter (any
    backend) — the CPU test path."""
    b = s_w.shape[1]
    assert b % TILE == 0, f"batch {b} not a multiple of {TILE}"
    grid = (b // TILE,)
    cst_spec = pl.BlockSpec((NLIMB, CONST_COLS.shape[1]), lambda i: (0, 0))
    word_spec = pl.BlockSpec((NWORDS, TILE), lambda i: (0, i))
    row_spec = pl.BlockSpec((1, TILE), lambda i: (0, i))
    out = pl.pallas_call(
        _verify_tile_kernel,
        grid=grid,
        in_specs=[cst_spec] + [word_spec] * 6 + [row_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.int32),
        interpret=interpret,
    )(
        jnp.asarray(CONST_COLS),
        a_x_w, a_y_w, a_t_w, s_w, h_w, yr_w,
        x_parity.reshape(1, -1).astype(jnp.int32),
    )
    return out.reshape(-1) != 0
