"""Pallas TPU kernel for batched Ed25519 verification.

Same math as ops/ed25519_batch.verify_kernel (radix-4 joint Straus over
GF(2^255-19) in 12-bit limbs), but compiled as ONE Mosaic kernel per batch
tile so the 127-iteration loop, its 16-entry table, and every field
intermediate stay in VMEM instead of round-tripping HBM between XLA fusions.

Layout (the perf-critical choice): a field element is a python list of
NLIMB arrays, each shaped (8, 128) — one full TPU vector register per limb
(sublanes x lanes = 1024 batch elements per tile). The first kernel kept
elements as (22, T=128) and every schoolbook product was a (1, 128) row op
using 1 of 8 sublanes; measured on v5e that left >2x on the floor. In this
layout every multiply/add/select is a whole-vreg op.

Field/curve constants are baked in as per-limb python-int immediates
(Mosaic folds scalar splats); there is no constants operand.

Falls back transparently: ops/kcache prefers this kernel on TPU when it
lowers, else the XLA kernel. CPU tests jit `verify_tile` directly (the
Pallas interpreter is far too slow for a 127-iteration loop).

Replaces the reference's serial verify loops: types/vote_set.go:189,
types/validator_set.go:609-627, state/validation.go:99.
"""
from __future__ import annotations

from functools import partial, reduce

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.ops import field
from tendermint_tpu.ops.ed25519_batch import NDIGITS, NWORDS
from tendermint_tpu.ops.limbs import LIMB_BITS, LIMB_MASK, NLIMB

TILE = 1024          # batch lanes per kernel instance: 8 sublanes x 128 lanes
SUB, LANE = 8, 128

FOLD = field.FOLD
P = field.P


def _limbs_of(v: int) -> list[int]:
    return [(v >> (LIMB_BITS * k)) & LIMB_MASK for k in range(NLIMB)]


BIAS_LIMBS = [int(x) for x in field.BIAS.reshape(-1)]
NEGP_LIMBS = _limbs_of((1 << (NLIMB * LIMB_BITS)) - P)
D2_LIMBS = _limbs_of(2 * em.D % P)


def _const_fe(v_limbs, like):
    """Per-limb scalar constants -> field element broadcast to like's shape."""
    return [jnp.full_like(like, c) for c in v_limbs]


# ------------------------------------------------------------------- field
# A field element is a list of NLIMB int32 arrays of identical shape
# (one vreg each in-kernel). All ops mirror ops/field.py bit-for-bit.


def _carry(c):
    """One carry pass with top fold (mirrors field.carry_pass)."""
    cc = [x >> LIMB_BITS for x in c]
    lo = [x & LIMB_MASK for x in c]
    return [lo[0] + cc[NLIMB - 1] * FOLD] + [
        lo[k] + cc[k - 1] for k in range(1, NLIMB)
    ]


def _mul_tail(c):
    """Reduce 44 product columns: two wide passes (column 43 exists to
    receive the carry out of column 42 — keeping 42 unmasked overflows int32
    at the FOLD multiply for class-R inputs), fold, four narrow passes.
    Mirrors field.mul's bound contract exactly."""
    n2 = 2 * NLIMB
    for _ in range(2):
        cc = [x >> LIMB_BITS for x in c]
        lo = [x & LIMB_MASK for x in c]
        c = [lo[0]] + [lo[k] + cc[k - 1] for k in range(1, n2 - 1)] + [
            lo[n2 - 1] + cc[n2 - 2] + (cc[n2 - 1] << LIMB_BITS)
        ]
    d = [c[k] + FOLD * c[NLIMB + k] for k in range(NLIMB)]
    for _ in range(4):
        d = _carry(d)
    return d


def fmul(a, b):
    """Schoolbook 22x22 -> 43 columns + the _mul_tail reduction."""
    n2 = 2 * NLIMB
    c = [None] * n2
    for i in range(NLIMB):
        ai = a[i]
        for j in range(NLIMB):
            k = i + j
            p = ai * b[j]
            c[k] = p if c[k] is None else c[k] + p
    c[n2 - 1] = jnp.zeros_like(a[0])
    return _mul_tail(c)


def fsq(a):
    """Squaring: cross products counted once then doubled (253 multiplies
    vs fmul's 484). Column bound check vs class R (limb0 <= ~24k, others
    <= ~4120): 2*cross + diag <= 2*(a0*ak + 9*4120^2) + 4120^2 ~= 5.3e8,
    column 0 = a0^2 <= 5.6e8 — all under 2^31 like fmul's columns."""
    n2 = 2 * NLIMB
    c = [None] * n2
    for i in range(NLIMB):
        ai = a[i]
        for j in range(i + 1, NLIMB):
            k = i + j
            p = ai * a[j]
            c[k] = p if c[k] is None else c[k] + p
    for k in range(n2):
        if c[k] is not None:
            c[k] = c[k] + c[k]
    for i in range(NLIMB):
        k = 2 * i
        d = a[i] * a[i]
        c[k] = d if c[k] is None else c[k] + d
    c[n2 - 1] = jnp.zeros_like(a[0])
    return _mul_tail(c)


def fadd(a, b):
    return _carry([x + y for x, y in zip(a, b)])


def fsub(a, b):
    return _carry([x + (bk - y) for x, y, bk in zip(a, b, BIAS_LIMBS)])


def fsel(cond, a, b):
    """cond: boolean array of the limb shape."""
    return [jnp.where(cond, x, y) for x, y in zip(a, b)]


def _pow2k(a, k):
    return list(
        jax.lax.fori_loop(0, k, lambda _, x: tuple(fsq(list(x))), tuple(a))
    )


def finv(a):
    """a^(p-2), standard 25519 chain (mirrors field.inv)."""
    t0 = fsq(a)
    t1 = fsq(fsq(t0))
    t1 = fmul(a, t1)
    t0 = fmul(t0, t1)
    t2 = fsq(t0)
    t1 = fmul(t1, t2)
    t2 = _pow2k(t1, 5); t1 = fmul(t2, t1)
    t2 = _pow2k(t1, 10); t2 = fmul(t2, t1)
    t3 = _pow2k(t2, 20); t2 = fmul(t3, t2)
    t2 = _pow2k(t2, 10); t1 = fmul(t2, t1)
    t2 = _pow2k(t1, 50); t2 = fmul(t2, t1)
    t3 = _pow2k(t2, 100); t2 = fmul(t3, t2)
    t2 = _pow2k(t2, 50); t1 = fmul(t2, t1)
    t1 = _pow2k(t1, 5)
    return fmul(t1, t0)


def _seq_carry(a, topfold: bool):
    a = list(a)
    for k in range(NLIMB - 1):
        cc = a[k] >> LIMB_BITS
        a[k] = a[k] & LIMB_MASK
        a[k + 1] = a[k + 1] + cc
    if topfold:
        cc = a[NLIMB - 1] >> LIMB_BITS
        a[NLIMB - 1] = a[NLIMB - 1] & LIMB_MASK
        a[0] = a[0] + cc * FOLD
    return a


def fcanon(a):
    """Exact canonical digits of (a mod p) (mirrors field.canonicalize)."""
    a = _carry(_carry(a))
    a = _seq_carry(a, True)
    a = _seq_carry(a, True)
    for _ in range(2):
        hi = a[NLIMB - 1] >> 3
        a = list(a)
        a[NLIMB - 1] = a[NLIMB - 1] & 0x7
        a[0] = a[0] + hi * 19
        a = _seq_carry(a, False)
    t = [x + nk for x, nk in zip(a, NEGP_LIMBS)]
    for k in range(NLIMB - 1):
        cc = t[k] >> LIMB_BITS
        t[k] = t[k] & LIMB_MASK
        t[k + 1] = t[k + 1] + cc
    overflow = t[NLIMB - 1] >> LIMB_BITS
    t[NLIMB - 1] = t[NLIMB - 1] & LIMB_MASK
    return fsel(overflow > 0, t, a)


# ------------------------------------------------------------------- curve
# Points: 4-tuples (X, Y, Z, T) of field elements (RFC 8032 §5.1.4 complete
# a=-1 twisted-Edwards formulas); cached addends: (Y-X, Y+X, 2d*T, 2Z).


def to_cached(p):
    x, y, z, t = p
    d2 = _const_fe(D2_LIMBS, t[0])
    return (fsub(y, x), fadd(y, x), fmul(t, d2), fadd(z, z))


def add_cached(p, q, need_t: bool = True):
    """P + Q with Q cached. The Straus loop's adds pass need_t=False: the
    result's T is consumed by nothing (doubles don't read T), saving the
    e*h multiply."""
    x, y, z, t = p
    ymx, ypx, t2d, z2 = q
    a = fmul(fsub(y, x), ymx)
    b = fmul(fadd(y, x), ypx)
    c = fmul(t, t2d)
    d = fmul(z, z2)
    e = fsub(b, a)
    f = fsub(d, c)
    g = fadd(d, c)
    h = fadd(b, a)
    t_out = fmul(e, h) if need_t else None
    return (fmul(e, f), fmul(g, h), fmul(f, g), t_out)


def pdouble(p, need_t: bool = True):
    """Doubling never reads P's T; the first of two chained doubles also
    skips producing it (only the cached-add consumes T)."""
    x, y, z, _ = p
    a = fsq(x)
    b = fsq(y)
    zz = fsq(z)
    c = fadd(zz, zz)
    h = fadd(a, b)
    e = fsub(h, fsq(fadd(x, y)))
    g = fsub(a, b)
    f = fadd(c, g)
    t_out = fmul(e, h) if need_t else None
    return (fmul(e, f), fmul(g, h), fmul(f, g), t_out)


def csel(cond, a, b):
    return tuple(fsel(cond, x, y) for x, y in zip(a, b))


def _sel2(b0, b1, e0, e1, e2, e3):
    lo = csel(b0, e1, e0)
    hi = csel(b0, e3, e2)
    return csel(b1, hi, lo)


# -------------------------------------------- compile-time [i]B constants


def _b_mult_limbs():
    """[0..3]B as per-limb python ints: (points affine-extended, cached)."""
    pts, cached = [], []
    bx, by = em.BASE_X, em.BASE_Y
    d2 = 2 * em.D % P
    cur = None
    raw = [(0, 1, 1, 0)]
    for _ in range(3):
        nxt = (bx, by, 1, bx * by % P)
        cur = nxt if cur is None else em.point_add(cur, nxt)
        raw.append(cur)
    for (x, y, z, t) in raw:
        zi = pow(z, P - 2, P)
        xa, ya = x * zi % P, y * zi % P
        ta = xa * ya % P
        pts.append(tuple(_limbs_of(v) for v in (xa, ya, 1, ta)))
        cached.append(
            tuple(
                _limbs_of(v)
                for v in ((ya - xa) % P, (ya + xa) % P, ta * d2 % P, 2)
            )
        )
    return pts, cached


_B_PTS_LIMBS, _B_CACHED_LIMBS = _b_mult_limbs()
IDENT_LIMBS = tuple(_limbs_of(v) for v in (0, 1, 1, 0))


# ------------------------------------------------------------- the kernel


def _digit_at(w_rows, d):
    """2-bit digit d (traced scalar) of scalars packed in 8 little-endian
    int32 word arrays. Computed arithmetically — Mosaic cannot lower a
    dynamic_slice over a (127, ...) digit array inside the loop. All int32:
    the arithmetic shift's sign extension only reaches bits >= 2 even at
    the maximum shift of 30, and `& 3` discards them."""
    wi = d // 16
    sh = 2 * (d % 16)
    acc = w_rows[0]
    for k in range(1, NWORDS):
        acc = jnp.where(wi == k, w_rows[k], acc)
    return (acc >> sh) & 3


def _words_to_limbs(w_rows):
    """8 int32 word arrays -> 22-limb field element. The arithmetic right
    shift sign-extends, so where a limb straddles a word boundary the low
    word's field is masked to its true width before OR-ing the high word."""
    limbs = []
    for k in range(NLIMB):
        lo_bit = LIMB_BITS * k
        a, s = lo_bit // 32, lo_bit % 32
        v = w_rows[a] >> s
        if s > 32 - LIMB_BITS and a + 1 < NWORDS:
            v = (v & ((1 << (32 - s)) - 1)) | (w_rows[a + 1] << (32 - s))
        limbs.append(v & LIMB_MASK)
    return limbs


def verify_tile(ax, ay, at, s, h, yr, par):
    """The whole per-tile verification as a pure array function.

    ax/ay/at/s/h/yr: (NWORDS, *S) int32 little-endian words (-A affine
    extended coords with Z=1, scalars S and h, R's y); par: (*S,) int32 sign
    bits. *S is any array shape — (8, 128) in-kernel, (1, T) in CPU tests.
    Returns (*S,) int32 verdicts. Mirrors ed25519_batch.verify_kernel.
    """
    ax_r = [ax[i] for i in range(NWORDS)]
    ay_r = [ay[i] for i in range(NWORDS)]
    at_r = [at[i] for i in range(NWORDS)]
    s_rows = [s[i] for i in range(NWORDS)]
    h_rows = [h[i] for i in range(NWORDS)]
    like = ax_r[0]

    one = _const_fe(_limbs_of(1), like)
    neg_a = (_words_to_limbs(ax_r), _words_to_limbs(ay_r), one,
             _words_to_limbs(at_r))

    # 16-entry table [s2]B + [h2](-A), cached form
    b_cached = [
        tuple(_const_fe(l, like) for l in c) for c in _B_CACHED_LIMBS
    ]
    ca1 = to_cached(neg_a)
    a2 = pdouble(neg_a)
    a3 = add_cached(a2, ca1)
    a_pts = [None, neg_a, a2, a3]
    table = []
    for s2 in range(4):
        for h2 in range(4):
            if h2 == 0:
                table.append(b_cached[s2])
            elif s2 == 0:
                table.append(to_cached(a_pts[h2]))
            else:
                table.append(to_cached(add_cached(a_pts[h2], b_cached[s2])))

    # loop carry is (X, Y, Z) only: T of the running point is produced by
    # the second double and consumed inside the same iteration's add
    p0 = tuple(tuple(_const_fe(l, like)) for l in IDENT_LIMBS[:3])

    def body(i, p):
        d = NDIGITS - 1 - i
        sd = _digit_at(s_rows, d)
        hd = _digit_at(h_rows, d)
        s0, s1 = (sd & 1) != 0, (sd >> 1) != 0
        h0, h1 = (hd & 1) != 0, (hd >> 1) != 0
        rows = [
            _sel2(h0, h1, table[4 * s2 + 0], table[4 * s2 + 1],
                  table[4 * s2 + 2], table[4 * s2 + 3])
            for s2 in range(4)
        ]
        entry = _sel2(s0, s1, rows[0], rows[1], rows[2], rows[3])
        x, y, z = p
        d1 = pdouble((list(x), list(y), list(z), None), need_t=False)
        d2 = pdouble(d1, need_t=True)
        r = add_cached(d2, entry, need_t=False)
        return tuple(tuple(e) for e in r[:3])

    rp = jax.lax.fori_loop(0, NDIGITS, body, p0)

    x, y, z = (list(e) for e in rp)
    zi = finv(z)
    xa = fcanon(fmul(x, zi))
    ya = fcanon(fmul(y, zi))
    y_r = fcanon(_words_to_limbs([yr[i] for i in range(NWORDS)]))
    y_eq = reduce(
        jnp.logical_and, [p == q for p, q in zip(ya, y_r)]
    )
    par_ok = (xa[0] & 1) == par
    return (y_eq & par_ok).astype(jnp.int32)


def _verify_tile_kernel(keys_ref, sigs_ref, out_ref):
    keys = keys_ref[:]  # (KEY_ROWS, SUB, LANE)
    sigs = sigs_ref[:]  # (SIG_ROWS, SUB, LANE)

    out_ref[:] = verify_tile(
        keys[0:NWORDS], keys[NWORDS:2 * NWORDS], keys[2 * NWORDS:3 * NWORDS],
        sigs[0:NWORDS], sigs[NWORDS:2 * NWORDS], sigs[2 * NWORDS:3 * NWORDS],
        sigs[3 * NWORDS],
    )


@partial(jax.jit, static_argnames=("interpret",))
def pallas_verify_kernel(keys, sigs, interpret: bool = False):
    """Drop-in for ed25519_batch.verify_kernel: keys (24, B) + sigs (25, B)
    wire blocks in, (B,) bool out. B is padded on device to a TILE multiple;
    padded lanes compute garbage verdicts that are sliced off (the formulas
    are complete, so junk inputs cannot fault)."""
    from tendermint_tpu.ops.ed25519_batch import KEY_ROWS, SIG_ROWS

    b = sigs.shape[1]
    padded = -(-b // TILE) * TILE
    pad = padded - b
    if pad:
        keys = jnp.pad(keys, ((0, 0), (0, pad)))
        sigs = jnp.pad(sigs, ((0, 0), (0, pad)))
    # (R, B) -> (R, rows, 128): row-major, so lanes stay put
    keys = keys.reshape(KEY_ROWS, padded // LANE, LANE)
    sigs = sigs.reshape(SIG_ROWS, padded // LANE, LANE)

    grid = (padded // TILE,)
    out = pl.pallas_call(
        _verify_tile_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((KEY_ROWS, SUB, LANE), lambda i: (0, i, 0)),
            pl.BlockSpec((SIG_ROWS, SUB, LANE), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((SUB, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded // LANE, LANE), jnp.int32),
        interpret=interpret,
    )(keys, sigs)
    return out.reshape(-1)[:b] != 0
