"""Batched edwards25519 point arithmetic on limb arrays.

Points are batched in extended homogeneous coordinates (X, Y, Z, T) with
x = X/Z, y = Y/Z, T = XY/Z — each coordinate a (22, B) limb array (see
ops/field.py). Formulas are the complete a=-1 twisted-Edwards ones from
RFC 8032 §5.1.4, valid for all inputs including the identity, so the
scalar-multiplication loop needs no branches — the constant-time pattern
that XLA compiles well.

Table entries for the Straus/Shamir double-scalar multiplication are kept in
"cached" form (Y-X, Y+X, 2d*T, 2Z), which turns each addition into exactly
8 field multiplies.
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto.ed25519_math import BASE_X, BASE_Y, D
from tendermint_tpu.ops import field
from tendermint_tpu.ops.limbs import NLIMB, int_to_limb_column

D2 = (2 * D) % field.P


class Point(NamedTuple):
    """Extended coordinates, each (22, B)."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


class CachedPoint(NamedTuple):
    """Precomputed addend: (Y-X, Y+X, 2d*T, 2Z), each (22, B) or (22, 1)."""

    ymx: jnp.ndarray
    ypx: jnp.ndarray
    t2d: jnp.ndarray
    z2: jnp.ndarray


# Module-level constants as (22, 1) columns (broadcast over the batch).
_ONE = int_to_limb_column(1)
_ZERO = np.zeros((NLIMB, 1), dtype=np.int32)
_TWO = int_to_limb_column(2)
_D2 = int_to_limb_column(D2)
_BASE_T = BASE_X * BASE_Y % field.P

IDENTITY = Point(_ZERO, _ONE, _ONE, _ZERO)
IDENTITY_CACHED = CachedPoint(_ONE, _ONE, _ZERO, _TWO)
BASE = Point(
    int_to_limb_column(BASE_X),
    int_to_limb_column(BASE_Y),
    _ONE,
    int_to_limb_column(_BASE_T),
)
BASE_CACHED = CachedPoint(
    int_to_limb_column((BASE_Y - BASE_X) % field.P),
    int_to_limb_column((BASE_Y + BASE_X) % field.P),
    int_to_limb_column(_BASE_T * D2 % field.P),
    _TWO,
)


def to_cached(p: Point) -> CachedPoint:
    return CachedPoint(
        field.sub(p.y, p.x),
        field.add(p.y, p.x),
        field.mul(p.t, jnp.broadcast_to(jnp.asarray(_D2), p.t.shape)),
        field.add(p.z, p.z),
    )


def add_cached(p: Point, q: CachedPoint) -> Point:
    """Complete addition P + Q with Q precomputed (RFC 8032 §5.1.4): 8 muls."""
    a = field.mul(field.sub(p.y, p.x), q.ymx)
    b = field.mul(field.add(p.y, p.x), q.ypx)
    c = field.mul(p.t, q.t2d)
    d = field.mul(p.z, q.z2)
    e = field.sub(b, a)
    f = field.sub(d, c)
    g = field.add(d, c)
    h = field.add(b, a)
    return Point(field.mul(e, f), field.mul(g, h), field.mul(f, g), field.mul(e, h))


def double(p: Point) -> Point:
    """Dedicated doubling (RFC 8032 §5.1.4): 4 squares + 4 muls."""
    a = field.square(p.x)
    b = field.square(p.y)
    zz = field.square(p.z)
    c = field.add(zz, zz)
    h = field.add(a, b)
    e = field.sub(h, field.square(field.add(p.x, p.y)))
    g = field.sub(a, b)
    f = field.add(c, g)
    return Point(field.mul(e, f), field.mul(g, h), field.mul(f, g), field.mul(e, h))


def select_cached(cond, a: CachedPoint, b: CachedPoint) -> CachedPoint:
    """Per-element select between cached points; cond (B,)."""
    return CachedPoint(
        field.select(cond, a.ymx, b.ymx),
        field.select(cond, a.ypx, b.ypx),
        field.select(cond, a.t2d, b.t2d),
        field.select(cond, a.z2, b.z2),
    )


def to_affine(p: Point) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(x, y) canonical digits — one batched inversion."""
    zinv = field.inv(p.z)
    x = field.canonicalize(field.mul(p.x, zinv))
    y = field.canonicalize(field.mul(p.y, zinv))
    return x, y
