"""The batched Ed25519 verification kernel + host-side batch builder.

This is the TPU replacement for the reference's strictly serial signature
loops (types/vote_set.go:189, types/validator_set.go:609-627,
state/validation.go:99, lite/dynamic_verifier.go): one device launch
verifies a whole batch.

Split of work:
- Host (cheap, per signature): SHA-512(R||A||M) and reduction mod L, scalar
  range check S < L, pubkey decompression to extended coordinates (cached
  per pubkey — validator keys are stable across heights, so steady-state
  commits pay zero decompression), R parsed with a strict y_R < p check.
- Wire format host->device: ONE (49, B) int32 array per batch — six (8, B)
  little-endian 32-bit word planes (-A.x, -A.y, -A.t, S, h, y_R) stacked
  with the parity row (~200 B/signature total). A single array means a
  single host->device transfer per batch: on a tunneled/remote device every
  separate `device_put` pays a full RPC round trip (measured ~60 ms on the
  axon tunnel vs ~4 ms for one 2.4 MB copy), so the 7-array round-1 format
  spent 6x more time placing arguments than moving bytes. Limb expansion
  (12-bit limbs for the field core) and 2-bit digit extraction happen ON
  DEVICE — bandwidth, not FLOPs, is the scarce resource on that path
  (shipping pre-expanded bit arrays was 14x the bytes).
- Device (the FLOPs): radix-4 joint Straus/Shamir double-scalar
  multiplication R' = [S]B + [h](-A): 127 iterations of (2 doubles + 1
  complete cached add), with a 16-entry table [i]B + [j](-A) (i,j in 0..3)
  built once per launch (~1% of the loop cost) and selected per lane with a
  4-level binary select tree. Then one batched field inversion, canonical
  encode, compare with R. ~25% fewer field multiplies than the bit-serial
  form (253 D + 253 A -> 254 D + 127 A).

The verification equation is the strict cofactorless one used by Go's
x/crypto/ed25519 (the reference's verifier): encode([S]B + [h](-A)) == R,
with S < L enforced and non-canonical R encodings rejected.
"""
from __future__ import annotations

import hashlib
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.device import profiler as _profiler
from tendermint_tpu.device import scheduler as _dsched
from tendermint_tpu.device.priorities import current_priority as _current_priority
from tendermint_tpu.libs import trace as _trace
from tendermint_tpu.ops import curve, field
from tendermint_tpu.ops.limbs import LIMB_BITS, NLIMB

NBITS = 253   # scalars are < L < 2^253
NDIGITS = 127  # 2-bit digits (bit 253 is always 0)
NWORDS = 8
# Packed wire-format rows: six 8-word planes then the parity row. The
# first KEY_ROWS rows are the pubkey block (-A coords) — a function of the
# validator set only, identical across commits for a stable valset — and
# the rest is the per-commit signature block, so `split()` yields the two
# as zero-copy views and the key block can stay device-resident between
# commits (verify_batch keeps a small content-addressed device cache;
# steady-state commits ship 100 B/sig instead of 200).
ROW_AX, ROW_AY, ROW_AT, ROW_S, ROW_H, ROW_YR = (8 * k for k in range(6))
ROW_PARITY = 48
ROWS = 49
KEY_ROWS = 24   # ax, ay, at planes
SIG_ROWS = 25   # s, h, yr planes + parity row


# ---------------------------------------------------------------- device side


def _extract_chunks(w, width: int, count: int):
    """(8, B) uint32 words -> (count, B) int32 little-endian `width`-bit
    chunks (static shifts; chunks may straddle 32-bit word boundaries).
    The one extractor behind limb (12-bit), radix-4 digit (2-bit) and
    radix-8 digit (3-bit) decompositions."""
    w = w.astype(jnp.uint32)
    mask = (1 << width) - 1
    out = []
    for k in range(count):
        p = width * k
        a, s = p // 32, p % 32
        v = w[a] >> s
        if s > 32 - width and a + 1 < NWORDS:
            v = v | (w[a + 1] << (32 - s))
        out.append((v & mask).astype(jnp.int32))
    return jnp.stack(out)


def words_to_limbs(w):
    """(8, B) uint32 words -> (22, B) int32 12-bit limbs."""
    return _extract_chunks(w, LIMB_BITS, NLIMB)


def words_to_digits(w):
    """(8, B) uint32 words -> (127, B) int32 2-bit digits, little-endian."""
    return _extract_chunks(w, 2, NDIGITS)


def _sel2(bit0, bit1, e0, e1, e2, e3) -> curve.CachedPoint:
    """Select e[bit1*2 + bit0] with 3 cached-point selects."""
    lo = curve.select_cached(bit0, e1, e0)
    hi = curve.select_cached(bit0, e3, e2)
    return curve.select_cached(bit1, hi, lo)


def _build_table(neg_a: curve.Point, b: int) -> list[curve.CachedPoint]:
    """table[s2*4 + h2] = [s2]B + [h2](-A) in cached form, s2,h2 in 0..3."""

    def bcast(c):
        return jnp.broadcast_to(jnp.asarray(c), (NLIMB, b)).astype(jnp.int32)

    # B multiples as broadcast constants (points + cached forms)
    b_pts = [curve.Point(*[bcast(c) for c in p]) for p in _B_MULT_POINTS]
    b_cached = [curve.CachedPoint(*[bcast(c) for c in p]) for p in _B_MULT_CACHED]

    # A multiples per lane: -A, -2A, -3A
    ca1 = curve.to_cached(neg_a)
    a2 = curve.double(neg_a)
    a3 = curve.add_cached(a2, ca1)
    a_pts = [None, neg_a, a2, a3]

    table: list[curve.CachedPoint] = []
    for s2 in range(4):
        for h2 in range(4):
            if h2 == 0:
                table.append(b_cached[s2])  # [s2]B (+ identity cached at s2=0)
            elif s2 == 0:
                table.append(curve.to_cached(a_pts[h2]))
            else:
                table.append(curve.to_cached(curve.add_cached(a_pts[h2], b_cached[s2])))
    return table


def _straus_loop(neg_a: curve.Point, s_digits, h_digits) -> curve.Point:
    """[S]B + [h](-A), radix-4 joint digits MSB-first."""
    b = s_digits.shape[1]
    table = _build_table(neg_a, b)

    def bcast(c):
        return jnp.broadcast_to(jnp.asarray(c), (NLIMB, b)).astype(jnp.int32)

    p0 = curve.Point(*[bcast(c) for c in curve.IDENTITY])
    # stack the table into 4 arrays of shape (16, 22, B) for traced select
    # (kept as a python list of CachedPoints; select tree below indexes it)

    def body(i, p):
        d = NDIGITS - 1 - i
        sd = jax.lax.dynamic_index_in_dim(s_digits, d, 0, keepdims=False)
        hd = jax.lax.dynamic_index_in_dim(h_digits, d, 0, keepdims=False)
        s0, s1 = sd & 1, sd >> 1
        h0, h1 = hd & 1, hd >> 1
        rows = [
            _sel2(h0, h1, table[4 * s2 + 0], table[4 * s2 + 1],
                  table[4 * s2 + 2], table[4 * s2 + 3])
            for s2 in range(4)
        ]
        entry = _sel2(s0, s1, rows[0], rows[1], rows[2], rows[3])
        p = curve.double(curve.double(p))
        return curve.add_cached(p, entry)

    return jax.lax.fori_loop(0, NDIGITS, body, p0)


# ------------------------------------------------ radix-8 variant (A/B)
# Measures the larger-radix Straus loop suggested in review: 85 3-bit
# digits of (3 doubles + 1 add) over a 64-entry table vs 127 2-bit
# digits of (2 doubles + 1 add) over 16. Counting field ops predicts
# ~parity, not a win: the joint table depends on A, so it is built PER
# LANE — the 64-entry build costs ~52 adds vs ~10 for 16 entries, which
# exactly cancels the loop's 42 saved adds (doubles stay ~255 either
# way), while the select tree grows 2.8x (63 vs 15 cached-point selects
# per iteration). The variant exists so benchmarks/kernel_compare.py can
# RECORD that answer on real hardware instead of arguing it; production
# stays radix-4 unless the measurement disagrees with the count.

NDIGITS8 = 85  # ceil(255 / 3); scalars are < L < 2^253


def words_to_digits3(w):
    """(8, B) uint32 words -> (85, B) int32 3-bit digits, little-endian
    (3-bit chunks straddle 32-bit word boundaries)."""
    return _extract_chunks(w, 3, NDIGITS8)


def _sel3(b0, b1, b2, entries) -> curve.CachedPoint:
    """Select entries[b2*4 + b1*2 + b0] with 7 cached-point selects."""
    q = [curve.select_cached(b0, entries[2 * k + 1], entries[2 * k])
         for k in range(4)]
    lo = curve.select_cached(b1, q[1], q[0])
    hi = curve.select_cached(b1, q[3], q[2])
    return curve.select_cached(b2, hi, lo)


def _build_table8(neg_a: curve.Point, b: int) -> list[curve.CachedPoint]:
    """table[s3*8 + h3] = [s3]B + [h3](-A), s3,h3 in 0..7."""

    def bcast(c):
        return jnp.broadcast_to(jnp.asarray(c), (NLIMB, b)).astype(jnp.int32)

    b_cached = [curve.CachedPoint(*[bcast(c) for c in p]) for p in _B8_CACHED]
    # A multiples 1..7: chains of doubles + cached adds
    ca1 = curve.to_cached(neg_a)
    a2 = curve.double(neg_a)
    a3 = curve.add_cached(a2, ca1)
    a4 = curve.double(a2)
    a5 = curve.add_cached(a4, ca1)
    a6 = curve.double(a3)
    a7 = curve.add_cached(a6, ca1)
    a_pts = [None, neg_a, a2, a3, a4, a5, a6, a7]

    table: list[curve.CachedPoint] = []
    for s3 in range(8):
        for h3 in range(8):
            if h3 == 0:
                table.append(b_cached[s3])
            elif s3 == 0:
                table.append(curve.to_cached(a_pts[h3]))
            else:
                table.append(
                    curve.to_cached(curve.add_cached(a_pts[h3], b_cached[s3]))
                )
    return table


def _straus_loop8(neg_a: curve.Point, s_digits, h_digits) -> curve.Point:
    """[S]B + [h](-A), radix-8 joint digits MSB-first."""
    b = s_digits.shape[1]
    table = _build_table8(neg_a, b)

    def bcast(c):
        return jnp.broadcast_to(jnp.asarray(c), (NLIMB, b)).astype(jnp.int32)

    p0 = curve.Point(*[bcast(c) for c in curve.IDENTITY])

    def body(i, p):
        d = NDIGITS8 - 1 - i
        sd = jax.lax.dynamic_index_in_dim(s_digits, d, 0, keepdims=False)
        hd = jax.lax.dynamic_index_in_dim(h_digits, d, 0, keepdims=False)
        s0, s1, s2 = sd & 1, (sd >> 1) & 1, sd >> 2
        h0, h1, h2 = hd & 1, (hd >> 1) & 1, hd >> 2
        rows = [
            _sel3(h0, h1, h2, table[8 * s3:8 * s3 + 8]) for s3 in range(8)
        ]
        entry = _sel3(s0, s1, s2, rows)
        p = curve.double(curve.double(curve.double(p)))
        return curve.add_cached(p, entry)

    return jax.lax.fori_loop(0, NDIGITS8, body, p0)


def verify_core_r8(a_x_w, a_y_w, a_t_w, s_w, h_w, yr_w, x_parity):
    """Radix-8 variant of verify_core — identical contract."""
    b = s_w.shape[1]
    neg_a = curve.Point(
        words_to_limbs(a_x_w),
        words_to_limbs(a_y_w),
        jnp.broadcast_to(jnp.asarray(curve._ONE), (NLIMB, b)).astype(jnp.int32),
        words_to_limbs(a_t_w),
    )
    rp = _straus_loop8(neg_a, words_to_digits3(s_w), words_to_digits3(h_w))
    x, y = curve.to_affine(rp)
    y_r = field.canonicalize(words_to_limbs(yr_w))
    return field.eq(y, y_r) & (field.is_odd(x) == x_parity)


@partial(jax.jit, static_argnames=())
def verify_kernel_r8(keys, sigs):
    """Radix-8 batched verify, split wire format (A/B experiments only)."""
    return verify_core_r8(*unpack_pair(keys, sigs))


def unpack(packed):
    """(49, B) packed wire array -> the seven logical views (static slices,
    free under jit). Rows: -A.x/-A.y/-A.t/S/h/y_R word planes + parity."""
    return (
        packed[ROW_AX:ROW_AX + NWORDS],
        packed[ROW_AY:ROW_AY + NWORDS],
        packed[ROW_AT:ROW_AT + NWORDS],
        packed[ROW_S:ROW_S + NWORDS],
        packed[ROW_H:ROW_H + NWORDS],
        packed[ROW_YR:ROW_YR + NWORDS],
        packed[ROW_PARITY],
    )


def split(packed):
    """(49, B) packed -> (keys (24, B), sigs (25, B)) zero-copy row views."""
    return packed[:KEY_ROWS], packed[KEY_ROWS:]


def unpack_pair(keys, sigs):
    """Split wire blocks -> the seven logical views (static slices)."""
    return (
        keys[0:NWORDS],
        keys[NWORDS:2 * NWORDS],
        keys[2 * NWORDS:3 * NWORDS],
        sigs[0:NWORDS],
        sigs[NWORDS:2 * NWORDS],
        sigs[2 * NWORDS:3 * NWORDS],
        sigs[3 * NWORDS],
    )


def verify_core(a_x_w, a_y_w, a_t_w, s_w, h_w, yr_w, x_parity):
    """Batched verify core (un-jitted; see verify_kernel for the wire entry).

    a_{x,y,t}_w: (8, B) int32 words of -A's affine extended coords (Z=1).
    s_w, h_w:    (8, B) int32 words of the scalars S and h (each < L).
    yr_w:        (8, B) int32 words of R's y coordinate (canonical, < p).
    x_parity:    (B,) int32 — R's sign bit.
    Returns (B,) bool.
    """
    b = s_w.shape[1]
    neg_a = curve.Point(
        words_to_limbs(a_x_w),
        words_to_limbs(a_y_w),
        jnp.broadcast_to(jnp.asarray(curve._ONE), (NLIMB, b)).astype(jnp.int32),
        words_to_limbs(a_t_w),
    )
    rp = _straus_loop(neg_a, words_to_digits(s_w), words_to_digits(h_w))
    x, y = curve.to_affine(rp)
    y_r = field.canonicalize(words_to_limbs(yr_w))
    return field.eq(y, y_r) & (field.is_odd(x) == x_parity)


@partial(jax.jit, static_argnames=())
def verify_kernel(keys, sigs):
    """Batched verify, split wire format: keys (24, B) + sigs (25, B) int32
    in, (B,) bool out. Two arguments so the valset-dependent key block can
    be passed device-resident while only the sig block transfers."""
    return verify_core(*unpack_pair(keys, sigs))


# ------------------------------------------------- module constants ([i]B)


def _b_mult_consts(count: int = 4):
    """Limb columns for [0..count-1]B as points + cached forms."""
    pts, cached = [], []
    ident = (0, 1, 1, 0)
    bx, by = em.BASE_X, em.BASE_Y
    P = em.P
    D2 = 2 * em.D % P

    def to_col(v):
        from tendermint_tpu.ops.limbs import int_to_limb_column

        return int_to_limb_column(v % P)

    cur = None
    raw = [ident]
    for _ in range(count - 1):
        if cur is None:
            cur = (bx, by, 1, bx * by % P)
        else:
            cur = em.point_add(cur, (bx, by, 1, bx * by % P))
        raw.append(cur)
    for (x, y, z, t) in raw:
        zi = pow(z, P - 2, P)
        xa, ya = x * zi % P, y * zi % P
        ta = xa * ya % P
        pts.append(tuple(to_col(v) for v in (xa, ya, 1, ta)))
        cached.append(
            tuple(
                to_col(v)
                for v in ((ya - xa) % P, (ya + xa) % P, ta * D2 % P, 2)
            )
        )
    return pts, cached


# one pass builds [0..7]B; the radix-4 kernel uses the first 4 entries,
# the radix-8 A/B variant the full cached list
_B8_POINTS, _B8_CACHED = _b_mult_consts(8)
_B_MULT_POINTS, _B_MULT_CACHED = _B8_POINTS[:4], _B8_CACHED[:4]


# ---------------------------------------------------------------- host side


class _PubkeyCache:
    """pubkey bytes -> np (3, 8) uint32 words of -A (x, y, t), LRU-bounded."""

    def __init__(self, maxsize: int = 65536) -> None:
        self._d: dict[bytes, np.ndarray | None] = {}
        self._maxsize = maxsize

    def get(self, pub: bytes) -> np.ndarray | None:
        if pub in self._d:
            return self._d[pub]
        pt = em.decompress(pub)
        if pt is None:
            entry = None
        else:
            nx, ny, _, nt = em.point_neg(pt)
            buf = b"".join(v.to_bytes(32, "little") for v in (nx, ny, nt))
            entry = np.frombuffer(buf, dtype=np.uint32).reshape(3, NWORDS).copy()
        if len(self._d) >= self._maxsize:
            self._d.pop(next(iter(self._d)))
        self._d[pub] = entry
        return entry


_cache = _PubkeyCache()


def _pad_to_bucket(n: int, min_bucket: int = 128) -> int:
    """Bucket batch sizes to bound jit recompilations while capping padding
    waste: powers of two up to 4096, then multiples of 4096 (batch sizes
    that are small-multiples of large powers of two tile better on the TPU
    vector unit than other composites — measured: 12288 beats 10240), then
    multiples of 16384 above 65536 (coarser steps: padding compute is
    cheap next to the per-launch dispatch floor, and fewer buckets bound
    the compile-variant count). Chunking at kcache.MAX_BUCKET caps it."""
    b = min_bucket
    while b < n and b < 4096:
        b *= 2
    if n <= b:
        return b
    if n <= 65536:
        return -(-n // 4096) * 4096
    return -(-n // 16384) * 16384


def _pack_inputs(a_words, s_words, h_words, yr_words, parity, n, min_bucket):
    """(n, …) u32 arrays -> padded (49, B) int32 packed wire array."""
    padded = _pad_to_bucket(n, min_bucket)
    packed = np.zeros((ROWS, padded), dtype=np.int32)

    def put(row, a):  # (n, 8) words -> rows [row, row+8)
        packed[row:row + NWORDS, :n] = a.T.view(np.int32)

    put(ROW_AX, a_words[:, 0])
    put(ROW_AY, a_words[:, 1])
    put(ROW_AT, a_words[:, 2])
    put(ROW_S, s_words)
    put(ROW_H, h_words)
    put(ROW_YR, yr_words)
    packed[ROW_PARITY, :n] = parity
    return packed


def prepare_batch(pubs, msgs, sigs, min_bucket: int = 128):
    """Host-side batch build. Returns (packed (49, B) array | None, valid_mask).

    valid_mask marks signatures that failed structural checks (bad lengths,
    undecompressable A, S >= L, non-canonical R) — already final False.

    Fast path: native tm_ed25519_prepare_batch (threads + cached
    decompression, ~1us/sig); fallback: the pure-Python loop below.
    """
    n = len(pubs)
    from tendermint_tpu.crypto import native as _native

    prepped = _native.ed25519_prepare_device_inputs(
        pubs, msgs, sigs, _pad_to_bucket(n, min_bucket)
    )
    if prepped is not None:
        packed, mask = prepped
        if not mask.any():
            return None, mask
        return packed, mask
    mask = np.ones(n, dtype=bool)
    a_words = np.zeros((n, 3, NWORDS), dtype=np.uint32)
    s_words = np.zeros((n, NWORDS), dtype=np.uint32)
    h_words = np.zeros((n, NWORDS), dtype=np.uint32)
    yr_words = np.zeros((n, NWORDS), dtype=np.uint32)
    parity = np.zeros(n, dtype=np.int32)
    for i in range(n):
        pub, msg, sig = pubs[i], msgs[i], sigs[i]
        if len(pub) != 32 or len(sig) != 64:
            mask[i] = False
            continue
        entry = _cache.get(bytes(pub))
        if entry is None:
            mask[i] = False
            continue
        r_bytes, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= em.L:
            mask[i] = False
            continue
        r_int = int.from_bytes(r_bytes, "little")
        y_r = r_int & ((1 << 255) - 1)
        if y_r >= em.P:  # strict: reject non-canonical R encodings
            mask[i] = False
            continue
        a_words[i] = entry
        s_words[i] = np.frombuffer(s_bytes, dtype=np.uint32)
        yr_words[i] = np.frombuffer(
            y_r.to_bytes(32, "little"), dtype=np.uint32
        )
        parity[i] = r_int >> 255
        h = em.reduce_scalar(hashlib.sha512(r_bytes + pub + msg).digest())
        h_words[i] = np.frombuffer(h.to_bytes(32, "little"), dtype=np.uint32)
    if not mask.any():
        return None, mask
    return _pack_inputs(a_words, s_words, h_words, yr_words, parity, n, min_bucket), mask


class _DeviceKeyCache:
    """Content-addressed cache of device-resident pubkey blocks.

    Validator sets are stable across heights, so consecutive commits (and
    every chunk of a fast-sync stream over an unchanged valset) reuse the
    same (24, B) key block; keeping it on device halves the per-commit
    host->device traffic — and on a tunneled device skips one transfer RPC
    entirely. Keyed by (pubkey bytes, bucket, placement) — placement must
    be part of the key because a mesh resize (TMTPU_MESH flip, config
    change) changes the sharding a block was committed to, and feeding a
    stale-placed block to the new mesh's executable is at best a silent
    per-dispatch reshard and at worst a shape/sharding error that degrades
    the dispatch to single-device every commit. NamedShardings hash by
    value, so a plan rebuild at the same mesh size still hits. Bounded
    LRU (8 x ~12 MB at the max bucket)."""

    def __init__(self, maxsize: int = 8) -> None:
        # (pubkey digest, bucket, sharding | None) -> device-resident block
        self._d: dict[tuple[bytes, int, object], object] = {}
        self._maxsize = maxsize

    def get(self, chunk_pubs, keys_np, sharding=None, cacheable=True):
        """cacheable must be False unless every lane passed its structural
        checks: prep zeroes the key planes of lanes whose SIGNATURE failed
        (not just bad pubkeys), so a partially-invalid batch's key block is
        not a pure function of the pubkey list and caching it would poison
        later batches that share the pubs with then-valid signatures.
        Lookup is always safe — cached blocks were built all-valid."""
        import hashlib as _hl

        import jax

        h = _hl.sha256()
        for p in chunk_pubs:
            h.update(bytes(p))
        key = (h.digest(), keys_np.shape[1], sharding)
        dev = self._d.pop(key, None)
        if dev is None:
            # device_put treats sharding=None as default placement
            dev = jax.device_put(keys_np, sharding)
            if not cacheable:
                return dev
        self._d[key] = dev  # re-insert: LRU order
        while len(self._d) > self._maxsize:
            self._d.pop(next(iter(self._d)))
        return dev


_dev_keys = _DeviceKeyCache()

# The wedged-device circuit breaker and the bounded verdict-fetch pool
# moved to the unified dispatch service (tendermint_tpu/device/scheduler.py,
# ROADMAP item 1): ONE breaker per DeviceScheduler instead of a module
# global that secp_batch borrowed from this module, one fetch pool owned
# by the scheduler. The names below are compatibility aliases; `breaker`
# itself is served by the module __getattr__ at the bottom of this file
# so debug_fault's trip_breaker/reset_breaker and the
# nemesis_flapping_device scenario keep working unchanged.
_CircuitBreaker = _dsched._CircuitBreaker
fetch_verdicts = _dsched.fetch_verdicts
_FETCH_TIMEOUT_S = _dsched._FETCH_TIMEOUT_S
_BREAKER_RETRY_S = _dsched._BREAKER_RETRY_S

# Multi-device dispatch: mesh routing is owned by device/mesh.py — the
# config/env-driven mesh plan (`TMTPU_MESH`: auto = all visible devices,
# 1 = today's single-device path bit-for-bit, N = clamp; power-of-two
# sizes only, so every _pad_to_bucket bucket divides over the mesh).
# When the resolved mesh has >= 2 devices every chunk is batch-sharded
# across it via shard_map (jit respecializes the one memoized callable
# per bucket shape). The single-device path keeps kcache's export-blob
# fast start (exports don't carry shardings).
_sharded = None  # (fn, NamedSharding, mesh size) | None, rebuilt on change


def _multi_device_fn():
    from tendermint_tpu.device import mesh as dmesh

    n = dmesh.mesh_size("ed25519")
    if n < 2:
        return None, None
    global _sharded
    if _sharded is None or _sharded[2] != n:
        built = dmesh.build_plan("ed25519", n)
        if built is None:
            return None, None
        _sharded = (built[0], built[1], n)
    return _sharded[0], _sharded[1]


def invalidate_mesh_plan() -> None:
    """Drop every cache bound to the current device layout — the built
    mesh plan and the device-resident key blocks. Called by
    device/mesh.reset() when the layout changes: the plan is keyed only
    by mesh SIZE, so a same-size rebuild would otherwise keep
    dispatching over dead device objects."""
    global _sharded
    _sharded = None
    _dev_keys._d.clear()


def verify_batch(pubs, msgs, sigs) -> list[bool]:
    """DEPRECATED direct entry — thin compatibility wrapper.

    Device verification flows through the process-wide DeviceScheduler
    (tendermint_tpu/device/): this wrapper submits a device-targeted
    request at the caller's priority class (device/priorities.py) and
    blocks for the verdicts, so stray direct callers still share the one
    admission queue, packer and breaker. On the scheduler's own dispatch
    thread it runs the real dispatch body instead (tmlint TM501 flags new
    direct calls outside tendermint_tpu/device/)."""
    if _dsched.in_dispatch():
        return _verify_batch_local(pubs, msgs, sigs)
    return _dsched.get_scheduler().submit_sync(
        "ed25519", pubs, msgs, sigs
    ).result()


def _verify_batch_local(pubs, msgs, sigs) -> list[bool]:
    """Full batched verification: host prep + one device launch per chunk.
    Scheduler-dispatch body (callers go through `verify_batch`).

    Batches above kcache.MAX_BUCKET are verified in chunks so the set of
    compiled kernel variants stays bounded; the per-bucket callable comes
    from kcache (export-blob fast path or the module jit kernel). Chunk
    launches are dispatched asynchronously (at most one device_put + one
    execute each) and collected at the end, so a long stream of commits —
    the fast sync / light client shape — keeps the device queue full
    instead of paying a round trip per chunk. Pubkey blocks are served
    from the device-resident cache when the valset repeats.

    Observability: the whole call is one `ed25519_batch` trace span
    (batch size, bucket, dispatch and fetch latency, timeout/fallback
    tags) attached to whatever consensus span is active, and every
    dispatch/fetch/degrade event updates libs/trace.DEVICE. A tripped
    circuit breaker (the dispatching scheduler's) short-circuits to the
    device-free crypto path.
    """
    n = len(pubs)
    if not _dsched.active_breaker().allow():
        # wedged device link: route straight to the CPU path instead of
        # re-blocking _FETCH_TIMEOUT_S on every commit verify (ADVICE r5)
        from tendermint_tpu import ops as _ops

        _trace.DEVICE.record_fallback("breaker_open")
        with _trace.span("ed25519_cpu_fallback", batch_size=n, reason="breaker_open"):
            return list(_ops._ed25519_small(pubs, msgs, sigs))
    from tendermint_tpu.ops import kcache

    with _trace.span("ed25519_batch", batch_size=n) as sp:
        return _verify_batch_device(pubs, msgs, sigs, n, kcache, sp)


def _verify_batch_device(pubs, msgs, sigs, n, kcache, sp) -> list[bool]:
    """verify_batch body under an open `ed25519_batch` span `sp`."""
    breaker = _dsched.active_breaker()
    t_dispatch0 = time.monotonic()
    pending: list[tuple[int, int, object, tuple, np.ndarray, bool]] = []
    out = np.zeros(n, dtype=bool)
    for lo in range(0, n, kcache.MAX_BUCKET):
        hi = min(lo + kcache.MAX_BUCKET, n)
        packed, mask = prepare_batch(pubs[lo:hi], msgs[lo:hi], sigs[lo:hi])
        if packed is None:
            continue
        _trace.DEVICE.record_dispatch(int(mask.sum()), packed.shape[1])
        sp.set(bucket=packed.shape[1])
        keys_np, sigs_np = split(packed)
        mfn, sharding = _multi_device_fn()
        dev_out = None
        from_sharded = False
        if mfn is not None:
            import jax

            try:
                keys_dev = _dev_keys.get(
                    pubs[lo:hi], keys_np, sharding, cacheable=bool(mask.all())
                )
                dev_out = mfn(keys_dev, jax.device_put(sigs_np, sharding))
                from_sharded = True
            except Exception:  # noqa: BLE001 — a sharding/mesh/transfer
                # failure is not a kernel failure: degrade to the
                # single-device path
                dev_out = None
            if from_sharded:
                # outside the dispatch try: a throwing telemetry sink
                # must not discard the completed mesh result or mislabel
                # the fallback as sharded
                try:
                    _trace.DEVICE.record_mesh_dispatch(
                        int(mask.sum()), packed.shape[1],
                        int(sharding.mesh.size),
                    )
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
        if dev_out is None:
            try:
                import jax

                fn = kcache.get_verify_fn(packed.shape[1])
                # placement is part of the key-cache key, so this lookup
                # serves the default-placed block — never the mesh-placed
                # one a failed sharded attempt above may have cached
                keys_arg = _dev_keys.get(
                    pubs[lo:hi], keys_np, cacheable=bool(mask.all())
                )
                # commit the sig block explicitly: a committed/uncommitted
                # argument mix is a different jit cache key than the
                # all-committed prewarm call, and the re-trace+lowering of
                # the 127-iteration kernel costs ~20s (measured) even with
                # the compiled executable already cached
                dev_out = fn(keys_arg, jax.device_put(sigs_np))
            except Exception:  # noqa: BLE001 — e.g. a Mosaic lowering
                # regression on a new backend: the preferred (pallas)
                # kernel failing must degrade to the XLA kernel, never
                # break verification
                if kcache._kernel_for(kcache._platform())[0] == "xla":
                    raise  # the failing kernel IS the XLA kernel
                dev_out = verify_kernel(keys_np, sigs_np)
        try:
            # cumulative waste ledger (device/profiler): the priority
            # class resolves here because _dispatch_group_inner runs
            # under the lead request's contextvars
            _profiler.PROFILER.record_padding(
                int(mask.sum()), packed.shape[1],
                cls=_current_priority().label,
                shards=int(sharding.mesh.size) if from_sharded else 1,
            )
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        pending.append(
            (lo, hi, dev_out, (keys_np, sigs_np), mask, from_sharded)
        )
    # fetch all chunks' verdict arrays CONCURRENTLY and BOUNDED
    # (fetch_verdicts): each fetch is a full RPC round trip on a tunneled
    # device (~65 ms) — threads collapse K round trips toward one — and a
    # dead tunnel makes every fetch hang forever, so on expiry every
    # chunk degrades to the local recompute below instead of blocking
    # the node indefinitely (ADVICE r4).
    sp.set(chunks=len(pending),
           dispatch_ms=round((time.monotonic() - t_dispatch0) * 1e3, 3))
    t_fetch0 = time.monotonic()
    fetched = fetch_verdicts([p[2] for p in pending])
    fetch_s = time.monotonic() - t_fetch0
    sp.set(fetch_ms=round(fetch_s * 1e3, 3))
    timed_out = False
    for (lo, hi, _, blocks, mask, from_sharded), got in zip(pending, fetched):
        if isinstance(got, TimeoutError):
            timed_out = True
            _trace.DEVICE.record_fallback("fetch_timeout")
            # wedged device link: every further jax call — including the
            # local-recompute degrade below — would hang the same way.
            # Recompute this chunk on the device-free crypto path (native
            # C++ batch core, serial OpenSSL behind it).
            from tendermint_tpu import ops as _ops

            ok = np.asarray(
                _ops._ed25519_small(pubs[lo:hi], msgs[lo:hi], sigs[lo:hi]),
                dtype=bool,
            )
        elif isinstance(got, Exception):
            # async dispatch surfaces kernel runtime failures at fetch
            # time; same degradation contract. A sharded-path failure may
            # be a mesh/transfer problem rather than a kernel defect, so
            # it degrades to the single-device XLA kernel even when XLA is
            # the platform kernel ('degrade, never break verification');
            # only a single-device XLA failure — a genuine kernel defect —
            # re-raises.
            if not from_sharded and (
                kcache._kernel_for(kcache._platform())[0] == "xla"
            ):
                raise got
            _trace.DEVICE.record_fallback("kernel_error")
            ok = np.asarray(verify_kernel(*blocks))[: hi - lo]
        else:
            ok = got[: hi - lo]
        out[lo:hi] = ok & mask
    if pending:
        # occupancy: this call held the device busy from first dispatch
        # to last verdict fetched, with len(pending) chunks in flight
        _trace.DEVICE.record_busy(
            (time.monotonic() - t_dispatch0), queue_depth=len(pending)
        )
    if timed_out:
        # first wedge observation trips the breaker: later calls skip the
        # device until the retry deadline (the half-open probe re-enters
        # here and either re-trips or closes the breaker below)
        breaker.trip()
        _trace.DEVICE.record_timeout()
        sp.set(timeout=True)
    elif pending:
        breaker.reset()
        _trace.DEVICE.record_fetch(fetch_s)
    else:
        # nothing dispatched (all lanes structurally invalid): don't burn
        # a claimed half-open probe on a call that never hit the device
        breaker.release_probe()
    return out.tolist()


def __getattr__(name):
    # Deprecated alias: the circuit breaker is a DeviceScheduler instance
    # now (device/scheduler.py), not this module's global. Served lazily so
    # debug_fault's trip_breaker/reset_breaker and the
    # nemesis_flapping_device scenario keep working unchanged; a real
    # module attribute (tests monkeypatch one) shadows this.
    if name == "breaker":
        return _dsched.get_scheduler().breaker
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
