"""The batched Ed25519 verification kernel + host-side batch builder.

This is the TPU replacement for the reference's strictly serial signature
loops (types/vote_set.go:189, types/validator_set.go:609-627,
state/validation.go:99, lite/dynamic_verifier.go): one device launch
verifies a whole batch.

Split of work:
- Host (cheap, per signature): SHA-512(R||A||M) and reduction mod L, scalar
  range check S < L, pubkey decompression to extended coordinates (cached
  per pubkey — validator keys are stable across heights, so steady-state
  commits pay zero decompression), R parsed as (y_R canonical digits,
  x parity) with a strict y_R < p check.
- Device (the FLOPs): Straus/Shamir interleaved double-scalar multiplication
  R' = [S]B + [h](-A) over 253 constant-time iterations (table
  {O, B, -A, B-A} in cached form), one batched field inversion, canonical
  encode, compare with R. Verdict bitmap (B,) comes back; host ANDs it with
  the structural-validity mask.

The verification equation is the strict cofactorless one used by Go's
x/crypto/ed25519 (the reference's verifier): encode([S]B + [h](-A)) == R,
with S < L enforced and non-canonical R encodings rejected.
"""
from __future__ import annotations

import hashlib
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.ops import curve, field
from tendermint_tpu.ops.limbs import NLIMB, ints_to_limbs, scalars_to_bits

NBITS = 253  # scalars are < L < 2^253


def _shamir_loop(neg_a: curve.Point, s_bits, h_bits) -> curve.Point:
    """[S]B + [h]*negA, MSB-first, one double + one complete add per bit."""
    b = s_bits.shape[1]

    def bcast(c):  # (22,1) module constant -> (22,B)
        return jnp.broadcast_to(jnp.asarray(c), (NLIMB, b)).astype(jnp.int32)

    t_base = curve.CachedPoint(*[bcast(c) for c in curve.BASE_CACHED])
    t_nega = curve.to_cached(neg_a)
    t_both = curve.to_cached(curve.add_cached(neg_a, t_base))
    t_id = curve.CachedPoint(*[bcast(c) for c in curve.IDENTITY_CACHED])

    p0 = curve.Point(*[bcast(c) for c in curve.IDENTITY])

    def body(i, p):
        bit = NBITS - 1 - i
        sb = jax.lax.dynamic_index_in_dim(s_bits, bit, 0, keepdims=False)
        hb = jax.lax.dynamic_index_in_dim(h_bits, bit, 0, keepdims=False)
        lo = curve.select_cached(sb, t_base, t_id)  # h=0: O or B
        hi = curve.select_cached(sb, t_both, t_nega)  # h=1: -A or B-A
        entry = curve.select_cached(hb, hi, lo)
        return curve.add_cached(curve.double(p), entry)

    return jax.lax.fori_loop(0, NBITS, body, p0)


@partial(jax.jit, static_argnames=())
def verify_kernel(neg_a_x, neg_a_y, neg_a_t, s_bits, h_bits, y_r, x_parity):
    """Batched verify core.

    neg_a_{x,y,t}: (22, B) limbs of -A in affine extended form (Z=1).
    s_bits, h_bits: (253, B) int32 bit arrays.
    y_r: (22, B) canonical digits of R's y coordinate.
    x_parity: (B,) int32 — R's sign bit.
    Returns (B,) bool.
    """
    b = s_bits.shape[1]
    one = jnp.broadcast_to(jnp.asarray(curve._ONE), (NLIMB, b)).astype(jnp.int32)
    neg_a = curve.Point(neg_a_x, neg_a_y, one, neg_a_t)
    rp = _shamir_loop(neg_a, s_bits, h_bits)
    x, y = curve.to_affine(rp)
    return field.eq(y, y_r) & (field.is_odd(x) == x_parity)


class _PubkeyCache:
    """pubkey bytes -> np (3, 22) int32 limbs of -A (x, y, t), LRU-bounded."""

    def __init__(self, maxsize: int = 65536) -> None:
        self._d: dict[bytes, np.ndarray | None] = {}
        self._maxsize = maxsize

    def get(self, pub: bytes) -> np.ndarray | None:
        if pub in self._d:
            return self._d[pub]
        pt = em.decompress(pub)
        if pt is None:
            entry = None
        else:
            nx, ny, _, nt = em.point_neg(pt)
            entry = ints_to_limbs([nx, ny, nt]).T.copy()  # (3, 22)
        if len(self._d) >= self._maxsize:
            self._d.pop(next(iter(self._d)))
        self._d[pub] = entry
        return entry


_cache = _PubkeyCache()


def _pad_to_bucket(n: int, min_bucket: int = 128) -> int:
    """Pad batch sizes to power-of-two buckets to bound jit recompilations."""
    b = min_bucket
    while b < n:
        b *= 2
    return b


def prepare_batch(pubs, msgs, sigs, min_bucket: int = 128):
    """Host-side batch build. Returns (device_inputs dict | None, valid_mask).

    valid_mask marks signatures that failed structural checks (bad lengths,
    undecompressable A, S >= L, non-canonical R) — already final False.
    """
    n = len(pubs)
    mask = np.ones(n, dtype=bool)
    neg_a = np.zeros((n, 3, NLIMB), dtype=np.int32)
    y_r_int = [0] * n
    parity = np.zeros(n, dtype=np.int32)
    s_int = [0] * n
    h_int = [0] * n
    for i in range(n):
        pub, msg, sig = pubs[i], msgs[i], sigs[i]
        if len(pub) != 32 or len(sig) != 64:
            mask[i] = False
            continue
        entry = _cache.get(bytes(pub))
        if entry is None:
            mask[i] = False
            continue
        r_bytes, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= em.L:
            mask[i] = False
            continue
        r_int = int.from_bytes(r_bytes, "little")
        y_r = r_int & ((1 << 255) - 1)
        if y_r >= em.P:  # strict: reject non-canonical R encodings
            mask[i] = False
            continue
        neg_a[i] = entry
        y_r_int[i] = y_r
        parity[i] = r_int >> 255
        s_int[i] = s
        h_int[i] = em.reduce_scalar(hashlib.sha512(r_bytes + pub + msg).digest())
    if not mask.any():
        return None, mask
    padded = _pad_to_bucket(n, min_bucket)
    pad = padded - n

    def padl(limbs):  # (22, n) -> (22, padded)
        return np.pad(limbs, ((0, 0), (0, pad)))

    na = np.pad(neg_a, ((0, pad), (0, 0), (0, 0)))
    inputs = dict(
        neg_a_x=np.ascontiguousarray(na[:, 0].T),
        neg_a_y=np.ascontiguousarray(na[:, 1].T),
        neg_a_t=np.ascontiguousarray(na[:, 2].T),
        s_bits=np.pad(scalars_to_bits(s_int, NBITS), ((0, 0), (0, pad))),
        h_bits=np.pad(scalars_to_bits(h_int, NBITS), ((0, 0), (0, pad))),
        y_r=padl(ints_to_limbs(y_r_int)),
        x_parity=np.pad(parity, (0, pad)),
    )
    return inputs, mask


def verify_batch(pubs, msgs, sigs) -> list[bool]:
    """Full batched verification: host prep + one device launch."""
    inputs, mask = prepare_batch(pubs, msgs, sigs)
    if inputs is None:
        return mask.tolist()
    ok = np.asarray(verify_kernel(**inputs))[: len(pubs)]
    return (ok & mask).tolist()
