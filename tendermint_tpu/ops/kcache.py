"""Kernel start-time cache: persistent XLA compiles + jax.export blobs.

Round-1 VERDICT weak #1: 133s cold compile per process with no persistent
cache is operationally disqualifying. Two layers fix it:

1. JAX's persistent compilation cache (XLA binaries keyed by HLO
   fingerprint) — cuts the XLA compile to ~2s on a warm cache.
2. A per-bucket `jax.export` blob of the verify kernel. Tracing + lowering
   the 127-iteration Straus kernel costs ~10s of pure Python/StableHLO work
   per process; deserializing the exported artifact skips it entirely.
   Blobs are keyed by a hash of the kernel sources + jax version +
   platform + batch bucket, so stale blobs die with any kernel edit.

Measured second-process start-to-first-verify: 37.7s (no caches) -> 7.7s
(both layers warm). Blobs are written by a background thread after the
first in-process compile so the foreground path never pays the ~12s
re-trace that `jax.export` needs.

The bucket set is capped (`MAX_BUCKET`) — larger batches are verified in
chunks — so the number of compiled variants is bounded (9 buckets).
"""
from __future__ import annotations

import hashlib
import os
import threading

_CACHE_DIR = os.environ.get(
    "TMTPU_CACHE_DIR", os.path.expanduser("~/.cache/tendermint_tpu")
)

MAX_BUCKET = 16384

_lock = threading.Lock()
_fns: dict[tuple[str, int], object] = {}  # (platform, bucket) -> callable
_exports_scheduled: set[tuple[str, int]] = set()
_enabled = False

# Background threads are non-daemon (daemon threads mid-XLA-compile caused
# SIGABRTs at interpreter teardown), so interpreter shutdown joins them.
# This flag bounds that join to at most the in-flight compile: it is set by
# threading's shutdown hook BEFORE non-daemon threads are joined, and the
# workers check it between compiles.
_cancel = threading.Event()
try:
    threading._register_atexit(_cancel.set)  # runs before the join
except Exception:  # noqa: BLE001 — private API (stable since 3.9). The
    # atexit fallback runs AFTER non-daemon threads are joined, so it does
    # not bound the exit delay — it only keeps later atexit-ordered cleanup
    # (e.g. a second interpreter in the same process) from starting work.
    import atexit

    atexit.register(_cancel.set)


def enable_persistent_cache() -> None:
    """Point JAX's compilation cache at our cache dir (idempotent)."""
    global _enabled
    if _enabled or os.environ.get("TMTPU_NO_COMPILE_CACHE"):
        return
    import jax

    try:
        os.makedirs(os.path.join(_CACHE_DIR, "xla"), exist_ok=True)
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(_CACHE_DIR, "xla")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception:  # noqa: BLE001 — cache is best-effort, never fatal
        _enabled = True


_source_version_memo: str | None = None


def _source_version() -> str:
    """Hash of the kernel source files: any edit invalidates export blobs.
    Raises when sources aren't readable (pyc-only/zipimport installs) —
    callers treat that as "no blob cache", never as fatal."""
    global _source_version_memo
    if _source_version_memo is not None:
        return _source_version_memo
    import jax

    from tendermint_tpu.ops import curve, ed25519_batch, field, limbs

    h = hashlib.sha256()
    mods = [ed25519_batch, field, curve, limbs]
    try:
        from tendermint_tpu.ops import pallas_verify

        mods.append(pallas_verify)
    except Exception:  # noqa: BLE001 — pallas may not import on all backends
        pass
    for m in mods:
        with open(m.__file__, "rb") as f:
            h.update(f.read())
    h.update(jax.__version__.encode())
    _source_version_memo = h.hexdigest()[:16]
    return _source_version_memo


def _platform() -> str:
    import jax

    return jax.devices()[0].platform


def _blob_path(platform: str, bucket: int) -> str:
    return os.path.join(
        _CACHE_DIR,
        "export",
        f"ed25519_verify_{platform}_{bucket}_{_source_version()}.jaxexport",
    )


def _input_shapes(bucket: int):
    import jax
    import numpy as np

    from tendermint_tpu.ops.ed25519_batch import NWORDS

    word = jax.ShapeDtypeStruct((NWORDS, bucket), np.int32)
    return dict(
        a_x_w=word, a_y_w=word, a_t_w=word, s_w=word, h_w=word, yr_w=word,
        x_parity=jax.ShapeDtypeStruct((bucket,), np.int32),
    )


def _write_export_blob(platform: str, bucket: int) -> None:
    """Trace, export, and persist the kernel for one bucket (slow: ~12s of
    lowering — always runs on a background thread)."""
    import jax

    from tendermint_tpu.ops import ed25519_batch

    path = _blob_path(platform, bucket)
    try:
        if _cancel.is_set():
            return
        exp = jax.export.export(ed25519_batch.verify_kernel)(
            **_input_shapes(bucket)
        )
        blob = exp.serialize()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        # The export path compiles under a different XLA cache key than the
        # in-process jit path; run the artifact once now (still background)
        # so the export-keyed binary lands in the persistent cache and the
        # NEXT process skips both the trace and the compile.
        if _cancel.is_set():
            return
        import numpy as np

        reloaded = jax.export.deserialize(blob)
        inputs = {
            k: np.zeros(s.shape, s.dtype)
            for k, s in _input_shapes(bucket).items()
        }
        np.asarray(reloaded.call(**inputs))
    except Exception:  # noqa: BLE001 — export is an optimization only
        pass


def get_verify_fn(bucket: int):
    """Callable(**inputs) -> (bucket,) bool for this batch bucket.

    Prefers a deserialized export blob (no trace cost); falls back to the
    module-level jit kernel and schedules a background export for next time.
    """
    enable_persistent_cache()
    platform = _platform()
    key = (platform, bucket)
    with _lock:
        fn = _fns.get(key)
    if fn is not None:
        return fn

    import jax

    from tendermint_tpu.ops import ed25519_batch

    fn = None
    path = None
    if not os.environ.get("TMTPU_NO_EXPORT_CACHE"):
        try:
            path = _blob_path(platform, bucket)
        except Exception:  # noqa: BLE001 — unreadable sources: no blob cache
            path = None
    if path is not None:
        try:
            with open(path, "rb") as f:
                exp = jax.export.deserialize(f.read())
            fn = lambda **kw: exp.call(**kw)  # noqa: E731
        except FileNotFoundError:
            pass
        except Exception:  # noqa: BLE001 — corrupt/stale blob: fall through
            try:
                os.unlink(path)
            except OSError:
                pass
        if fn is None:
            with _lock:
                first = key not in _exports_scheduled
                _exports_scheduled.add(key)
            if first:
                # Non-daemon: interpreter shutdown joins the thread, so the
                # process never tears down the XLA runtime mid-compile
                # (daemon threads here caused SIGABRTs at exit — "FATAL:
                # exception not rethrown" from the runtime's thread pools).
                threading.Thread(
                    target=_write_export_blob,
                    args=(platform, bucket),
                    daemon=False,
                    name=f"tmtpu-export-{bucket}",
                ).start()
    if fn is None:
        fn = lambda **kw: ed25519_batch.verify_kernel(**kw)  # noqa: E731
    with _lock:
        _fns[key] = fn
    return fn


def prewarm(buckets=(128,), background: bool = True):
    """Compile + run the verify kernel on dummy inputs for each bucket so a
    node's first real commit doesn't pay compile/dispatch warmup. Buckets
    above MAX_BUCKET are clamped. Returns the worker thread when
    background=True."""
    import numpy as np

    def work():
        for b in sorted({min(b, MAX_BUCKET) for b in buckets}):
            if _cancel.is_set():
                return
            try:
                fn = get_verify_fn(b)
                inputs = {
                    k: np.zeros(s.shape, s.dtype)
                    for k, s in _input_shapes(b).items()
                }
                np.asarray(fn(**inputs))
            except Exception:  # noqa: BLE001 — prewarm must never kill a node
                pass

    if background:
        # Non-daemon for the same reason as the export thread above.
        t = threading.Thread(target=work, daemon=False, name="tmtpu-prewarm")
        t.start()
        return t
    work()
    return None
